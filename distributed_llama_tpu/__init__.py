"""distributed_llama_tpu — a TPU-native tensor-parallel Llama inference framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of distributed-llama
(reference: /root/reference, b4rtaz/distributed-llama): Q40-quantized weights,
Q80-quantized activation exchange, 2^n-way tensor parallelism, llama2.c tokenizer,
and the reference's logit-level numerics — expressed as sharded, jitted step
functions over a `jax.sharding.Mesh` instead of hand-scheduled task tables over
TCP sockets.

Layer map (ours ⇄ reference):
  ops.quants      ⇄ src/quants.cpp        (block codecs)
  ops             ⇄ src/funcs.cpp         (kernels: XLA/Pallas instead of NEON)
  models          ⇄ src/transformer.cpp   (spec, weights, buffers)
  parallel        ⇄ src/socket.cpp + transformer-tasks.cpp sync* (ICI collectives
                                           instead of star-topology TCP)
  runtime         ⇄ src/transformer-tasks.cpp + tokenizer.cpp generate()
  frontend.cli    ⇄ src/main.cpp
  convert         ⇄ converter/converter.py
  csrc/           ⇄ the reference's native (C++) host role
"""

__version__ = "0.1.0"
