from .frontend.cli import main

raise SystemExit(main())
