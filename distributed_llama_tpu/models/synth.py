"""Seeded synthetic parameter trees for tests, dryruns, and benches.

Mirrors the shape/layout contract of io.loader.load_model: per-layer matmul
weights stacked along a leading layer axis, Q40 weights as codec-layout
Q40Weight pairs (kernel re-tiling happens downstream in params_to_device /
shard_params, like for file-loaded weights).
"""

from __future__ import annotations

import functools as _functools

import numpy as np

from ..io.loader import Q40Weight
from ..ops.quants import quantize_q40
from .spec import TransformerSpec


def _build_tree(spec: TransformerSpec, t, mm) -> dict:
    """Assemble the param tree from a dense builder ``t`` and a matmul-weight
    builder ``mm`` — the one place that knows the tree's key set."""
    p = {"tok_embedding": t(spec.vocab_size, spec.dim),
         "rms_final": 1 + t(spec.dim),
         "rms_att": 1 + t(spec.n_layers, spec.dim),
         "rms_ffn": 1 + t(spec.n_layers, spec.dim),
         "wcls": mm(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        p[name] = mm(spec.n_layers, *shape)
    return p


def synth_q40_fast(spec: TransformerSpec, seed: int = 0) -> dict:
    """Random Q40 params built directly as packed bytes — for benchmarks.

    Skips the float-generate + quantize pass (minutes for 7B in numpy):
    decode TIMING is value-independent, so random nibble codes + small
    positive f16 deltas give the exact memory layout and dataflow of real
    weights at negligible synthesis cost. Not for numerics tests.
    """
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.05).astype(np.float32)

    def mm(*shape):
        *lead, d, n = shape
        qs = rng.integers(0, 256, (*lead, d, n // 32, 16), dtype=np.uint8)
        d16 = (rng.random((*lead, d, n // 32), dtype=np.float32)
               * 0.01 + 1e-4).astype(np.float16)
        return Q40Weight(qs, d16)

    return _build_tree(spec, t, mm)


def device_params_like(tree, seed: int = 0):
    """Rebuild ``tree`` as ON-DEVICE arrays of the same shapes/dtypes with
    synthetic values — no host->device transfer of the actual bytes.

    Why this exists (VERDICT r2 #7, warm start): on the tunneled TPU runtime
    ``device_put`` is LAZY — ``block_until_ready`` returns in under a second
    while the real upload (~17 MB/s measured) happens at first use, so a
    host-synthesized 7B tree stalls the first decode chain for ~4 GB / 17
    MB/s = ~240 s. Values are timing-irrelevant for the bench (module
    docstring), so generating them on device removes the upload entirely.
    Real --model runs still pay the honest upload (their bytes exist only on
    the host).

    ONE jitted program generates the whole tree (module-level cache per
    distinct shape/dtype signature — repeat calls in one process reuse the
    trace): a cold process pays a single generator compile instead of one
    per leaf (~12 compile-service round-trips at 7B, ~30 s of the measured
    cold start).
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    sig = tuple(
        (tuple(leaf.shape),
         str(np.asarray(leaf).dtype if not hasattr(leaf, "dtype")
             else leaf.dtype))
        for leaf in leaves)
    out = _gen_all(sig)(np.uint32(seed))
    return jax.tree_util.tree_unflatten(treedef, out)


def _gen_leaf(shape, dt, s):
    import jax
    import jax.numpy as jnp

    key = jax.random.key(s)
    if dt == jnp.dtype(jnp.uint8):
        return jax.random.bits(key, shape, jnp.uint8)
    if jnp.issubdtype(dt, jnp.floating):
        # small positive values: safe for every leaf role (Q40 scales
        # must be positive; norm gains near small values are fine;
        # magnitudes never reach inf/nan paths)
        return (jax.random.uniform(key, shape, jnp.float32)
                * 0.01 + 1e-4).astype(dt)
    return jnp.zeros(shape, dt)


@_functools.lru_cache(maxsize=None)
def _gen_all(sig):
    """jit'd whole-tree generator for one (shape, dtype) signature."""
    import jax
    import jax.numpy as jnp

    def gen(s0):
        return [_gen_leaf(shape, jnp.dtype(dtype), s0 + i)
                for i, (shape, dtype) in enumerate(sig)]

    return jax.jit(gen)


def synth_params(spec: TransformerSpec, q40: bool, seed: int = 0,
                 scale: float = 0.05) -> dict:
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    def mm(*shape):
        x = t(*shape)
        if not q40:
            return x
        qs, d16 = quantize_q40(x)
        return Q40Weight(qs, d16)

    return _build_tree(spec, t, mm)


def llama2_7b_spec(**overrides) -> TransformerSpec:
    """The Llama-2-7B shape (converter header values) at Q40 — THE benchmark
    config, shared by bench.py and the tools so a shape correction happens
    in exactly one place."""
    from ..ops.quants import FloatType

    kw = dict(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32,
              n_kv_heads=32, vocab_size=32000, seq_len=2048,
              weights_float_type=FloatType.Q40)
    kw.update(overrides)
    return TransformerSpec(**kw)


def llama2_13b_spec(**overrides) -> TransformerSpec:
    """Llama-2-13B shape (params.json: dim 5120, 40 layers/heads, MHA).
    Q40 kernel-layout ~8.0 GB — fits a 16 GB v5e chip whole, so this rounds
    out the measured ladder against the reference's 13B rows
    (README.md:47, best 848.19 ms/token)."""
    from ..ops.quants import FloatType

    kw = dict(dim=5120, hidden_dim=13824, n_layers=40, n_heads=40,
              n_kv_heads=40, vocab_size=32000, seq_len=2048,
              weights_float_type=FloatType.Q40)
    kw.update(overrides)
    return TransformerSpec(**kw)


def llama2_70b_spec(**overrides) -> TransformerSpec:
    """Llama-2-70B shape (dim 8192, 80 layers, GQA 64q/8kv, hidden 28672) —
    the north-star config (BASELINE.json). Whole-model Q40 is ~38.7 GB: runs
    only sharded; one tp=8 rank's bands (~5 GB) fit one chip
    (parallel/shard_sim.py)."""
    from ..ops.quants import FloatType

    kw = dict(dim=8192, hidden_dim=28672, n_layers=80, n_heads=64,
              n_kv_heads=8, vocab_size=32000, seq_len=2048,
              weights_float_type=FloatType.Q40)
    kw.update(overrides)
    return TransformerSpec(**kw)


def small_bench_spec(**overrides) -> TransformerSpec:
    """Tiny Q40 config for CI/CPU smoke runs of the benchmarks."""
    from ..ops.quants import FloatType

    kw = dict(dim=256, hidden_dim=704, n_layers=4, n_heads=4, n_kv_heads=4,
              vocab_size=1024, seq_len=256,
              weights_float_type=FloatType.Q40)
    kw.update(overrides)
    return TransformerSpec(**kw)
