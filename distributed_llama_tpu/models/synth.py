"""Seeded synthetic parameter trees for tests, dryruns, and benches.

Mirrors the shape/layout contract of io.loader.load_model: per-layer matmul
weights stacked along a leading layer axis, Q40 weights as codec-layout
Q40Weight pairs (kernel re-tiling happens downstream in params_to_device /
shard_params, like for file-loaded weights).
"""

from __future__ import annotations

import numpy as np

from ..io.loader import Q40Weight
from ..ops.quants import quantize_q40
from .spec import TransformerSpec


def synth_params(spec: TransformerSpec, q40: bool, seed: int = 0,
                 scale: float = 0.05) -> dict:
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    def mm(*shape):
        x = t(*shape)
        if not q40:
            return x
        qs, d16 = quantize_q40(x)
        return Q40Weight(qs, d16)

    p = {"tok_embedding": t(spec.vocab_size, spec.dim),
         "rms_final": 1 + t(spec.dim),
         "rms_att": 1 + t(spec.n_layers, spec.dim),
         "rms_ffn": 1 + t(spec.n_layers, spec.dim),
         "wcls": mm(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        p[name] = mm(spec.n_layers, *shape)
    return p
