from .spec import TransformerSpec  # noqa: F401
