"""Model spec + .bin file layout accounting.

File format parity with the reference: 28-byte header of 7 little-endian int32
{dim, hiddenDim, nLayers, nHeads, nKvHeads, vocabSize, seqLen} (reference
src/transformer.hpp:23-31, src/transformer.cpp:52-95), then tensors in the
fixed order written by converter/converter.py:85-151 and read by
src/transformer.cpp:298-352:

  tok_embeddings (F32, vocab x dim)
  per layer: attention_norm (F32 dim), ffn_norm (F32 dim),
             wq (dim x dim), wk (kvDim x dim), wv (kvDim x dim), wo (dim x dim),
             w1 (hidden x dim), w2 (dim x hidden), w3 (hidden x dim)
             [all in weightsFloatType]
  norm (F32 dim)
  <gap: 2 * seqLen * headSize/2 f32 — the legacy freq_cis region, skipped>
  output/wcls (vocab x dim, weightsFloatType)

Matmul weights are stored row-major (d, n): out[i] = sum_j w[i, j] * x[j]
(reference src/funcs.cpp:269-299 semantics).
"""

from __future__ import annotations

import dataclasses
import struct

from ..ops.quants import FloatType, batch_bytes

HEADER_STRUCT = struct.Struct("<7i")
HEADER_BYTES = HEADER_STRUCT.size  # 28


@dataclasses.dataclass(frozen=True)
class TransformerSpec:
    dim: int
    hidden_dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    vocab_size: int
    seq_len: int
    weights_float_type: FloatType = FloatType.F32
    buffer_float_type: FloatType = FloatType.F32

    @property
    def head_size(self) -> int:
        return self.dim // self.n_heads

    @property
    def kv_dim(self) -> int:
        return (self.dim * self.n_kv_heads) // self.n_heads

    @property
    def kv_mul(self) -> int:
        """GQA group size: queries per kv head (reference transformer-tasks.cpp:214)."""
        return self.n_heads // self.n_kv_heads

    # -- header ------------------------------------------------------------

    @classmethod
    def from_header(cls, raw: bytes, weights_float_type=FloatType.F32,
                    buffer_float_type=FloatType.F32) -> "TransformerSpec":
        dim, hidden, n_layers, n_heads, n_kv, vocab, seq = HEADER_STRUCT.unpack(
            raw[:HEADER_BYTES])
        # llama2.c-style exports flag a shared classifier with a negative
        # vocab size; the reference takes abs() (transformer.cpp:73)
        return cls(dim, hidden, n_layers, n_heads, n_kv, abs(vocab), seq,
                   FloatType(weights_float_type), FloatType(buffer_float_type))

    def header(self) -> bytes:
        return HEADER_STRUCT.pack(self.dim, self.hidden_dim, self.n_layers,
                                  self.n_heads, self.n_kv_heads,
                                  self.vocab_size, self.seq_len)

    # -- per-tensor shapes (d, n) in file order ----------------------------

    def layer_matmul_shapes(self) -> list[tuple[str, tuple[int, int]]]:
        d, h, kv = self.dim, self.hidden_dim, self.kv_dim
        return [("wq", (d, d)), ("wk", (kv, d)), ("wv", (kv, d)),
                ("wo", (d, d)), ("w1", (h, d)), ("w2", (d, h)), ("w3", (h, d))]

    def matmul_bytes(self, shape: tuple[int, int]) -> int:
        dd, nn = shape
        return batch_bytes(self.weights_float_type, nn, dd)

    @property
    def rope_gap_bytes(self) -> int:
        """Legacy freq_cis_real+imag region (transformer.cpp:338-339)."""
        return 2 * (self.seq_len * self.head_size // 2) * 4

    def block_bytes(self) -> int:
        b = 2 * self.dim * 4  # rmsAtt + rmsFfn, always F32
        for _, shape in self.layer_matmul_shapes():
            b += self.matmul_bytes(shape)
        return b

    def file_size(self) -> int:
        """Byte-exact total, mirroring the check at transformer.cpp:344-348."""
        b = HEADER_BYTES
        b += self.vocab_size * self.dim * 4          # tok_embeddings, F32
        b += self.n_layers * self.block_bytes()
        b += self.dim * 4                            # rmsFinal, F32
        b += self.rope_gap_bytes
        b += self.matmul_bytes((self.vocab_size, self.dim))  # wcls
        return b
