"""Functional JAX Llama-2 forward pass (7B/13B/70B incl. GQA).

The single-chip "program" that replaces the reference's 32-step root task table
(src/transformer-tasks.cpp:485-518): one traced function, `lax.scan` over
stacked layer weights, static shapes throughout. Numerics follow the parity
contract in SURVEY.md §5:

* RoPE: interleaved (i, i+1) pairs, freq = 10000^-( (i mod headSize)/headSize ),
  q rotated over the full dim, k over kvDim (transformer-tasks.cpp:228-242).
* Attention: score = q.k/sqrt(headSize); GQA maps query head h to kv head
  h // kvMul (transformer-tasks.cpp:214,254,268). KV cache copies kvDim floats
  (the reference's dim-float memcpy at transformer-tasks.cpp:224-225 is the
  documented over-read bug; we implement the spec, not the bug).
* SwiGLU: silu(w1 x) * (w3 x), silu(x) = x/(1+e^-x).
* rmsnorm with eps=1e-5 added after the mean.
* When buffer_float_type == Q80, matmul inputs pass through Q80
  quantize->dequantize at the points the reference feeds quantized buffers to
  its kernels (the quantize* tasks).

The forward consumes T tokens at positions pos..pos+T-1 against a seq_len-sized
KV cache — T=1 is single-token decode (the reference's only mode), T>1 is
chunked prefill (a capability the reference lacks; it replays the decode path
per prompt token, tokenizer.cpp:352-366).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from ..io.loader import (Q40Kernel, Q40KernelI4, Q40KernelI4PackedD,
                         Q40KernelI4PackedNb, Q40KernelNb, Q40KernelNbI4)
# the single-chip forward emits the SAME canonical trace scopes as the tp
# forward (parallel/tp.py), so a --profile capture of either program
# attributes through one obs/xprof.py vocabulary
from ..obs.spans import SCOPE_ATTN, SCOPE_EMBED, SCOPE_FFN, SCOPE_LOGITS
from ..ops.linear import StackedQ40, fake_quant_q80, matmul, rmsnorm, silu
from ..ops.quants import FloatType
from .spec import TransformerSpec


class KVCache(NamedTuple):
    k: jax.Array  # (n_layers, seq_len, n_kv_heads, head_size) f32
    v: jax.Array


def init_cache(spec: TransformerSpec, dtype=jnp.float32) -> KVCache:
    shape = (spec.n_layers, spec.seq_len, spec.n_kv_heads, spec.head_size)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def rope_rotate(x: jax.Array, positions: jax.Array, head_size: int) -> jax.Array:
    """Interleaved-pair RoPE over the leading ``x.shape[-1]`` features.

    x: (T, n), positions: (T,). Pair p = features (2p, 2p+1); the angle uses
    head_dim = (2p) mod head_size, matching the reference's per-element loop.
    """
    n = x.shape[-1]
    pairs = x.reshape(*x.shape[:-1], n // 2, 2)
    i = jnp.arange(0, n, 2, dtype=jnp.float32)  # feature index of each pair
    head_dim = jnp.mod(i, head_size)
    freq = 1.0 / jnp.power(jnp.float32(10000.0), head_dim / head_size)
    val = positions[:, None].astype(jnp.float32) * freq[None, :]  # (T, n/2)
    fcr, fci = jnp.cos(val), jnp.sin(val)
    v0, v1 = pairs[..., 0], pairs[..., 1]
    return jnp.stack([v0 * fcr - v1 * fci, v0 * fci + v1 * fcr],
                     axis=-1).reshape(x.shape)


def _maybe_q80(spec: TransformerSpec, x: jax.Array) -> jax.Array:
    if spec.buffer_float_type == FloatType.Q80:
        return fake_quant_q80(x)
    return x


def attention_core(head_size: int, kv_mul: int, q: jax.Array, k: jax.Array,
                   v: jax.Array, mask: jax.Array) -> jax.Array:
    """Grouped-GQA causal attention — THE attention math, shared by the
    single-chip, sequence (training), and tensor-parallel paths.

    q: (..., T, n_q, hs) reshaped to kv groups; k/v: (..., S, n_kv, hs);
    mask: (T, S) True where key position is visible. Query head h = g*kv_mul+m
    attends kv head g = h//kv_mul (transformer-tasks.cpp:214), via einsum
    against the unexpanded cache (no materialized kv_mul-fold repeat).
    Masking with -inf before the max-subtracted softmax reproduces the
    reference's 0..pos loop bounds exactly. f32 accumulation at HIGHEST
    precision (the logit-parity contract).
    """
    *lead, t_len, n_q, _ = q.shape
    n_kv = k.shape[-2]
    qg = q.reshape(*lead, t_len, n_kv, kv_mul, head_size)
    scale = 1.0 / jnp.sqrt(jnp.float32(head_size))
    # fast-prefill (trace-time flag): bf16 MXU passes for the score and
    # weighted-sum einsums, f32 accumulation + f32 softmax — the same
    # documented-tolerance contract as the matmuls (ops/linear)
    from ..ops.linear import matmul_mode

    prec = (None if matmul_mode() == "bf16"
            else jax.lax.Precision.HIGHEST)
    scores = jnp.einsum("...tgmd,...sgd->...gmts", qg, k,
                        preferred_element_type=jnp.float32,
                        precision=prec) * scale
    scores = jnp.where(mask[..., None, None, :, :], scores, -jnp.inf)
    att = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("...gmts,...sgd->...tgmd", att, v,
                     preferred_element_type=jnp.float32,
                     precision=prec)
    return out.reshape(*lead, t_len, n_q * head_size)


def causal_cache_mask(seq_len: int, pos: jax.Array, t_len: int) -> jax.Array:
    """(T, S) visibility of cache slots for queries at pos..pos+T-1."""
    q_pos = pos + jnp.arange(t_len)
    return jnp.arange(seq_len)[None, :] <= q_pos[:, None]


def _prefill_attn_mode() -> str:
    """T>8 attention strategy — DLLAMA_PREFILL_ATTN: 'flash' (in-VMEM
    Pallas online-softmax walk over live KV blocks, scores never touch
    HBM — ops/pallas_attention.prefill_attention), 'block' (while_loop of
    XLA einsum partials over live KV blocks), 'dense' (score the whole
    seq_len plane, mask the rest), 'auto' (= flash where the kernel +
    pallas backend apply, else block). Read at trace time — programs
    already traced (an existing Engine's cached jits) keep the mode they
    were traced with; construct a new Engine to change it. Unknown values
    raise (a typo would otherwise silently run a slower path)."""
    import os

    mode = os.environ.get("DLLAMA_PREFILL_ATTN") or "auto"  # '' = unset
    if mode not in ("auto", "flash", "block", "dense"):
        raise ValueError(f"DLLAMA_PREFILL_ATTN={mode!r}: "
                         f"expected auto|flash|block|dense")
    return mode


_flash_degrade_warned = False


def _warn_flash_degrade(spec: TransformerSpec, t_len: int) -> None:
    """One-time loud warning when an EXPLICIT DLLAMA_PREFILL_ATTN=flash
    cannot take the Pallas kernel and degrades to the blockwise XLA walk.
    'auto' degrading silently is by design; an explicit mode falling back
    silently violates the fail-loud policy (_prefill_attn_mode raises on
    typos for the same reason). A warning, not a raise: the walk computes
    the same attention, just slower — aborting a long run over a perf mode
    would be worse. Fires at trace time, once per process."""
    global _flash_degrade_warned
    if _flash_degrade_warned:
        return
    _flash_degrade_warned = True
    import sys

    from ..ops.pallas_attention import attn_kernel_mode

    print(f"⚠️  DLLAMA_PREFILL_ATTN=flash requested but the Pallas prefill "
          f"kernel does not apply (attn kernel mode "
          f"{attn_kernel_mode()!r}, seq_len {spec.seq_len}, head_size "
          f"{spec.head_size}, chunk T={t_len}, kv_mul {spec.kv_mul}); "
          f"falling back to the blockwise XLA walk for this trace. Use "
          f"DLLAMA_PREFILL_ATTN=block to pick the walk explicitly, or "
          f"unset the variable for auto.", file=sys.stderr)


def _pick_attn_block(seq_len: int) -> int | None:
    """Largest KV block <= 512 dividing seq_len (None -> dense path)."""
    for cand in (512, 256, 128, 64, 32):
        if seq_len % cand == 0:
            return cand
    return None


def _attention_blockwise(spec: TransformerSpec, q: jax.Array,
                         k_cache: jax.Array, v_cache: jax.Array,
                         pos: jax.Array, t_len: int,
                         block: int) -> jax.Array:
    """Prefill attention with work bounded by the LIVE prefix: a while_loop
    over ceil((pos+T)/block) KV blocks with running-LSE accumulation
    (parallel.ring._partial_attention — the same flash partials the sp and
    ring paths use), merged block by block.

    The dense path (attention_core) scores every one of seq_len cache slots
    and masks the dead ones — at seq_len 8192 an early chunk of a
    long-context prefill wastes ~4x its attention FLOPs and score traffic
    on masked keys (measured ~35% of deep-chunk op time, BASELINE.md r3
    ladder note 4). Same masking contract, f32 accumulation; online-softmax
    reassociation only (prefill parity tolerances unchanged). The walk
    itself is parallel.ring.blockwise_chunk_partials (shared with the
    sp-sharded path), with chunk_start=0 for the unsharded plane.
    """
    from ..ops.linear import matmul_mode
    from ..parallel.ring import blockwise_chunk_partials  # lazy: no cycle

    q_pos = pos + jnp.arange(t_len)
    _, l, o = blockwise_chunk_partials(
        spec.head_size, spec.kv_mul, q, k_cache, v_cache, jnp.int32(0),
        q_pos, block=block, bf16=matmul_mode() == "bf16")
    return (o / jnp.maximum(l, 1e-38)).reshape(t_len, -1)


def attention(spec: TransformerSpec, q: jax.Array, k_cache: jax.Array,
              v_cache: jax.Array, pos: jax.Array, t_len: int) -> jax.Array:
    """Causal attention of t_len new queries against the full cache.

    q: (T, n_heads, head_size); caches: (seq_len, n_kv_heads, head_size).
    Returns (T, dim). T>8 (prefill chunks) takes the blockwise live-prefix
    path by default; T<=8 and the dense fallback score the full plane.
    """
    mode = _prefill_attn_mode() if t_len > 8 else "dense"
    if mode in ("auto", "flash"):
        from ..ops.pallas_attention import (attn_kernel_mode,
                                            prefill_attention,
                                            supports_prefill)

        if (attn_kernel_mode() == "pallas"
                and supports_prefill(spec.seq_len, spec.head_size, t_len,
                                     spec.kv_mul)):
            from ..ops.linear import matmul_mode

            out = prefill_attention(q, k_cache, v_cache, pos,
                                    kv_mul=spec.kv_mul,
                                    bf16=matmul_mode() == "bf16")
            return out.reshape(t_len, -1)
        if mode == "flash":  # explicit request degrading: say so, once
            _warn_flash_degrade(spec, t_len)
        mode = "block" if mode == "auto" else mode
    if mode in ("block", "flash"):  # flash unsupported here: live-prefix walk
        block = _pick_attn_block(spec.seq_len)
        if block is not None:
            return _attention_blockwise(spec, q, k_cache, v_cache, pos,
                                        t_len, block)
    mask = causal_cache_mask(spec.seq_len, pos, t_len)
    return attention_core(spec.head_size, spec.kv_mul, q, k_cache, v_cache,
                          mask)


def _qkv_proj(spec: TransformerSpec, lw: dict[str, Any], x: jax.Array,
              positions: jax.Array):
    """Shared attention input path: norm -> (q80) -> q/k/v matmuls -> RoPE.

    Works on (T, dim) or batched (B, T, dim) activations.
    """
    xb = rmsnorm(x, lw["rms_att"])
    xb = _maybe_q80(spec, xb)
    if "wqkv" in lw:  # load-time fused kernel (ops/linear.fuse_q40_layer_matmuls)
        qkv = matmul(lw["wqkv"], xb)
        kv_dim = spec.n_kv_heads * spec.head_size
        q = qkv[..., :spec.dim]
        k = qkv[..., spec.dim:spec.dim + kv_dim]
        v = qkv[..., spec.dim + kv_dim:]
    else:
        q = matmul(lw["wq"], xb)
        k = matmul(lw["wk"], xb)
        v = matmul(lw["wv"], xb)

    def rot(a):
        return rope_rotate(a, positions, spec.head_size)

    if x.ndim == 3:
        rot_fn = jax.vmap(rot)
    else:
        rot_fn = rot
    return rot_fn(q), rot_fn(k), v


def _post_attention(spec: TransformerSpec, lw: dict[str, Any], x: jax.Array,
                    ao: jax.Array) -> jax.Array:
    """Shared layer tail: wo + residual, then the SwiGLU ffn sub-block."""
    with jax.named_scope(SCOPE_ATTN):
        ao = _maybe_q80(spec, ao)
        x = x + matmul(lw["wo"], ao)
    with jax.named_scope(SCOPE_FFN):
        xb = rmsnorm(x, lw["rms_ffn"])
        xb = _maybe_q80(spec, xb)
        if "w13" in lw:  # load-time fused kernel (linear.fuse_q40_layer_matmuls)
            h13 = matmul(lw["w13"], xb)
            hid = h13.shape[-1] // 2
            hb = silu(h13[..., :hid]) * h13[..., hid:]
        else:
            hb = silu(matmul(lw["w1"], xb)) * matmul(lw["w3"], xb)
        hb = _maybe_q80(spec, hb)
        return x + matmul(lw["w2"], hb)


def _layer(spec: TransformerSpec, x: jax.Array, lw: dict[str, Any],
           k_all: jax.Array, v_all: jax.Array, idx, pos: jax.Array,
           positions: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One transformer layer against the STACKED (L, S, n_kv, hs) caches,
    updated in place at layer ``idx``. This is the body `forward`'s layer
    scan runs (and what the golden-parity test drives with L=1)."""
    t_len = x.shape[0]
    with jax.named_scope(SCOPE_ATTN):
        q, k, v = _qkv_proj(spec, lw, x, positions)
        dt = k_all.dtype  # f32 parity default; bf16 halves cache HBM
        k_new = k.reshape(1, t_len, spec.n_kv_heads,
                          spec.head_size).astype(dt)
        v_new = v.reshape(1, t_len, spec.n_kv_heads,
                          spec.head_size).astype(dt)
        k_all = jax.lax.dynamic_update_slice(k_all, k_new, (idx, pos, 0, 0))
        v_all = jax.lax.dynamic_update_slice(v_all, v_new, (idx, pos, 0, 0))

        from ..ops.pallas_attention import maybe_flash_decode

        # flash-decode kernel: reads only the live chunks of the stacked
        # cache (pos-proportional HBM traffic, like the reference's 0..pos
        # attention loop) instead of the full static plane
        ao = maybe_flash_decode(
            q, k_all, v_all, idx, pos, seq_len=spec.seq_len,
            head_size=spec.head_size, t_len=t_len, n_kv=spec.n_kv_heads,
            kv_mul=spec.kv_mul)
        if ao is None:
            k_c = jax.lax.dynamic_index_in_dim(k_all, idx, 0,
                                               keepdims=False)
            v_c = jax.lax.dynamic_index_in_dim(v_all, idx, 0,
                                               keepdims=False)
            ao = attention(spec,
                           q.reshape(t_len, spec.n_heads, spec.head_size),
                           k_c, v_c, pos, t_len)
    x = _post_attention(spec, lw, x, ao)
    return x, k_all, v_all


LAYER_KEYS = ("rms_att", "rms_ffn", "wq", "wk", "wv", "wo", "w1", "w2", "w3")
# load-time fusions (ops/linear) + the megakernel's permuted wo
FUSED_KEYS = ("wqkv", "w13", "wo_mega")


def split_layer_weights(params: dict[str, Any]):
    """Partition per-layer weights for the layer scan: stacked Q40Kernel
    weights stay OUTSIDE the scan carry (the kernel indexes the stack
    directly via scalar prefetch — see ops/linear.StackedQ40); everything
    else is scanned normally (sliced per step)."""
    keys = [k for k in LAYER_KEYS + FUSED_KEYS if k in params]
    stacked = {k: params[k] for k in keys
               if isinstance(params[k], (Q40Kernel, Q40KernelNb,
                                         Q40KernelI4, Q40KernelNbI4,
                                         Q40KernelI4PackedD,
                                         Q40KernelI4PackedNb))}
    scanned = {k: params[k] for k in keys if k not in stacked}
    return stacked, scanned


def layer_view(stacked: dict[str, Any], scanned_slice: dict[str, Any],
               idx) -> dict[str, Any]:
    lw = dict(scanned_slice)
    for k, v in stacked.items():
        lw[k] = StackedQ40(v, idx)
    return lw


def _forward_fused(spec: TransformerSpec, params: dict[str, Any],
                   cache: KVCache, tokens: jax.Array,
                   pos: jax.Array) -> tuple[jax.Array, KVCache]:
    """T=1 decode with the fused per-layer kernels (ops/pallas_layer): two
    pallas_calls per layer (head: rms+wqkv+rope, tail: wo+res+rms+w13+
    silu+w2+res) around the flash-attention kernel — the launch-tax cut of
    VERDICT r2 #2. The residual stream rides in COLUMN form (dim, 1)
    between kernels (the layout the fused kernels exchange; see
    pallas_layer docstring). Same value map as the unfused path."""
    from ..ops.pallas_layer import (q40_head_fused, q40_layer_mega,
                                    q40_tail_fused, rope_freq_cols)

    hs, n_kv, kv_dim = spec.head_size, spec.n_kv_heads, spec.kv_dim
    x = params["tok_embedding"][tokens].astype(jnp.float32)  # (1, dim)
    x_col = jnp.transpose(x)                                 # (dim, 1)
    freq_np, even_np = rope_freq_cols(spec)
    freq_col, even_col = jnp.asarray(freq_np), jnp.asarray(even_np)
    stacked, scanned = split_layer_weights(params)
    use_mega = "wo_mega" in stacked  # prepare_mega_params gated shapes

    from ..ops.pallas_attention import maybe_flash_decode

    def scan_body(carry, per_layer):
        x_col, k_all, v_all = carry
        idx, lw = per_layer
        if use_mega:
            # the endgame: ONE device op for the whole layer — matvec
            # phases, in-kernel RoPE, the flash cache walk, and the cache
            # write all inside a single pallas_call (launch overhead on
            # this runtime is ~10-15 us/op; at 32 layers each op saved is
            # ~0.4 ms/token)
            x_col, k_all, v_all = q40_layer_mega(
                spec, stacked["wqkv"], stacked["wo_mega"], stacked["w13"],
                stacked["w2"], lw["rms_att"][:, None],
                lw["rms_ffn"][:, None], freq_col, even_col, x_col,
                k_all, v_all, idx, pos)
            return (x_col, k_all, v_all), None
        qkv_col = q40_head_fused(spec, stacked["wqkv"],
                                 lw["rms_att"][:, None], freq_col, even_col,
                                 x_col, idx, pos)
        q = jnp.transpose(qkv_col[:spec.dim])                # (1, dim)
        dt = k_all.dtype
        k_new = qkv_col[spec.dim:spec.dim + kv_dim].reshape(
            1, 1, n_kv, hs).astype(dt)
        v_new = qkv_col[spec.dim + kv_dim:].reshape(
            1, 1, n_kv, hs).astype(dt)
        k_all = jax.lax.dynamic_update_slice(k_all, k_new, (idx, pos, 0, 0))
        v_all = jax.lax.dynamic_update_slice(v_all, v_new, (idx, pos, 0, 0))
        ao = maybe_flash_decode(
            q, k_all, v_all, idx, pos, seq_len=spec.seq_len, head_size=hs,
            t_len=1, n_kv=n_kv, kv_mul=spec.kv_mul)
        if ao is None:  # interpret/test fallback: XLA attention core
            k_c = jax.lax.dynamic_index_in_dim(k_all, idx, 0, keepdims=False)
            v_c = jax.lax.dynamic_index_in_dim(v_all, idx, 0, keepdims=False)
            ao = attention(spec, q.reshape(1, spec.n_heads, hs), k_c, v_c,
                           pos, 1)
        x_col = q40_tail_fused(spec, stacked["wo"], stacked["w13"],
                               stacked["w2"], lw["rms_ffn"][:, None],
                               jnp.transpose(ao), x_col, idx)
        return (x_col, k_all, v_all), None

    idxs = jnp.arange(spec.n_layers, dtype=jnp.int32)
    (x_col, k_new, v_new), _ = jax.lax.scan(
        scan_body, (x_col, cache.k, cache.v), (idxs, scanned))
    x = rmsnorm(jnp.transpose(x_col), params["rms_final"])
    logits = matmul(params["wcls"], x)
    return logits, KVCache(k_new, v_new)


def forward(spec: TransformerSpec, params: dict[str, Any], cache: KVCache,
            tokens: jax.Array, pos: jax.Array) -> tuple[jax.Array, KVCache]:
    """Run T tokens (at absolute positions pos..pos+T-1) through the model.

    Returns (logits (T, vocab) f32, updated cache). jit with spec static.
    """
    t_len = tokens.shape[0]
    if t_len == 1:
        from ..ops import pallas_layer

        if pallas_layer.fusion_enabled() and pallas_layer.supports(spec,
                                                                   params):
            return _forward_fused(spec, params, cache, tokens, pos)
    positions = pos + jnp.arange(t_len)
    with jax.named_scope(SCOPE_EMBED):
        x = params["tok_embedding"][tokens].astype(jnp.float32)  # (T, dim)

    stacked, scanned = split_layer_weights(params)

    # The full stacked caches ride in the scan CARRY (updated in place by
    # dynamic_update_slice at (layer, pos); the per-layer read is a
    # dynamic-slice XLA fuses into the attention dot). Scanning them as
    # xs/ys instead would materialize a slice copy in and a re-stack out of
    # every layer's (seq_len, n_kv, hs) cache plane per token — measured
    # ~11ms/token extra at 7B/2048 on v5e.
    def scan_body(carry, per_layer):
        x, k_all, v_all = carry
        idx, lw_slice = per_layer
        lw = layer_view(stacked, lw_slice, idx)
        x, k_all, v_all = _layer(spec, x, lw, k_all, v_all, idx, pos,
                                 positions)
        return (x, k_all, v_all), None

    idxs = jnp.arange(spec.n_layers, dtype=jnp.int32)
    (x, k_new, v_new), _ = jax.lax.scan(scan_body, (x, cache.k, cache.v),
                                        (idxs, scanned))

    with jax.named_scope(SCOPE_LOGITS):
        x = rmsnorm(x, params["rms_final"])
        logits = matmul(params["wcls"], x)
    return logits, KVCache(k_new, v_new)


def batch_decode_attention(head_size: int, kv_mul: int, seq_len: int,
                           q: jax.Array, k: jax.Array, v: jax.Array,
                           k_all: jax.Array, v_all: jax.Array, idx,
                           pos: jax.Array):
    """Shared batch-decode attention sub-block: append k/v at (layer ``idx``,
    column ``pos``) of the rank-4 (L*B, S, n_kv, hs) cache carry, then attend
    via the flash kernel (XLA einsum fallback). q (B, n_q*hs); k/v
    (B, n_kv*hs). Returns (ao (B, n_q*hs), k_all, v_all).

    ``pos`` is a scalar (lockstep batch: one shared clock, one cache write
    covering all B rows) or a (B,) vector (continuous batching: per-row
    clocks, one write per row). All batch paths — single-chip lockstep
    (forward_batch), tp-shard-local (parallel/tp.make_sharded_forward_batch,
    with local head counts), and ragged (forward_batch_ragged) — run THIS
    function, so cache indexing/attention semantics cannot drift."""
    B = q.shape[0]
    n_kv = k_all.shape[-2]
    n_q = q.shape[-1] // head_size
    dt = k_all.dtype
    k_new = k.reshape(B, 1, n_kv, head_size).astype(dt)
    v_new = v.reshape(B, 1, n_kv, head_size).astype(dt)
    ragged = jnp.ndim(pos) == 1
    if ragged:
        # per-row columns: B updates, each in place on the carry (a scatter
        # would materialize a second cache-sized buffer — forward_batch
        # docstring)
        for b in range(B):
            k_all = jax.lax.dynamic_update_slice(
                k_all, k_new[b:b + 1], (idx * B + b, pos[b], 0, 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, v_new[b:b + 1], (idx * B + b, pos[b], 0, 0))
    else:
        k_all = jax.lax.dynamic_update_slice(k_all, k_new,
                                             (idx * B, pos, 0, 0))
        v_all = jax.lax.dynamic_update_slice(v_all, v_new,
                                             (idx * B, pos, 0, 0))

    from ..ops.pallas_attention import maybe_flash_decode

    # per-row flash kernel: live-chunk DMA walk, no cache slice copy (the
    # XLA einsum path below doesn't fuse the layer slice read — measured
    # ~10x slower per step at 7B/B=4)
    ao = maybe_flash_decode(
        q, k_all, v_all, idx, pos, seq_len=seq_len, head_size=head_size,
        t_len=1, n_kv=n_kv, kv_mul=kv_mul, batch=True)
    if ao is None:
        k_c = jax.lax.dynamic_slice_in_dim(k_all, idx * B, B, 0)
        v_c = jax.lax.dynamic_slice_in_dim(v_all, idx * B, B, 0)
        if ragged:
            # (B, 1, S): row b sees cache slots 0..pos[b]
            mask = jnp.arange(seq_len)[None, None, :] <= pos[:, None, None]
        else:
            mask = causal_cache_mask(seq_len, pos, 1)
        ao = attention_core(head_size, kv_mul,
                            q.reshape(B, 1, n_q, head_size), k_c, v_c,
                            mask)
    return ao.reshape(B, -1), k_all, v_all


def init_cache_paged(spec: TransformerSpec, n_pages: int, page_size: int,
                     dtype=jnp.float32) -> KVCache:
    """Paged pool cache: (L, P, page_size, n_kv, hs) — physical page p of
    layer l is the (page_size, n_kv, hs) plane at [l, p]. ``n_pages`` is
    the TOTAL physical page count including the reserved scrap page 0
    (runtime/paging.SCRAP_PAGE); slots map logical sequence pages onto
    physical pages through an int32 page-table row, so the pool can be
    sized far below slots * seq_len (the HBM lever of vLLM's
    PagedAttention)."""
    if spec.seq_len % page_size:
        raise ValueError(f"page_size={page_size} must divide "
                         f"seq_len={spec.seq_len}")
    shape = (spec.n_layers, n_pages, page_size, spec.n_kv_heads,
             spec.head_size)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


class PagedKVQ8(NamedTuple):
    """Q8-quantized page pool (ISSUE 11): the Q80 wire layout from
    ops/quants.py laid out plane-wise per pool page. ``kq``/``vq`` are the
    int8 code planes with EXACTLY the f32 pool's (L, P, page_size, n_kv,
    hs) geometry (every index computation — page tables, scrap parking,
    rollback truncation — carries over unchanged); ``kd``/``vd`` are the
    f16 block deltas, one per QK values of a position's flattened
    (n_kv * hs) row: (L, P, page_size, n_kv * hs // QK). Per position
    that is kv_dim + 2*kv_dim/QK bytes against the f32 pool's 4*kv_dim —
    a ~3.8x page-byte cut (~1.9x vs bf16), which
    analysis/memory_model.kv_page_pool_bytes prices exactly and the
    engine turns into ~2-4x pool pages at equal HBM."""

    kq: jax.Array  # (L, P, page_size, n_kv, hs) int8 Q80 codes
    kd: jax.Array  # (L, P, page_size, n_kv*hs//QK) f16 block deltas
    vq: jax.Array
    vd: jax.Array


def init_cache_paged_q8(spec: TransformerSpec, n_pages: int,
                        page_size: int) -> PagedKVQ8:
    """Q8 page pool: init_cache_paged's quantized twin. The flattened
    per-position row (n_kv * hs values) must divide into Q80 blocks —
    callers shard kv heads over tp first, so the constraint is on the
    LOCAL width (parallel/tp.py validates the sharded case)."""
    from ..ops.quants import QK

    if spec.seq_len % page_size:
        raise ValueError(f"page_size={page_size} must divide "
                         f"seq_len={spec.seq_len}")
    kv_dim = spec.n_kv_heads * spec.head_size
    if kv_dim % QK:
        raise ValueError(
            f"q8 KV pages quantize the flattened (n_kv, hs) position row "
            f"in {QK}-value Q80 blocks: kv_dim={kv_dim} must divide by "
            f"{QK}")
    codes = (spec.n_layers, n_pages, page_size, spec.n_kv_heads,
             spec.head_size)
    deltas = (spec.n_layers, n_pages, page_size, kv_dim // QK)
    return PagedKVQ8(jnp.zeros(codes, jnp.int8),
                     jnp.zeros(deltas, jnp.float16),
                     jnp.zeros(codes, jnp.int8),
                     jnp.zeros(deltas, jnp.float16))


def paged_cache_planes(cache):
    """Flatten a paged pool cache — KVCache (f32/bf16) or PagedKVQ8 —
    into its rank-4 (L*P, page_size, ...) scan-carry views (the
    lane-friendly merge rationale of forward_batch_paged). THE one
    implementation shared by both single-chip paged forwards and both
    tp factories, so a plane-layout change cannot drift between the
    four scan bodies. Returns (planes tuple, n_pages)."""
    if isinstance(cache, PagedKVQ8):
        L, P, ps, n_kv, hs = cache.kq.shape
        nb = cache.kd.shape[-1]
        return (cache.kq.reshape(L * P, ps, n_kv, hs),
                cache.kd.reshape(L * P, ps, nb),
                cache.vq.reshape(L * P, ps, n_kv, hs),
                cache.vd.reshape(L * P, ps, nb)), P
    L, P, ps, n_kv, hs = cache.k.shape
    return (cache.k.reshape(L * P, ps, n_kv, hs),
            cache.v.reshape(L * P, ps, n_kv, hs)), P


def rebuild_paged_cache(planes, n_layers: int):
    """paged_cache_planes' inverse: reassemble the scan-carry views into
    the rank-5 pool cache (2 planes -> KVCache, 4 -> PagedKVQ8)."""
    L = n_layers
    if len(planes) == 4:
        kq4, kd4, vq4, vd4 = planes
        LP, ps, n_kv, hs = kq4.shape
        P = LP // L
        nb = kd4.shape[-1]
        return PagedKVQ8(kq4.reshape(L, P, ps, n_kv, hs),
                         kd4.reshape(L, P, ps, nb),
                         vq4.reshape(L, P, ps, n_kv, hs),
                         vd4.reshape(L, P, ps, nb))
    k4, v4 = planes
    LP, ps, n_kv, hs = k4.shape
    P = LP // L
    return KVCache(k4.reshape(L, P, ps, n_kv, hs),
                   v4.reshape(L, P, ps, n_kv, hs))


def fetch_page_planes(cache, pid: int) -> tuple:
    """Host numpy copy of ONE physical page's planes — the KV-tiering
    demotion read (runtime/paging.PagedAllocator.demote_cold fetches
    through this before releasing the HBM page). The planes come back in
    the page WIRE layout — (k, v) for f32/bf16 pools, (kq, kd, vq, vd)
    for Q8 — so a demote→promote round trip is byte-identical: f32 pages
    bitwise, Q8 pages code-exact (no re-quantization anywhere on the
    path). Host-blocking by design: demotion is a scheduler-thread
    write-behind, not hot-path work."""
    import numpy as np

    if isinstance(cache, PagedKVQ8):
        return tuple(np.asarray(plane[:, pid]) for plane in cache)
    return (np.asarray(cache.k[:, pid]), np.asarray(cache.v[:, pid]))


def write_page_planes(cache, pid, planes):
    """Write one page's planes back into the pool at physical page
    ``pid`` — the KV-tiering promotion apply (the engine jits this with
    the POOL cache donated, so the upload lands in place at a step
    boundary). ``planes`` is fetch_page_planes' tuple (or the
    PageUploader's staged device copies of it)."""
    if isinstance(cache, PagedKVQ8):
        kq, kd, vq, vd = planes
        return PagedKVQ8(cache.kq.at[:, pid].set(kq),
                         cache.kd.at[:, pid].set(kd),
                         cache.vq.at[:, pid].set(vq),
                         cache.vd.at[:, pid].set(vd))
    k, v = planes
    return KVCache(cache.k.at[:, pid].set(k), cache.v.at[:, pid].set(v))


def paged_attention_q8(head_size: int, kv_mul: int, page_size: int,
                       n_pages: int, q: jax.Array, k: jax.Array,
                       v: jax.Array, kq_all, kd_all, vq_all, vd_all,
                       idx, pos: jax.Array, table: jax.Array,
                       span: jax.Array | None = None):
    """Q8-page twin of paged_decode_attention AND spec_verify_attention in
    one function: T=1 is the decode step, T=K the speculative-verify
    window (the location/mask math is spec_verify_attention's, which
    reduces to the decode case at T=1). ``span`` (B,) int32, when given,
    is the mixed-batch write gate: window offsets at or past a row's span
    route their dead quantized writes to the scrap page exactly like
    budget-edge positions (mixed_attention's contract) — None preserves
    the decode/verify behavior where every offset is live.

    Quantize-on-write: each (row, window-offset) position Q80-encodes its
    flattened (n_kv*hs) k/v row — int8 codes into the code plane at the
    page-table-mapped (physical page, offset), f16 block deltas into the
    delta plane at the same coordinates. Dequantize-on-read happens
    inside the paged flash kernel's page loop, or in the XLA gather
    fallback below — SAME value map (codes.astype(f32) * d.astype(f32)),
    so both routes agree and quantization error is paid exactly once per
    written position. q (B, T, n_q*hs); k/v (B, T, n_kv*hs) f32. Returns
    (ao (B, T, n_q*hs), kq_all, kd_all, vq_all, vd_all)."""
    from ..ops.quants import QK, quantize_q80_jax
    from ..runtime.paging import SCRAP_PAGE

    B, t_len = q.shape[0], q.shape[1]
    n_kv = kq_all.shape[-2]
    n_q = q.shape[-1] // head_size
    nb = (n_kv * head_size) // QK
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    max_pages = table.shape[1]
    s_virt = max_pages * page_size
    k_qs, k_d = quantize_q80_jax(k)   # (B,T,nb,QK) int8, (B,T,nb) f16
    v_qs, v_d = quantize_q80_jax(v)
    k_codes = k_qs.reshape(B, t_len, n_kv, head_size)
    v_codes = v_qs.reshape(B, t_len, n_kv, head_size)
    span_b = (None if span is None
              else jnp.broadcast_to(jnp.asarray(span, jnp.int32), (B,)))
    # per-(row, window-offset) writes, in place on the carries — the same
    # B-updates-not-scatter rationale (and the same scrap-page overflow
    # routing) as spec_verify_attention
    for b in range(B):
        for i in range(t_len):
            p = pos_b[b] + i
            logical = jnp.minimum(p // page_size, max_pages - 1)
            live = p < s_virt
            if span_b is not None:
                live = live & (i < span_b[b])
            page = jnp.where(live,
                             jnp.take(table[b], logical), SCRAP_PAGE)
            row = idx * n_pages + page
            off = p % page_size
            kq_all = jax.lax.dynamic_update_slice(
                kq_all, k_codes[b, i][None, None], (row, off, 0, 0))
            kd_all = jax.lax.dynamic_update_slice(
                kd_all, k_d[b, i][None, None], (row, off, 0))
            vq_all = jax.lax.dynamic_update_slice(
                vq_all, v_codes[b, i][None, None], (row, off, 0, 0))
            vd_all = jax.lax.dynamic_update_slice(
                vd_all, v_d[b, i][None, None], (row, off, 0))

    from ..ops.pallas_paged_attention import maybe_paged_flash_decode

    ao = maybe_paged_flash_decode(
        q, (kq_all, kd_all, vq_all, vd_all), idx, pos_b, table,
        page_size=page_size, n_pages=n_pages, head_size=head_size,
        t_len=t_len, n_kv=n_kv, kv_mul=kv_mul, kv_quant="q8")
    if ao is None:
        # XLA fallback: gather the code/delta rows, dequantize (the ONE
        # shared value map, quants.dequantize_q80_planes), and run the
        # shared attention core over the virtual plane — the same mask
        # contract as the f32 paged paths
        from ..ops.quants import dequantize_q80_planes

        rows = (idx * n_pages + table).reshape(-1)
        kq_c = jnp.take(kq_all, rows, axis=0).reshape(B, s_virt, n_kv,
                                                      head_size)
        kd_c = jnp.take(kd_all, rows, axis=0).reshape(B, s_virt, nb)
        vq_c = jnp.take(vq_all, rows, axis=0).reshape(B, s_virt, n_kv,
                                                      head_size)
        vd_c = jnp.take(vd_all, rows, axis=0).reshape(B, s_virt, nb)
        q_pos = pos_b[:, None] + jnp.arange(t_len)[None, :]
        mask = jnp.arange(s_virt)[None, None, :] <= q_pos[:, :, None]
        ao = attention_core(head_size, kv_mul,
                            q.reshape(B, t_len, n_q, head_size),
                            dequantize_q80_planes(kq_c, kd_c),
                            dequantize_q80_planes(vq_c, vd_c), mask)
    return ao, kq_all, kd_all, vq_all, vd_all


def paged_decode_attention(head_size: int, kv_mul: int, page_size: int,
                           n_pages: int, q: jax.Array, k: jax.Array,
                           v: jax.Array, k_all: jax.Array, v_all: jax.Array,
                           idx, pos: jax.Array, table: jax.Array):
    """batch_decode_attention over the PAGED pool: write each row's k/v at
    (physical page ``table[b, pos_b // page_size]``, offset
    ``pos_b % page_size``) of the rank-4 (L*P, page_size, n_kv, hs) carry,
    then attend over the row's gathered page sequence.

    q (B, n_q*hs); k/v (B, n_kv*hs); ``table`` (B, max_pages) int32
    physical page ids in logical order (entries beyond a row's live pages
    point at the scrap page — their junk is masked below). The gathered
    view lays pages out in logical order, so position p of the virtual
    (B, S, n_kv, hs) plane holds exactly the value the contiguous cache
    holds at column p — the ragged mask and attention_core are shared with
    the contiguous path, making the XLA route's paged logits BITWISE equal
    to contiguous logits (the parity gate of tests/test_paging.py, and
    what CPU engines run). On TPU the paged flash-decode Pallas kernel
    (ops/pallas_paged_attention.py, ISSUE 11) takes over via the routing
    gate below: the DMA loop walks the page table directly — live pages
    only, no gather copy — at the documented flash reassociation
    tolerance vs this XLA route.
    """
    B = q.shape[0]
    n_kv = k_all.shape[-2]
    n_q = q.shape[-1] // head_size
    dt = k_all.dtype
    k_new = k.reshape(B, 1, n_kv, head_size).astype(dt)
    v_new = v.reshape(B, 1, n_kv, head_size).astype(dt)
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    page_b = jnp.take_along_axis(table, (pos_b // page_size)[:, None],
                                 axis=1)[:, 0]
    off_b = pos_b % page_size
    # per-row writes, each in place on the carry (the same B-updates-not-
    # scatter rationale as the ragged contiguous path, forward_batch)
    for b in range(B):
        row = idx * n_pages + page_b[b]
        k_all = jax.lax.dynamic_update_slice(k_all, k_new[b:b + 1],
                                             (row, off_b[b], 0, 0))
        v_all = jax.lax.dynamic_update_slice(v_all, v_new[b:b + 1],
                                             (row, off_b[b], 0, 0))
    from ..ops.pallas_paged_attention import maybe_paged_flash_decode

    # paged flash kernel (ISSUE 11): the DMA loop walks the page table
    # directly — live pages only, no gather copy. One routing gate shared
    # with the verify shape and both tp factories; None = XLA fallback
    # (CPU engines and unsupported shapes), which stays BITWISE equal to
    # the contiguous path (the PR 6 parity gate).
    ao = maybe_paged_flash_decode(
        q.reshape(B, 1, -1), (k_all, v_all), idx, pos_b, table,
        page_size=page_size, n_pages=n_pages, head_size=head_size,
        t_len=1, n_kv=n_kv, kv_mul=kv_mul)
    if ao is not None:
        return ao.reshape(B, -1), k_all, v_all
    s_virt = table.shape[1] * page_size
    rows = (idx * n_pages + table).reshape(-1)            # (B * max_pages,)
    k_c = jnp.take(k_all, rows, axis=0).reshape(B, s_virt, n_kv, head_size)
    v_c = jnp.take(v_all, rows, axis=0).reshape(B, s_virt, n_kv, head_size)
    # (B, 1, S): row b sees virtual positions 0..pos[b] — same mask as the
    # ragged contiguous path, so softmax sees identical live values and
    # exact zeros for everything else
    mask = jnp.arange(s_virt)[None, None, :] <= pos_b[:, None, None]
    ao = attention_core(head_size, kv_mul, q.reshape(B, 1, n_q, head_size),
                        k_c, v_c, mask)
    return ao.reshape(B, -1), k_all, v_all


def forward_batch_paged(spec: TransformerSpec, page_size: int,
                        params: dict[str, Any], cache,
                        tokens: jax.Array, pos_vec: jax.Array,
                        table: jax.Array, *, kv_quant: str = "f32"):
    """Decode one token per row against the PAGED page-pool cache.

    forward_batch_ragged's twin for the paged layout: cache planes are
    (L, P, page_size, n_kv, hs) pool pages (init_cache_paged), ``table``
    (B, seq_len // page_size) int32 maps each row's logical pages to
    physical ones (runtime/continuous.py stages it host-side, one upload
    per step). Per-row math is identical to the contiguous path — shared
    _qkv_proj/_post_attention, and paged_decode_attention reproduces
    batch_decode_attention's virtual (B, S) plane exactly — so logits are
    bitwise equal to forward_batch_ragged given the same history (the
    pinned parity gate). jit with (spec, page_size) static and the cache
    donated: the rank-4 page-plane view rides the scan carry in place, so
    J002's zero-copy-per-token contract holds under paging too.

    ``kv_quant='q8'`` (ISSUE 11) swaps the pool for the Q80-quantized
    PagedKVQ8 planes: decode quantizes each position's k/v row on write
    and the attention path dequantizes on read (paged_attention_q8) —
    parity against f32 moves to distribution-pinned tolerance gates, the
    documented quantization contract.
    """
    B = tokens.shape[0]
    x = params["tok_embedding"][tokens].astype(jnp.float32)  # (B, dim)
    positions = pos_vec if jnp.ndim(pos_vec) == 1 else jnp.full((B,),
                                                                pos_vec)
    hs, kv_mul = spec.head_size, spec.kv_mul
    q8 = kv_quant == "q8"
    L = spec.n_layers
    # rank-4 (L*P, page_size, ...) carry views — same layout rationale
    # as forward_batch's (L*B, S, ...) merge: the rank-5 carry provokes a
    # lane-padded normalization copy out of XLA's layout assignment
    planes, P = paged_cache_planes(cache)

    stacked, scanned = split_layer_weights(params)

    def scan_body(carry, per_layer):
        x, *kv = carry
        idx, lw_slice = per_layer
        lw = layer_view(stacked, lw_slice, idx)
        q, k, v = _qkv_proj(spec, lw, x, positions)
        if q8:
            ao, *kv = paged_attention_q8(
                hs, kv_mul, page_size, P, q[:, None], k[:, None],
                v[:, None], *kv, idx, pos_vec, table)
            ao = ao.reshape(B, -1)
        else:
            ao, *kv = paged_decode_attention(
                hs, kv_mul, page_size, P, q, k, v, *kv, idx, pos_vec,
                table)
        x = _post_attention(spec, lw, x, ao)
        return (x, *kv), None

    idxs = jnp.arange(L, dtype=jnp.int32)
    (x, *kv), _ = jax.lax.scan(scan_body, (x, *planes), (idxs, scanned))
    x = rmsnorm(x, params["rms_final"])
    logits = matmul(params["wcls"], x)
    return logits, rebuild_paged_cache(tuple(kv), L)


def spec_verify_attention(head_size: int, kv_mul: int, page_size: int,
                          n_pages: int, q: jax.Array, k: jax.Array,
                          v: jax.Array, k_all: jax.Array, v_all: jax.Array,
                          idx, pos: jax.Array, table: jax.Array):
    """paged_decode_attention widened to K queries per row — the
    speculative-verify attention (ISSUE 7): row b scores its current token
    plus K-1 drafted tokens at positions pos_b..pos_b+K-1 in ONE pass,
    with query i seeing virtual positions 0..pos_b+i (the causal window
    sequential decode would have seen at that step), so each position's
    output is BITWISE what K single-token decode steps would produce given
    the same inputs — the losslessness anchor of runtime/speculative.py.

    q (B, K, n_q*hs); k/v (B, K, n_kv*hs); ``table`` as in
    paged_decode_attention. K/V writes land per (row, offset-in-window) at
    the page-table-mapped physical slot; a window position at or past the
    virtual plane (a row decoding at the budget edge) routes its dead
    write to the scrap page instead of clamping onto live pages — the same
    junk-is-invisible contract parked rows rely on. Returns
    (ao (B, K, n_q*hs), k_all, v_all)."""
    B, t_len = q.shape[0], q.shape[1]
    n_kv = k_all.shape[-2]
    n_q = q.shape[-1] // head_size
    dt = k_all.dtype
    k_new = k.reshape(B, t_len, n_kv, head_size).astype(dt)
    v_new = v.reshape(B, t_len, n_kv, head_size).astype(dt)
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    max_pages = table.shape[1]
    s_virt = max_pages * page_size
    from ..runtime.paging import SCRAP_PAGE

    # per-(row, window-offset) writes, each in place on the carry — the
    # same B-updates-not-scatter rationale as paged_decode_attention (B and
    # K are static, so the loop unrolls at trace time)
    for b in range(B):
        for i in range(t_len):
            p = pos_b[b] + i
            logical = jnp.minimum(p // page_size, max_pages - 1)
            page = jnp.where(p < s_virt,
                             jnp.take(table[b], logical), SCRAP_PAGE)
            row = idx * n_pages + page
            k_all = jax.lax.dynamic_update_slice(
                k_all, k_new[b, i][None, None], (row, p % page_size, 0, 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, v_new[b, i][None, None], (row, p % page_size, 0, 0))
    from ..ops.pallas_paged_attention import maybe_paged_flash_decode

    # the K-query verify shape rides the SAME paged flash kernel (t_len=K
    # stacked causal windows) through the same routing gate as decode
    ao = maybe_paged_flash_decode(
        q, (k_all, v_all), idx, pos_b, table, page_size=page_size,
        n_pages=n_pages, head_size=head_size, t_len=t_len, n_kv=n_kv,
        kv_mul=kv_mul)
    if ao is not None:
        return ao, k_all, v_all
    rows = (idx * n_pages + table).reshape(-1)            # (B * max_pages,)
    k_c = jnp.take(k_all, rows, axis=0).reshape(B, s_virt, n_kv, head_size)
    v_c = jnp.take(v_all, rows, axis=0).reshape(B, s_virt, n_kv, head_size)
    # (B, K, S): query i of row b sees virtual positions 0..pos_b+i — the
    # per-step causal windows of sequential decode, stacked
    q_pos = pos_b[:, None] + jnp.arange(t_len)[None, :]   # (B, K)
    mask = jnp.arange(s_virt)[None, None, :] <= q_pos[:, :, None]
    ao = attention_core(head_size, kv_mul,
                        q.reshape(B, t_len, n_q, head_size), k_c, v_c, mask)
    return ao, k_all, v_all


def forward_batch_spec_paged(spec: TransformerSpec, page_size: int,
                             params: dict[str, Any], cache,
                             tokens: jax.Array, pos_vec: jax.Array,
                             table: jax.Array, *, kv_quant: str = "f32"):
    """The K-query speculative VERIFY step over the paged pool cache.

    forward_batch_paged's sibling for draft verification (ISSUE 7): row b
    feeds its current token plus K-1 drafted tokens ``tokens[b]`` at
    positions pos_vec[b]..pos_vec[b]+K-1 and gets ALL K next-token logit
    rows from ONE dispatch — the collective-latency amortization lever (a
    dispatch pays the per-layer collective schedule once whether it scores
    1 or K positions; comm_stats.tp_collective_budget(t_len=K) models it).

    tokens (B, K) int32; pos_vec (B,); returns (logits (B, K, vocab), cache).
    Everything except attention treats the B*K query rows as a flat batch
    through the SAME _qkv_proj/_post_attention blocks as decode, so logits
    at position i are bitwise the single-token decode logits given the
    same history — rejected-suffix KV lands beyond the accepted rollback
    point and is masked/overwritten, never read (runtime/continuous.py
    truncates the page table back to the accepted length host-side).
    jit with (spec, page_size) static and the cache donated (J002 holds:
    the rank-4 page-plane view rides the scan carry in place).
    """
    B, K = tokens.shape
    x = params["tok_embedding"][tokens.reshape(-1)].astype(jnp.float32)
    pos_b = jnp.broadcast_to(jnp.asarray(pos_vec, jnp.int32), (B,))
    positions = (pos_b[:, None]
                 + jnp.arange(K, dtype=jnp.int32)[None, :]).reshape(-1)
    hs, kv_mul = spec.head_size, spec.kv_mul
    q8 = kv_quant == "q8"
    L = spec.n_layers
    planes, P = paged_cache_planes(cache)

    stacked, scanned = split_layer_weights(params)

    def scan_body(carry, per_layer):
        x, *kv = carry
        idx, lw_slice = per_layer
        lw = layer_view(stacked, lw_slice, idx)
        q, k, v = _qkv_proj(spec, lw, x, positions)        # (B*K, ...)
        if q8:
            ao, *kv = paged_attention_q8(
                hs, kv_mul, page_size, P, q.reshape(B, K, -1),
                k.reshape(B, K, -1), v.reshape(B, K, -1), *kv, idx,
                pos_b, table)
        else:
            ao, *kv = spec_verify_attention(
                hs, kv_mul, page_size, P, q.reshape(B, K, -1),
                k.reshape(B, K, -1), v.reshape(B, K, -1), *kv, idx,
                pos_b, table)
        x = _post_attention(spec, lw, x, ao.reshape(B * K, -1))
        return (x, *kv), None

    idxs = jnp.arange(L, dtype=jnp.int32)
    (x, *kv), _ = jax.lax.scan(scan_body, (x, *planes), (idxs, scanned))
    x = rmsnorm(x, params["rms_final"])
    logits = matmul(params["wcls"], x)                     # (B*K, vocab)
    return logits.reshape(B, K, -1), rebuild_paged_cache(tuple(kv), L)


def mixed_attention(head_size: int, kv_mul: int, page_size: int,
                    n_pages: int, q: jax.Array, k: jax.Array,
                    v: jax.Array, k_all: jax.Array, v_all: jax.Array,
                    idx, pos: jax.Array, table: jax.Array,
                    span: jax.Array):
    """spec_verify_attention generalized to per-row ARBITRARY spans — the
    mixed prefill+decode attention (ISSUE 18): row b contributes
    ``span[b]`` live query positions starting at pos_b (a decode row has
    span 1, the prefill-slice row has span up to the remaining token
    budget, a padded/idle row has span 0), all in ONE (B, T) dispatch
    where T is the dispatch token budget.

    The location math is spec_verify_attention's; the only change is the
    write gate: a window offset at or past a row's span routes its dead
    K/V write to the scrap page (the same junk-is-invisible contract as
    budget-edge positions), so padded offsets never touch live pages.
    The causal masks are untouched — padded queries attend whatever the
    virtual plane holds and produce junk logit rows the engine discards
    host-side (never an empty mask, so softmax stays finite). Live query
    i of row b therefore sees EXACTLY the virtual window sequential
    decode/prefill would have seen at that position, which is what makes
    mixed-dispatch streams bitwise equal to the separate-dispatch engine.
    Returns (ao (B, T, n_q*hs), k_all, v_all)."""
    B, t_len = q.shape[0], q.shape[1]
    n_kv = k_all.shape[-2]
    n_q = q.shape[-1] // head_size
    dt = k_all.dtype
    k_new = k.reshape(B, t_len, n_kv, head_size).astype(dt)
    v_new = v.reshape(B, t_len, n_kv, head_size).astype(dt)
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    span_b = jnp.broadcast_to(jnp.asarray(span, jnp.int32), (B,))
    max_pages = table.shape[1]
    s_virt = max_pages * page_size
    from ..runtime.paging import SCRAP_PAGE

    # per-(row, window-offset) writes, each in place on the carry — the
    # same trace-time-unrolled B-updates-not-scatter loop as
    # spec_verify_attention, with the span gate added to the routing
    for b in range(B):
        for i in range(t_len):
            p = pos_b[b] + i
            logical = jnp.minimum(p // page_size, max_pages - 1)
            page = jnp.where((p < s_virt) & (i < span_b[b]),
                             jnp.take(table[b], logical), SCRAP_PAGE)
            row = idx * n_pages + page
            k_all = jax.lax.dynamic_update_slice(
                k_all, k_new[b, i][None, None], (row, p % page_size, 0, 0))
            v_all = jax.lax.dynamic_update_slice(
                v_all, v_new[b, i][None, None], (row, p % page_size, 0, 0))
    from ..ops.pallas_paged_attention import maybe_paged_flash_decode

    # the (B, T) window rides the SAME paged flash kernel (stacked causal
    # windows) through the same routing gate as decode/verify
    ao = maybe_paged_flash_decode(
        q, (k_all, v_all), idx, pos_b, table, page_size=page_size,
        n_pages=n_pages, head_size=head_size, t_len=t_len, n_kv=n_kv,
        kv_mul=kv_mul)
    if ao is not None:
        return ao, k_all, v_all
    rows = (idx * n_pages + table).reshape(-1)            # (B * max_pages,)
    k_c = jnp.take(k_all, rows, axis=0).reshape(B, s_virt, n_kv, head_size)
    v_c = jnp.take(v_all, rows, axis=0).reshape(B, s_virt, n_kv, head_size)
    # (B, T, S): query i of row b sees virtual positions 0..pos_b+i — the
    # per-step causal windows of sequential decode, stacked; offsets past
    # span[b] compute junk the engine never reads
    q_pos = pos_b[:, None] + jnp.arange(t_len)[None, :]   # (B, T)
    mask = jnp.arange(s_virt)[None, None, :] <= q_pos[:, :, None]
    ao = attention_core(head_size, kv_mul,
                        q.reshape(B, t_len, n_q, head_size), k_c, v_c, mask)
    return ao, k_all, v_all


def forward_batch_mixed_paged(spec: TransformerSpec, page_size: int,
                              params: dict[str, Any], cache,
                              tokens: jax.Array, pos_vec: jax.Array,
                              span: jax.Array, table: jax.Array, *,
                              kv_quant: str = "f32"):
    """The token-budget MIXED dispatch over the paged pool cache
    (ISSUE 18): one fused forward scores all active decode rows (span 1)
    plus ONE prefill slice (span up to the remaining budget) in a single
    (B, T) window — prefill no longer stalls in-flight decodes behind a
    separate chunk dispatch, and the per-layer collective schedule is
    paid once per budget of tokens (comm_stats.tp_collective_budget at
    t_len=budget models it; contract_mixed_collectives pins it).

    forward_batch_spec_paged's sibling: tokens (B, T) int32 with row b
    live in columns 0..span[b]-1 (junk beyond — embedded and computed but
    write-gated off live pages and discarded host-side); pos_vec (B,);
    span (B,) int32. Returns (logits (B, T, vocab), cache). Everything
    except attention treats the B*T rows as a flat batch through the SAME
    _qkv_proj/_post_attention blocks as decode, so live logit rows are
    bitwise the single-token decode logits given the same history — the
    parity anchor of tests/test_mixed_batch.py. jit with
    (spec, page_size) static and the cache donated (J002 holds: the
    rank-4 page-plane view rides the scan carry in place).
    """
    B, T = tokens.shape
    x = params["tok_embedding"][tokens.reshape(-1)].astype(jnp.float32)
    pos_b = jnp.broadcast_to(jnp.asarray(pos_vec, jnp.int32), (B,))
    span_b = jnp.broadcast_to(jnp.asarray(span, jnp.int32), (B,))
    positions = (pos_b[:, None]
                 + jnp.arange(T, dtype=jnp.int32)[None, :]).reshape(-1)
    hs, kv_mul = spec.head_size, spec.kv_mul
    q8 = kv_quant == "q8"
    L = spec.n_layers
    planes, P = paged_cache_planes(cache)

    stacked, scanned = split_layer_weights(params)

    def scan_body(carry, per_layer):
        x, *kv = carry
        idx, lw_slice = per_layer
        lw = layer_view(stacked, lw_slice, idx)
        q, k, v = _qkv_proj(spec, lw, x, positions)        # (B*T, ...)
        if q8:
            ao, *kv = paged_attention_q8(
                hs, kv_mul, page_size, P, q.reshape(B, T, -1),
                k.reshape(B, T, -1), v.reshape(B, T, -1), *kv, idx,
                pos_b, table, span=span_b)
        else:
            ao, *kv = mixed_attention(
                hs, kv_mul, page_size, P, q.reshape(B, T, -1),
                k.reshape(B, T, -1), v.reshape(B, T, -1), *kv, idx,
                pos_b, table, span_b)
        x = _post_attention(spec, lw, x, ao.reshape(B * T, -1))
        return (x, *kv), None

    idxs = jnp.arange(L, dtype=jnp.int32)
    (x, *kv), _ = jax.lax.scan(scan_body, (x, *planes), (idxs, scanned))
    x = rmsnorm(x, params["rms_final"])
    logits = matmul(params["wcls"], x)                     # (B*T, vocab)
    return logits.reshape(B, T, -1), rebuild_paged_cache(tuple(kv), L)


def gather_pages(cache: KVCache, table: jax.Array,
                 page_size: int) -> KVCache:
    """Materialize one slot's virtual (L, S, n_kv, hs) sequence cache from
    its pool pages — the admission-prefill seed: chunked prefill of an
    UNSHARED suffix must attend over the shared prefix k/v, and the
    single-sequence prefill program expects a contiguous plane. ``table``
    is the slot's full (max_pages,) logical->physical row; entries beyond
    the live prefix gather scrap-page junk that prefill overwrites (its
    chunk at position p writes p before any later chunk reads it)."""
    def g(plane):
        L = plane.shape[0]
        got = jnp.take(plane, table, axis=1)  # (L, max_pages, ps, kv, hs)
        return got.reshape(L, table.shape[0] * page_size, *plane.shape[3:])

    return KVCache(g(cache.k), g(cache.v))


def scatter_pages(cache: KVCache, seq_cache: KVCache, table: jax.Array,
                  page_size: int) -> KVCache:
    """Write a prefilled virtual sequence cache back into the pool at the
    slot's physical pages — gather_pages' inverse (admission-prefill
    insert). Shared prefix pages receive byte-identical content (the seed
    copied them out and prefill never touches positions below its start),
    and table entries parked on the scrap page absorb the junk tail.
    jit with the POOL cache donated: the scatter updates in place."""
    def s(plane, seq_plane):
        L = plane.shape[0]
        upd = seq_plane.reshape(L, table.shape[0], page_size,
                                *plane.shape[3:])
        return plane.at[:, table].set(upd)

    return KVCache(s(cache.k, seq_cache.k), s(cache.v, seq_cache.v))


def gather_pages_q8(cache: PagedKVQ8, table: jax.Array,
                    page_size: int) -> KVCache:
    """gather_pages' Q8 twin: materialize one slot's virtual (L, S, n_kv,
    hs) sequence cache FROM the quantized pool, dequantized to f32 — the
    admission-prefill seed (the single-sequence prefill program computes
    in f32 and must attend over the shared prefix's dequantized k/v, the
    same values decode reads)."""
    from ..ops.quants import QK, dequantize_q80_planes

    L, _, ps, n_kv, hs = cache.kq.shape
    nb = n_kv * hs // QK
    S = table.shape[0] * page_size

    def g(codes, d):
        qc = jnp.take(codes, table, axis=1).reshape(L, S, n_kv, hs)
        dc = jnp.take(d, table, axis=1).reshape(L, S, nb)
        return dequantize_q80_planes(qc, dc)

    return KVCache(g(cache.kq, cache.kd), g(cache.vq, cache.vd))


def scatter_pages_q8(cache: PagedKVQ8, seq_cache: KVCache,
                     table: jax.Array, page_size: int) -> PagedKVQ8:
    """scatter_pages' Q8 twin: Q80-quantize the prefilled virtual plane
    per position and write codes + block deltas back into the pool at the
    slot's physical pages. UNLIKE the f32 scatter, re-writing a SHARED
    prefix page is not byte-idempotent (quantize∘dequantize moves codes
    whose block max shrank), so the engine passes a table whose shared
    entries are redirected to the scrap page — shared pages keep the
    bytes their first prefiller wrote, and every reader sees one
    deterministic encoding. jit with the POOL cache donated."""
    from ..ops.quants import QK, quantize_q80_jax

    L, _, ps, n_kv, hs = cache.kq.shape
    nb = n_kv * hs // QK
    n_pages_tbl = table.shape[0]

    def s(codes_plane, d_plane, seq_plane):
        qs, d = quantize_q80_jax(seq_plane.reshape(L, -1, n_kv * hs))
        codes = qs.reshape(L, n_pages_tbl, page_size, n_kv, hs)
        deltas = d.reshape(L, n_pages_tbl, page_size, nb)
        return (codes_plane.at[:, table].set(codes),
                d_plane.at[:, table].set(deltas))

    kq, kd = s(cache.kq, cache.kd, seq_cache.k)
    vq, vd = s(cache.vq, cache.vd, seq_cache.v)
    return PagedKVQ8(kq, kd, vq, vd)


def init_cache_batch(spec: TransformerSpec, batch: int,
                     dtype=jnp.float32) -> KVCache:
    """Batched cache: (L, B, S, n_kv, hs) — each (b, layer) row has the same
    (S, n_kv, hs) layout as the single-sequence cache (forward_batch carries
    it as a rank-4 (L*B, S, n_kv, hs) view; see there for why)."""
    shape = (spec.n_layers, batch, spec.seq_len, spec.n_kv_heads,
             spec.head_size)
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def forward_batch(spec: TransformerSpec, params: dict[str, Any],
                  cache: KVCache, tokens: jax.Array,
                  pos: jax.Array) -> tuple[jax.Array, KVCache]:
    """Decode one token for each of B sequences.

    tokens (B,); pos scalar (lockstep: one SHARED position clock) or (B,)
    (ragged: per-row clocks — continuous batching); cache is
    (L, B, S, n_kv, hs). Returns (logits (B, vocab), cache). The reference
    is strictly batch=1 (one token per task-table cycle, SURVEY.md §2 'no
    batching'); batching is the natural TPU extension — B rows turn the
    per-layer matvecs into MXU matmuls at the same weight traffic, so
    throughput scales ~B until the MXU saturates.

    With the shared clock (lockstep rows; ragged prompts right-pad and
    sample early — runtime/decode.make_batch_decode_loop) the cache update
    is one dynamic_update_slice, which XLA performs IN PLACE on the scan
    carry. The per-row-clock case uses B row updates instead of a scatter,
    which XLA does NOT update in place — it materializes a second
    cache-sized buffer, doubling cache HBM (measured: OOM at B=4/7B/16GB).
    Both live in batch_decode_attention.

    Numerics per row match forward(): same kernels via the T=B path, same
    RoPE/GQA/softmax math (batched einsums over the head-major cache —
    see init_cache_batch for why the layout differs from the B=1 path).
    """
    B = tokens.shape[0]
    x = params["tok_embedding"][tokens].astype(jnp.float32)  # (B, dim)
    # each row rotates at its own clock (identical under the shared one)
    positions = pos if jnp.ndim(pos) == 1 else jnp.full((B,), pos)
    n_kv, hs, kv_mul = spec.n_kv_heads, spec.head_size, spec.kv_mul
    L, S = spec.n_layers, spec.seq_len

    # the scan carries a RANK-4 (L*B, S, n_kv, hs) view: with the rank-5
    # carry, XLA's layout assignment propagates a batch-minor operand layout
    # from the attention dot into the whole carried cache and inserts a
    # lane-padded normalization copy (1GB cache -> 137GB allocation at B=4).
    # The merged leading dim mirrors the rank pattern of the B=1 path, which
    # lays out cleanly; the boundary reshapes are bitcasts. Row layer*B+b has
    # the single-sequence (S, n_kv, hs) layout.
    k4 = cache.k.reshape(L * B, S, n_kv, hs)
    v4 = cache.v.reshape(L * B, S, n_kv, hs)

    stacked, scanned = split_layer_weights(params)

    def scan_body(carry, per_layer):
        x, k_all, v_all = carry
        idx, lw_slice = per_layer
        lw = layer_view(stacked, lw_slice, idx)
        q, k, v = _qkv_proj(spec, lw, x, positions)
        ao, k_all, v_all = batch_decode_attention(hs, kv_mul, S, q, k, v,
                                                  k_all, v_all, idx, pos)
        x = _post_attention(spec, lw, x, ao)
        return (x, k_all, v_all), None

    idxs = jnp.arange(L, dtype=jnp.int32)
    (x, k4, v4), _ = jax.lax.scan(scan_body, (x, k4, v4), (idxs, scanned))
    x = rmsnorm(x, params["rms_final"])
    logits = matmul(params["wcls"], x)
    return logits, KVCache(k4.reshape(L, B, S, n_kv, hs),
                           v4.reshape(L, B, S, n_kv, hs))


def forward_batch_ragged(spec: TransformerSpec, params: dict[str, Any],
                         cache: KVCache, tokens: jax.Array,
                         pos_vec: jax.Array) -> tuple[jax.Array, KVCache]:
    """Decode one token for each of B sequences at PER-ROW positions —
    forward_batch with a (B,) position vector (the continuous-batching step,
    runtime/continuous.py): rows advance on independent clocks, so a
    finished row's slot can be re-used by a new request mid-flight.

    Inactive/parked rows simply keep writing at their current position; a
    newly admitted request starts at pos 0 and only ever attends to slots
    0..pos, so stale cache content beyond a row's clock is invisible.
    """
    return forward_batch(spec, params, cache, tokens, pos_vec)


def forward_seq(spec: TransformerSpec, params: dict[str, Any],
                tokens: jax.Array, positions: jax.Array | None = None,
                attention_fn=None) -> jax.Array:
    """Batched full-sequence forward without a KV cache: (B, T) -> (B, T, vocab).

    The training/evaluation path (the reference is inference-only; training is
    a capability extension). Causal attention inside the T window, same
    numerics as the cached forward — shared attention_core, same precision,
    same Q80 wire-quantization cut points.

    ``positions``/``attention_fn`` parameterize the sequence-parallel
    training path (parallel/sp_train.py): positions are this shard's
    absolute offsets and attention_fn(q, k, v) -> (B, T, n_q*hs) runs ring
    attention across the sp axis — everything else (embedding, layer scan,
    fused-weight handling, SwiGLU tail, final norm/logits) is shared, so
    the two paths cannot drift.
    """
    B, T = tokens.shape
    x = params["tok_embedding"][tokens].astype(jnp.float32)  # (B, T, D)
    if positions is None:
        positions = jnp.arange(T)
    mask = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]  # (T, T) causal

    stacked, scanned = split_layer_weights(params)

    def body(x, per_layer):
        idx, lw_slice = per_layer
        lw = layer_view(stacked, lw_slice, idx)
        q, k, v = _qkv_proj(spec, lw, x, positions)
        if attention_fn is not None:
            ao = attention_fn(q, k, v)
        else:
            ao = attention_core(
                spec.head_size, spec.kv_mul,
                q.reshape(B, T, spec.n_heads, spec.head_size),
                k.reshape(B, T, spec.n_kv_heads, spec.head_size),
                v.reshape(B, T, spec.n_kv_heads, spec.head_size), mask)
        x = _post_attention(spec, lw, x, ao)
        return x, None

    idxs = jnp.arange(spec.n_layers, dtype=jnp.int32)
    x, _ = jax.lax.scan(body, x, (idxs, scanned))
    x = rmsnorm(x, params["rms_final"])
    return matmul(params["wcls"], x)


@functools.partial(jax.jit, static_argnums=0, donate_argnums=2)
def decode_step(spec: TransformerSpec, params: dict[str, Any], cache: KVCache,
                token: jax.Array, pos: jax.Array) -> tuple[jax.Array, KVCache]:
    """Single-token step: the hot per-token function (T=1)."""
    logits, cache = forward(spec, params, cache, token[None], pos)
    return logits[0], cache


def params_to_device(params: dict[str, Any], dtype=None,
                     spec: TransformerSpec | None = None) -> dict[str, Any]:
    """Move a numpy param tree onto the default device as jax arrays.

    Q40 weights are re-tiled to the Pallas kernel layout here (once, host
    side) when the Q40 fast path is active — see ops/linear.pack_q40_params.
    With ``spec`` given, the megakernel's permuted-wo stack is prepared too
    (ops/pallas_layer.prepare_mega_params) so T=1 decode can run one fused
    op per layer.
    """
    from ..io.loader import Q40Kernel, Q40Weight
    from ..ops.linear import fuse_q40_layer_matmuls, pack_q40_params

    params = fuse_q40_layer_matmuls(pack_q40_params(params,
                                                    allow_nb_major=True))
    if spec is not None:
        from ..ops.pallas_layer import prepare_mega_params

        params = prepare_mega_params(spec, params)

    def conv(a):
        x = jnp.asarray(a)
        if dtype is not None and x.dtype in (jnp.float32, jnp.float16):
            x = x.astype(dtype)
        return x

    out = {}
    for k, v in params.items():
        if isinstance(v, (Q40Weight, Q40Kernel, Q40KernelNb)):
            # quantized leaves keep their exact codec/kernel dtypes — the
            # dtype knob is for dense weights only (scales must stay f32/f16)
            out[k] = jax.tree_util.tree_map(jnp.asarray, v)
        else:
            out[k] = conv(v)
    return out
