from .loader import Q40Weight, load_model, read_spec, write_model  # noqa: F401
