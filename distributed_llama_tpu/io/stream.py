"""Root -> worker weight streaming: workers need ZERO local model files.

The reference scatters weight slices from the root's mmap to each worker over
its TCP star at startup (transformer.cpp:250-273 root side, :354-380 worker
side, with the kB/s progress log). On TPU the "scatter" onto chips is the
sharded device_put — but the HOST still needs the bytes, and round 1 required
every host to have the .bin locally. This module closes that gap:

* ``WeightServer`` (root): serves byte ranges of the .bin over TCP. The
  protocol is three line-framed requests — ``SPEC`` (header + file size),
  ``GET <offset> <length>`` (raw bytes), ``DONE`` — deliberately tiny, like
  the reference's implicit statically-known-sizes framing, but explicit so a
  version mismatch fails loudly instead of desynchronizing.
* ``fetch_model`` (worker): downloads the file into a local cache path with
  the reference's ⏩ kB/s progress line, then the normal loader takes over.
  Chunked GETs keep memory flat; a size/byte-count mismatch raises (the
  reference exits on any short read, socket.cpp:38-43).

Design deviation, documented: the reference streams each worker ONLY its
slices (1/n of the file). Here every fetching host pulls the whole file —
JAX's multi-controller model wants each host able to build any of its
devices' shards, and hosts that already have the file skip the fetch
entirely. The fetch is a one-time load-phase cost on the LAN, traded for
zero special-casing in the sharded load path.
"""

from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time

_MAGIC = b"DLTPU1"  # protocol version tag; bump on any framing change
_CHUNK = 4 << 20


class WeightServer:
    """Serve a .bin's bytes to fetching hosts (root side).

    Runs a daemon thread per connection; ``port=0`` picks a free port
    (exposed as ``.port``). The server stays up until ``close()`` — workers
    may connect at any point of the root's own load.

    Trust model: UNAUTHENTICATED byte service, same as the reference's
    worker sockets — anyone who can reach the port can read the model file.
    Run it on a trusted/cluster network; ``host`` restricts the listening
    interface (the CLI exposes it as --serve-weights-bind).
    """

    def __init__(self, path: str, host: str = "0.0.0.0", port: int = 0):
        self.path = os.path.abspath(path)
        self.size = os.path.getsize(self.path)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with open(outer.path, "rb") as fh:
                    f = self.request.makefile("rb")
                    while True:
                        line = f.readline()
                        if not line or line.strip() == b"DONE":
                            return
                        parts = line.split()
                        if not parts:
                            return  # blank line: malformed, drop
                        if parts[0] == b"SPEC":
                            self.request.sendall(
                                _MAGIC + struct.pack("<q", outer.size))
                        elif parts[0] == b"GET" and len(parts) == 3:
                            off, ln = int(parts[1]), int(parts[2])
                            if off < 0 or ln < 0 or off + ln > outer.size:
                                return  # malformed: drop the connection
                            fh.seek(off)
                            remaining = ln
                            while remaining:
                                chunk = fh.read(min(remaining, _CHUNK))
                                if not chunk:
                                    return
                                self.request.sendall(chunk)
                                remaining -= len(chunk)
                        else:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _recv_exact(sock: socket.socket, n: int, into=None) -> bytes | None:
    buf = into if into is not None else bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("weight stream closed mid-transfer "
                                  "(short read)")
        got += r
    return None if into is not None else bytes(buf)


def _connect_with_retry(host: str, port: int, timeout: float,
                        connect_window: float) -> socket.socket:
    """Retry connection-refused for up to ``connect_window`` seconds: the
    worker may legitimately start before the root's server binds (the
    reference's worker likewise sits in accept() waiting for the root)."""
    deadline = time.time() + connect_window
    while True:
        try:
            return socket.create_connection((host, port), timeout=timeout)
        except (ConnectionRefusedError, socket.timeout, OSError):
            if time.time() >= deadline:
                raise
            time.sleep(0.25)


def fetch_model(addr: str, cache_path: str, quiet: bool = False,
                timeout: float = 600.0,
                connect_window: float = 60.0) -> str:
    """Download the model from ``host:port`` into ``cache_path``.

    Returns ``cache_path``. If the file already exists with the advertised
    size, the fetch is skipped (a host that has the model keeps using it —
    re-running a worker does not re-pull gigabytes). A wrong-size existing
    file is re-fetched — this is the ONE place that decides staleness, so
    callers should invoke it unconditionally.
    """
    host, port = addr.rsplit(":", 1)
    with _connect_with_retry(host, int(port), timeout, connect_window) as s:
        s.sendall(b"SPEC\n")
        head = _recv_exact(s, len(_MAGIC) + 8)
        if head[:len(_MAGIC)] != _MAGIC:
            raise ValueError("weight server protocol mismatch "
                             f"(got {head[:len(_MAGIC)]!r})")
        size = struct.unpack("<q", head[len(_MAGIC):])[0]
        if (os.path.exists(cache_path)
                and os.path.getsize(cache_path) == size):
            s.sendall(b"DONE\n")
            if not quiet:
                print(f"⏩ weight cache hit: {cache_path} ({size} bytes)")
            return cache_path

        t0 = time.time()
        # per-process unique temp in the target dir: two fetchers racing on
        # the same cache_path each write their own file; os.replace installs
        # whichever finishes (both byte-identical by the size check)
        import tempfile

        dst_dir = os.path.dirname(os.path.abspath(cache_path))
        os.makedirs(dst_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=dst_dir, suffix=".part")
        try:
            with os.fdopen(fd, "wb") as out:
                off = 0
                buf = bytearray(_CHUNK)
                while off < size:
                    ln = min(_CHUNK, size - off)
                    s.sendall(f"GET {off} {ln}\n".encode())
                    _recv_exact(s, ln, into=memoryview(buf)[:ln])
                    out.write(memoryview(buf)[:ln])
                    off += ln
                    if not quiet and off % (256 << 20) < _CHUNK:
                        kbs = off / 1024 / max(time.time() - t0, 1e-9)
                        print(f"⏩ fetched {off >> 20}/{size >> 20} MB "
                              f"({kbs:.0f} kB/s)")
            if os.path.getsize(tmp) != size:
                raise ValueError(f"fetched {os.path.getsize(tmp)} bytes, "
                                 f"expected {size}")
            os.replace(tmp, cache_path)
        except BaseException:
            # never leave a multi-GB orphan behind (repeated retries of a
            # 40 GB fetch would otherwise fill the disk with .part files)
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        s.sendall(b"DONE\n")
        if not quiet:
            kbs = size / 1024 / max(time.time() - t0, 1e-9)
            print(f"⏩ fetched model: {size} bytes in "
                  f"{time.time() - t0:.1f}s ({kbs:.0f} kB/s)")
    return cache_path
