"""Root -> worker weight streaming: workers need ZERO local model files.

The reference scatters weight slices from the root's mmap to each worker over
its TCP star at startup (transformer.cpp:250-273 root side, :354-380 worker
side, with the kB/s progress log). On TPU the "scatter" onto chips is the
sharded device_put — but the HOST still needs the bytes, and round 1 required
every host to have the .bin locally. This module closes that gap:

* ``WeightServer`` (root): serves byte ranges of the .bin over TCP. The
  protocol is three line-framed requests — ``SPEC`` (header + file size),
  ``GET <offset> <length>`` (raw bytes), ``DONE`` — deliberately tiny, like
  the reference's implicit statically-known-sizes framing, but explicit so a
  version mismatch fails loudly instead of desynchronizing.
* ``fetch_model`` (worker): downloads the file into a local cache path with
  the reference's ⏩ kB/s progress line, then the normal loader takes over.
  Chunked GETs keep memory flat; a size/byte-count mismatch raises (the
  reference exits on any short read, socket.cpp:38-43).

Two fetch granularities:

* ``fetch_model`` — the whole file (any host can then build any shard).
* ``fetch_model_slices`` — ONLY the byte ranges a host's devices need
  (replicated tensors full + this host's tp row bands of every matmul
  tensor), written sparsely into a full-size file, with a ``.slices``
  sidecar recording which ranges are real. This is the reference's
  slice-granular scatter (transformer.cpp:250-273 root / :354-380 worker
  — each worker receives ~1/n of the file); at 70B tp=8 it cuts a worker
  host's fetch from ~37 GB to ~5.6 GB. The loader then reads unneeded
  bands as zeros — values that only ever land on OTHER hosts' devices
  (each host device_puts just its addressable shards), so the computed
  model is unchanged; the CLI cross-checks the assumed rank set against
  the actual mesh before any forward runs (frontend/cli.py) so a wrong
  host->rank assumption fails loudly instead of computing on zeros.
  Whole-file fetch remains the fallback for any topology the rank
  arithmetic can't describe.

Crash safety (ISSUE 9): the ``.slices`` sidecar records a CRC32 per
resident range, computed by reading the file BACK after the fetch — the
sidecar vouches for what actually landed on disk, and the next fetch
verifies each range before trusting it (a failed range re-fetches; torn
writes and crash residue never load as weights). A connection dropped
mid-transfer resumes through the same range machinery: progress is
persisted to the sidecar, the socket reconnects (exponential backoff),
and only the still-missing ranges re-fetch. ``_connect_with_retry``
retries only TRANSIENT failures — a DNS failure or invalid address
raises immediately instead of burning the whole connect window.
"""

from __future__ import annotations

import errno
import json
import os
import socket
import socketserver
import struct
import sys
import threading
import time
import zlib

from ..obs.log import log_event

_MAGIC = b"DLTPU1"  # protocol version tag; bump on any framing change
_CHUNK = 4 << 20


class WeightServer:
    """Serve a .bin's bytes to fetching hosts (root side).

    Runs a daemon thread per connection; ``port=0`` picks a free port
    (exposed as ``.port``). The server stays up until ``close()`` — workers
    may connect at any point of the root's own load.

    Trust model: UNAUTHENTICATED byte service, same as the reference's
    worker sockets — anyone who can reach the port can read the model file.
    Run it on a trusted/cluster network; ``host`` restricts the listening
    interface (the CLI exposes it as --serve-weights-bind).
    """

    def __init__(self, path: str, host: str = "0.0.0.0", port: int = 0):
        self.path = os.path.abspath(path)
        self.size = os.path.getsize(self.path)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with open(outer.path, "rb") as fh:
                    f = self.request.makefile("rb")
                    while True:
                        line = f.readline()
                        if not line or line.strip() == b"DONE":
                            return
                        parts = line.split()
                        if not parts:
                            return  # blank line: malformed, drop
                        if parts[0] == b"SPEC":
                            self.request.sendall(
                                _MAGIC + struct.pack("<q", outer.size))
                        elif parts[0] == b"GET" and len(parts) == 3:
                            off, ln = int(parts[1]), int(parts[2])
                            if off < 0 or ln < 0 or off + ln > outer.size:
                                return  # malformed: drop the connection
                            fh.seek(off)
                            remaining = ln
                            while remaining:
                                chunk = fh.read(min(remaining, _CHUNK))
                                if not chunk:
                                    return
                                self.request.sendall(chunk)
                                remaining -= len(chunk)
                        else:
                            return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _recv_exact(sock: socket.socket, n: int, into=None) -> bytes | None:
    buf = into if into is not None else bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("weight stream closed mid-transfer "
                                  "(short read)")
        got += r
    return None if into is not None else bytes(buf)


# errno values worth retrying: the server has not bound yet, the network
# hiccuped, or a half-open connection died. Anything else (bad address,
# DNS failure, permission) is a configuration error — retrying it for the
# whole connect window just delays the real diagnosis.
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name) for name in (
        "ECONNREFUSED", "ECONNRESET", "ECONNABORTED", "ETIMEDOUT",
        "EHOSTUNREACH", "ENETUNREACH", "EHOSTDOWN", "ENETDOWN", "EPIPE",
        "EAGAIN", "EINTR") if hasattr(errno, name))


def _is_transient(exc: BaseException) -> bool:
    if isinstance(exc, (ConnectionError, TimeoutError, socket.timeout)):
        return True
    if isinstance(exc, socket.gaierror):
        # EAI_AGAIN is the resolver saying "not yet" (container boots
        # before DNS is ready) — retry it; every other resolution
        # failure is a typo retrying will not fix
        return exc.errno == socket.EAI_AGAIN
    return isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS


def _connect_with_retry(host: str, port: int, timeout: float,
                        connect_window: float) -> socket.socket:
    """Retry transient connect failures for up to ``connect_window``
    seconds with exponential backoff (50 ms doubling to a 2 s cap): the
    worker may legitimately start before the root's server binds (the
    reference's worker likewise sits in accept() waiting for the root).
    NON-transient failures — DNS errors, invalid addresses — raise
    immediately instead of spinning out the window."""
    deadline = time.monotonic() + connect_window
    delay = 0.05
    while True:
        try:
            return socket.create_connection((host, port), timeout=timeout)
        except OSError as e:
            if not _is_transient(e) or time.monotonic() >= deadline:
                raise
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2.0, 2.0)


def _connect_spec(host: str, port: int, timeout: float,
                  connect_window: float) -> tuple[socket.socket, int]:
    """Connect and run the SPEC handshake: returns (socket, served file
    size). A protocol-magic mismatch raises immediately — the endpoint is
    the WRONG SERVER, which no amount of retrying fixes."""
    s = _connect_with_retry(host, port, timeout, connect_window)
    try:
        s.sendall(b"SPEC\n")
        head = _recv_exact(s, len(_MAGIC) + 8)
    except BaseException:
        s.close()
        raise
    if head[:len(_MAGIC)] != _MAGIC:
        s.close()
        raise ValueError("weight server protocol mismatch "
                         f"(got {head[:len(_MAGIC)]!r})")
    return s, struct.unpack("<q", head[len(_MAGIC):])[0]


# public names for the transfer machinery the DCN page channel
# (runtime/page_channel.py, ISSUE 14) builds on: exact receives, the
# transient/permanent failure split, and backoff-retried connects — the
# page channel must resume mid-transfer with the same discipline the
# weight stream does, not reinvent a worse copy of it
recv_exact = _recv_exact
is_transient = _is_transient
connect_with_retry = _connect_with_retry


def merge_ranges(ranges: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Sort and coalesce (offset, length) ranges (adjacent or overlapping)."""
    out: list[list[int]] = []
    for off, ln in sorted(r for r in ranges if r[1] > 0):
        if out and off <= out[-1][0] + out[-1][1]:
            out[-1][1] = max(out[-1][1], off + ln - out[-1][0])
        else:
            out.append([off, ln])
    return [(o, l) for o, l in out]


def subtract_ranges(need: list[tuple[int, int]],
                    have: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Ranges of ``need`` not covered by ``have`` (both coalesced)."""
    out: list[tuple[int, int]] = []
    have = merge_ranges(have)
    for off, ln in merge_ranges(need):
        end = off + ln
        cur = off
        for ho, hl in have:
            he = ho + hl
            if he <= cur or ho >= end:
                continue
            if ho > cur:
                out.append((cur, ho - cur))
            cur = max(cur, he)
            if cur >= end:
                break
        if cur < end:
            out.append((cur, end - cur))
    return out


def needed_byte_ranges(spec, tp: int,
                       ranks: set[int]) -> list[tuple[int, int]]:
    """The byte ranges a host holding tp ranks ``ranks`` needs from the .bin:
    the header + every replicated tensor in full + each matmul tensor's
    contiguous row band per rank (MatmulSlice bands — the same 1/tp output-
    dim cut shard_params device_puts). The rope gap is skipped (the loader
    skips it; sparse zeros are byte-identical)."""
    from ..models.spec import HEADER_BYTES
    from .loader import tensor_byte_ranges

    if tp < 1 or any(r < 0 or r >= tp for r in ranks):
        raise ValueError(f"ranks {sorted(ranks)} invalid for tp={tp}")
    ranges: list[tuple[int, int]] = [(0, HEADER_BYTES)]
    for tr in tensor_byte_ranges(spec):
        if tr.name == "_rope_gap":
            continue
        if tr.rows is None or tp == 1:
            ranges.append((tr.offset, tr.nbytes))
            continue
        if tr.rows % tp:
            raise ValueError(f"{tr.name}: rows {tr.rows} not divisible by "
                             f"tp={tp}")
        band = (tr.rows // tp) * (tr.nbytes // tr.rows)
        for r in sorted(set(ranks)):
            ranges.append((tr.offset + r * band, band))
    return merge_ranges(ranges)


def _sidecar_path(cache_path: str) -> str:
    return cache_path + ".slices"


def write_record_sidecar(path: str, size: int, entries) -> None:
    """Write a record-granular ``.slices`` sidecar — the weight-cache
    format with ranges left UNMERGED so each record keeps its own CRC
    (``_read_sidecar``/``verified_ranges`` then verify per record).
    Atomic (temp + ``os.replace``), like ``_write_sidecar``."""
    tmp = _sidecar_path(path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"size": size,
                   "ranges": [list(e) for e in entries]}, fh)
    os.replace(tmp, _sidecar_path(path))


def append_record_verified(path: str, blob: bytes,
                           entries=None) -> tuple[int, int, int]:
    """Append ``blob`` to ``path`` and CRC it by READING IT BACK — the
    weight-cache sidecar contract (the sidecar vouches for bytes that
    actually landed on disk, not bytes a buffer once held) — then fold
    the new range into the file's ``.slices`` sidecar WITHOUT merging
    ranges (per-record CRCs must survive for record-granular
    verification; the KV disk tier, runtime/paging.DiskPageStore, reads
    one page record at a time). Returns ``(offset, length, crc)``.

    ``entries``: a caller-kept list of this segment's ``[off, len,
    crc]`` entries. When provided, the new entry is appended to it and
    the sidecar write is DEFERRED to the caller (DiskPageStore flushes
    at segment seal / audit) — per-append cost stays O(record) instead
    of re-reading and rewriting a sidecar that grows with the segment.
    Without it, the sidecar is read-modify-replaced here (small or
    one-off appends)."""
    with open(path, "ab") as fh:
        off = fh.tell()
        fh.write(blob)
    with open(path, "rb") as fh:
        crc = _crc_file_range(fh, off, len(blob))
    if crc is None:
        raise OSError(f"{path}: appended record [{off}, "
                      f"{off + len(blob)}) did not land on disk")
    if entries is not None:
        entries.append([off, len(blob), crc])
        return off, len(blob), crc
    try:
        with open(_sidecar_path(path)) as fh:
            meta = json.load(fh)
    except (FileNotFoundError, ValueError):
        meta = {"ranges": []}
    write_record_sidecar(path, off + len(blob),
                         list(meta.get("ranges", []))
                         + [[off, len(blob), crc]])
    return off, len(blob), crc


def read_record_verified(path: str, off: int, length: int,
                         crc: int) -> bytes | None:
    """One record of an append-only segment, verified against its
    read-back CRC before a byte is trusted. ``None`` on any damage —
    short file, IO error, or CRC mismatch — so the caller can re-derive
    the payload instead of consuming corrupt bytes (the KV disk tier
    re-prefills a page whose record fails here)."""
    try:
        with open(path, "rb") as fh:
            fh.seek(off)
            data = fh.read(length)
    except OSError:
        return None
    if len(data) != length or zlib.crc32(data) != crc:
        return None
    return data


def verified_ranges(path: str) -> list[tuple[int, int]] | None:
    """The sidecar-recorded ranges of ``path`` that still verify against
    their read-back CRCs (the ``_read_sidecar`` machinery, made public
    for the KV disk tier's audit). None = no sidecar at all."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return []
    return _read_sidecar(path, size)


def _crc_file_range(fh, off: int, ln: int) -> int | None:
    """CRC32 of ``ln`` bytes at ``off`` of an open binary file; None when
    the file is too short to cover the range."""
    fh.seek(off)
    crc = 0
    remaining = ln
    while remaining:
        chunk = fh.read(min(_CHUNK, remaining))
        if not chunk:
            return None
        crc = zlib.crc32(chunk, crc)
        remaining -= len(chunk)
    return crc


def _write_sidecar(cache_path: str, size: int, ranges,
                   crc: bool = True) -> None:
    """Persist the sparse file's resident ranges with a CRC32 per range,
    computed by READING THE FILE BACK — the sidecar vouches for bytes that
    actually landed on disk, not bytes a buffer once held. Atomic (temp +
    ``os.replace``): a kill mid-write leaves the previous sidecar, whose
    ranges still verify. ``crc=False`` writes checksum-less (legacy
    two-field) ranges — the mid-transfer RESUME checkpoint uses it so a
    flaky multi-GB fetch does not re-read its whole progress on every
    disconnect; the fetch's final sidecar always carries CRCs."""
    entries = []
    merged = merge_ranges(list(ranges))
    if merged and not crc:
        entries = [[off, ln] for off, ln in merged]
    elif merged:
        with open(cache_path, "rb") as fh:
            for off, ln in merged:
                rc = _crc_file_range(fh, off, ln)
                if rc is None:
                    raise ValueError(
                        f"{cache_path} shorter than its recorded range "
                        f"[{off}, {off + ln})")
                entries.append([off, ln, rc])
    tmp = _sidecar_path(cache_path) + ".tmp"
    with open(tmp, "w") as fh:
        json.dump({"size": size, "ranges": entries}, fh)
    os.replace(tmp, _sidecar_path(cache_path))


def _read_sidecar(cache_path: str, size: int) -> list[tuple[int, int]] | None:
    """Fetched ranges of an existing sparse file; None = not a sparse file.

    Ranges carrying a CRC32 (the third field) are VERIFIED against the
    data file before being trusted — a mismatched range is dropped, so the
    caller's range subtraction re-fetches exactly the damaged bytes.
    Legacy two-field ranges (pre-checksum sidecars) pass through. A
    corrupt or wrong-size sidecar yields [] — nothing usable, full
    re-fetch of the needed ranges."""
    try:
        with open(_sidecar_path(cache_path)) as fh:
            meta = json.load(fh)
    except FileNotFoundError:
        return None
    except ValueError:
        return []
    try:
        if meta.get("size") != size:
            return []  # different model: nothing usable
        out: list[tuple[int, int]] = []
        with open(cache_path, "rb") as data:
            for entry in meta.get("ranges", []):
                off, ln = int(entry[0]), int(entry[1])
                if len(entry) > 2:
                    crc = _crc_file_range(data, off, ln)
                    if crc != int(entry[2]):
                        # stderr: fires regardless of ``quiet`` (damage
                        # must never pass silently) so it must not
                        # pollute machine-readable stdout
                        log_event(
                            "weights.crc_mismatch",
                            f"🔶 weight cache range [{off}, {off + ln}) "
                            f"of {cache_path} failed its CRC — "
                            f"re-fetching it",
                            file=sys.stderr, path=cache_path, offset=off,
                            length=ln)
                        continue
                out.append((off, ln))
        return out
    except (ValueError, KeyError, IndexError, TypeError, OSError):
        return []


def fetch_model_slices(addr: str, cache_path: str, weights_float_type,
                       tp: int, ranks: set[int], quiet: bool = False,
                       timeout: float = 600.0,
                       connect_window: float = 60.0,
                       max_resumes: int = 8,
                       chunk_bytes: int = _CHUNK) -> str:
    """Fetch ONLY the ranges a host with tp ranks ``ranks`` needs.

    The header is fetched first and parsed into the spec (the byte layout
    depends on ``weights_float_type``, which the caller knows from its own
    CLI flags — the file format itself does not encode it). The result is a
    full-size sparse file; a ``.slices`` sidecar records which ranges hold
    real bytes (with a CRC32 per range, verified before re-use), so re-runs
    with the same or fewer ranks skip the fetch, a wider rank set tops up
    only the missing ranges, and a full-file cache (no sidecar, right size,
    HEADER matching the served bytes) is a hit. One fetcher per cache_path
    at a time (hosts have distinct paths; the empty sidecar is written
    before the first data byte, so a killed fetch re-fetches rather than
    trusting holes). A connection dropped mid-transfer resumes up to
    ``max_resumes`` times: progress persists to the sidecar, the socket
    reconnects, and only the still-missing ranges re-fetch —
    ``chunk_bytes`` is the resume granularity (small files in drills
    shrink it so a cut connection still leaves completed chunks behind).
    """
    from ..models.spec import HEADER_BYTES, TransformerSpec

    host, port_s = addr.rsplit(":", 1)
    port = int(port_s)
    s, size = _connect_spec(host, port, timeout, connect_window)
    try:
        s.sendall(f"GET 0 {HEADER_BYTES}\n".encode())
        raw = _recv_exact(s, HEADER_BYTES)
        spec = TransformerSpec.from_header(raw, weights_float_type,
                                           weights_float_type)
        if spec.file_size() != size:
            raise ValueError(
                f"served file is {size} bytes but its header implies "
                f"{spec.file_size()} for {weights_float_type} weights — "
                f"wrong --weights-float-type?")
        need = needed_byte_ranges(spec, tp, ranks)

        have = None
        existing = (os.path.exists(cache_path)
                    and os.path.getsize(cache_path) == size)
        if existing:
            have = _read_sidecar(cache_path, size)
            if have is None:
                # right size, NO sidecar: claimed full file. Verify the
                # claim against the served header before trusting it — a
                # killed fetch that left data without a sidecar (or a
                # hand-truncated hole file) reads as zeros here and gets
                # re-fetched instead of loaded as weights
                with open(cache_path, "rb") as fh:
                    if fh.read(HEADER_BYTES) == raw:
                        s.sendall(b"DONE\n")
                        if not quiet:
                            log_event("weights.cache_hit",
                                      f"⏩ weight cache hit: {cache_path} "
                                      f"({size} bytes)",
                                      path=cache_path, bytes=size)
                        return cache_path
                log_event("weights.cache_suspect",
                          f"🔶 {cache_path} is full-size but its header "
                          f"does not match the served file — treating as "
                          f"crash residue and re-fetching",
                          file=sys.stderr, path=cache_path)
                have = []
        missing = subtract_ranges(need, have or [])
        if not missing:
            s.sendall(b"DONE\n")
            if not quiet:
                log_event("weights.slice_cache_hit",
                          f"⏩ weight slice cache hit: {cache_path} "
                          f"({sum(l for _, l in have or [])} bytes "
                          f"resident)",
                          path=cache_path,
                          resident_bytes=sum(l for _, l in have or []))
            return cache_path

        t0 = time.time()
        total = sum(ln for _, ln in missing)
        dst_dir = os.path.dirname(os.path.abspath(cache_path))
        os.makedirs(dst_dir, exist_ok=True)
        done = 0
        if not have:
            # claim sparse-ness BEFORE the file can reach full size: a fetch
            # killed mid-way must leave a sidecar with no ranges, so the next
            # run re-fetches instead of misreading a right-sized holey file
            # as a complete full-file cache
            tmp = _sidecar_path(cache_path) + ".tmp"
            with open(tmp, "w") as fh:
                json.dump({"size": size, "ranges": []}, fh)
            os.replace(tmp, _sidecar_path(cache_path))
        # ``got`` grows one entry per chunk that reached the file — on a
        # mid-transfer disconnect it IS the resume state: persist it to
        # the sidecar, reconnect, and subtract it from ``need`` again
        got: list[tuple[int, int]] = list(have or [])
        resumes = 0
        with open(cache_path, "r+b" if existing else "wb") as out:
            out.truncate(size)
            buf = bytearray(chunk_bytes)
            while True:
                todo = subtract_ranges(need, got)
                if not todo:
                    break
                try:
                    for off, ln in todo:
                        out.seek(off)
                        cur = 0
                        while cur < ln:
                            step = min(chunk_bytes, ln - cur)
                            s.sendall(f"GET {off + cur} {step}\n".encode())
                            _recv_exact(s, step,
                                        into=memoryview(buf)[:step])
                            out.write(memoryview(buf)[:step])
                            got.append((off + cur, step))
                            cur += step
                            done += step
                            if not quiet and done % (256 << 20) < _CHUNK:
                                kbs = (done / 1024
                                       / max(time.time() - t0, 1e-9))
                                log_event(
                                    "weights.fetch_progress",
                                    f"⏩ fetched {done >> 20}/"
                                    f"{total >> 20} MB of slices "
                                    f"({kbs:.0f} kB/s)",
                                    done_bytes=done, total_bytes=total,
                                    kb_per_s=round(kbs))
                except OSError as e:
                    if not _is_transient(e):
                        raise  # a LOCAL fault (disk full, I/O error):
                        #   reconnecting the socket cannot fix it
                    resumes += 1
                    if resumes > max_resumes:
                        raise
                    # mid-transfer disconnect: persist progress, reconnect,
                    # and let the range subtraction resume where the wire
                    # dropped — never refetch bytes already on disk.
                    # crc=False: this is a checkpoint, not the final
                    # sidecar — re-CRCing every resident byte per drop
                    # would cost a full disk pass exactly when the
                    # transfer is already degraded. The fsync is what
                    # lets the checksum-less checkpoint vouch for its
                    # ranges: the data must be ON DISK before the sidecar
                    # rename can claim it (power loss between the two
                    # would otherwise load holes as weights)
                    out.flush()
                    os.fsync(out.fileno())
                    _write_sidecar(cache_path, size, got, crc=False)
                    try:
                        s.close()
                    except OSError:
                        pass
                    log_event("weights.stream_resume",
                              f"🔶 weight stream dropped mid-transfer "
                              f"({type(e).__name__}: {e}); resuming "
                              f"({resumes}/{max_resumes}) from "
                              f"{done >> 20} MB",
                              file=sys.stderr,
                              error=f"{type(e).__name__}: {e}",
                              resume=resumes, done_bytes=done)
                    s, size2 = _connect_spec(host, port, timeout,
                                             connect_window)
                    if size2 != size:
                        raise ValueError(
                            f"served file changed size mid-fetch "
                            f"({size} -> {size2} bytes)")
        _write_sidecar(cache_path, size, got)
        s.sendall(b"DONE\n")
        if not quiet:
            kbs = total / 1024 / max(time.time() - t0, 1e-9)
            log_event("weights.fetched_slices",
                      f"⏩ fetched {total} slice bytes of {size} "
                      f"({100.0 * total / size:.0f}%, tp ranks "
                      f"{sorted(ranks)}) in {time.time() - t0:.1f}s "
                      f"({kbs:.0f} kB/s)",
                      fetched_bytes=total, file_bytes=size,
                      tp_ranks=sorted(ranks),
                      seconds=round(time.time() - t0, 1),
                      kb_per_s=round(kbs))
    finally:
        try:
            s.close()
        except OSError:
            pass
    return cache_path


def fetch_model(addr: str, cache_path: str, quiet: bool = False,
                timeout: float = 600.0,
                connect_window: float = 60.0) -> str:
    """Download the model from ``host:port`` into ``cache_path``.

    Returns ``cache_path``. If the file already exists with the advertised
    size, the fetch is skipped (a host that has the model keeps using it —
    re-running a worker does not re-pull gigabytes). A wrong-size existing
    file is re-fetched — this is the ONE place that decides staleness, so
    callers should invoke it unconditionally.
    """
    host, port = addr.rsplit(":", 1)
    s, size = _connect_spec(host, int(port), timeout, connect_window)
    with s:
        if (os.path.exists(cache_path)
                and os.path.getsize(cache_path) == size
                # a .slices sidecar marks a SPARSE file (fetch_model_slices):
                # right-sized but holey — never a full-file hit
                and not os.path.exists(_sidecar_path(cache_path))):
            s.sendall(b"DONE\n")
            if not quiet:
                log_event("weights.cache_hit",
                          f"⏩ weight cache hit: {cache_path} "
                          f"({size} bytes)",
                          path=cache_path, bytes=size)
            return cache_path

        t0 = time.time()
        # per-process unique temp in the target dir: two fetchers racing on
        # the same cache_path each write their own file; os.replace installs
        # whichever finishes (both byte-identical by the size check)
        import tempfile

        dst_dir = os.path.dirname(os.path.abspath(cache_path))
        os.makedirs(dst_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=dst_dir, suffix=".part")
        try:
            with os.fdopen(fd, "wb") as out:
                off = 0
                buf = bytearray(_CHUNK)
                while off < size:
                    ln = min(_CHUNK, size - off)
                    s.sendall(f"GET {off} {ln}\n".encode())
                    _recv_exact(s, ln, into=memoryview(buf)[:ln])
                    out.write(memoryview(buf)[:ln])
                    off += ln
                    if not quiet and off % (256 << 20) < _CHUNK:
                        kbs = off / 1024 / max(time.time() - t0, 1e-9)
                        log_event("weights.fetch_progress",
                                  f"⏩ fetched {off >> 20}/{size >> 20} MB "
                                  f"({kbs:.0f} kB/s)",
                                  done_bytes=off, total_bytes=size,
                                  kb_per_s=round(kbs))
            if os.path.getsize(tmp) != size:
                raise ValueError(f"fetched {os.path.getsize(tmp)} bytes, "
                                 f"expected {size}")
            os.replace(tmp, cache_path)
            try:  # the file is complete now: drop any stale sparse marker
                os.unlink(_sidecar_path(cache_path))
            except FileNotFoundError:
                pass
        except BaseException:
            # never leave a multi-GB orphan behind (repeated retries of a
            # 40 GB fetch would otherwise fill the disk with .part files)
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        s.sendall(b"DONE\n")
        if not quiet:
            kbs = size / 1024 / max(time.time() - t0, 1e-9)
            log_event("weights.fetched",
                      f"⏩ fetched model: {size} bytes in "
                      f"{time.time() - t0:.1f}s ({kbs:.0f} kB/s)",
                      bytes=size, seconds=round(time.time() - t0, 1),
                      kb_per_s=round(kbs))
    return cache_path
