""".bin model file reader/writer (reference format parity).

Reader walks the exact tensor order of reference src/transformer.cpp:298-352
(see models/spec.py docstring for the layout) and returns a numpy parameter
pytree with per-layer weights stacked along a leading layer axis — the shape a
`lax.scan` over layers consumes. Quantized (Q40) matmul weights come back as
`Q40Weight(qs, d16)` planar pairs; F16 as float16 arrays; F32 as float32.

Writer emits the same byte layout (used by our converter and by tests to
synthesize models); the legacy freq_cis gap is written as zeros, matching what
``seek`` past EOF produces in the reference converter (converter.py:124-127).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ..models.spec import HEADER_BYTES, TransformerSpec
from ..ops.quants import (
    FloatType,
    pack_q40_bytes,
    quantize_q40,
    unpack_q40_bytes,
)


class Q40Weight(NamedTuple):
    """Planar Q40 tensor: qs uint8 (..., d, n/32, 16), d16 float16 (..., d, n/32).

    This is the codec-canonical layout (it mirrors the wire format's 16
    nibble-bytes per block, reference src/quants.hpp:16-19). The TPU matmul
    kernel wants ``Q40Kernel`` instead — see ``to_kernel_layout``.

    NamedTuple => automatically a jax pytree; usable directly under jit/scan.
    """

    qs: np.ndarray
    d16: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.qs.shape[:-2], self.qs.shape[-2] * 32)


class Q40Kernel(NamedTuple):
    """Kernel-tiled planar Q40: qs_t uint8 (..., 16, d, n/32), scale f32
    (..., d, n/32).

    The nibble-position axis leads so the Pallas kernel (ops/pallas_q40.py)
    streams plain 2D (rows, blocks) tiles whose minor dim is the block index:
    the per-block scale then lines up with the codes elementwise and the
    kernel needs no minor-dim reshape/interleave (which Mosaic does not
    support). Scales are f32 because Mosaic has no f16 vectors — f16->f32 is
    exact, so the value map is unchanged. Produced once at load time by
    ``to_kernel_layout`` — never re-tile inside a jitted per-token step.
    """

    qs_t: np.ndarray
    scale: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.scale.shape[:-1], self.scale.shape[-1] * 32)


class Q40KernelNb(NamedTuple):
    """Lane-aligned kernel tiling for awkward block counts: qs_t uint8
    (..., 16, nb, d), scale f32 (..., nb, d) — the OUTPUT dim d is minor.

    TPU physical layouts tile the last two dims to (8, 128); the standard
    ``Q40Kernel`` puts the block count nb minor, which pads nb up to a
    multiple of 128 — at 13B (dim 5120 -> nb=160 -> padded 256) that is a
    1.6x inflation of both HBM footprint AND every weight-streaming byte
    the decode loop reads. This transposed layout puts d minor instead
    (d is 128-aligned for every Llama shape), so there is NO padding.
    Selected automatically by ``pack_q40_params`` when the padding ratio
    is material; the matvec kernel has a dedicated body for it
    (ops/pallas_q40._matvec_body_nb).
    """

    qs_t: np.ndarray
    scale: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.scale.shape[:-2], self.scale.shape[-1],
                self.scale.shape[-2] * 32)


class Q40KernelI4(NamedTuple):
    """Signed-int4 plane form of ``Q40Kernel``: qs4 int4 (..., 32, d, nb)
    holding (code - 8) directly (range -8..7 fits int4 exactly — planes
    0..15 are the low nibbles, 16..31 the high), scale f32 (..., d, nb).

    DEVICE-ONLY and chain-internal: this runtime cannot pass int4 arrays
    across a jit boundary (dispatch-layer recursion), so the fused decode
    chain materializes this form ON DEVICE from the resident uint8 tree
    at chain start (ops/pallas_q40.to_i4_planes) and the u8 original
    stays the placed argument. Why it exists: the T=1 matvec body drops
    from ~9 to ~3 VPU ops per packed byte (no mask, no shift, one convert,
    no xsum correction) — measured 701 GB/s vs 638 on the 13B w13 shape
    against a 746 GB/s DMA floor (tools/nb_probe.py).
    """

    qs4: np.ndarray
    scale: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.scale.shape[:-1], self.scale.shape[-1] * 32)


class Q40KernelNbI4(NamedTuple):
    """Signed-int4 plane form of ``Q40KernelNb``: qs4 int4 (..., 32, nb, d),
    scale f32 (..., nb, d). See Q40KernelI4 for the why and the
    device-only caveat."""

    qs4: np.ndarray
    scale: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.scale.shape[:-2], self.scale.shape[-1],
                self.scale.shape[-2] * 32)


class Q40KernelI4PackedD(NamedTuple):
    """RESIDENT uint8 carrier of the d-major int4 planes: qs_p uint8
    (..., 32, d, nb/2) packs (code - 8) signed nibbles pairwise along the
    minor dim, LOW nibble = even index — exactly XLA's S4 bit layout, so
    the decode chain turns this into ``Q40KernelI4`` with ONE
    bitcast_convert_type + minor reshape (a reinterpretation, not a
    GB-scale compute pass, and no u8+i4 double residency — the fix for
    the 13B OOM the in-chain conversion hit). uint8 because int4 arrays
    cannot cross this runtime's jit/dispatch boundary. TESTS/EXPERIMENTS
    ONLY: production repack_i4_packed emits only the Nb variant (the
    d-major s4 body and the bitcast-materialized layout both measured as
    hardware negatives — BASELINE.md r5)."""

    qs_p: np.ndarray
    scale: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.scale.shape[:-1], self.scale.shape[-1] * 32)


class Q40KernelI4PackedNb(NamedTuple):
    """nb-major sibling of Q40KernelI4PackedD: qs_p uint8
    (..., 32, nb, d/2), scale f32 (..., nb, d)."""

    qs_p: np.ndarray
    scale: np.ndarray

    @property
    def logical_shape(self) -> tuple[int, ...]:
        return (*self.scale.shape[:-2], self.scale.shape[-1],
                self.scale.shape[-2] * 32)


def to_kernel_layout_nb(w: Q40Weight) -> Q40KernelNb:
    """(..., d, nb, 16) -> (..., 16, nb, d) with f32 scales (..., nb, d)."""
    qs = w.qs
    nd = qs.ndim
    perm = tuple(range(nd - 3)) + (nd - 1, nd - 2, nd - 3)
    qs_t = qs.transpose(perm)
    if isinstance(qs_t, np.ndarray):
        qs_t = np.ascontiguousarray(qs_t)
    sperm = tuple(range(nd - 3)) + (nd - 2, nd - 3)
    scale = w.d16.transpose(sperm).astype(np.float32)
    if isinstance(scale, np.ndarray):
        scale = np.ascontiguousarray(scale)
    return Q40KernelNb(qs_t, scale)


def from_kernel_layout_nb(w: Q40KernelNb) -> Q40Weight:
    qs_t = w.qs_t
    nd = qs_t.ndim
    perm = tuple(range(nd - 3)) + (nd - 1, nd - 2, nd - 3)
    qs = qs_t.transpose(perm)
    if isinstance(qs, np.ndarray):
        qs = np.ascontiguousarray(qs)
    scale = np.ascontiguousarray(np.swapaxes(w.scale, -1, -2))
    return Q40Weight(qs, scale.astype(np.float16))


def to_kernel_layout(w: Q40Weight) -> Q40Kernel:
    """(..., d, nb, 16) -> (..., 16, d, nb), one-time load-side re-tiling.

    numpy inputs go through the THREADED C++ path when the host library is
    available (csrc/host.cpp q40_tile_kernel_layout — this is a GB-scale
    strided transpose at 7B/70B sizes); jax arrays and fallback use the
    numpy transpose.
    """
    qs = w.qs
    if isinstance(qs, np.ndarray) and isinstance(w.d16, np.ndarray):
        from ..utils import native

        tiled = native.q40_tile_kernel_layout(qs, w.d16)
        if tiled is not None:
            return Q40Kernel(*tiled)
    nd = qs.ndim
    perm = tuple(range(nd - 3)) + (nd - 1, nd - 3, nd - 2)
    qs_t = qs.transpose(perm)
    if isinstance(qs_t, np.ndarray):
        qs_t = np.ascontiguousarray(qs_t)
    return Q40Kernel(qs_t, w.d16.astype(np.float32))


def from_kernel_layout(w: Q40Kernel) -> Q40Weight:
    qs_t = w.qs_t
    nd = qs_t.ndim
    perm = tuple(range(nd - 3)) + (nd - 2, nd - 1, nd - 3)
    qs = qs_t.transpose(perm)
    if isinstance(qs, np.ndarray):
        qs = np.ascontiguousarray(qs)
    # scales were exactly upconverted f16->f32; the downcast is lossless
    return Q40Weight(qs, w.scale.astype(np.float16))


def read_spec(path: str, weights_float_type=FloatType.F32,
              buffer_float_type=FloatType.F32) -> TransformerSpec:
    with open(path, "rb") as f:
        raw = f.read(HEADER_BYTES)
    return TransformerSpec.from_header(raw, weights_float_type, buffer_float_type)


class _Walker:
    def __init__(self, mm: np.ndarray, offset: int):
        self.mm = mm
        self.off = offset

    def take(self, nbytes: int) -> np.ndarray:
        chunk = self.mm[self.off:self.off + nbytes]
        if chunk.nbytes != nbytes:
            raise ValueError(
                f"file truncated: wanted {nbytes} bytes at {self.off}, "
                f"got {chunk.nbytes}")
        self.off += nbytes
        return chunk

    def f32(self, shape: tuple[int, ...]) -> np.ndarray:
        n = int(np.prod(shape))
        return self.take(n * 4).view(np.float32).reshape(shape).copy()

    def matmul(self, spec: TransformerSpec, shape: tuple[int, int]):
        ft = spec.weights_float_type
        raw = self.take(spec.matmul_bytes(shape))
        if ft == FloatType.F32:
            return raw.view(np.float32).reshape(shape).copy()
        if ft == FloatType.F16:
            return raw.view(np.float16).reshape(shape).copy()
        if ft == FloatType.Q40:
            qs, d16 = unpack_q40_bytes(raw, shape)  # unpack always copies
            return Q40Weight(qs, d16)
        raise ValueError(f"unsupported weights float type {ft}")


def load_model(path: str, spec: TransformerSpec | None = None,
               weights_float_type=FloatType.F32,
               buffer_float_type=FloatType.F32) -> tuple[TransformerSpec, dict]:
    """Load a .bin file into a stacked-layer numpy param tree.

    Size accounting is byte-exact, like the reference's missedBytes check
    (transformer.cpp:344-348).
    """
    if spec is None:
        spec = read_spec(path, weights_float_type, buffer_float_type)
    mm = np.memmap(path, dtype=np.uint8, mode="r")
    expected = spec.file_size()
    if mm.nbytes != expected:
        raise ValueError(
            f"file size mismatch: {path} has {mm.nbytes} bytes, "
            f"spec requires {expected}")
    w = _Walker(mm, HEADER_BYTES)

    params: dict = {}
    params["tok_embedding"] = w.f32((spec.vocab_size, spec.dim))

    # preallocate the stacked arrays and stream each layer straight into its
    # slot (avoids transiently holding list-of-layers + np.stack copies of
    # multi-GB tensors)
    shapes = spec.layer_matmul_shapes()
    L = spec.n_layers
    ft = spec.weights_float_type
    params["rms_att"] = np.empty((L, spec.dim), np.float32)
    params["rms_ffn"] = np.empty((L, spec.dim), np.float32)
    for name, (dd, nn) in shapes:
        if ft == FloatType.Q40:
            params[name] = Q40Weight(np.empty((L, dd, nn // 32, 16), np.uint8),
                                     np.empty((L, dd, nn // 32), np.float16))
        else:
            dtype = np.float32 if ft == FloatType.F32 else np.float16
            params[name] = np.empty((L, dd, nn), dtype)
    for layer in range(L):
        params["rms_att"][layer] = w.f32((spec.dim,))
        params["rms_ffn"][layer] = w.f32((spec.dim,))
        for name, shape in shapes:
            val = w.matmul(spec, shape)
            if isinstance(val, Q40Weight):
                params[name].qs[layer] = val.qs
                params[name].d16[layer] = val.d16
            else:
                params[name][layer] = val

    params["rms_final"] = w.f32((spec.dim,))
    w.take(spec.rope_gap_bytes)  # legacy freq_cis region, skipped
    params["wcls"] = w.matmul(spec, (spec.vocab_size, spec.dim))

    if w.off != expected:
        raise ValueError(f"missed {expected - w.off} bytes")  # parity check
    return spec, params


class TensorRange(NamedTuple):
    """One tensor's byte placement in the .bin: ``rows`` is the output dim
    for matmul tensors (whose contiguous row bands are what MatmulSlice
    shards — band r of S occupies bytes [offset + r*(nbytes/rows)*(rows/S),
    ...)), None for replicated tensors (norms, embedding) and the rope gap.
    """

    name: str
    layer: int | None
    offset: int
    nbytes: int
    rows: int | None


def tensor_byte_ranges(spec: TransformerSpec) -> list[TensorRange]:
    """The exact byte placement of every tensor in a .bin of ``spec`` —
    the offset table slice-granular weight streaming fetches against
    (io/stream.fetch_model_slices; the reference's root likewise computes
    per-slice offsets into its mmap, transformer.cpp:250-273). Walks the
    same order as load_model; the total is asserted == spec.file_size().
    """
    out: list[TensorRange] = []
    off = HEADER_BYTES

    def add(name, layer, nbytes, rows=None):
        nonlocal off
        out.append(TensorRange(name, layer, off, nbytes, rows))
        off += nbytes

    add("tok_embedding", None, spec.vocab_size * spec.dim * 4)
    shapes = spec.layer_matmul_shapes()
    for layer in range(spec.n_layers):
        add("rms_att", layer, spec.dim * 4)
        add("rms_ffn", layer, spec.dim * 4)
        for name, shape in shapes:
            add(name, layer, spec.matmul_bytes(shape), rows=shape[0])
    add("rms_final", None, spec.dim * 4)
    add("_rope_gap", None, spec.rope_gap_bytes)
    add("wcls", None, spec.matmul_bytes((spec.vocab_size, spec.dim)),
        rows=spec.vocab_size)
    assert off == spec.file_size(), (off, spec.file_size())
    return out


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

def _write_matmul(f, spec: TransformerSpec, x: np.ndarray) -> None:
    ft = spec.weights_float_type
    if ft == FloatType.F32:
        f.write(np.ascontiguousarray(x, dtype=np.float32).tobytes())
    elif ft == FloatType.F16:
        f.write(np.ascontiguousarray(x, dtype=np.float32)
                .astype(np.float16).tobytes())
    elif ft == FloatType.Q40:
        qs, d16 = quantize_q40(np.ascontiguousarray(x, dtype=np.float32))
        f.write(pack_q40_bytes(qs, d16))
    else:
        raise ValueError(f"unsupported weights float type {ft}")


def write_model(path: str, spec: TransformerSpec, tensors: dict) -> None:
    """Write a reference-format .bin from f32 logical tensors.

    ``tensors`` keys match load_model's output (stacked layer axis), values f32.
    """
    with open(path, "wb") as f:
        f.write(spec.header())
        f.write(np.ascontiguousarray(
            tensors["tok_embedding"], dtype=np.float32).tobytes())
        for layer in range(spec.n_layers):
            f.write(np.ascontiguousarray(
                tensors["rms_att"][layer], dtype=np.float32).tobytes())
            f.write(np.ascontiguousarray(
                tensors["rms_ffn"][layer], dtype=np.float32).tobytes())
            for name, _ in spec.layer_matmul_shapes():
                _write_matmul(f, spec, tensors[name][layer])
        f.write(np.ascontiguousarray(
            tensors["rms_final"], dtype=np.float32).tobytes())
        f.write(b"\x00" * spec.rope_gap_bytes)
        _write_matmul(f, spec, tensors["wcls"])
    # byte-exact invariant
    import os

    assert os.path.getsize(path) == spec.file_size()


def densify_params(params: dict) -> dict:
    """Dequantize/upcast a loaded param tree to dense float32 — the training
    entry point (parallel/train.py optimizes dense weights; Q40/F16 files
    are inference formats). Q40Weight leaves decode with the exact codec
    value map; F16 upcasts exactly."""
    from ..ops.quants import dequantize_q40

    out = {}
    for name, val in params.items():
        if isinstance(val, Q40Weight):
            out[name] = dequantize_q40(val.qs, val.d16)
        elif isinstance(val, Q40Kernel):  # pre-tiled: go through the codec
            w = from_kernel_layout(val)
            out[name] = dequantize_q40(w.qs, w.d16)
        elif isinstance(val, Q40KernelNb):  # nb-major pre-tiled likewise
            w = from_kernel_layout_nb(val)
            out[name] = dequantize_q40(w.qs, w.d16)
        else:
            out[name] = np.asarray(val, dtype=np.float32)
    return out
