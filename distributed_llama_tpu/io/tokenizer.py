"""llama2.c-format BPE tokenizer (tokenizer.bin).

File format and algorithm parity with reference src/tokenizer.cpp:31-204:
header int32 max_token_length, then per token {f32 score, int32 len, bytes}.
encode = optional BOS(1) + dummy-prefix space token + UTF-8 codepoint split
with byte-fallback (token = byte + 3) + greedy best-score pair merging.
decode = piece lookup, strip one leading space right after BOS, map '<0xNN>'
byte tokens to raw bytes.
"""

from __future__ import annotations

import re
import struct

BOS = 1
EOS = 2

_BYTE_RE = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")


class Tokenizer:
    def __init__(self, path: str, vocab_size: int):
        self.vocab_size = vocab_size
        self.vocab: list[bytes] = []
        self.scores: list[float] = []
        with open(path, "rb") as f:
            (self.max_token_length,) = struct.unpack("<i", f.read(4))
            for _ in range(vocab_size):
                score, ln = struct.unpack("<fi", f.read(8))
                self.vocab.append(f.read(ln))
                self.scores.append(score)
        self._lookup = {}
        for i, piece in enumerate(self.vocab):
            # first occurrence wins, like bsearch over a stable-sorted table
            self._lookup.setdefault(piece, i)
        # native C++ encoder (csrc/host.cpp tok_encode) when buildable; the
        # Python merge loop below is the always-available fallback
        from ..utils.native import NativeBpe

        self._native = NativeBpe(self.vocab, self.scores)

    def encode(self, text: str | bytes, bos: bool = True,
               eos: bool = False) -> list[int]:
        if isinstance(text, str):
            text = text.encode("utf-8")
        tokens: list[int] = []
        if bos:
            tokens.append(BOS)
        dummy = self._lookup.get(b" ") if text else None

        if text and self._native.available:
            # the dummy-prefix space participates in the merge loop; " " is a
            # single-codepoint chunk, so prepending the byte reproduces
            # append-dummy-then-split exactly
            payload = (b" " + text) if dummy is not None else text
            tokens.extend(self._native.encode(payload))
            if eos:
                tokens.append(EOS)
            return tokens

        if dummy is not None:
            tokens.append(dummy)

        # split into UTF-8 codepoints (max 4 bytes), byte-fallback (+3) on miss
        i = 0
        n = len(text)
        while i < n:
            j = i + 1
            while j < n and (text[j] & 0xC0) == 0x80 and j - i < 4:
                j += 1
            chunk = text[i:j]
            tid = self._lookup.get(chunk)
            if tid is not None:
                tokens.append(tid)
            else:
                tokens.extend(b + 3 for b in chunk)
            i = j

        # greedy highest-score merges (reference tokenizer.cpp:169-194)
        while True:
            best_score = -1e10
            best_id = best_idx = -1
            for k in range(len(tokens) - 1):
                merged = self.vocab[tokens[k]] + self.vocab[tokens[k + 1]]
                tid = self._lookup.get(merged)
                if tid is not None and self.scores[tid] > best_score:
                    best_score, best_id, best_idx = self.scores[tid], tid, k
            if best_idx == -1:
                break
            tokens[best_idx:best_idx + 2] = [best_id]

        if eos:
            tokens.append(EOS)
        return tokens

    def decode_piece(self, prev_token: int, token: int) -> bytes:
        piece = self.vocab[token]
        if prev_token == BOS and piece.startswith(b" "):
            piece = piece[1:]
        m = _BYTE_RE.match(piece.decode("latin-1"))
        if m:
            return bytes([int(m.group(1), 16)])
        return piece

    def decode(self, tokens: list[int]) -> bytes:
        out = []
        prev = BOS
        for t in tokens:
            out.append(self.decode_piece(prev, t))
            prev = t
        return b"".join(out)


def write_tokenizer(path: str, pieces: list[bytes],
                    scores: list[float]) -> None:
    """Write a tokenizer.bin (test fixtures / conversions)."""
    with open(path, "wb") as f:
        f.write(struct.pack("<i", max((len(p) for p in pieces), default=0)))
        for piece, score in zip(pieces, scores):
            f.write(struct.pack("<fi", score, len(piece)))
            f.write(piece)
