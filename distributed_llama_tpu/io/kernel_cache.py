"""Pre-tiled kernel-layout sidecar cache (VERDICT r4 #7).

The reference's model load is mmap-and-stream (transformer.cpp:280-296).
Ours additionally re-tiles every Q40 tensor into the Pallas kernel layout
(csrc/host.cpp q40_tile_kernel_layout) and concatenates the fused
wqkv/w13 stacks — GB-scale host passes that used to repeat on EVERY load.
This module persists the FINAL packed+fused host tree next to the model
(`<model>.kcache`) in one mmap-able file; later loads memory-map the
leaves directly (~0 s host prep, pages stream from disk on demand during
device placement — the same thinness as the reference's loader).

File format (little-endian):
    MAGIC(8) | u32 header_len | header JSON | 4096-aligned raw arrays
header = {"key": layout-key, "entries": [{"name", "kind",
          "arrays": [{"shape", "dtype", "offset", "nbytes"}]}]}
kinds: dense (1 array), q40w (qs, d16), q40k (qs_t, scale),
       q40knb (qs_t, scale).

The layout key captures everything that changes the packed tree's
CONTENTS (kernel mode, matvec row cap, nb-major policy, fusion mode,
format version); a mismatch falls back to a rebuild, never to silently
wrong layouts. DLLAMA_TILED_CACHE=0 disables both read and write.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

from .loader import (Q40Kernel, Q40KernelI4PackedD, Q40KernelI4PackedNb,
                     Q40KernelNb, Q40Weight)

MAGIC = b"DLKC0001"
_ALIGN = 4096

_KINDS = {
    "dense": (None, 1),
    "q40w": (Q40Weight, 2),
    "q40k": (Q40Kernel, 2),
    "q40knb": (Q40KernelNb, 2),
    "q40i4pd": (Q40KernelI4PackedD, 2),
    "q40i4pnb": (Q40KernelI4PackedNb, 2),
}


def _kind_of(v) -> str:
    if isinstance(v, Q40Weight):
        return "q40w"
    if isinstance(v, Q40Kernel):
        return "q40k"
    if isinstance(v, Q40KernelNb):
        return "q40knb"
    if isinstance(v, Q40KernelI4PackedD):
        return "q40i4pd"
    if isinstance(v, Q40KernelI4PackedNb):
        return "q40i4pnb"
    return "dense"


def layout_key(model_path: str | None = None, tp: int = 1,
               weights_float_type=None, buffer_float_type=None) -> str:
    """Everything that decides the packed tree's contents: the layout
    knobs (mirroring the bench shape-manifest key), the float types the
    tree was decoded/packed under (a future packed form for another float
    type must not collide under the same key), AND the model file's
    identity (size + mtime) — overwriting the .bin with a new checkpoint
    at the same path must invalidate the sidecar, never silently serve
    the old weights."""
    from ..ops.linear import q40_kernel_mode
    from ..ops.pallas_layer import fusion_cache_key
    from ..ops.pallas_q40 import _matvec_cap

    # DLLAMA_Q40_I4 is deliberately NOT in this key: the sidecar stores
    # the host u8 tree either way (i4 conversion is in-chain), and keying
    # on it would rebuild the GB-scale sidecar on every flag flip
    src = ""
    if model_path is not None:
        st = os.stat(model_path)
        src += f"|src={st.st_size}:{st.st_mtime_ns}"
    nbm = os.environ.get("DLLAMA_NB_MAJOR", "auto") or "auto"
    wf = getattr(weights_float_type, "name", weights_float_type) or "Q40"
    bf = getattr(buffer_float_type, "name", buffer_float_type) or "F32"
    return (f"v1|{q40_kernel_mode()}|{_matvec_cap()}|{fusion_cache_key()}"
            f"|nb={nbm}|tp={tp}|wf={wf}|bf={bf}{src}")


def sidecar_path(model_path: str) -> str:
    return model_path + ".kcache"


# A build lock older than this is presumed orphaned (holder crashed between
# O_EXCL create and unlink) and is broken. GB-scale sidecar writes take
# minutes, not hours.
_LOCK_STALE_S = 3600.0


def _lock_path(side: str) -> str:
    return side + ".lock"


def try_build_lock(side: str):
    """O_EXCL lock file guarding the sidecar build: two concurrent loads of
    the same model must not BOTH stream GB-scale .tmp<pid> files onto disk
    (ADVICE r5). Returns an opaque token (pass to release_build_lock) or
    None when another live process holds the lock — the caller then skips
    the write; its own load already has the packed tree in memory, and the
    other process's completed sidecar serves every later load."""
    lock = _lock_path(side)
    for _ in range(2):
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, f"{os.getpid()}\n".encode())
            os.close(fd)
            return lock
        except FileExistsError:
            try:
                age = time.time() - os.stat(lock).st_mtime
            except OSError:
                continue  # holder released between open and stat: retry
            if age < _LOCK_STALE_S:
                return None
            # stale: the holder crashed. Claim the break by RENAME (atomic;
            # exactly one racer succeeds) rather than unlink — a bare
            # unlink could delete a FRESH lock another breaker just
            # re-created, letting two writers in
            try:
                claimed = lock + f".stale{os.getpid()}"
                os.rename(lock, claimed)
                # the rename could still have grabbed a FRESH lock (a
                # racing breaker re-created it between our stat and our
                # rename): re-check on the claimed copy, and restore it
                # atomically (link fails if a new lock appeared) if so
                if time.time() - os.stat(claimed).st_mtime < _LOCK_STALE_S:
                    try:
                        os.link(claimed, lock)
                    except OSError:
                        pass  # a newer lock exists; it stands
                    os.unlink(claimed)
                    return None
                os.unlink(claimed)
            except OSError:
                return None  # another breaker won the rename: back off
        except OSError:
            return None  # unwritable dir: save_packed will say so itself
    return None


def release_build_lock(token) -> None:
    try:
        os.unlink(token)
    except OSError:
        pass


def save_packed(path: str, key: str, tree: dict) -> None:
    """Write the packed tree atomically (tmp + rename)."""
    entries = []
    arrays: list[np.ndarray] = []
    off = 0

    def admit(a):
        nonlocal off
        a = np.ascontiguousarray(a)
        off = (off + _ALIGN - 1) // _ALIGN * _ALIGN
        meta = {"shape": list(a.shape), "dtype": a.dtype.str,
                "offset": off, "nbytes": int(a.nbytes)}
        off += a.nbytes
        arrays.append(a)
        return meta

    for name, v in tree.items():
        kind = _kind_of(v)
        fields = [v] if kind == "dense" else list(v)
        entries.append({"name": name, "kind": kind,
                        "arrays": [admit(np.asarray(f)) for f in fields]})
    header = json.dumps({"key": key, "entries": entries}).encode()
    base = len(MAGIC) + 4 + len(header)
    base_pad = (base + _ALIGN - 1) // _ALIGN * _ALIGN

    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            fh.write(MAGIC)
            fh.write(np.uint32(len(header)).tobytes())
            fh.write(header)
            pos = base
            for meta, a in zip(
                    [m for ent in entries for m in ent["arrays"]], arrays):
                want = base_pad + meta["offset"]
                fh.write(b"\x00" * (want - pos))
                fh.write(memoryview(a).cast("B"))
                pos = want + a.nbytes
        os.replace(tmp, path)
    except BaseException:
        # a GB-scale half-written tmp must not outlive a failed write
        # (ENOSPC would otherwise leak an orphan per retrying pid,
        # consuming the very space that made the write fail)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_packed(path: str, key: str) -> dict | None:
    """Memory-map a sidecar written by save_packed; None on any mismatch
    (wrong magic/key/shape trouble) — the caller rebuilds."""
    try:
        with open(path, "rb") as fh:
            if fh.read(len(MAGIC)) != MAGIC:
                return None
            hlen = int(np.frombuffer(fh.read(4), np.uint32)[0])
            header = json.loads(fh.read(hlen).decode())
        if header.get("key") != key:
            print(f"kernel cache key mismatch ({path}): cached for "
                  f"{header.get('key')!r}, want {key!r}; rebuilding",
                  file=sys.stderr)
            return None
        base = len(MAGIC) + 4 + hlen
        base_pad = (base + _ALIGN - 1) // _ALIGN * _ALIGN
        buf = np.memmap(path, dtype=np.uint8, mode="r")
        tree: dict = {}
        for e in header["entries"]:
            fields = []
            for m in e["arrays"]:
                start = base_pad + m["offset"]
                raw = buf[start:start + m["nbytes"]]
                fields.append(raw.view(np.dtype(m["dtype"]))
                              .reshape(m["shape"]))
            cls, n = _KINDS[e["kind"]]
            if len(fields) != n:
                return None
            tree[e["name"]] = fields[0] if cls is None else cls(*fields)
        return tree
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
        print(f"kernel cache unreadable ({type(e).__name__}: {e}); "
              f"rebuilding", file=sys.stderr)
        return None


def cache_enabled() -> bool:
    return os.environ.get("DLLAMA_TILED_CACHE", "1") != "0"


def load_model_packed(path: str, spec=None, weights_float_type=None,
                      buffer_float_type=None):
    """load_model + pack_q40_params + fuse_q40_layer_matmuls, with the
    sidecar shortcut: a valid `<model>.kcache` skips BOTH the .bin walk
    and the GB-scale re-tiling/fusion (the tree's leaves are memmap views
    into the sidecar). Single-chip decode path only — the nb-major leaves
    this packs are rejected by the shard_map sharding specs; mesh runs
    keep load_model + tp-aware packing (parallel/tp.shard_params)."""
    from ..ops.linear import (fuse_q40_layer_matmuls, pack_q40_params,
                              q40_kernel_mode)
    from ..ops.quants import FloatType
    from .loader import load_model, read_spec

    wft = FloatType.Q40 if weights_float_type is None else weights_float_type
    kw = {} if buffer_float_type is None else {
        "buffer_float_type": buffer_float_type}
    packing = wft == FloatType.Q40 and q40_kernel_mode() == "pallas"
    use_cache = cache_enabled() and packing
    side = sidecar_path(path)
    key = layout_key(path, weights_float_type=wft,
                     buffer_float_type=buffer_float_type)
    if use_cache and os.path.exists(side):
        t0 = time.perf_counter()
        if spec is None:
            spec = read_spec(path, wft, **kw)
        tree = load_packed(side, key)
        if tree is not None:
            print(f"⏩ kernel-layout cache hit ({side}): "
                  f"{time.perf_counter() - t0:.1f}s host prep "
                  f"(mmap, 0 bytes re-tiled)", file=sys.stderr)
            return spec, tree
    spec, params = load_model(path, spec=spec, weights_float_type=wft, **kw)
    t0 = time.perf_counter()
    packed = fuse_q40_layer_matmuls(
        pack_q40_params(params, allow_nb_major=True))
    dt = time.perf_counter() - t0
    if packing:
        print(f"kernel re-tile + fuse: {dt:.1f}s", file=sys.stderr)
    if use_cache and any(isinstance(v, (Q40Kernel, Q40KernelNb))
                         for v in packed.values()):
        lock = try_build_lock(side)
        if lock is None:
            print(f"⏩ another process is writing {side}; skipping the "
                  f"sidecar write (this load keeps its in-memory tree)",
                  file=sys.stderr)
            return spec, packed
        try:
            t0 = time.perf_counter()
            save_packed(side, key, packed)
            print(f"⏩ kernel-layout cache written ({side}, "
                  f"{os.path.getsize(side) / 1e9:.2f} GB, "
                  f"{time.perf_counter() - t0:.1f}s); next load skips "
                  f"re-tiling", file=sys.stderr)
        except OSError as e:
            print(f"kernel cache not written ({e}); loads keep re-tiling",
                  file=sys.stderr)
        finally:
            release_build_lock(lock)
    return spec, packed
