"""Pallas TPU kernel: flash-decode attention over the PAGED page-pool KV
cache (ISSUE 11 — the vLLM/PagedAttention move, Kwon et al. SOSP'23).

PR 6 made the paged pool the production layout but left it on the slowest
attention path: ``models/llama.paged_decode_attention`` falls back to an
XLA gather that materializes the row's whole virtual (B, S, n_kv, hs)
plane in HBM every token (``jnp.take`` over the pool), because the
contiguous flash kernel (ops/pallas_attention.py) assumes one contiguous
cache row. This kernel walks the page table DIRECTLY: block = page is the
natural tiling, and the DMA loop indexes each K/V page plane through the
per-row int32 table — page i+1 prefetches while page i reduces, riding
the SAME double-buffered machinery as the contiguous kernel
(``pallas_attention._flash_walk``) with flash-decoding-style (Dao et al.)
split-KV (m, l, o) accumulation. HBM traffic becomes pos-proportional
again (live pages only) and the gather copy disappears.

Shapes: ONE kernel covers both hot paged shapes — single-token decode
(t_len=1, the forward_batch_paged step) and the (B, K) speculative-verify
window (t_len=K, forward_batch_spec_paged; query i of a row sees virtual
positions 0..pos+i, the stacked causal windows of sequential decode).

KV dtypes: f32/bf16 pages DMA raw planes; Q8 pages
(``DLLAMA_KV_QUANT=q8``) DMA the int8 code planes PLUS the per-position
f16 Q80 block-delta planes and dequantize inside the page loop — the
same ``codes.astype(f32) * delta.astype(f32)`` value map as the XLA
fallback's gather-side dequant (ops/quants.dequantize_q80_jax), so both
routes see identical f32 K/V values.

Parity contract (tests/test_pallas_paged_attention.py): the kernel is
INVARIANT to physical page placement — any permutation of the pool that
updates the table produces bitwise-identical output — and element-level
equal to the XLA gather path at the documented flash tolerance (the
split-KV accumulation reassociates the softmax sums across page
boundaries; the reduction-order deltas are ~1e-7 at f32, the same
reassociation-only contract as the prefill flash kernel). The XLA gather
fallback itself stays BITWISE equal to the contiguous cache — the PR 6
gate — and is what CPU engines run (``attn_kernel_mode()`` auto-selects
'xla' off-TPU, exactly like the contiguous kernel's gate).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.quants import QK
from .pallas_attention import (_VMEM64_PARAMS, _VMEM_BUDGET, NEG_INF,
                               _flash_walk, attn_kernel_mode)

KV_QUANTS = ("f32", "q8")  # the --kv-quant vocabulary (f32 = cache dtype)


def kv_quant_mode() -> str:
    """The KV page quantization in effect: DLLAMA_KV_QUANT=f32|q8,
    overridden by the CLI --kv-quant flag (which sets the env var, the
    DLLAMA_TP_SCHEME pattern — one resolution point, launch scripts and
    flags agree). Unknown values raise: a typo would otherwise silently
    serve f32 pages and read as 'no capacity win'."""
    import os

    env = os.environ.get("DLLAMA_KV_QUANT") or "f32"  # '' = unset
    if env not in KV_QUANTS:
        raise ValueError(f"DLLAMA_KV_QUANT={env!r}: expected "
                         f"{'|'.join(KV_QUANTS)}")
    return env


def _paged_scratch_bytes(page_size: int, n_kv: int, hs: int,
                         itemsize: int, q8: bool) -> int:
    """2 slots x {K, V} page planes, plus the Q8 scale planes (f16, one
    delta per QK values of the flattened (n_kv, hs) position row)."""
    planes = 2 * 2 * page_size * n_kv * hs * itemsize
    if q8:
        planes += 2 * 2 * page_size * (n_kv * hs // QK) * 2
    return planes


def supports_paged(page_size: int, n_kv: int, head_size: int, t_len: int,
                   itemsize: int = 4, q8: bool = False) -> bool:
    """The kernel handles decode/verify windows up to 8 queries with
    lane-width head_size and a page plane whose double-buffered scratch
    fits the VMEM budget; Q8 pages additionally need the flattened
    (n_kv, hs) row to divide into Q80 blocks. Callers take the XLA gather
    fallback otherwise — same gating contract as the contiguous
    ``supports()``."""
    if q8 and (n_kv * head_size) % QK:
        return False
    return (1 <= t_len <= 8 and head_size % 128 == 0
            and _paged_scratch_bytes(page_size, n_kv, head_size, itemsize,
                                     q8) <= _VMEM_BUDGET)


def _flash_pages(b, pos, q, table_ref, layer_ref, read_page, *,
                 page_size: int, n_pages: int, max_pages: int, kv_mul: int,
                 t_len: int):
    """The paged flash walk for one batch row: double-buffered page DMA
    through the table (``_flash_walk`` — the contiguous kernel's loop),
    (m, l, o) accumulation widened to t_len queries. ``read_page`` is the
    dtype hook: (slot, i, row) -> (start, wait) where wait(slot) returns
    the landed page as f32 (k, v) planes — raw planes for f32/bf16 pages,
    in-loop Q80 dequant for q8 pages. q: (t_len, n_kv, kv_mul, hs)."""
    n_kv, hs = q.shape[1], q.shape[3]
    scale = 1.0 / jnp.sqrt(jnp.float32(hs))
    s_virt = max_pages * page_size
    # live pages: the deepest query's position, clamped into the virtual
    # plane (a budget-edge verify window walks every mapped page; its
    # beyond-plane dead writes went to the scrap page and are never read)
    last = jnp.minimum(pos + t_len - 1, s_virt - 1)
    n_live = last // page_size + 1
    q_pos = pos + jax.lax.broadcasted_iota(jnp.int32, (t_len, 1, 1), 0)

    def row_of(i):
        # the page-table indirection: logical page i of row b lives at
        # physical plane table[b, i] of layer layer_ref[0]
        return layer_ref[0] * n_pages + table_ref[b, i]

    def start_dma(slot, i):
        read_page(slot, row_of(i)).start()

    def wait_dma(slot, i):
        read_page(slot, row_of(i)).wait()

    def update(i, slot, carry):
        k, v = read_page.landed(slot)                # (ps, n_kv, hs) f32
        key_pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, n_kv), 0)
        valid = key_pos[None] <= q_pos               # (t, ps, n_kv)
        out = []
        for mqi in range(kv_mul):
            m_old, l_old, o_old = carry[mqi]         # (t,n_kv),(t,n_kv),
            #                                          (t,n_kv,hs)
            qm = q[:, :, mqi, :]                     # (t, n_kv, hs)
            s = jnp.sum(k[None] * qm[:, None], axis=-1) * scale
            s = jnp.where(valid, s, NEG_INF)         # (t, ps, n_kv)
            m_new = jnp.maximum(m_old, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])          # (t, ps, n_kv)
            corr = jnp.exp(m_old - m_new)            # (t, n_kv)
            l_new = l_old * corr + jnp.sum(p, axis=1)
            po = jnp.sum(p[..., None] * v[None], axis=1)   # (t, n_kv, hs)
            o_new = o_old * corr[..., None] + po
            out.append((m_new, l_new, o_new))
        return tuple(out)

    init = tuple((jnp.full((t_len, n_kv), NEG_INF, jnp.float32),
                  jnp.zeros((t_len, n_kv), jnp.float32),
                  jnp.zeros((t_len, n_kv, hs), jnp.float32))
                 for _ in range(kv_mul))
    return _flash_walk(n_live, start_dma, wait_dma, update, init)


class _RawPages:
    """f32/bf16 page reader: one K + one V plane DMA per page."""

    def __init__(self, k_hbm, v_hbm, k_buf, v_buf, sems):
        self.k_hbm, self.v_hbm = k_hbm, v_hbm
        self.k_buf, self.v_buf = k_buf, v_buf
        self.sems = sems

    def __call__(self, slot, row):
        reader = self

        class _Pair:
            def start(self):
                pltpu.make_async_copy(reader.k_hbm.at[row],
                                      reader.k_buf.at[slot],
                                      reader.sems.at[slot, 0]).start()
                pltpu.make_async_copy(reader.v_hbm.at[row],
                                      reader.v_buf.at[slot],
                                      reader.sems.at[slot, 1]).start()

            def wait(self):
                pltpu.make_async_copy(reader.k_hbm.at[row],
                                      reader.k_buf.at[slot],
                                      reader.sems.at[slot, 0]).wait()
                pltpu.make_async_copy(reader.v_hbm.at[row],
                                      reader.v_buf.at[slot],
                                      reader.sems.at[slot, 1]).wait()

        return _Pair()

    def landed(self, slot):
        return (self.k_buf[slot].astype(jnp.float32),
                self.v_buf[slot].astype(jnp.float32))


class _Q8Pages:
    """Q8 page reader: int8 code planes + f16 Q80 delta planes (4 DMAs per
    page), dequantized on land with the exact XLA-fallback value map
    (codes.astype(f32).reshape(ps, nb, QK) * d.astype(f32)[..., None])."""

    def __init__(self, kq_hbm, kd_hbm, vq_hbm, vd_hbm, kq_buf, kd_buf,
                 vq_buf, vd_buf, sems):
        self.planes = ((kq_hbm, kq_buf, 0), (kd_hbm, kd_buf, 1),
                       (vq_hbm, vq_buf, 2), (vd_hbm, vd_buf, 3))
        self.sems = sems

    def __call__(self, slot, row):
        reader = self

        class _Quad:
            def start(self):
                for hbm, buf, j in reader.planes:
                    pltpu.make_async_copy(hbm.at[row], buf.at[slot],
                                          reader.sems.at[slot, j]).start()

            def wait(self):
                for hbm, buf, j in reader.planes:
                    pltpu.make_async_copy(hbm.at[row], buf.at[slot],
                                          reader.sems.at[slot, j]).wait()

        return _Quad()

    def landed(self, slot):
        from ..ops.quants import dequantize_q80_planes

        (_, kq_buf, _), (_, kd_buf, _), (_, vq_buf, _), (_, vd_buf, _) = \
            self.planes
        return (dequantize_q80_planes(kq_buf[slot], kd_buf[slot]),
                dequantize_q80_planes(vq_buf[slot], vd_buf[slot]))


def _write_flash_out(final, out_ref, kv_mul: int):
    """THE (m, l, o) -> output normalization epilogue, shared by the f32
    and q8 kernels so a change to the finalization cannot drift between
    the two routes (they differ ONLY in how pages land in VMEM)."""
    for mqi in range(kv_mul):
        _, l_i, o_i = final[mqi]
        out_ref[0, :, :, mqi, :] = o_i / l_i[..., None]


def _kernel_paged(layer_ref, pos_ref, table_ref, q_ref, k_hbm, v_hbm,
                  out_ref, k_buf, v_buf, sems, *, page_size: int,
                  kv_mul: int, n_pages: int, t_len: int):
    """grid=(B,): program b flash-walks its live pages through the table.
    q_ref/out_ref: per-b (1, t_len, n_kv, kv_mul, hs) VMEM blocks;
    k/v_hbm: (L*P, ps, n_kv, hs) pool planes in HBM; k/v_buf: (2, ps,
    n_kv, hs) VMEM scratch; sems (2, 2) DMA semaphores (slot x {k, v})."""
    b = pl.program_id(0)
    reader = _RawPages(k_hbm, v_hbm, k_buf, v_buf, sems)
    final = _flash_pages(b, pos_ref[b], q_ref[0], table_ref, layer_ref,
                         reader, page_size=page_size, n_pages=n_pages,
                         max_pages=table_ref.shape[1], kv_mul=kv_mul,
                         t_len=t_len)
    _write_flash_out(final, out_ref, kv_mul)


def _kernel_paged_q8(layer_ref, pos_ref, table_ref, q_ref, kq_hbm, kd_hbm,
                     vq_hbm, vd_hbm, out_ref, kq_buf, kd_buf, vq_buf,
                     vd_buf, sems, *, page_size: int, kv_mul: int,
                     n_pages: int, t_len: int):
    """_kernel_paged's Q8 twin: int8 code + f16 delta planes per page,
    dequantized inside the page loop; sems (2, 4)."""
    b = pl.program_id(0)
    reader = _Q8Pages(kq_hbm, kd_hbm, vq_hbm, vd_hbm, kq_buf, kd_buf,
                      vq_buf, vd_buf, sems)
    final = _flash_pages(b, pos_ref[b], q_ref[0], table_ref, layer_ref,
                         reader, page_size=page_size, n_pages=n_pages,
                         max_pages=table_ref.shape[1], kv_mul=kv_mul,
                         t_len=t_len)
    _write_flash_out(final, out_ref, kv_mul)


@functools.partial(jax.jit, static_argnames=("page_size", "n_pages",
                                             "kv_mul", "t_len",
                                             "interpret"))
def paged_decode_attention_kernel(q, k4, v4, layer, pos, table, *,
                                  page_size: int, n_pages: int,
                                  kv_mul: int, t_len: int = 1,
                                  interpret: bool | None = None):
    """Paged flash-decode attention over the rank-4 (L*P, ps, n_kv, hs)
    pool planes carried by models/llama.forward_batch_paged.

    q: (B, t_len, n_q*hs) f32; pos: (B,) per-row clocks; table:
    (B, max_pages) int32 physical page ids in logical order. Returns
    (B, t_len, n_q * hs) f32. Gate with supports_paged()."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    LP, ps, n_kv, hs = k4.shape
    B = q.shape[0]
    qg = q.reshape(B, t_len, n_kv, kv_mul, hs).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_kernel_paged, page_size=page_size,
                          kv_mul=kv_mul, n_pages=n_pages, t_len=t_len),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, t_len, n_kv, kv_mul, hs),
                         lambda b: (b, 0, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, t_len, n_kv, kv_mul, hs),
                               lambda b: (b, 0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, t_len, n_kv, kv_mul, hs),
                                       jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, ps, n_kv, hs), k4.dtype),
            pltpu.VMEM((2, ps, n_kv, hs), k4.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1),
      jnp.asarray(pos, jnp.int32).reshape(B),
      jnp.asarray(table, jnp.int32), qg, k4, v4)
    return out.reshape(B, t_len, n_kv * kv_mul * hs)


@functools.partial(jax.jit, static_argnames=("page_size", "n_pages",
                                             "kv_mul", "t_len",
                                             "interpret"))
def paged_decode_attention_kernel_q8(q, kq4, kd4, vq4, vd4, layer, pos,
                                     table, *, page_size: int,
                                     n_pages: int, kv_mul: int,
                                     t_len: int = 1,
                                     interpret: bool | None = None):
    """Q8 twin of paged_decode_attention_kernel: pool planes are the Q80
    int8 codes (L*P, ps, n_kv, hs) plus f16 block deltas (L*P, ps, nb),
    dequantized inside the kernel's page loop."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    LP, ps, n_kv, hs = kq4.shape
    nb = n_kv * hs // QK
    B = q.shape[0]
    qg = q.reshape(B, t_len, n_kv, kv_mul, hs).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_kernel_paged_q8, page_size=page_size,
                          kv_mul=kv_mul, n_pages=n_pages, t_len=t_len),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, t_len, n_kv, kv_mul, hs),
                         lambda b: (b, 0, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, t_len, n_kv, kv_mul, hs),
                               lambda b: (b, 0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, t_len, n_kv, kv_mul, hs),
                                       jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, ps, n_kv, hs), jnp.int8),
            pltpu.VMEM((2, ps, nb), jnp.float16),
            pltpu.VMEM((2, ps, n_kv, hs), jnp.int8),
            pltpu.VMEM((2, ps, nb), jnp.float16),
            pltpu.SemaphoreType.DMA((2, 4)),
        ],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1),
      jnp.asarray(pos, jnp.int32).reshape(B),
      jnp.asarray(table, jnp.int32), qg, kq4, kd4, vq4, vd4)
    return out.reshape(B, t_len, n_kv * kv_mul * hs)


def would_use_paged_kernel(page_size: int, n_kv: int, head_size: int,
                           t_len: int, itemsize: int = 4,
                           q8: bool = False) -> bool:
    """The routing gate's VERDICT, queryable without running it: mode
    check + shape support exactly as maybe_paged_flash_decode applies
    them. Anything that needs to predict the route (the engine's q8
    fallback warning, future bench columns) asks HERE instead of
    re-deriving the gate — one source of truth, no drift."""
    return (attn_kernel_mode() == "pallas"
            and supports_paged(page_size, n_kv, head_size, t_len,
                               itemsize, q8=q8))


def maybe_paged_flash_decode(q, planes, idx, pos, table, *, page_size: int,
                             n_pages: int, head_size: int, t_len: int,
                             n_kv: int, kv_mul: int, kv_quant: str = "f32"):
    """The ONE gate for routing paged decode/verify attention to the paged
    flash kernel — models/llama.paged_decode_attention and
    spec_verify_attention (and through them BOTH tp factories,
    make_sharded_forward_batch_paged / make_sharded_verify, under all
    three collective schemes) call this, so the mode/shape gating can
    never drift between the five call sites.

    q: (B, t_len, n_q*hs); ``planes`` is (k4, v4) for f32/bf16 pages or
    (kq4, kd4, vq4, vd4) for Q8 pages — the rank-4 (L*P, ps, ...) carry
    views. Returns (B, t_len, n_q*hs) f32, or None when the caller must
    take its XLA gather fallback (kernel disabled or shape unsupported).
    """
    q8 = kv_quant == "q8"
    itemsize = 1 if q8 else planes[0].dtype.itemsize
    if not would_use_paged_kernel(page_size, n_kv, head_size, t_len,
                                  itemsize, q8=q8):
        return None
    B = q.shape[0]
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    if q8:
        kq4, kd4, vq4, vd4 = planes
        return paged_decode_attention_kernel_q8(
            q, kq4, kd4, vq4, vd4, idx, pos_b, table, page_size=page_size,
            n_pages=n_pages, kv_mul=kv_mul, t_len=t_len)
    k4, v4 = planes
    return paged_decode_attention_kernel(
        q, k4, v4, idx, pos_b, table, page_size=page_size,
        n_pages=n_pages, kv_mul=kv_mul, t_len=t_len)
