"""Q40 / Q80 block quantization codecs.

Format parity with the reference (same wire/file bytes, same decoded values):

* Q40 (reference src/quants.hpp:16-19, converter/converter.py:13-43): blocks of
  32 values -> one float16 delta + 16 bytes of packed 4-bit codes. Byte ``i``
  holds code ``i`` in its low nibble and code ``i+16`` in its high nibble.
  Decode is ``(code - 8) * delta`` (src/quants.cpp:133-180). Encode picks
  ``delta = signed-max-magnitude / -8``, scales by ``1/delta`` (computed in f32
  *before* the f16 rounding of delta), offsets by +8.5, clamps to 15 and
  truncates — exactly converter.py:22-28.

* Q80 (src/quants.hpp:21-24, src/quants.cpp:182-262): blocks of 32 values ->
  one float16 delta + 32 int8. ``delta = amax/127``; codes round to nearest
  with ties-to-even (the reference's NEON ``vcvtnq_s32_f32``; its scalar
  fallback uses roundf — we follow the NEON semantics the published numbers
  were measured with). Decode is ``code * delta``.

float16<->float32 conversion uses IEEE semantics via numpy, which matches the
reference's 65536-entry LUT (src/quants.cpp:49-92) on all values.

Two array layouts are provided:
* "planar" — ``(qs, d)`` pairs of ndarrays, the layout device code wants
  (scales and codes in separate, densely-typed arrays);
* "wire"   — the reference's interleaved block bytes for file/network parity.
"""

from __future__ import annotations

import enum

import numpy as np

QK = 32  # block size for both Q40 and Q80 (reference src/quants.hpp:13-14)


class FloatType(enum.IntEnum):
    """Weight/buffer dtypes, same codes as reference src/quants.hpp:6-11."""

    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3


_BLOCK_BYTES = {
    FloatType.F32: (1, 4),     # (values per batch, bytes per batch)
    FloatType.F16: (1, 2),
    FloatType.Q40: (QK, 18),   # f16 delta + 16 nibble bytes
    FloatType.Q80: (QK, 34),   # f16 delta + 32 int8
}


def numbers_per_batch(ftype: FloatType) -> int:
    """Reference ``getNumbersPerBatch`` (src/quants.cpp:17-28)."""
    return _BLOCK_BYTES[FloatType(ftype)][0]


def batch_bytes(ftype: FloatType, n: int, d: int = 1) -> int:
    """Reference ``getBatchBytes`` (src/quants.cpp:30-47): bytes of an n*d tensor.

    Validates per-row divisibility (n % 32), like the reference: quant blocks
    never span rows.
    """
    per, nbytes = _BLOCK_BYTES[FloatType(ftype)]
    if n % per != 0:
        raise ValueError(f"row length {n} not divisible by block size {per}")
    return (n // per) * d * nbytes


# ---------------------------------------------------------------------------
# Q40
# ---------------------------------------------------------------------------

def quantize_q40(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Encode f32 -> (qs uint8 [..., n/32, 16], delta float16 [..., n/32]).

    Matches converter.py:13-43 bit-for-bit (including the f32-reciprocal-of-
    unrounded-delta detail and the +8.5/clamp-15/truncate code mapping).
    """
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    if n % QK != 0:
        raise ValueError(f"last dim {n} not divisible by {QK}")
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    gmax = g.max(axis=-1)
    gmin = g.min(axis=-1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / np.float32(-8.0)
    deltas16 = deltas.astype(np.float16)
    with np.errstate(divide="ignore"):  # zero blocks take the where-branch
        ids = np.where(deltas != 0, np.float32(1.0) / deltas, np.float32(0.0))
    q = g * ids[..., None] + np.float32(8.5)
    # np.where (not minimum): converter.py:27 semantics, NaN clamps to 15
    q = np.where(q < np.float32(15.0), q, np.float32(15.0))
    q = q.astype(np.int32)  # truncation toward zero, like int() in the converter
    lo = q[..., :QK // 2] & 0xF
    hi = q[..., QK // 2:] & 0xF
    qs = (lo | (hi << 4)).astype(np.uint8)
    return qs, deltas16


def dequantize_q40(qs: np.ndarray, d: np.ndarray) -> np.ndarray:
    """Decode (qs uint8 [..., nb, 16], d f16 [..., nb]) -> f32 [..., nb*32]."""
    lo = (qs & 0xF).astype(np.int8) - np.int8(8)
    hi = (qs >> 4).astype(np.int8) - np.int8(8)
    codes = np.concatenate([lo, hi], axis=-1).astype(np.float32)  # [..., nb, 32]
    y = codes * d.astype(np.float32)[..., None]
    return y.reshape(*qs.shape[:-2], qs.shape[-2] * QK)


def pack_q40_bytes(qs: np.ndarray, d: np.ndarray) -> bytes:
    """Planar -> reference wire bytes (f16 delta || 16 qs bytes per block)."""
    nb = int(np.prod(qs.shape[:-1]))
    out = np.empty((nb, 18), dtype=np.uint8)
    out[:, :2] = d.reshape(nb, 1).view(np.uint8)
    out[:, 2:] = qs.reshape(nb, 16)
    return out.tobytes()


def unpack_q40_bytes(buf: np.ndarray | bytes, shape: tuple[int, ...]) -> tuple[np.ndarray, np.ndarray]:
    """Reference wire bytes -> planar (qs [..., nb, 16], d [..., nb]).

    ``shape`` is the logical f32 shape, last dim divisible by 32.
    """
    n = shape[-1]
    nb = n // QK
    lead = tuple(shape[:-1])
    raw = np.frombuffer(buf, dtype=np.uint8).reshape(*lead, nb, 18)
    # always materialize fresh writable arrays (never alias the input buffer)
    d = raw[..., :2].copy().view(np.float16)[..., 0]
    qs = raw[..., 2:].copy()
    return qs, d


# ---------------------------------------------------------------------------
# Q80
# ---------------------------------------------------------------------------

def quantize_q80(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Encode f32 -> (qs int8 [..., nb, 32], delta float16 [..., nb])."""
    x = np.asarray(x, dtype=np.float32)
    n = x.shape[-1]
    if n % QK != 0:
        raise ValueError(f"last dim {n} not divisible by {QK}")
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    amax = np.abs(g).max(axis=-1)
    d = amax / np.float32(127.0)
    with np.errstate(divide="ignore"):  # zero blocks take the where-branch
        id_ = np.where(d != 0, np.float32(1.0) / d, np.float32(0.0))
    qs = np.rint(g * id_[..., None]).astype(np.int8)  # ties-to-even, NEON vcvtnq
    return qs, d.astype(np.float16)


def dequantize_q80(qs: np.ndarray, d: np.ndarray) -> np.ndarray:
    y = qs.astype(np.float32) * d.astype(np.float32)[..., None]
    return y.reshape(*qs.shape[:-2], qs.shape[-2] * QK)


def pack_q80_bytes(qs: np.ndarray, d: np.ndarray) -> bytes:
    nb = int(np.prod(qs.shape[:-1]))
    out = np.empty((nb, 34), dtype=np.uint8)
    out[:, :2] = d.reshape(nb, 1).view(np.uint8)
    out[:, 2:] = qs.reshape(nb, 32).view(np.uint8)
    return out.tobytes()


def unpack_q80_bytes(buf: np.ndarray | bytes, shape: tuple[int, ...]) -> tuple[np.ndarray, np.ndarray]:
    n = shape[-1]
    nb = n // QK
    lead = tuple(shape[:-1])
    raw = np.frombuffer(buf, dtype=np.uint8).reshape(*lead, nb, 34)
    d = raw[..., :2].copy().view(np.float16)[..., 0]
    qs = raw[..., 2:].copy().view(np.int8)
    return qs, d


# ---------------------------------------------------------------------------
# JAX (on-device) variants
# ---------------------------------------------------------------------------
# Imported lazily so pure-IO users (the converter) never pay for jax import.

def dequantize_q40_jax(qs, d):
    """jnp decode of planar Q40 -> f32 [..., nb*32]. Same value map as numpy."""
    import jax.numpy as jnp

    lo = (qs & 0xF).astype(jnp.int8) - jnp.int8(8)
    hi = (qs >> 4).astype(jnp.int8) - jnp.int8(8)
    codes = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
    y = codes * d.astype(jnp.float32)[..., None]
    return y.reshape(*qs.shape[:-2], qs.shape[-2] * QK)


def quantize_q80_jax(x):
    """jnp encode f32 -> (qs int8, d f16); jnp.rint is ties-to-even like NEON."""
    import jax.numpy as jnp

    n = x.shape[-1]
    g = x.reshape(*x.shape[:-1], n // QK, QK)
    amax = jnp.abs(g).max(axis=-1)
    d = amax / jnp.float32(127.0)
    id_ = jnp.where(d != 0, jnp.float32(1.0) / d, jnp.float32(0.0))
    qs = jnp.rint(g * id_[..., None]).astype(jnp.int8)
    return qs, d.astype(jnp.float16)


def dequantize_q80_jax(qs, d):
    import jax.numpy as jnp

    y = qs.astype(jnp.float32) * d.astype(jnp.float32)[..., None]
    return y.reshape(*qs.shape[:-2], qs.shape[-2] * QK)


def dequantize_q80_planes(codes, d):
    """Q80 decode for PLANE-shaped codes (..., n_kv, hs) with per-block
    deltas (..., nb = n_kv*hs/QK) — the q8 KV-page layout (ISSUE 11).

    THE one value map every q8 KV read route shares (the paged Pallas
    kernel's page loop, the XLA gather fallback, and the prefill
    gather): blocks run over the flattened head-major (n_kv, hs) row, so
    all routes see identical f32 values and the kernel/fallback parity
    contract reduces to reduction order alone."""
    *lead, n_kv, hs = codes.shape
    y = dequantize_q80_jax(codes.reshape(*lead, n_kv * hs // QK, QK), d)
    return y.reshape(*lead, n_kv, hs)
