"""Registry of the sanctioned Q40 dequantization sites.

A Q40 weight must live in HBM as packed codes + scales; materializing its
f32 form costs 8x the bytes (and on the XLA fallback path it is the single
largest transient in the program). Exactly a handful of functions are
ALLOWED to do that materialization:

* ``ops/linear.dequantize_weight`` — the XLA dequantize-then-dot fallback
  (and the parity/test path on CPU). On the Pallas serving path the same
  values are produced in-kernel from VMEM tiles and never hit HBM.
* ``ops/pallas_q40`` internals — in-kernel/per-tile dequant helpers and the
  i4-carrier unpackers (layout reinterpretations of resident packed bytes).
* ``parallel/tp._wire_gather`` / ``_wire`` and ``ops/linear.fake_quant_q80``
  — the Q80 *buffer* codec on activation vectors (dim-sized, not
  weight-sized; listed so the int8->f32 detector does not misread the wire
  path as a weight dequant).

``analysis/shardcheck.py`` enforces this as contract J005: any large
int->f32 materialization in a traced forward whose call stack touches none
of these sites is a rogue dequant — a weight-sized f32 copy the memory
model does not account for. The registry lives in ops/ (next to the codecs)
so a new sanctioned site lands here, beside its implementation, and the
checker follows automatically. ``tests/test_shardcheck_repo.py`` pins every
entry to a real function so the registry cannot rot.
"""

from __future__ import annotations

# (repo-relative file suffix, function name) pairs. The function name is
# what jax source_info records per traced eqn; the file suffix disambiguates
# same-named helpers across modules.
ALLOWED_DEQUANT_SITES: tuple[tuple[str, str], ...] = (
    ("ops/linear.py", "dequantize_weight"),
    ("ops/linear.py", "fake_quant_q80"),
    ("ops/pallas_q40.py", "unpack_i4_packed"),
    ("ops/pallas_q40.py", "_dequant_i4"),
    ("ops/pallas_q40.py", "_dequant_nb"),
    ("parallel/tp.py", "_wire_gather"),
    ("parallel/tp.py", "_wire"),
)


def frame_allowed(file_name: str, function_name: str) -> bool:
    """Is one (file, function) stack frame a registered dequant site?"""
    for suffix, fn in ALLOWED_DEQUANT_SITES:
        if function_name == fn and file_name.replace("\\", "/").endswith(
                suffix):
            return True
    return False


def frames_allowed(frames) -> bool:
    """True when ANY frame of an eqn's user stack is a registered site.

    ``frames`` yields objects with ``file_name``/``function_name`` (the
    jax source_info user-frame surface).
    """
    return any(frame_allowed(f.file_name, f.function_name) for f in frames)
