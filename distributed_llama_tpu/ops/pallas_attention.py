"""Pallas TPU kernel: single-token flash-decode attention over the stacked
KV cache.

The XLA attention path reads the ENTIRE static (seq_len, n_kv, hs) cache
plane every token (static shapes force it), so decode attention costs
seq_len-proportional HBM traffic even at pos=3. This kernel is the
TPU-native replacement for the hot T=1 case: it DMAs only the ceil((pos+1)/C)
LIVE chunks of K/V out of the stacked (L, S, n_kv, hs) HBM cache (layer and
pos arrive as scalars; a lax.fori_loop with a data-dependent trip count walks
the chunks, double-buffered), accumulating flash-style running (m, l, o)
per head in VMEM. Attention cost becomes pos-proportional — the shape of the
reference's own per-position attention loop (transformer-tasks.cpp:246-276),
which scans exactly 0..pos, not 0..seqLen.

Numerics: f32 throughout, max-subtracted softmax, GQA via a static python
loop over the kv_mul query heads per kv head — same math as
models/llama.attention_core (the parity anchor; the interpret-mode test
checks element-level agreement).

Scores/weighted sums are computed on the VPU (broadcast-multiply-reduce over
the head dim): per-head matvecs are too thin for the MXU, and the kernel is
DMA-bound at decode shapes anyway.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = float("-inf")


def _flash_walk(n_chunks, start_dma, wait_dma, update, init):
    """THE double-buffered flash DMA loop, shared by the contiguous kernels
    here and the paged kernels (ops/pallas_paged_attention.py): start chunk
    0, then per iteration prefetch chunk i+1 into the other slot while
    chunk i is reduced into the carry. ``start_dma(slot, i)`` issues the
    copies for chunk i, ``wait_dma(slot, i)`` blocks on them, and
    ``update(i, slot, carry)`` folds the landed chunk into the running
    (m, l, o) state."""
    start_dma(0, 0)

    def body(i, carry):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_chunks)
        def _():
            start_dma(jax.lax.rem(i + 1, 2), i + 1)

        wait_dma(slot, i)
        return update(i, slot, carry)

    return jax.lax.fori_loop(0, n_chunks, body, init)


def _flash_over_row(row, pos, q, k_hbm, v_hbm, k_buf, v_buf, sems, *,
                    chunk: int, kv_mul: int):
    """Shared flash loop: walk the live chunks of cache row ``row`` (an index
    into the leading dim of the (R, S, n_kv, hs) HBM caches), double-buffered
    DMA, running (m, l, o) per query-head-in-group carried as flat tuples
    (static kv_mul unroll; functional .at-column updates don't lower well).
    q: (n_kv, kv_mul, hs). Returns the kv_mul final (m, l, o) tuples."""
    n_kv = q.shape[0]
    hs = q.shape[2]
    n_chunks = pos // chunk + 1  # live chunks only

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[row, pl.ds(i * chunk, chunk)], k_buf.at[slot],
            sems.at[slot, 0])

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[row, pl.ds(i * chunk, chunk)], v_buf.at[slot],
            sems.at[slot, 1])

    def start_dma(slot, i):
        k_dma(slot, i).start()
        v_dma(slot, i).start()

    def wait_dma(slot, i):
        k_dma(slot, i).wait()
        v_dma(slot, i).wait()

    scale = 1.0 / jnp.sqrt(jnp.float32(hs))

    def update(i, slot, carry):
        k = k_buf[slot]                              # (chunk, n_kv, hs)
        v = v_buf[slot]

        key_pos = i * chunk + jax.lax.broadcasted_iota(
            jnp.int32, (chunk, n_kv), 0)
        valid = key_pos <= pos                       # (chunk, n_kv)

        out = []
        for mqi in range(kv_mul):
            m_old, l_old, o_old = carry[mqi]         # (1,n_kv),(1,n_kv),(n_kv,hs)
            qm = q[:, mqi, :]                        # (n_kv, hs)
            s = jnp.sum(k * qm[None, :, :], axis=-1) * scale  # (chunk, n_kv)
            s = jnp.where(valid, s, NEG_INF)
            m_new = jnp.maximum(m_old, jnp.max(s, axis=0, keepdims=True))
            p = jnp.exp(s - m_new)                   # (chunk, n_kv)
            corr = jnp.exp(m_old - m_new)            # (1, n_kv)
            l_new = l_old * corr + jnp.sum(p, axis=0, keepdims=True)
            po = jnp.sum(p[:, :, None] * v, axis=0)  # (n_kv, hs)
            o_new = o_old * jnp.transpose(corr) + po
            out.append((m_new, l_new, o_new))
        return tuple(out)

    init = tuple((jnp.full((1, n_kv), NEG_INF, jnp.float32),
                  jnp.zeros((1, n_kv), jnp.float32),
                  jnp.zeros((n_kv, hs), jnp.float32))
                 for _ in range(kv_mul))
    return _flash_walk(n_chunks, start_dma, wait_dma, update, init)


def _kernel(layer_ref, pos_ref, q_ref, k_hbm, v_hbm, out_ref,
            k_buf, v_buf, sems, *, chunk: int, kv_mul: int):
    """q_ref (n_kv, kv_mul, hs) VMEM; k/v_hbm (L, S, n_kv, hs) in HBM;
    out_ref (n_kv, kv_mul, hs); k/v_buf (2, chunk, n_kv, hs) VMEM scratch;
    sems (2, 2) DMA semaphores (slot x {k, v})."""
    final = _flash_over_row(layer_ref[0], pos_ref[0], q_ref[...], k_hbm,
                            v_hbm, k_buf, v_buf, sems, chunk=chunk,
                            kv_mul=kv_mul)
    for mqi in range(kv_mul):
        _, l_i, o_i = final[mqi]
        out_ref[:, mqi, :] = o_i / jnp.transpose(l_i)


def _kernel_batch(layer_ref, pos_ref, q_ref, k_hbm, v_hbm, out_ref,
                  k_buf, v_buf, sems, *, chunk: int, kv_mul: int,
                  batch: int):
    """Per-row flash decode over the rank-4 (L*B, S, n_kv, hs) batched cache.

    grid=(B,): program b walks row layer*batch+b's live chunks via the same
    shared flash loop as the single-sequence kernel (prefix-indexed DMAs).
    pos_ref is (B,) — each row has its own position clock (identical values
    in the lockstep case; ragged for continuous batching).
    q_ref/out_ref get per-b blocks (1, n_kv, kv_mul, hs).
    """
    b = pl.program_id(0)
    row = layer_ref[0] * batch + b
    final = _flash_over_row(row, pos_ref[b], q_ref[0], k_hbm, v_hbm,
                            k_buf, v_buf, sems, chunk=chunk, kv_mul=kv_mul)
    for mqi in range(kv_mul):
        _, l_i, o_i = final[mqi]
        out_ref[0, :, mqi, :] = o_i / jnp.transpose(l_i)


@functools.partial(jax.jit, static_argnames=("kv_mul", "interpret"))
def decode_attention_batch(q, k4, v4, layer, pos, *, kv_mul: int,
                           interpret: bool | None = None):
    """Batched flash-decode attention over the rank-4 (L*B, S, n_kv, hs)
    cache carried by models/llama.forward_batch.

    q: (B, n_q, hs) f32; pos: scalar (shared clock, lockstep batch) or (B,)
    (per-row clocks, continuous batching). Returns (B, n_q * hs) f32.
    Live-chunk walking per row, like decode_attention.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    LB, S, n_kv, hs = k4.shape
    B = q.shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    chunk = _chunk(S, n_kv, hs, k4.dtype.itemsize)
    if chunk is None:
        raise ValueError(
            f"no cache chunking fits VMEM for seq_len={S}, n_kv={n_kv}, "
            f"hs={hs} (gate with supports())")
    qg = q.reshape(B, n_kv, kv_mul, hs).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_kernel_batch, chunk=chunk, kv_mul=kv_mul,
                          batch=B),
        grid=(B,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, n_kv, kv_mul, hs), lambda b: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, n_kv, kv_mul, hs), lambda b: (b, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, n_kv, kv_mul, hs), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, chunk, n_kv, hs), k4.dtype),
            pltpu.VMEM((2, chunk, n_kv, hs), k4.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), pos, qg, k4, v4)
    return out.reshape(B, n_kv * kv_mul * hs)


def maybe_flash_decode(q2, k_all, v_all, idx, pos, *, seq_len: int,
                       head_size: int, t_len: int, n_kv: int, kv_mul: int,
                       batch: bool = False):
    """The ONE gate for routing decode attention to the flash kernel.

    Returns the attention output, or None when the caller must take its XLA
    fallback (kernel disabled or shape unsupported). All three decode paths
    (single-chip, TP shard-local, batched) call this so the mode/shape
    gating can never drift between them.

    q2 arrives in the caller's natural shape — (T, n_q*hs) or (T, n_q, hs)
    for the single/TP paths, (B, n_q*hs)/(B, n_q, hs) with ``batch=True``
    (rank-4 (L*B, S, n_kv, hs) caches) — and is reshaped here, so call
    sites carry no per-site shape logic.
    """
    if (attn_kernel_mode() != "pallas"
            or not supports(seq_len, head_size, t_len, n_kv,
                            k_all.dtype.itemsize)):
        return None
    if batch:
        q2 = q2.reshape(q2.shape[0], -1, head_size)
        return decode_attention_batch(q2, k_all, v_all, idx, pos,
                                      kv_mul=kv_mul)
    return decode_attention(q2.reshape(-1, head_size), k_all, v_all, idx,
                            pos, kv_mul=kv_mul)


def attn_kernel_mode() -> str:
    """'pallas' (flash-decode kernel) or 'xla' (full-cache einsum).

    DLLAMA_ATTN_KERNEL=pallas|xla|auto; auto = pallas on TPU, xla elsewhere.
    """
    import os

    env = os.environ.get("DLLAMA_ATTN_KERNEL", "auto")
    if env == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return env


_VMEM_BUDGET = 12 * 1024 * 1024  # scratch budget: bounds the DMA chunk size

# Raised scoped-VMEM limit (v5e has 128 MB physical): with the DEFAULT
# 16 MB limit, shapes whose scratch sits near the 12 MB budget can exceed
# the limit once the compiler's own temporaries stack on top — measured:
# 13B tp=4 rank (n_kv=10, hs=128, f32 cache, chunk 512) needs 16.07 MB and
# fell back to the XLA attention path (or compiled a pessimized marginal
# kernel), costing ~4 ms/token rank time — the r4 scaling curve's tp=4
# anomaly. ONE shared constant with the matmul kernels: a missed copy
# reintroduces exactly this silent-fallback class of bug.
from .pallas_q40 import _VMEM64_PARAMS  # noqa: E402


def _scratch_bytes(chunk: int, n_kv: int, hs: int, itemsize: int) -> int:
    # 2 slots x {K,V} x (chunk, n_kv, hs) in the cache dtype
    return 2 * 2 * chunk * n_kv * hs * itemsize


def _chunk(seq_len: int, n_kv: int, hs: int, itemsize: int = 4) -> int | None:
    """Largest cache chunk that divides seq_len within the VMEM budget
    (bf16 caches fit chunks twice as long as f32)."""
    for c in (512, 256, 128, 64, 32, 16, 8):
        if (seq_len % c == 0
                and _scratch_bytes(c, n_kv, hs, itemsize) <= _VMEM_BUDGET):
            return min(c, seq_len)
    if (seq_len <= 8
            and _scratch_bytes(seq_len, n_kv, hs, itemsize) <= _VMEM_BUDGET):
        return seq_len
    return None


def supports(seq_len: int, head_size: int, t_len: int,
             n_kv: int = 32, itemsize: int = 2) -> bool:
    """The kernel handles T=1 decode with lane-width head_size and a cache
    the chunking divides within the VMEM scratch budget; callers fall back
    to the XLA path otherwise. ``itemsize`` defaults to the smaller (bf16)
    cache: if the bf16 chunking fits, so does some f32 chunking and vice
    versa for these shapes — decode_attention re-derives the real chunk."""
    return (t_len == 1 and head_size % 128 == 0
            and _chunk(seq_len, n_kv, head_size, itemsize) is not None)


@functools.partial(jax.jit, static_argnames=("kv_mul", "interpret"))
def decode_attention(q, k_all, v_all, layer, pos, *, kv_mul: int,
                     interpret: bool | None = None):
    """Flash-decode attention of one query token against the live prefix of
    layer ``layer``'s cache.

    q: (n_q, hs) f32 (n_q = n_kv * kv_mul, grouped so query head
    g*kv_mul+m attends kv head g — the attention_core contract);
    k_all/v_all: (L, S, n_kv, hs) stacked caches; pos: the query's absolute
    position (keys 0..pos are visible). Returns (1, n_q * hs) f32.

    ``interpret=None`` auto-selects interpret mode off-TPU (like q40_matmul),
    so DLLAMA_ATTN_KERNEL=pallas works everywhere.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    L, S, n_kv, hs = k_all.shape
    chunk = _chunk(S, n_kv, hs, k_all.dtype.itemsize)
    if chunk is None:
        raise ValueError(
            f"no cache chunking fits VMEM for seq_len={S}, n_kv={n_kv}, "
            f"hs={hs} (gate with supports())")
    qg = q.reshape(n_kv, kv_mul, hs).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_kernel, chunk=chunk, kv_mul=kv_mul),
        grid=(),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_kv, kv_mul, hs), jnp.float32),
        scratch_shapes=[
            # scratch matches the cache dtype (bf16 caches halve the DMA);
            # score/softmax math promotes to f32 in the kernel body
            pltpu.VMEM((2, chunk, n_kv, hs), k_all.dtype),
            pltpu.VMEM((2, chunk, n_kv, hs), k_all.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1),
      jnp.asarray(pos, jnp.int32).reshape(1), qg, k_all, v_all)
    return out.reshape(1, n_kv * kv_mul * hs)


# --------------------------------------------------------------------------
# Prefill flash attention (T > 8), VERDICT r4 #5.
#
# The blockwise live-prefix prefill path (models/llama._attention_blockwise)
# builds its flash partials from XLA einsums: every KV block materializes a
# (T, n_q, block) score plane plus separate m/l/o merge traffic through HBM,
# and the surrounding reshapes/transposes land in the profiler's layout
# bucket (~38% of chunk-1920 op time is attention + glue + layout,
# tools/prefill_floor.py). This kernel runs the whole online-softmax walk
# in VMEM: grid over (kv head, q block), and per invocation an in-kernel
# double-buffered DMA loop (the decode kernel's machinery, _flash_over_row's
# pattern) walks ONLY the live KV blocks. Scores never touch HBM; the causal
# bound clamps the walk exactly like blockwise_chunk_partials' n_live.
#
# Layout: Mosaic blocks the LAST TWO dims of an operand, so q/out are
# carried group-major — the wrapper transposes (T, n_q, hs) to
# (n_kv, T, kv_mul*hs) on the way in and back on the way out (two real
# layout passes XLA usually fuses into neighbors; they replace the
# per-KV-block score/merge reshapes of the einsum path). The q block is
# as tall as VMEM allows (default: the whole chunk), so each kv head's
# cache plane streams from HBM once per chunk.
#
# Numerics: same contract as ring._partial_attention — bf16 MXU passes with
# f32 accumulation under fast-prefill, HIGHEST-precision f32 dots in parity
# mode; softmax stats and merges always f32. Reassociation-only deltas vs
# the dense path (the documented prefill tolerance).
# --------------------------------------------------------------------------

def _prefill_kernel(pos_ref, q_ref, k_hbm, v_hbm, out_ref, k_buf, v_buf,
                    sems, *, bq: int, bk: int, kv_mul: int, hs: int,
                    bf16: bool):
    """One (kv head g, q block qb) tile: flash walk over live KV blocks.

    q_ref/out_ref: (1, bq, kv_mul*hs) VMEM blocks of the group-major
    (n_kv, T, kv_mul*hs) planes (the last two dims must be the blocked
    ones — Mosaic's (8, 128)-divisibility rule); k_hbm/v_hbm:
    (S, n_kv, hs) in HBM; k/v_buf: (2, bk, hs) VMEM scratch; sems: (2, 2)
    DMA semaphores (slot x {k, v}).
    """
    g = pl.program_id(0)
    qb = pl.program_id(1)
    pos = pos_ref[0]
    S = k_hbm.shape[0]
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else jax.lax.Precision.HIGHEST
    dn = (((1,), (1,)), ((), ()))      # contract hs x hs
    dn_pv = (((1,), (0,)), ((), ()))   # (bq, bk) @ (bk, hs)
    scale = 1.0 / jnp.sqrt(jnp.float32(hs))

    # causal bound: the deepest query row of this block sees keys
    # 0 .. pos + qb*bq + bq - 1 (the chunk's keys are already in the cache)
    n_blk = jnp.clip((pos + qb * bq + bq + bk - 1) // bk, 1, S // bk)
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
    q_pos_rows = pos + qb * bq + rows                  # (bq, 1)

    def k_dma(slot, i):
        return pltpu.make_async_copy(
            k_hbm.at[pl.ds(i * bk, bk), g], k_buf.at[slot],
            sems.at[slot, 0])

    def v_dma(slot, i):
        return pltpu.make_async_copy(
            v_hbm.at[pl.ds(i * bk, bk), g], v_buf.at[slot],
            sems.at[slot, 1])

    k_dma(0, 0).start()
    v_dma(0, 0).start()

    def body(i, carry):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < n_blk)
        def _():
            nxt = jax.lax.rem(i + 1, 2)
            k_dma(nxt, i + 1).start()
            v_dma(nxt, i + 1).start()

        k_dma(slot, i).wait()
        v_dma(slot, i).wait()
        k = k_buf[slot].astype(wdt)                    # (bk, hs)
        v = v_buf[slot].astype(wdt)
        key_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        valid = key_pos <= q_pos_rows                  # (bq, bk)

        out = []
        for j in range(kv_mul):
            m_old, l_old, o_old = carry[j]
            qj = q_ref[0, :, j * hs:(j + 1) * hs].astype(wdt)  # (bq, hs)
            s = jax.lax.dot_general(qj, k, dn,
                                    preferred_element_type=jnp.float32,
                                    precision=prec) * scale
            s = jnp.where(valid, s, NEG_INF)
            # block 0 holds key 0, visible to every query row, so m is
            # finite from the first walked block on (no -inf guard needed)
            m_new = jnp.maximum(m_old, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)                     # (bq, bk)
            corr = jnp.exp(m_old - m_new)              # (bq, 1)
            l_new = l_old * corr + jnp.sum(p, axis=1, keepdims=True)
            po = jax.lax.dot_general(p.astype(wdt), v, dn_pv,
                                     preferred_element_type=jnp.float32,
                                     precision=prec)
            out.append((m_new, l_new, o_old * corr + po))
        return tuple(out)

    init = tuple((jnp.full((bq, 1), NEG_INF, jnp.float32),
                  jnp.zeros((bq, 1), jnp.float32),
                  jnp.zeros((bq, hs), jnp.float32))
                 for _ in range(kv_mul))
    final = jax.lax.fori_loop(0, n_blk, body, init)
    for j in range(kv_mul):
        _, l_j, o_j = final[j]
        out_ref[0, :, j * hs:(j + 1) * hs] = o_j / l_j


# q-block rows: bounded so (bq, bk) score temporaries + q/out blocks stay
# comfortably inside the 64 MB scoped-VMEM limit at kv_mul<=8
_PREFILL_BQ_CAP = 1920


def _pick_prefill_bq(t_len: int, kv_mul: int) -> int | None:
    cap = min(_PREFILL_BQ_CAP, max(128, 245_760 // (kv_mul * 16)))
    for cand in range(min(t_len, cap), 7, -1):
        if t_len % cand == 0 and cand % 8 == 0:
            return cand
    return None


def _pick_prefill_bk(seq_len: int) -> int | None:
    for cand in (512, 256, 128, 64, 32, 16, 8):
        if seq_len % cand == 0:
            return cand
    return None


def supports_prefill(seq_len: int, head_size: int, t_len: int,
                     kv_mul: int) -> bool:
    return (t_len > 8 and head_size % 128 == 0
            and _pick_prefill_bq(t_len, kv_mul) is not None
            and _pick_prefill_bk(seq_len) is not None)


@functools.partial(jax.jit, static_argnames=("kv_mul", "bf16", "interpret"))
def prefill_attention(q, k_cache, v_cache, pos, *, kv_mul: int,
                      bf16: bool = False, interpret: bool | None = None):
    """Flash prefill attention of T queries at positions pos..pos+T-1
    against one layer's cache (keys 0..pos+T-1 live; the chunk's own keys
    are already written).

    q: (T, n_q, hs) f32; k/v_cache: (S, n_kv, hs) (f32 or bf16).
    Returns (T, n_q, hs) f32. Gate with supports_prefill().
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t_len, n_q, hs = q.shape
    S, n_kv, _ = k_cache.shape
    assert n_q == n_kv * kv_mul, (n_q, n_kv, kv_mul)
    bq = _pick_prefill_bq(t_len, kv_mul)
    bk = _pick_prefill_bk(S)
    # group-major carry: Mosaic blocks the LAST TWO dims, so the kv-head
    # axis must lead — (T, n_kv*kv_mul, hs) -> (n_kv, T, kv_mul*hs)
    qg = jnp.transpose(q.astype(jnp.float32)
                       .reshape(t_len, n_kv, kv_mul * hs), (1, 0, 2))
    out = pl.pallas_call(
        functools.partial(_prefill_kernel, bq=bq, bk=bk, kv_mul=kv_mul,
                          hs=hs, bf16=bf16),
        grid=(n_kv, t_len // bq),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, kv_mul * hs), lambda g, qb: (g, qb, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, bq, kv_mul * hs),
                               lambda g, qb: (g, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((n_kv, t_len, kv_mul * hs),
                                       jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, bk, hs), k_cache.dtype),
            pltpu.VMEM((2, bk, hs), k_cache.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), qg, k_cache, v_cache)
    return jnp.transpose(out, (1, 0, 2)).reshape(t_len, n_q, hs)
