"""Matmul dispatch over weight dtypes + the norm/activation kernels.

This is the XLA-side equivalent of reference src/funcs.cpp: the dtype-dispatched
``matmul`` (funcs.cpp:269-299), ``rms``/``rmsnorm`` (funcs.cpp:43-90),
``softmax`` (funcs.cpp:12-41) and SwiGLU glue (transformer-tasks.cpp:369-379).
Kernels are written for XLA fusion (elementwise chains fuse into the matmuls);
the Pallas fast path for Q40 weights lives in ops/pallas_q40.py and is picked
by ``matmul`` when enabled.

Semantics contract (BASELINE.md logit parity):
* matmul: weight w of shape (d, n), out[i] = sum_j w[i,j] * x[..., j], f32
  accumulation.
* rms: 1/sqrt(sum(x^2)/size + 1e-5) — eps added AFTER the mean
  (funcs.cpp:60-62).
* rmsnorm(out, x, rms, w): out = x * rms * w.
* silu(x) = x / (1 + e^-x).
"""

from __future__ import annotations

import contextlib
import contextvars
import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.loader import (Q40Kernel, Q40KernelI4, Q40KernelI4PackedD,
                         Q40KernelI4PackedNb, Q40KernelNb, Q40KernelNbI4,
                         Q40Weight, from_kernel_layout, to_kernel_layout,
                         to_kernel_layout_nb)
from .quants import dequantize_q40_jax, dequantize_q80_jax, quantize_q80_jax

RMS_EPS = 1e-5

# trace-time matmul precision mode. "parity" = f32 accumulation at HIGHEST
# (the logit-parity contract); "bf16" = bf16 MXU passes with f32 accumulation
# — ~3-6x the matmul throughput at a documented tolerance, used for the
# opt-in fast-prefill path (--fast-prefill) where T is large and the outputs
# only seed the KV cache. Read when a program is TRACED, so the mode must be
# active inside the jitted function being built (Engine wraps its prefill
# step in matmul_precision("bf16")); compiled parity programs are untouched.
_MATMUL_MODE = contextvars.ContextVar("dllama_matmul_mode", default="parity")


@contextlib.contextmanager
def matmul_precision(mode: str):
    if mode not in ("parity", "bf16"):
        raise ValueError(f"unknown matmul precision mode {mode!r}")
    token = _MATMUL_MODE.set(mode)
    try:
        yield
    finally:
        _MATMUL_MODE.reset(token)


def matmul_mode() -> str:
    return _MATMUL_MODE.get()


def bf16_prefill(fn):
    """Wrap a forward so it TRACES under bf16 matmul precision — THE one
    fast-prefill wrapper (Engine and ContinuousEngine both build their
    prefill programs through this, so the precision protocol lives in one
    place). Works on raw or already-jitted ``fn``: a jitted fn traces on
    first call, and the context is active around every call."""

    def wrapped(*args):
        with matmul_precision("bf16"):
            return fn(*args)

    return wrapped


class StackedQ40(NamedTuple):
    """A view of one layer inside a stacked Q40Kernel: the weight stays in
    its (L, ...) stacked array and the Pallas kernel DMAs layer ``layer``
    directly via scalar prefetch. This is how ``lax.scan`` over layers avoids
    materializing a per-step copy of each layer's packed weights (XLA's
    dynamic-slice before a pallas_call would triple weight HBM traffic)."""

    w: Any       # stacked Q40Kernel, qs_t (L, 16, d, nb)
    layer: Any   # traced scalar int32


def rms_inv(x: jax.Array) -> jax.Array:
    """The reference's ``rms()``: inverse RMS with eps added after the mean."""
    ss = jnp.sum(x.astype(jnp.float32) ** 2, axis=-1, keepdims=True)
    ss = ss / x.shape[-1] + RMS_EPS
    return jax.lax.rsqrt(ss)


def rmsnorm(x: jax.Array, weight: jax.Array) -> jax.Array:
    return (x * rms_inv(x)) * weight


def silu(x: jax.Array) -> jax.Array:
    return x / (1.0 + jnp.exp(-x))


def dequantize_weight(w) -> jax.Array:
    """Materialize any weight representation as f32 (d, n)."""
    if isinstance(w, StackedQ40):
        w = jax.tree_util.tree_map(lambda a: a[w.layer], w.w)
    if isinstance(w, (Q40KernelI4PackedD, Q40KernelI4PackedNb)):
        from .pallas_q40 import unpack_i4_packed

        w = unpack_i4_packed(w)
    if isinstance(w, (Q40KernelI4, Q40KernelNbI4)):
        from .pallas_q40 import _dequant_i4

        return _dequant_i4(w)
    if isinstance(w, Q40KernelNb):
        from .pallas_q40 import _dequant_nb

        return _dequant_nb(jnp.asarray(w.qs_t), jnp.asarray(w.scale))
    if isinstance(w, Q40Kernel):
        w = from_kernel_layout(w)
    if isinstance(w, Q40Weight):
        return dequantize_q40_jax(w.qs, w.d16)
    return jnp.asarray(w).astype(jnp.float32)


def q40_kernel_mode() -> str:
    """'pallas' (fused HBM-packed kernel) or 'xla' (dequantize-then-dot).

    DLLAMA_Q40_KERNEL=pallas|xla|auto overrides; auto = pallas on TPU, xla
    elsewhere (the kernel still runs in interpret mode off-TPU when forced,
    which is what the parity tests do).
    """
    env = os.environ.get("DLLAMA_Q40_KERNEL", "auto")
    if env == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return env


def matmul(w, x: jax.Array, *, prefer_pallas: bool = False) -> jax.Array:
    """out[..., d] = w(d, n) @ x[..., n] with f32 accumulation.

    ``w`` may be a dense array (f32/f16/bf16) or a planar ``Q40Weight``. The
    dense path lets XLA drive the MXU directly; the Q40 path either calls the
    Pallas fused-dequant kernel (HBM traffic = packed bytes; the default on
    TPU) or dequantizes inline and dots (the XLA fallback).
    """
    if isinstance(w, StackedQ40):
        from .pallas_q40 import q40_matmul  # packing implies kernel support

        return q40_matmul(w.w, x, layer=w.layer)
    if isinstance(w, (Q40KernelNb, Q40KernelI4, Q40KernelNbI4,
                      Q40KernelI4PackedD, Q40KernelI4PackedNb)):
        from .pallas_q40 import q40_matmul  # dedicated dispatches

        return q40_matmul(w, x)
    if isinstance(w, (Q40Weight, Q40Kernel)) and (
            prefer_pallas or q40_kernel_mode() == "pallas"):
        from .pallas_q40 import kernel_supports, q40_matmul  # lazy

        if kernel_supports(w.logical_shape[-2], w.logical_shape[-1]):
            return q40_matmul(w, x)
        # fall through: dims the matvec tiler can't place at all (large d
        # with no multiple-of-8 divisor) take the dequantize-then-dot path
        # below; supported dims with awkward T combos fall back INSIDE
        # q40_matmul instead
    wf = dequantize_weight(w)
    if matmul_mode() == "bf16":
        # fast-prefill mode: bf16 MXU passes, f32 accumulation
        return jnp.einsum("dn,...n->...d", wf.astype(jnp.bfloat16),
                          x.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    # HIGHEST: true f32 MXU accumulation — required for the 1e-5 logit-parity
    # contract on TPU (default TPU precision is bf16-input). The quantized
    # fast path (Pallas) has its own precision story.
    return jnp.einsum("dn,...n->...d", wf, x.astype(jnp.float32),
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)


def pack_q40_params(params: dict, enable: bool | None = None,
                    tp: int = 1, allow_nb_major: bool | None = None,
                    input_sharded=()) -> dict:
    """Re-tile every Q40Weight in a param tree to the kernel layout, once.

    ``enable=None`` means "iff the Pallas kernel will be used" — so CPU/test
    runs keep the codec layout and the golden-parity paths are untouched.
    ``tp`` is the tensor-parallel degree the weights will be sharded to:
    kernel support is decided on the shard-LOCAL shape, since that is what
    the kernel tiles inside shard_map. ``input_sharded`` names the keys the
    fused tp scheme shards along the INPUT dim (wo/w2 — parallel/tp.py):
    their local shape is (d, n/tp) instead of (d/tp, n).
    Call this at load time, before device_put; never inside a jitted step.
    """
    if enable is None:
        enable = q40_kernel_mode() == "pallas"
    if not enable:
        return params
    if allow_nb_major is None:
        # nb-major is UNSHARDED-only (the sharding specs reject it), and
        # tp==1 does not imply unsharded (an sp>1 mesh packs with tp=1) —
        # so the truly-single-chip callers must OPT IN explicitly
        # (params_to_device, shard_sim.rank_params_to_device, bench.py)
        allow_nb_major = False
    from .pallas_q40 import _pick_rows_nb, kernel_supports

    def pick(k, v):
        if not isinstance(v, Q40Weight):
            return v
        d, n = v.logical_shape[-2], v.logical_shape[-1]
        if k in input_sharded and tp > 1:
            # fused-scheme wo/w2: full output rows, 1/tp of the input
            # blocks per shard — the nb axis is the sharded one, so the
            # local block count must stay whole (shard_params validated
            # divisibility already; re-check defensively)
            if (n // 32) % tp:
                raise ValueError(
                    f"{k}: input-dim sharding needs n/tp to be a "
                    f"32-multiple, got n={n} tp={tp}")
            if kernel_supports(d, n // tp):
                return to_kernel_layout(v)
            return v
        if d % tp:
            return v
        nb = n // 32
        pad_ratio = (nb + (-nb % 128)) / nb  # TPU lane padding of nb-minor
        # nb-major layout when the standard tiling would pad the packed
        # bytes materially (13B: nb=160 -> 1.6x HBM and read inflation).
        # DLLAMA_NB_MAJOR=force takes it for EVERY eligible leaf (the
        # i4-formulation experiment arm: the int4 body exists only for
        # nb-major, so pad-free shapes need the forced layout to reach it)
        force_nb = os.environ.get("DLLAMA_NB_MAJOR", "") == "force"
        if (allow_nb_major and tp == 1 and (pad_ratio > 1.25 or force_nb)
                and _pick_rows_nb(d, nb) is not None):
            return to_kernel_layout_nb(v)
        if kernel_supports(d // tp, n):
            return to_kernel_layout(v)
        # untileable dims stay codec-layout: they take the XLA fallback in
        # matmul(), which would otherwise pay a full re-transpose inside
        # the jitted step on every call
        return v

    return {k: pick(k, v) for k, v in params.items()}


def fuse_q40_layer_matmuls(params: dict) -> dict:
    """Concatenate the stacked Q40 qkv (and w1/w3) weights along the output
    dim into single kernel tensors ``wqkv`` / ``w13``, host-side, at load.

    The three qkv matmuls (and the two SwiGLU input matmuls) share the same
    input vector; one wide kernel call replaces three (two) narrow ones,
    which matters for single-token decode where the d=4096 matvec runs at
    roughly half the bytes/s of the d>=11008 ones (grid too short to hide
    pipeline ramp). Row-wise the math is unchanged — outputs are split back
    by models/llama (the reference computes the same three matmuls back to
    back, transformer-tasks.cpp:167-179).

    Only fires on stacked Q40Kernel entries (i.e. after pack_q40_params on
    the single-chip path); dense/TP trees pass through untouched.
    """
    from .pallas_q40 import _pick_rows_nb, kernel_supports

    out = dict(params)

    def fuse(dst, keys):
        # host numpy tree by contract (runs after pack_q40_params, before
        # device placement) — np.concatenate takes the leaves directly
        ws = [out.get(k) for k in keys]
        if all(isinstance(w, Q40Kernel) and w.qs_t.ndim == 4 for w in ws):
            qs_t = np.concatenate([w.qs_t for w in ws], axis=2)
            scale = np.concatenate([w.scale for w in ws], axis=1)
            if not kernel_supports(qs_t.shape[2], qs_t.shape[3] * 32):
                return
            out[dst] = Q40Kernel(qs_t, scale)
        elif all(isinstance(w, Q40KernelNb) and w.qs_t.ndim == 4
                 for w in ws):
            # nb-major: the output dim d is MINOR — concat along it
            qs_t = np.concatenate([w.qs_t for w in ws], axis=3)
            scale = np.concatenate([w.scale for w in ws], axis=2)
            if _pick_rows_nb(qs_t.shape[3], qs_t.shape[2]) is None:
                return
            out[dst] = Q40KernelNb(qs_t, scale)
        else:
            return
        for k in keys:
            del out[k]

    fuse("wqkv", ("wq", "wk", "wv"))
    fuse("w13", ("w1", "w3"))
    return out


def q40_body_policy(spec) -> tuple[str, str]:
    """Resolve the single-chip Q40 decode-body policy: (policy, reason).

    Promotes the bench's same-session A/B winner (BASELINE.md r5: 7B
    9.645 ms/token with the int4-plane body on forced nb-major layout, vs
    9.98-10.37 for the defaults) into the real CLI path — until now only
    ``bench.py:_row_env`` applied it, so a plain ``inference`` run left
    ~4% on the table (VERDICT round 5).

    Explicit ``DLLAMA_Q40_I4``/``DLLAMA_NB_MAJOR`` env wins over
    everything (including DLLAMA_Q40_BODY — nothing ever unsets a user
    knob), and the returned label then REPORTS what that env actually
    engages rather than a policy nobody chose. Otherwise
    ``DLLAMA_Q40_BODY`` overrides: ``auto`` (default), ``i4-nb`` (force
    the winning combo), ``d-major`` (keep the stock layout picks). auto
    picks ``i4-nb`` iff ALL of:
      * the Pallas kernel path is active (TPU; elsewhere layouts are moot),
      * every matmul leaf places on the nb-major row tiler (the i4 body is
        nb-major-only — pad-free 7B-class shapes need the forced layout),
      * the packed weights leave conversion headroom: the in-chain i4
        conversion transiently holds an extra ~half of the packed bytes
        while the chain runs, which OOMed 13B on a 16 GB chip (PARITY.md
        round-5 table) — gated at DLLAMA_Q40_BODY_MAX_GB packed (default
        6.0, between 7B's ~4.2 and 13B's ~7.8).
    """
    choice = os.environ.get("DLLAMA_Q40_BODY", "auto")
    if choice not in ("auto", "i4-nb", "d-major"):
        raise ValueError(f"DLLAMA_Q40_BODY={choice!r}: expected "
                         f"auto|i4-nb|d-major")
    i4 = os.environ.get("DLLAMA_Q40_I4")
    nbm = os.environ.get("DLLAMA_NB_MAJOR")
    if i4 or nbm:
        label = ("i4-nb" if i4 == "on" and nbm == "force"
                 else f"env(i4={i4 or 'off'}, nb-major={nbm or 'auto'})")
        return label, "explicit DLLAMA_Q40_I4/DLLAMA_NB_MAJOR env respected"
    if choice != "auto":
        return choice, "explicit DLLAMA_Q40_BODY"
    if q40_kernel_mode() != "pallas":
        return "d-major", "XLA matmul path (no Pallas kernels here)"
    from .pallas_q40 import _pick_rows_nb

    shapes = [shape for _, shape in spec.layer_matmul_shapes()]
    shapes.append((spec.vocab_size, spec.dim))  # wcls
    bad = [(d, n) for d, n in shapes if _pick_rows_nb(d, n // 32) is None]
    if bad:
        return "d-major", (f"shape {bad[0]} has no nb-major row tiling "
                           f"(rows must divide by 128)")
    packed_gb = (spec.n_layers * sum(d * (n // 32) * 18 for d, n in shapes[:-1])
                 + spec.vocab_size * (spec.dim // 32) * 18) / 1e9
    raw_gb = os.environ.get("DLLAMA_Q40_BODY_MAX_GB", "6")
    try:
        max_gb = float(raw_gb)
    except ValueError:
        raise ValueError(f"DLLAMA_Q40_BODY_MAX_GB={raw_gb!r}: expected a "
                         f"number of GB (e.g. 6)") from None
    if packed_gb > max_gb:
        return "d-major", (f"~{packed_gb:.1f} GB packed exceeds the "
                           f"{max_gb:.0f} GB i4-conversion headroom gate "
                           f"(DLLAMA_Q40_BODY_MAX_GB; 13B-class OOM, "
                           f"BASELINE.md r5)")
    return "i4-nb", (f"auto: shapes place nb-major, ~{packed_gb:.1f} GB "
                     f"packed fits the i4 headroom gate")


def apply_q40_body_policy(spec) -> str:
    """Apply q40_body_policy by setting the layout env knobs the packers
    and the decode chain already read (DLLAMA_NB_MAJOR=force +
    DLLAMA_Q40_I4=on), BEFORE any pack/sidecar load — the kcache layout
    key includes DLLAMA_NB_MAJOR. Prints the chosen policy to stderr
    unconditionally, even for quiet callers: a silent layout change would
    make runs incomparable. setdefault only: explicit user env is never
    overridden."""
    import sys

    policy, reason = q40_body_policy(spec)
    if policy == "i4-nb":
        os.environ.setdefault("DLLAMA_NB_MAJOR", "force")
        os.environ.setdefault("DLLAMA_Q40_I4", "on")
    print(f"💡 Q40 body policy: {policy} ({reason}; the i4 body "
          f"engages on fused decode chains)", file=sys.stderr)
    return policy


def fake_quant_q80(x: jax.Array) -> jax.Array:
    """Quantize->dequantize through Q80, used when buffer_float_type == Q80.

    The reference quantizes activations at every sync point (and feeds the
    quantized form to the matmuls even single-node: transformer-tasks.cpp
    quantize* tasks run regardless of socket count). This reproduces the value
    rounding of that path within the documented 0.0043 tolerance.
    """
    qs, d = quantize_q80_jax(x)
    return dequantize_q80_jax(qs, d)
