"""Pallas TPU kernel: fused Q40 dequant + matmul.

The TPU analog of the reference's hot NEON kernel ``matmulQ40vQ80``
(src/funcs.cpp:185-260): weights stay packed in HBM (0.5625 bytes/value) and
the nibble-unpack + f16-delta scale happens in VMEM on the way into the dot —
HBM traffic per token is the packed bytes, not dequantized f32. This is what
makes single-token decode HBM-bound at the Q40 size instead of the f32 size
(the dequantize-then-dot XLA fallback in ops/linear.py materializes f32 tiles).

Mosaic constraint that shapes this kernel: there is no supported way to
expand per-block scales (R, nb) to per-value (R, nb*16) inside the kernel
(minor-dim broadcast+reshape is an "unsupported shape cast"). So instead of
one wide dot over all 32 values per block, the grid carries the nibble
position j = 0..15 as its innermost axis and every step is pure 2D:

  qs_t   (16, d, nb) uint8  — qs_t[j, r, b] packs values x[b*32+j] (low
                               nibble) and x[b*32+j+16] (high nibble)
  scale  (d, nb) float32    — per-block deltas (f32: Mosaic has no f16
                               vectors; the f16->f32 upconvert is exact)
  xlo/xhi (16, t, nb) f32   — xlo[j, t, b] = x[t, b*32+j], xhi: +16

  step (ti, i):  out[ti, i] = sum_j  xlo[j] @ ((lo(qs_t[j]) - 8) * scale).T
                                  +  xhi[j] @ ((hi(qs_t[j]) - 8) * scale).T

The (16, d, nb) weight tiling is prepared ONCE at load time
(io.loader.to_kernel_layout); feeding a codec-layout Q40Weight works but
re-tiles on every call — fine under test, wrong for the per-token hot loop.

Grid: (t tiles, d tiles), one step per output tile with the 16 nibble planes
unrolled in the body — the packed bytes of a whole tile arrive as one big
DMA that Pallas double-buffers across grid steps. Non-TPU backends run in
interpret mode (tests); the numerics are the exact Q40 value map, so parity
with the XLA path is bit-tight at f32.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.loader import (Q40Kernel, Q40KernelI4, Q40KernelI4PackedD,
                         Q40KernelI4PackedNb, Q40KernelNb, Q40KernelNbI4,
                         Q40Weight, to_kernel_layout)

QK = 32
NJ = 16  # nibble positions per block byte-plane


def _prefill_matmul_mode() -> str:
    """T>8 (prefill-chunk) matmul strategy — DLLAMA_PREFILL_MATMUL:

    * 'dequant': unpack the packed weight once per chunk into an HBM
      bf16/f32 temp and run a plain XLA dot.
    * 'scratch': d-outer grid, unpack-once-to-VMEM-scratch MXU kernel —
      the packed tile is DMA'd and unpacked exactly once per chunk
      (_matmul_body_scratch), but every x tile re-streams once per d tile.
    * 'legacy': the original (t/bt, d/rows) grid, which re-fetches and
      re-unpacks every weight tile t/bt times per chunk.
    * 'auto' (default): 'dequant' under the bf16 fast-prefill precision,
      'legacy' in f32 parity mode.

    The arms are the prefill ladder (tools/prefill_ladder.py, VERDICT r2
    #6). Measured on v5e at 7B (tok/s at chunk 480/960/1920): dequant
    3255/4055/4487 beats scratch 2623/3685/3761 beats legacy
    2408/3565/4249 in bf16 — the Pallas grids re-stream one of the two
    operands t/bt or d/rows times, while XLA's dense dot tiles both ways
    and the one-time dequant temp costs less than either re-stream. In f32
    parity mode the dense path triples MXU passes (HIGHEST) on 4x the temp
    bytes, so the packed kernel stays ahead there (BASELINE.md r3 ladder).
    Read at trace time, like the precision contextvar — programs already
    traced (an existing Engine's cached jits) keep the mode they were
    traced with; construct a new Engine to change it. Unknown values
    raise (a typo would otherwise silently run a slower path)."""
    mode = os.environ.get("DLLAMA_PREFILL_MATMUL") or "auto"  # '' = unset
    if mode not in ("auto", "dequant", "scratch", "legacy"):
        raise ValueError(f"DLLAMA_PREFILL_MATMUL={mode!r}: "
                         f"expected auto|dequant|scratch|legacy")
    if mode == "auto":
        from .linear import matmul_mode

        return "dequant" if matmul_mode() == "bf16" else "legacy"
    return mode


def _matvec_body(qs3, s, xlo_ref, xhi_ref, xsum_ref, out_ref):
    """Shared T=1 body: qs3 (NJ, R, nb) codes view, s (R, nb) f32 scales,
    xsum (1, nb) per-block input sums.

    The -8 code offset is factored out of the per-plane loop:
      sum_j (code-8)*x = sum_j code*x - 8*sum_j x
    so the hot loop multiplies RAW codes (saves two vector subtracts per
    byte-plane — this loop is VPU-unpack-bound, not HBM-bound, at matvec
    shapes) and the correction lands once per block via the precomputed
    input sum."""
    acc = None
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)             # (R, nb)
        wlo = (q & 0xF).astype(jnp.float32)
        whi = (q >> 4).astype(jnp.float32)
        a = wlo * xlo_ref[j] + whi * xhi_ref[j]  # x rows (1, nb) bcast over R
        acc = a if acc is None else acc + a
    acc = acc - 8.0 * xsum_ref[...]              # (R, nb) - (1, nb) bcast
    out_ref[...] = jnp.sum(acc * s, axis=1, keepdims=True)  # (R, 1)


def _kernel_matvec(qs_ref, scale_ref, xlo_ref, xhi_ref, xsum_ref, out_ref):
    """T=1 specialization: pure VPU multiply-accumulate, no MXU.

    Thin M=1 dots waste the MXU (it processes 128-row tiles); for a matvec
    the whole contraction is elementwise work: accumulate the UNSCALED codes
    against x across the 16 nibble planes (the per-block scale is j-invariant,
    so it factors out), apply the scale once, lane-reduce. ~2.4x faster than
    the dot formulation on v5e at 7B shapes.
    """
    _matvec_body(qs_ref, scale_ref[...], xlo_ref, xhi_ref, xsum_ref, out_ref)


def _kernel_matvec_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref,
                           xsum_ref, out_ref):
    """Stacked-layer matvec: the layer index arrives as a prefetched scalar
    that the BlockSpec index maps use to DMA the right layer's tiles straight
    out of the stacked (L, ...) arrays — no XLA dynamic-slice copy of the
    whole layer's weights per scan step (which would triple weight HBM
    traffic: read stack + write slice + read slice)."""
    del layer_ref  # consumed by the index maps
    _matvec_body(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref, xsum_ref, out_ref)


def _matvec_body_multi(qs3, s, xlo_ref, xhi_ref, xsum_ref, out_ref):
    """Small-T (2..8) body: the matvec VPU formulation with one accumulator
    per batch row, so the nibble unpack (the VPU bottleneck) is paid ONCE
    for all T rows instead of per row. out (R, T); xlo/xhi (NJ, T, nb);
    xsum (T, nb). ~3x the bytes/s of the MXU body at T=4 on v5e."""
    t = xlo_ref.shape[1]
    accs = [None] * t
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)                 # (R, nb)
        wlo = (q & 0xF).astype(jnp.float32)
        whi = (q >> 4).astype(jnp.float32)
        for ti in range(t):
            a = wlo * xlo_ref[j, ti] + whi * xhi_ref[j, ti]
            accs[ti] = a if accs[ti] is None else accs[ti] + a
    cols = []
    for ti in range(t):
        acc = accs[ti] - 8.0 * xsum_ref[ti]          # (R, nb) - (nb,)
        cols.append(jnp.sum(acc * s, axis=1, keepdims=True))
    out_ref[...] = jnp.concatenate(cols, axis=1)     # (R, T)


def _kernel_multi(qs_ref, scale_ref, xlo_ref, xhi_ref, xsum_ref, out_ref):
    _matvec_body_multi(qs_ref, scale_ref[...], xlo_ref, xhi_ref, xsum_ref,
                       out_ref)


def _kernel_multi_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref,
                          xsum_ref, out_ref):
    del layer_ref  # consumed by the index maps
    _matvec_body_multi(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref, xsum_ref,
                       out_ref)


def _matvec_body_nb(qs3, s, xlo_ref, xhi_ref, xsum_ref, out_ref):
    """T=1 body for the nb-MAJOR layout (io.loader.Q40KernelNb): qs3
    (NJ, nb, R) codes, s (nb, R) f32 scales, xlo/xhi (NJ, nb, 1), xsum
    (nb, 1). Same math as _matvec_body with the tile transposed: the
    output dim R rides the LANES (128-aligned for every Llama d), so
    awkward nb values (160 at 13B) cost no tile padding. The reduction
    runs over sublanes (axis 0) instead of lanes."""
    acc = None
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)                 # (nb, R)
        wlo = (q & 0xF).astype(jnp.float32)
        whi = (q >> 4).astype(jnp.float32)
        a = wlo * xlo_ref[j] + whi * xhi_ref[j]      # (nb, 1) bcast over R
        acc = a if acc is None else acc + a
    acc = acc - 8.0 * xsum_ref[...]                  # (nb, R) - (nb, 1)
    out_ref[...] = jnp.sum(acc * s, axis=0, keepdims=True)  # (1, R)


def _kernel_matvec_nb(qs_ref, scale_ref, xlo_ref, xhi_ref, xsum_ref,
                      out_ref):
    _matvec_body_nb(qs_ref, scale_ref[...], xlo_ref, xhi_ref, xsum_ref,
                    out_ref)


def _kernel_matvec_nb_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref,
                              xsum_ref, out_ref):
    del layer_ref  # consumed by the index maps
    _matvec_body_nb(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref, xsum_ref,
                    out_ref)


def _matvec_body_multi_nb(qs3, s, xlo_ref, xhi_ref, xsum_ref, out_ref):
    """Small-T (2..8) nb-major body: qs3 (NJ, nb, R), s (nb, R), xlo/xhi
    (NJ, nb, T), xsum (nb, T); out (T, R). The d-major multi body
    transposed: unpack once per plane, one accumulator per batch row,
    sublane reduction."""
    t = xlo_ref.shape[2]
    accs = [None] * t
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)                 # (nb, R)
        wlo = (q & 0xF).astype(jnp.float32)
        whi = (q >> 4).astype(jnp.float32)
        for ti in range(t):
            a = (wlo * xlo_ref[j, :, ti][:, None]
                 + whi * xhi_ref[j, :, ti][:, None])
            accs[ti] = a if accs[ti] is None else accs[ti] + a
    rows = []
    for ti in range(t):
        acc = accs[ti] - 8.0 * xsum_ref[:, ti][:, None]
        rows.append(jnp.sum(acc * s, axis=0, keepdims=True))   # (1, R)
    out_ref[...] = jnp.concatenate(rows, axis=0)               # (T, R)


def _kernel_multi_nb(qs_ref, scale_ref, xlo_ref, xhi_ref, xsum_ref, out_ref):
    _matvec_body_multi_nb(qs_ref, scale_ref[...], xlo_ref, xhi_ref, xsum_ref,
                          out_ref)


def _kernel_multi_nb_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref,
                             xsum_ref, out_ref):
    del layer_ref
    _matvec_body_multi_nb(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref,
                          xsum_ref, out_ref)


def _matmul_body_nb(qs3, s, xlo_ref, xhi_ref, out_ref, bf16=False):
    """T>8 MXU body, nb-major: qs3 (NJ, nb, R), s (nb, R), xlo/xhi
    (NJ, bt, nb); out (bt, R). The contraction is a STANDARD (M,K)x(K,N)
    dot (x rows x nb against weights nb x R) — no minor-dim contraction
    gymnastics; bf16 as in _matmul_body."""
    dn = (((1,), (0,)), ((), ()))
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else jax.lax.Precision.HIGHEST
    acc = None
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)                 # (nb, R)
        wlo = (((q & 0xF) - 8).astype(jnp.float32) * s).astype(wdt)
        whi = (((q >> 4) - 8).astype(jnp.float32) * s).astype(wdt)
        a = jax.lax.dot_general(xlo_ref[j].astype(wdt), wlo, dn,
                                preferred_element_type=jnp.float32,
                                precision=prec)
        a = a + jax.lax.dot_general(xhi_ref[j].astype(wdt), whi, dn,
                                    preferred_element_type=jnp.float32,
                                    precision=prec)
        acc = a if acc is None else acc + a
    out_ref[...] = acc


def _kernel_mxu_nb(qs_ref, scale_ref, xlo_ref, xhi_ref, out_ref, *,
                   bf16=False):
    _matmul_body_nb(qs_ref, scale_ref[...], xlo_ref, xhi_ref, out_ref, bf16)


def _kernel_mxu_nb_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref,
                           out_ref, *, bf16=False):
    del layer_ref
    _matmul_body_nb(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref, out_ref, bf16)


MULTI_T_MAX = 8  # beyond this the per-row accumulators crowd VMEM; use MXU

# Raised scoped-VMEM limit for the T>1 kernels (MXU prefill bodies, the
# unpack-once scratch kernels, and the T<=8 VPU multi bodies batched decode
# uses): Mosaic's conservative stack accounting rejects several measured-fine
# tile sets at the default 16 MB (e.g. 22.6M at w2's nb=344/bt=32 prefill
# tile, 26.3M at the 13B B=2 multi tile) though v5e has 128 MB physical.
# Same approach as ops/pallas_layer._VMEM_LIMIT.
from ..utils.compat import pallas_tpu_compiler_params as _compiler_params

_VMEM64_PARAMS = _compiler_params(vmem_limit_bytes=64 * 1024 * 1024)


def q40_i4_enabled() -> bool:
    """DLLAMA_Q40_I4=on routes the fused decode chain through signed-int4
    weight planes (VERDICT r4 #2's second nb-major formulation).
    NB-MAJOR LEAVES ONLY: d-major trees (7B/70B shapes) are a silent
    no-op — their s4 body measured ~6x SLOWER on hardware (BASELINE.md
    r5), so the flag only changes 13B-class nb-major leaves.

    What it does: at CHAIN START (inside the jitted program — this
    runtime cannot pass int4 across a jit boundary) every Q40KernelNb
    leaf is re-expressed as (code - 8) int4 planes (to_i4_planes); the
    T=1 matvec body then needs ONE convert + mul + add per plane instead
    of convert/mask/shift/2xconvert/2xmul/2xadd — measured 701 GB/s vs
    638 on the 13B w13 shape, against a 746 GB/s DMA floor
    (tools/nb_probe.py). Cost: the conversion pass (~0.06 ms/token
    amortized over a 64-step chain) and TRANSIENT extra HBM for the i4
    copy while the chain runs (~+50% of the codes' bytes; the u8
    originals remain the placed arguments). Exact same integers — parity
    is bit-tight with the u8 bodies. Default off until the memory
    headroom story is per-model; the bench flips it per config."""
    mode = os.environ.get("DLLAMA_Q40_I4", "off")
    if mode not in ("on", "off"):
        raise ValueError(f"DLLAMA_Q40_I4={mode!r}: expected on|off")
    return mode == "on"


def to_i4_planes(tree):
    """Re-express every Q40Kernel / Q40KernelNb leaf of a param tree (or a
    single leaf) as its signed-int4 plane form. Jit-internal only — see
    Q40KernelI4's device-only caveat."""
    def planes(qs_t):
        # cast each nibble plane to int4 BEFORE the concat: an int32
        # intermediate of the whole concat is 8x the packed bytes and
        # OOMs 13B (24.3 GB observed); int4-typed pieces keep transients
        # at half the u8 size
        q = qs_t.astype(jnp.int32)
        lo = ((q & 0xF) - 8).astype(jnp.int4)
        hi = ((q >> 4) - 8).astype(jnp.int4)
        return jnp.concatenate([lo, hi], axis=-3)

    def conv(v):
        # nb-major only in production (see repack_i4_packed); the d-major
        # planes exist for tests/experiments via the single-leaf form
        if isinstance(v, Q40KernelNb):
            return Q40KernelNbI4(planes(v.qs_t), v.scale)
        return v

    if isinstance(tree, (Q40Kernel, Q40KernelNb)):
        if isinstance(tree, Q40Kernel):
            return Q40KernelI4(planes(tree.qs_t), tree.scale)
        return conv(tree)
    return {k: conv(v) for k, v in tree.items()}


def repack_i4_packed(tree):
    """HOST-side: re-express u8 kernel leaves as the RESIDENT packed-i4
    carrier (Q40KernelI4Packed*): (code - 8) signed nibbles, pairwise
    along the minor dim, low nibble = even index (XLA S4 bit order).
    (c - 8) & 0xF == c ^ 0x8 for 4-bit codes, so the repack is two XORs
    and an interleave. Leaves whose minor dim is odd (tiny test specs)
    stay u8 — the chain's legacy in-program conversion covers them."""
    import numpy as np

    def pack(qs_t):
        # qs_t is a host numpy plane stack (runs at load, after pack);
        # the nibble ops above keep it numpy end to end
        lo = (qs_t & 0xF) ^ 0x8
        hi = (qs_t >> 4) ^ 0x8
        pl = np.concatenate([lo, hi], axis=-3)
        return (pl[..., 0::2] | (pl[..., 1::2] << 4)).astype(np.uint8)

    def conv(v):
        # nb-major ONLY: the d-major s4 body measured ~6x SLOWER than u8
        # on hardware (64 vs 10.3 ms/token at 7B — Mosaic's s4->f32
        # unpack on (rows, nb) tiles is pathological), while the nb-major
        # body is the probe's 701 GB/s winner. Q40Kernel leaves stay u8.
        if isinstance(v, Q40KernelNb) and v.qs_t.shape[-1] % 2 == 0:
            return Q40KernelI4PackedNb(pack(v.qs_t), v.scale)
        return v

    return {k: conv(v) for k, v in tree.items()}


def unpack_i4_packed(v):
    """Jit-internal: the packed-u8 carrier -> int4 plane leaf. The
    bitcast adds a trailing pair dim that the minor reshape collapses —
    both are layout reinterpretations of the SAME packed bits (no second
    copy of the weights). On jax builds whose u8->s4 bitcast does NOT
    split pairs (int4 stored one byte per element, e.g. 0.4.37 CPU), the
    nibbles unpack arithmetically instead — same values, the bitcast's
    zero-copy property traded for a few VPU ops."""
    q8 = v.qs_p
    q4 = jax.lax.bitcast_convert_type(q8, jnp.int4)
    if q4.shape == (*q8.shape, 2):                        # (..., X, Y/2, 2)
        q4 = q4.reshape(*q4.shape[:-2], q4.shape[-2] * 2)  # (..., X, Y)
    else:
        # low nibble = even index (the repack_i4_packed layout); nibbles
        # hold (c - 8) two's-complement: ((n + 8) & 0xF) - 8 re-signs
        pairs = jnp.stack([q8 & 0xF, q8 >> 4], axis=-1)   # (..., Y/2, 2)
        signed = ((pairs.astype(jnp.int32) + 8) & 0xF) - 8
        q4 = signed.astype(jnp.int4).reshape(*q8.shape[:-1],
                                             q8.shape[-1] * 2)
    if isinstance(v, Q40KernelI4PackedD):
        return Q40KernelI4(q4, v.scale)
    return Q40KernelNbI4(q4, v.scale)


def chain_weight_prep(params):
    """Decode-chain weight prep, run INSIDE the jitted chain: packed-i4
    carriers always unpack (they are unreadable otherwise); u8 kernel
    leaves additionally convert to i4 planes when DLLAMA_Q40_I4=on (the
    legacy double-residency path — fine at 7B, OOMs 13B)."""
    i4 = q40_i4_enabled()

    def conv(v):
        if isinstance(v, (Q40KernelI4PackedD, Q40KernelI4PackedNb)):
            return unpack_i4_packed(v)
        # nb-major ONLY (the d-major s4 body is the documented ~6x
        # negative; the single-leaf to_i4_planes form still converts
        # d-major for tests, so gate HERE)
        if i4 and isinstance(v, Q40KernelNb):
            return to_i4_planes(v)
        return v

    return {k: conv(v) for k, v in params.items()}


def _matvec_body_i4(qs4, s, x32_ref, out_ref):
    """T=1 d-major int4 body: qs4 (32, R, nb) signed planes (code-8
    pre-applied), s (R, nb) f32, x32 (32, 1, nb) f32 plane-split inputs
    (lo planes then hi). One convert + broadcast-mul + add per plane —
    no mask, no shift, no xsum correction."""
    acc = None
    for j in range(2 * NJ):
        w = qs4[j].astype(jnp.float32)               # (R, nb)
        a = w * x32_ref[j]                           # (1, nb) bcast over R
        acc = a if acc is None else acc + a
    out_ref[...] = jnp.sum(acc * s, axis=1, keepdims=True)  # (R, 1)


def _kernel_matvec_i4_stacked(layer_ref, qs_ref, scale_ref, x32_ref,
                              out_ref):
    del layer_ref  # consumed by the index maps
    _matvec_body_i4(qs_ref[0], scale_ref[0], x32_ref, out_ref)


def _kernel_matvec_i4(qs_ref, scale_ref, x32_ref, out_ref):
    _matvec_body_i4(qs_ref, scale_ref[...], x32_ref, out_ref)


def _matvec_body_nb_i4(qs4, s, x32_ref, out_ref):
    """T=1 nb-major int4 body: qs4 (32, nb, R), s (nb, R), x32 (32, nb, 1);
    out (1, R). The tools/nb_probe.py 'i4' winner verbatim."""
    acc = None
    for j in range(2 * NJ):
        w = qs4[j].astype(jnp.float32)               # (nb, R)
        a = w * x32_ref[j]                           # (nb, 1) bcast over R
        acc = a if acc is None else acc + a
    out_ref[...] = jnp.sum(acc * s, axis=0, keepdims=True)  # (1, R)


def _kernel_matvec_nb_i4_stacked(layer_ref, qs_ref, scale_ref, x32_ref,
                                 out_ref):
    del layer_ref
    _matvec_body_nb_i4(qs_ref[0], scale_ref[0], x32_ref, out_ref)


def _kernel_matvec_nb_i4(qs_ref, scale_ref, x32_ref, out_ref):
    _matvec_body_nb_i4(qs_ref, scale_ref[...], x32_ref, out_ref)


def _multi_t_body() -> str:
    """T in (2..MULTI_T_MAX) body — DLLAMA_MULTI_T_BODY:

    * 'vpu' (default): the shared-unpack VPU accumulate body
      (_matvec_body_multi). Exact f32 math; per-row MAC work scales with
      T (the continuous-batching step-floor term, BASELINE.md r4:
      23.9 ms of the 8-slot 31 ms op floor).
    * 'dequant': one-dot MXU body (VERDICT r4 #6's "new formulation"):
      unpack each weight tile ONCE into a flat (rows, 32*nb) bf16
      scratch, then a single long dot (T, 32*nb) x (rows, 32*nb)^T —
      per-row work rides the otherwise-idle MXU instead of the VPU.
      bf16 multiply with f32 accumulation: a DOCUMENTED TOLERANCE on
      batched decode logits (same contract as --fast-prefill), so it is
      opt-in. Read at trace time.

    Unknown values raise (a typo would silently run the default)."""
    mode = os.environ.get("DLLAMA_MULTI_T_BODY") or "vpu"  # '' = unset
    if mode not in ("vpu", "dequant"):
        raise ValueError(f"DLLAMA_MULTI_T_BODY={mode!r}: "
                         f"expected vpu|dequant")
    return mode


def _multi_body_dequant(qs3, s, xp_ref, out_ref, w_ref):
    """T<=8 one-dot body: qs3 (NJ, R, nb) d-major codes, s (R, nb) f32,
    xp (T, 32*nb) bf16 in PLANE order (xp[t, j*nb + b] = x[t, b*32 + j]
    for j < 16, x[t, b*32 + j] for the hi planes at j-16 >= 0 shifted by
    +16), w_ref (R, 32*nb) bf16 scratch; out (R, T) — R minor-most rides
    the legal (8,128) block tiling (a (T, R) block with T=8 rows would
    need R % 128, which small-d leaves can't give).

    The VPU pays ~13 unpack ops/byte ONCE per tile (vs 5 + 4*T for the
    accumulate body); the T-proportional MAC work becomes one MXU dot
    with K = 32*nb — long enough to pipeline, M = T wasted rows accepted
    (the MXU is idle in this phase anyway)."""
    nb = s.shape[-1]
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)
        w_ref[:, j * nb:(j + 1) * nb] = \
            (((q & 0xF) - 8).astype(jnp.float32) * s).astype(jnp.bfloat16)
        w_ref[:, (NJ + j) * nb:(NJ + j + 1) * nb] = \
            (((q >> 4) - 8).astype(jnp.float32) * s).astype(jnp.bfloat16)
    out_ref[...] = jax.lax.dot_general(
        w_ref[...], xp_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _kernel_multi_dequant(qs_ref, scale_ref, xp_ref, out_ref, w_ref):
    _multi_body_dequant(qs_ref, scale_ref[...], xp_ref, out_ref, w_ref)


def _kernel_multi_dequant_stacked(layer_ref, qs_ref, scale_ref, xp_ref,
                                  out_ref, w_ref):
    del layer_ref  # consumed by the index maps
    _multi_body_dequant(qs_ref[0], scale_ref[0], xp_ref, out_ref, w_ref)


def _x_planes(x: jax.Array, nb: int) -> jax.Array:
    """(T, n) f32 -> (T, 32*nb) bf16 in the _multi_body_dequant plane
    order (lo planes 0..15 then hi planes 16..31, each nb wide)."""
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)     # (NJ, T, nb)
    xp = jnp.concatenate([xlo, xhi], axis=0)           # (32, T, nb)
    t = x.shape[0]
    return jnp.transpose(xp, (1, 0, 2)).reshape(t, 2 * NJ * nb) \
        .astype(jnp.bfloat16)


def _matmul_body_scratch(qs3, s, xlo_ref, xhi_ref, out_ref, wlo_ref, whi_ref,
                         bf16=False, nb_major=False):
    """T>8 MXU body, d-OUTER grid, unpack-once: grid is (d/rows, t/bt) with
    the t tiles innermost, so each packed weight tile is DMA'd and unpacked
    exactly ONCE (at ti == 0, into the wlo/whi VMEM scratch) and every t
    tile dots against the resident unpacked planes.

    The legacy body (_matmul_body) runs on a (t/bt, d/rows) grid where the
    weight tile is re-fetched and re-unpacked for EVERY t tile — t/bt = 15x
    the packed bytes and VPU work at a 1920-token chunk (the prefill-ladder
    finding, BASELINE.md r3). Decode (t == 1) is unaffected: one t tile
    means the two schedules are identical, so the matvec path keeps its
    tuned shape.

    ``nb_major``: the planes are (nb, R) instead of (R, nb) — the ONLY
    difference is which weight dim the x (bt, nb) tiles contract against,
    so one body serves both layouts via the dot dimension numbers.
    """
    dn = ((((1,), (0,)) if nb_major else ((1,), (1,))), ((), ()))
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else jax.lax.Precision.HIGHEST

    @pl.when(pl.program_id(1) == 0)
    def _unpack():
        for j in range(NJ):
            q = qs3[j].astype(jnp.int32)
            wlo_ref[j, :, :] = ((((q & 0xF) - 8).astype(jnp.float32))
                                * s).astype(wdt)
            whi_ref[j, :, :] = ((((q >> 4) - 8).astype(jnp.float32))
                                * s).astype(wdt)

    acc = None
    for j in range(NJ):
        a = jax.lax.dot_general(xlo_ref[j].astype(wdt), wlo_ref[j], dn,
                                preferred_element_type=jnp.float32,
                                precision=prec)
        a = a + jax.lax.dot_general(xhi_ref[j].astype(wdt), whi_ref[j], dn,
                                    preferred_element_type=jnp.float32,
                                    precision=prec)
        acc = a if acc is None else acc + a
    out_ref[...] = acc


def _kernel_scratch(qs_ref, scale_ref, xlo_ref, xhi_ref, out_ref,
                    wlo_ref, whi_ref, *, bf16=False):
    _matmul_body_scratch(qs_ref, scale_ref[...], xlo_ref, xhi_ref, out_ref,
                         wlo_ref, whi_ref, bf16)


def _kernel_scratch_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref,
                            out_ref, wlo_ref, whi_ref, *, bf16=False):
    del layer_ref  # consumed by the index maps
    _matmul_body_scratch(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref, out_ref,
                         wlo_ref, whi_ref, bf16)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16"))
def _q40_matmul_2d_scratch(qs_t, scale, x, *, block_rows, block_t,
                           interpret, bf16=False):
    _, d, nb = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    out = pl.pallas_call(
        functools.partial(_kernel_scratch, bf16=bf16),
        grid=(d // block_rows, t // block_t),
        in_specs=[
            pl.BlockSpec((NJ, block_rows, nb), lambda i, ti: (0, i, 0)),
            pl.BlockSpec((block_rows, nb), lambda i, ti: (i, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows), lambda i, ti: (ti, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((NJ, block_rows, nb), wdt),
                        pltpu.VMEM((NJ, block_rows, nb), wdt)],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(qs_t, scale, xlo, xhi)
    return out


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16"))
def _q40_matmul_stacked_scratch(layer, qs_t, scale, x, *, block_rows,
                                block_t, interpret, bf16=False):
    _, _, d, nb = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // block_rows, t // block_t),
        in_specs=[
            pl.BlockSpec((1, NJ, block_rows, nb),
                         lambda i, ti, L: (L[0], 0, i, 0)),
            pl.BlockSpec((1, block_rows, nb), lambda i, ti, L: (L[0], i, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti, L: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti, L: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows),
                               lambda i, ti, L: (ti, i)),
        scratch_shapes=[pltpu.VMEM((NJ, block_rows, nb), wdt),
                        pltpu.VMEM((NJ, block_rows, nb), wdt)],
    )
    return pl.pallas_call(
        functools.partial(_kernel_scratch_stacked, bf16=bf16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(layer, qs_t, scale, xlo, xhi)


def _matmul_body(qs3, s, xlo_ref, xhi_ref, out_ref, bf16=False):
    """Shared T>1 MXU body: qs3 (NJ, R, nb) codes view, s (R, nb) scales.

    ``bf16`` (fast-prefill, ops/linear.matmul_precision): bf16 MXU passes
    with f32 accumulation instead of the 3-pass HIGHEST f32 discipline —
    T>8 prefill is MXU-bound, so this is the big lever. The flag is threaded
    EXPLICITLY from q40_matmul (where the trace-time contextvar is read)
    because _q40_matmul_2d/_q40_matmul_stacked are themselves jitted and
    their trace cache cannot see the contextvar — a cached parity trace
    would silently serve the bf16 program (and did, round 2).
    """
    dn = (((1,), (1,)), ((), ()))                # contract both minor dims
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    prec = None if bf16 else jax.lax.Precision.HIGHEST
    acc = None
    # unrolled over the 16 nibble planes: one grid step computes the whole
    # output tile, so the packed bytes stream in as few large DMAs and the
    # compiler can software-pipeline unpack against the MXU
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)             # (R, nb)
        wlo = (((q & 0xF) - 8).astype(jnp.float32) * s).astype(wdt)
        whi = (((q >> 4) - 8).astype(jnp.float32) * s).astype(wdt)
        # parity mode: HIGHEST = true f32 MXU passes; decode is HBM-bound on
        # the packed weights, so the extra passes don't move the bottleneck
        a = jax.lax.dot_general(xlo_ref[j].astype(wdt), wlo, dn,
                                preferred_element_type=jnp.float32,
                                precision=prec)
        a = a + jax.lax.dot_general(xhi_ref[j].astype(wdt), whi, dn,
                                    preferred_element_type=jnp.float32,
                                    precision=prec)
        acc = a if acc is None else acc + a
    out_ref[...] = acc


def _kernel(qs_ref, scale_ref, xlo_ref, xhi_ref, out_ref, *, bf16=False):
    _matmul_body(qs_ref, scale_ref[...], xlo_ref, xhi_ref, out_ref, bf16)


def _kernel_stacked(layer_ref, qs_ref, scale_ref, xlo_ref, xhi_ref, out_ref,
                    *, bf16=False):
    del layer_ref  # consumed by the index maps
    _matmul_body(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref, out_ref, bf16)


def _split_x(x: jax.Array, nb: int) -> tuple[jax.Array, jax.Array]:
    """(T, n) f32 -> xlo/xhi (16, T, nb) in kernel plane order."""
    t = x.shape[0]
    x3 = x.reshape(t, nb, QK)
    xlo = jnp.transpose(x3[:, :, :NJ], (2, 0, 1))
    xhi = jnp.transpose(x3[:, :, NJ:], (2, 0, 1))
    return xlo, xhi


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16", "multi_body"))
def _q40_matmul_2d(qs_t, scale, x, *, block_rows, block_t, interpret,
                   bf16=False, multi_body="vpu"):
    _, d, nb = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    if t == 1:
        xsum = jnp.sum(xlo[:, 0] + xhi[:, 0], axis=0, keepdims=True)  # (1, nb)
        out = pl.pallas_call(
            _kernel_matvec,
            grid=(d // block_rows,),
            in_specs=[
                pl.BlockSpec((NJ, block_rows, nb), lambda i: (0, i, 0)),
                pl.BlockSpec((block_rows, nb), lambda i: (i, 0)),
                pl.BlockSpec((NJ, 1, nb), lambda i: (0, 0, 0)),
                pl.BlockSpec((NJ, 1, nb), lambda i: (0, 0, 0)),
                pl.BlockSpec((1, nb), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((d, 1), jnp.float32),
            interpret=interpret,
        )(qs_t, scale, xlo, xhi, xsum)
        return out.reshape(1, d)
    if t <= MULTI_T_MAX:
        if multi_body == "dequant":
            out = pl.pallas_call(
                _kernel_multi_dequant,
                grid=(d // block_rows,),
                in_specs=[
                    pl.BlockSpec((NJ, block_rows, nb), lambda i: (0, i, 0)),
                    pl.BlockSpec((block_rows, nb), lambda i: (i, 0)),
                    pl.BlockSpec((t, 2 * NJ * nb), lambda i: (0, 0)),
                ],
                out_specs=pl.BlockSpec((block_rows, t), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((d, t), jnp.float32),
                scratch_shapes=[
                    pltpu.VMEM((block_rows, 2 * NJ * nb), jnp.bfloat16)],
                compiler_params=_VMEM64_PARAMS,
                interpret=interpret,
            )(qs_t, scale, _x_planes(x, nb))
            return jnp.transpose(out)                # (t, d)
        xsum = jnp.sum(xlo + xhi, axis=0)            # (t, nb)
        out = pl.pallas_call(
            _kernel_multi,
            grid=(d // block_rows,),
            in_specs=[
                pl.BlockSpec((NJ, block_rows, nb), lambda i: (0, i, 0)),
                pl.BlockSpec((block_rows, nb), lambda i: (i, 0)),
                pl.BlockSpec((NJ, t, nb), lambda i: (0, 0, 0)),
                pl.BlockSpec((NJ, t, nb), lambda i: (0, 0, 0)),
                pl.BlockSpec((t, nb), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, t), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((d, t), jnp.float32),
            # wide-nb 13B shapes (w2 nb=432 at t=2) measure ~26M of scoped
            # stack against the 16M default — raise like the MXU kernels
            compiler_params=_VMEM64_PARAMS,
            interpret=interpret,
        )(qs_t, scale, xlo, xhi, xsum)
        return jnp.transpose(out)                    # (t, d)
    grid = (t // block_t, d // block_rows)
    out = pl.pallas_call(
        functools.partial(_kernel, bf16=bf16),
        compiler_params=_VMEM64_PARAMS,
        grid=grid,
        in_specs=[
            pl.BlockSpec((NJ, block_rows, nb), lambda ti, i: (0, i, 0)),
            pl.BlockSpec((block_rows, nb), lambda ti, i: (i, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows), lambda ti, i: (ti, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        interpret=interpret,
    )(qs_t, scale, xlo, xhi)
    return out


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16", "multi_body"))
def _q40_matmul_stacked(layer, qs_t, scale, x, *, block_rows, block_t,
                        interpret, bf16=False, multi_body="vpu"):
    _, _, d, nb = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    if t == 1:
        xsum = jnp.sum(xlo[:, 0] + xhi[:, 0], axis=0, keepdims=True)  # (1, nb)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(d // block_rows,),
            in_specs=[
                pl.BlockSpec((1, NJ, block_rows, nb),
                             lambda i, L: (L[0], 0, i, 0)),
                pl.BlockSpec((1, block_rows, nb), lambda i, L: (L[0], i, 0)),
                pl.BlockSpec((NJ, 1, nb), lambda i, L: (0, 0, 0)),
                pl.BlockSpec((NJ, 1, nb), lambda i, L: (0, 0, 0)),
                pl.BlockSpec((1, nb), lambda i, L: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, 1), lambda i, L: (i, 0)),
        )
        out = pl.pallas_call(
            _kernel_matvec_stacked, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((d, 1), jnp.float32),
            interpret=interpret,
        )(layer, qs_t, scale, xlo, xhi, xsum)
        return out.reshape(1, d)
    if t <= MULTI_T_MAX:
        if multi_body == "dequant":
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(d // block_rows,),
                in_specs=[
                    pl.BlockSpec((1, NJ, block_rows, nb),
                                 lambda i, L: (L[0], 0, i, 0)),
                    pl.BlockSpec((1, block_rows, nb),
                                 lambda i, L: (L[0], i, 0)),
                    pl.BlockSpec((t, 2 * NJ * nb), lambda i, L: (0, 0)),
                ],
                out_specs=pl.BlockSpec((block_rows, t),
                                       lambda i, L: (i, 0)),
                scratch_shapes=[
                    pltpu.VMEM((block_rows, 2 * NJ * nb), jnp.bfloat16)],
            )
            out = pl.pallas_call(
                _kernel_multi_dequant_stacked, grid_spec=grid_spec,
                out_shape=jax.ShapeDtypeStruct((d, t), jnp.float32),
                compiler_params=_VMEM64_PARAMS, interpret=interpret,
            )(layer, qs_t, scale, _x_planes(x, nb))
            return jnp.transpose(out)                # (t, d)
        xsum = jnp.sum(xlo + xhi, axis=0)            # (t, nb)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(d // block_rows,),
            in_specs=[
                pl.BlockSpec((1, NJ, block_rows, nb),
                             lambda i, L: (L[0], 0, i, 0)),
                pl.BlockSpec((1, block_rows, nb), lambda i, L: (L[0], i, 0)),
                pl.BlockSpec((NJ, t, nb), lambda i, L: (0, 0, 0)),
                pl.BlockSpec((NJ, t, nb), lambda i, L: (0, 0, 0)),
                pl.BlockSpec((t, nb), lambda i, L: (0, 0)),
            ],
            out_specs=pl.BlockSpec((block_rows, t), lambda i, L: (i, 0)),
        )
        out = pl.pallas_call(
            _kernel_multi_stacked, grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((d, t), jnp.float32),
            compiler_params=_VMEM64_PARAMS, interpret=interpret,
        )(layer, qs_t, scale, xlo, xhi, xsum)
        return jnp.transpose(out)                    # (t, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(t // block_t, d // block_rows),
        in_specs=[
            pl.BlockSpec((1, NJ, block_rows, nb),
                         lambda ti, i, L: (L[0], 0, i, 0)),
            pl.BlockSpec((1, block_rows, nb), lambda ti, i, L: (L[0], i, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i, L: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i, L: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows),
                               lambda ti, i, L: (ti, i)),
    )
    return pl.pallas_call(
        functools.partial(_kernel_stacked, bf16=bf16), grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(layer, qs_t, scale, xlo, xhi)


# T>1 tile cap: the MXU body materializes f32 (rows, nb) wlo/whi temporaries
# per unrolled plane on the scoped-VMEM stack; rows*nb above ~128k blows the
# 16MB limit at 7B shapes (observed: 512x344 -> 16.9M)
_MATMUL_ROWSXNB_CAP = 131072


def _pick_block_rows(d: int, t: int = 1, nb: int = 128,
                     block_t: int | None = None) -> int | None:
    """Output-tile rows, up to ~768/tile (amortizes grid-step overhead while
    keeping the unpack working set in VMEM).

    Three paths, three constraints (the t > 1 rules only bite on real TPU;
    interpret mode doesn't check):
    * t == 1 (matvec): out block (rows, 1) — rows is second-minor, any
      multiple-of-8 divisor works.
    * 1 < t <= MULTI_T_MAX (small-T VPU body): out block (rows, t) with the
      full t minor — rows again multiple-of-8, but the t per-row (rows, nb)
      f32 accumulators cap rows*nb*t for scoped-VMEM headroom.
    * t > MULTI_T_MAX (MXU body): out block (t_tile, rows) — rows is MINOR
      and must be a multiple of 128 or the whole d, with its own rows*nb cap
      for the f32 wlo/whi temporaries.
    """
    if t == 1:
        # rows*nb VMEM budget: the double-buffered tile set is ~(16+4) bytes
        # per (row, block) — 16 u8 code planes + one f32 scale — so 360k
        # keeps it under ~14.4 MB of the 16 MB scoped limit. Only binds at
        # very wide inputs (nb=896 at 70B's hidden/8=28672-wide w2 slice:
        # an uncapped 512-row tile measured 17.5 MB and failed to compile)
        step, cap = 8, max(8, 360_000 // nb)
    elif t <= MULTI_T_MAX:
        # the compiler keeps several unrolled-plane temporaries live next to
        # the t accumulators; the 300k rows*nb*t cap was sized against the
        # old 16MB scoped limit — the multi kernels now run with the raised
        # _VMEM64_PARAMS (wide-nb shapes measured ~26M), so the cap is a
        # tile-size heuristic, not a hard ceiling. DLLAMA_MULTI_CAP
        # overrides it (tile-size experiments via tools/batch_bench.py;
        # measured flat 300k/600k/1200k at 13B B=2 — tile granularity is
        # not that path's limiter)
        raw = os.environ.get("DLLAMA_MULTI_CAP", "")
        try:
            cap_words = int(raw) if raw else 300_000
        except ValueError:
            raise ValueError(
                f"DLLAMA_MULTI_CAP={raw!r}: expected a plain integer "
                f"(rows*nb*t word budget, e.g. 600000)") from None
        step, cap = 8, max(8, cap_words // (t * nb))
    else:
        # MXU path. With a FULL 128-row t-tile Mosaic pipelines the
        # unrolled-plane f32 temporaries within the budget; at smaller
        # t-tiles it keeps more of them live and big row tiles overflow
        # scoped VMEM. Measured boundary: (nb=128, bt=32, rows=640) needs
        # 17.6M and fails to compile; (nb=128, bt=32, rows=256) passes;
        # (nb=344, bt=64, rows=256) is the round-1-proven 7B w2 prefill
        # tile; (nb=128, bt=128, rows=640) passes. So: full-bt keeps the
        # rows*nb word cap, small-bt caps rows at 256.
        if (block_t or 128) >= 128:
            step, cap = 128, _MATMUL_ROWSXNB_CAP // nb
        else:
            step, cap = 128, 256
    top_rows = _matvec_cap() if t == 1 else 768
    top = (min(d, top_rows, cap) // step) * step
    for cand in range(top, 0, -step):
        if d % cand == 0:
            return cand
    # small odd dims: a full-d block is legal when it fits the same budget
    return d if d <= min(top_rows, cap) else None


def kernel_supports(d: int, n: int) -> bool:
    """Whether pre-tiling a (d, n) weight to the kernel layout pays off:
    decided by the T=1 matvec path (the per-token hot loop). Other T values
    that the tiling rules can't handle (e.g. d=1376 = 11008/tp8 has no
    multiple-of-128 divisor for the T>8 MXU path) fall back INSIDE
    q40_matmul to a dequantize-then-dot on the packed weight, so prefill
    still works on any packed shape."""
    return _pick_block_rows(d, 1, n // QK) is not None


def _pick_block_t(t: int, nb: int) -> int:
    # cap the T tile so the xlo/xhi plane-sets (2 x NJ*bt*nb f32, DOUBLE
    # buffered by the pipeline) stay within a few MB of VMEM next to the
    # packed weight tile (observed: bt=256 at nb=128 -> 16.9M scoped OOM)
    cap = max(8, (3 * 1024 * 1024) // (NJ * nb * 8))
    if t <= min(cap, 128):
        return t
    for cand in (128, 64, 32, 16, 8):
        if cand <= cap and t % cand == 0:
            return cand
    return t


def _dequant_matmul(w: Q40Kernel, x2: jax.Array,
                    layer: jax.Array | None) -> jax.Array:
    """XLA fallback on an already-packed weight: dequantize the (layer's)
    kernel-layout blocks inline and dot. Used only for (d, t) combos the
    tiling rules can't place (see q40_matmul)."""
    from .quants import dequantize_q40_jax

    if layer is not None:
        w = Q40Kernel(w.qs_t[layer], w.scale[layer])
    qs = jnp.transpose(w.qs_t, (1, 2, 0))            # (d, nb, 16)
    wf = dequantize_q40_jax(qs, w.scale)
    # fast-prefill applies to ALL dispatch targets — without this the
    # tp-sharded band shapes that land here (e.g. d=1376=11008/8, no legal
    # MXU tiling) would silently run at parity speed
    return _precision_dot(wf, x2)


def _precision_dot(wf, x2):
    """Dequant-fallback einsum honoring the fast-prefill precision mode —
    THE one copy of this dispatch for the dequantize-then-dot paths (the
    kernel bodies carry their own threaded ``bf16`` flag)."""
    from .linear import matmul_mode

    if matmul_mode() == "bf16":
        return jnp.einsum("dn,tn->td", wf.astype(jnp.bfloat16),
                          x2.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return jnp.einsum("dn,tn->td", wf, x2.astype(jnp.float32),
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)


def _matvec_cap() -> int:
    """T=1 matvec row-tile cap — DLLAMA_MATVEC_CAP, default 768 (the
    tuned d-major pick). Raising it trades grid-step count for longer
    per-tile DMAs (tile-size experiments on the real bench; the scoped-
    VMEM word budget still applies on top)."""
    raw = os.environ.get("DLLAMA_MATVEC_CAP", "")
    if not raw:
        return 768
    try:
        cap = int(raw)
    except ValueError:
        raise ValueError(f"DLLAMA_MATVEC_CAP={raw!r}: expected a plain "
                         f"integer row cap (e.g. 1536)") from None
    if cap < 128:
        # below the nb-major lane minimum the cap would silently drop
        # leaves off the kernel layout (a LAYOUT change, not a tile
        # change) — refuse rather than measure the wrong code path
        raise ValueError(f"DLLAMA_MATVEC_CAP={cap} < 128: the nb-major "
                         f"row tile needs a multiple of 128")
    return cap


def _pick_rows_nb(d: int, nb: int) -> int | None:
    """Row tile for the nb-major matvec: rows ride the LANES, so they must
    be a multiple of 128 — a d with no multiple-of-128 divisor (including
    every d < 128) returns None and the caller routes to the dequant
    fallback; rows*nb stays under the same ~(16+4)-bytes-per-word
    scoped-VMEM budget as the d-major matvec (DLLAMA_MATVEC_CAP lifts the
    768-row default for tile experiments)."""
    top = min(d, _matvec_cap(), max(128, 360_000 // nb))
    for cand in range(top - top % 128, 0, -128):
        if d % cand == 0:
            return cand
    return None


def _dequant_nb(qs_t, scale):
    """jnp dequant of an nb-major (16, nb, d) plane set -> f32 (d, n)."""
    lo = ((qs_t & 0xF).astype(jnp.int8) - jnp.int8(8))
    hi = ((qs_t >> 4).astype(jnp.int8) - jnp.int8(8))
    codes = jnp.concatenate([lo, hi], axis=0)        # (32, nb, d): j then j+16
    w = codes.astype(jnp.float32) * scale[None]
    d = scale.shape[-1]
    return jnp.transpose(w, (2, 1, 0)).reshape(d, -1)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matvec_nb_2d(qs_t, scale, x, *, block_rows, interpret):
    _, nb, d = qs_t.shape
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)   # (NJ, 1, nb)
    xlo = jnp.transpose(xlo, (0, 2, 1))              # (NJ, nb, 1)
    xhi = jnp.transpose(xhi, (0, 2, 1))
    xsum = jnp.sum(xlo[:, :, 0] + xhi[:, :, 0], axis=0)[:, None]  # (nb, 1)
    out = pl.pallas_call(
        _kernel_matvec_nb,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((NJ, nb, block_rows), lambda i: (0, 0, i)),
            pl.BlockSpec((nb, block_rows), lambda i: (0, i)),
            pl.BlockSpec((NJ, nb, 1), lambda i: (0, 0, 0)),
            pl.BlockSpec((NJ, nb, 1), lambda i: (0, 0, 0)),
            pl.BlockSpec((nb, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_rows), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        interpret=interpret,
    )(qs_t, scale, xlo, xhi, xsum)
    return out                                        # (1, d)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matvec_nb_stacked(layer, qs_t, scale, x, *, block_rows, interpret):
    _, _, nb, d = qs_t.shape
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    xlo = jnp.transpose(xlo, (0, 2, 1))
    xhi = jnp.transpose(xhi, (0, 2, 1))
    xsum = jnp.sum(xlo[:, :, 0] + xhi[:, :, 0], axis=0)[:, None]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((1, NJ, nb, block_rows),
                         lambda i, L: (L[0], 0, 0, i)),
            pl.BlockSpec((1, nb, block_rows), lambda i, L: (L[0], 0, i)),
            pl.BlockSpec((NJ, nb, 1), lambda i, L: (0, 0, 0)),
            pl.BlockSpec((NJ, nb, 1), lambda i, L: (0, 0, 0)),
            pl.BlockSpec((nb, 1), lambda i, L: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_rows), lambda i, L: (0, i)),
    )
    out = pl.pallas_call(
        _kernel_matvec_nb_stacked, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        interpret=interpret,
    )(layer, qs_t, scale, xlo, xhi, xsum)
    return out


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "interpret"))
def _q40_multi_nb_2d(qs_t, scale, x, *, block_rows, interpret):
    _, nb, d = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)   # (NJ, t, nb)
    xlo = jnp.transpose(xlo, (0, 2, 1))              # (NJ, nb, t)
    xhi = jnp.transpose(xhi, (0, 2, 1))
    xsum = jnp.sum(xlo + xhi, axis=0)                # (nb, t)
    out = pl.pallas_call(
        _kernel_multi_nb,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((NJ, nb, block_rows), lambda i: (0, 0, i)),
            pl.BlockSpec((nb, block_rows), lambda i: (0, i)),
            pl.BlockSpec((NJ, nb, t), lambda i: (0, 0, 0)),
            pl.BlockSpec((NJ, nb, t), lambda i: (0, 0, 0)),
            pl.BlockSpec((nb, t), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, block_rows), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        # 13B batch shapes (wqkv d=15360 at t=2) measure 16.9M of scoped
        # stack against the 16M default — raise like the MXU kernels
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(qs_t, scale, xlo, xhi, xsum)
    return out                                        # (t, d)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "interpret"))
def _q40_multi_nb_stacked(layer, qs_t, scale, x, *, block_rows, interpret):
    _, _, nb, d = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    xlo = jnp.transpose(xlo, (0, 2, 1))
    xhi = jnp.transpose(xhi, (0, 2, 1))
    xsum = jnp.sum(xlo + xhi, axis=0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((1, NJ, nb, block_rows),
                         lambda i, L: (L[0], 0, 0, i)),
            pl.BlockSpec((1, nb, block_rows), lambda i, L: (L[0], 0, i)),
            pl.BlockSpec((NJ, nb, t), lambda i, L: (0, 0, 0)),
            pl.BlockSpec((NJ, nb, t), lambda i, L: (0, 0, 0)),
            pl.BlockSpec((nb, t), lambda i, L: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, block_rows), lambda i, L: (0, i)),
    )
    return pl.pallas_call(
        _kernel_multi_nb_stacked, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(layer, qs_t, scale, xlo, xhi, xsum)


def _kernel_scratch_nb(qs_ref, scale_ref, xlo_ref, xhi_ref, out_ref,
                       wlo_ref, whi_ref, *, bf16=False):
    _matmul_body_scratch(qs_ref, scale_ref[...], xlo_ref, xhi_ref,
                         out_ref, wlo_ref, whi_ref, bf16, nb_major=True)


def _kernel_scratch_nb_stacked(layer_ref, qs_ref, scale_ref, xlo_ref,
                               xhi_ref, out_ref, wlo_ref, whi_ref, *,
                               bf16=False):
    del layer_ref
    _matmul_body_scratch(qs_ref[0], scale_ref[0], xlo_ref, xhi_ref,
                         out_ref, wlo_ref, whi_ref, bf16, nb_major=True)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16"))
def _q40_mxu_nb_2d_scratch(qs_t, scale, x, *, block_rows, block_t,
                           interpret, bf16=False):
    _, nb, d = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    return pl.pallas_call(
        functools.partial(_kernel_scratch_nb, bf16=bf16),
        grid=(d // block_rows, t // block_t),
        in_specs=[
            pl.BlockSpec((NJ, nb, block_rows), lambda i, ti: (0, 0, i)),
            pl.BlockSpec((nb, block_rows), lambda i, ti: (0, i)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows), lambda i, ti: (ti, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((NJ, nb, block_rows), wdt),
                        pltpu.VMEM((NJ, nb, block_rows), wdt)],
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(qs_t, scale, xlo, xhi)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16"))
def _q40_mxu_nb_stacked_scratch(layer, qs_t, scale, x, *, block_rows,
                                block_t, interpret, bf16=False):
    _, _, nb, d = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    wdt = jnp.bfloat16 if bf16 else jnp.float32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // block_rows, t // block_t),
        in_specs=[
            pl.BlockSpec((1, NJ, nb, block_rows),
                         lambda i, ti, L: (L[0], 0, 0, i)),
            pl.BlockSpec((1, nb, block_rows), lambda i, ti, L: (L[0], 0, i)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti, L: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda i, ti, L: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows),
                               lambda i, ti, L: (ti, i)),
        scratch_shapes=[pltpu.VMEM((NJ, nb, block_rows), wdt),
                        pltpu.VMEM((NJ, nb, block_rows), wdt)],
    )
    return pl.pallas_call(
        functools.partial(_kernel_scratch_nb_stacked, bf16=bf16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS,
        interpret=interpret,
    )(layer, qs_t, scale, xlo, xhi)


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16"))
def _q40_mxu_nb_2d(qs_t, scale, x, *, block_rows, block_t, interpret,
                   bf16=False):
    _, nb, d = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)   # (NJ, t, nb) — natural
    out = pl.pallas_call(
        functools.partial(_kernel_mxu_nb, bf16=bf16),
        compiler_params=_VMEM64_PARAMS,
        grid=(t // block_t, d // block_rows),
        in_specs=[
            pl.BlockSpec((NJ, nb, block_rows), lambda ti, i: (0, 0, i)),
            pl.BlockSpec((nb, block_rows), lambda ti, i: (0, i)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows), lambda ti, i: (ti, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        interpret=interpret,
    )(qs_t, scale, xlo, xhi)
    return out


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret",
                                    "bf16"))
def _q40_mxu_nb_stacked(layer, qs_t, scale, x, *, block_rows, block_t,
                        interpret, bf16=False):
    _, _, nb, d = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(t // block_t, d // block_rows),
        in_specs=[
            pl.BlockSpec((1, NJ, nb, block_rows),
                         lambda ti, i, L: (L[0], 0, 0, i)),
            pl.BlockSpec((1, nb, block_rows), lambda ti, i, L: (L[0], 0, i)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i, L: (0, ti, 0)),
            pl.BlockSpec((NJ, block_t, nb), lambda ti, i, L: (0, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows),
                               lambda ti, i, L: (ti, i)),
    )
    return pl.pallas_call(
        functools.partial(_kernel_mxu_nb_stacked, bf16=bf16),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(layer, qs_t, scale, xlo, xhi)


def _q40_matmul_nbmajor(w: Q40KernelNb, x: jax.Array,
                        interpret: bool | None,
                        layer: jax.Array | None,
                        block_rows: int | None = None) -> jax.Array:
    """nb-major dispatch, all T regimes on kernels (T=1 matvec, 2..8 VPU
    multi, >8 MXU with the standard (M,K)x(K,N) dot); the dequant fallback
    remains only for tilings the rules can't place.

    ``block_rows`` overrides the auto-picked row tile (q40_matmul's tuning
    knob, plumbed through for nb-major too). Lane-riding rows must be a
    multiple of 128 dividing d; the T-path VMEM caps below still apply, so
    an oversized override is shrunk, not obeyed blindly."""
    qs_t, scale = w.qs_t, w.scale
    nb, d = qs_t.shape[-2], qs_t.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    t = x2.shape[0]
    if t > MULTI_T_MAX and t % 8 != 0:
        pad = (-t) % 8
        out = _q40_matmul_nbmajor(w, jnp.pad(x2, ((0, pad), (0, 0))),
                                  interpret, layer, block_rows)
        return out[:t].reshape(*lead, d)
    if t > MULTI_T_MAX and _prefill_matmul_mode() == "dequant":
        # prefill-ladder experiment arm — see q40_matmul
        if layer is not None:
            qs_t = qs_t[layer]
            scale = scale[layer]
        return _precision_dot(_dequant_nb(qs_t, scale),
                              x2).reshape(*lead, d)
    if block_rows is not None:
        if block_rows % 128 or d % block_rows:
            raise ValueError(
                f"nb-major block_rows={block_rows} must be a multiple of "
                f"128 dividing d={d}")
        rows = block_rows
        if t == 1:
            # same scoped-VMEM budget the auto pick enforces — an oversized
            # override is shrunk, not obeyed blindly (the t>1 branches below
            # re-cap for themselves)
            cap = max(128, 360_000 // nb)
            if rows > cap:
                rows = next((r for r in range(cap - cap % 128, 0, -128)
                             if d % r == 0), rows)
    else:
        rows = _pick_rows_nb(d, nb)
    if rows is not None and 1 < t <= MULTI_T_MAX:
        # the multi body carries t (nb, rows) f32 accumulators plus 16*t
        # unrolled broadcast temporaries; measured on v5e: t=4/rows=256
        # compiles, t=8 overflows scoped VMEM even at rows=128 — so the
        # kernel serves t <= 4 and 5..8 take the dequant fallback below
        if t > 4:
            rows = None
        else:
            cap = max(128, 300_000 // (t * nb))
            rows = next((r for r in
                         range(min(rows, cap - cap % 128), 0, -128)
                         if d % r == 0), None)
    if rows is not None and t > MULTI_T_MAX:
        # the MXU body's f32 wlo/whi temporaries obey the same measured
        # rows*nb boundary as the d-major path (_MATMUL_ROWSXNB_CAP);
        # _pick_rows_nb's matvec budget is looser, so re-cap here
        cap = _MATMUL_ROWSXNB_CAP // nb
        rows = next((r for r in range(min(rows, cap - cap % 128), 0, -128)
                     if d % r == 0), None)
        block_t = _pick_block_t(t, nb)
        if rows is not None and block_t < 128 and rows > 256:
            # same Mosaic small-t-tile VMEM behavior as the d-major MXU
            # path: shrink the row tile (see _pick_block_rows)
            rows = 256 if d % 256 == 0 else (128 if d % 128 == 0 else None)
    if rows is not None:
        from .linear import matmul_mode

        bf16 = matmul_mode() == "bf16"
        scratch = t > MULTI_T_MAX and _prefill_matmul_mode() == "scratch"
        if layer is not None:
            lidx = jnp.asarray(layer, dtype=jnp.int32).reshape(1)
            if t == 1:
                out = _q40_matvec_nb_stacked(lidx, qs_t, scale, x2,
                                             block_rows=rows,
                                             interpret=interpret)
            elif t <= MULTI_T_MAX:
                out = _q40_multi_nb_stacked(lidx, qs_t, scale, x2,
                                            block_rows=rows,
                                            interpret=interpret)
            else:
                call = (_q40_mxu_nb_stacked_scratch if scratch
                        else _q40_mxu_nb_stacked)
                out = call(lidx, qs_t, scale, x2, block_rows=rows,
                           block_t=_pick_block_t(t, nb),
                           interpret=interpret, bf16=bf16)
        else:
            if t == 1:
                out = _q40_matvec_nb_2d(qs_t, scale, x2, block_rows=rows,
                                        interpret=interpret)
            elif t <= MULTI_T_MAX:
                out = _q40_multi_nb_2d(qs_t, scale, x2, block_rows=rows,
                                       interpret=interpret)
            else:
                call = (_q40_mxu_nb_2d_scratch if scratch
                        else _q40_mxu_nb_2d)
                out = call(qs_t, scale, x2, block_rows=rows,
                           block_t=_pick_block_t(t, nb),
                           interpret=interpret, bf16=bf16)
        return out.reshape(*lead, d)
    if layer is not None:
        qs_t = qs_t[layer]
        scale = scale[layer]
    wf = _dequant_nb(qs_t, scale)
    return _precision_dot(wf, x2).reshape(*lead, d)


def _dequant_i4(w) -> jax.Array:
    """f32 dense weight from int4 planes (the T>1 / untileable fallback):
    plane index IS the in-block value position (0..31)."""
    qs4, scale = w.qs4, w.scale
    vals = qs4.astype(jnp.float32)
    if isinstance(w, Q40KernelNbI4):
        # (..., 32, nb, d) -> (..., d, nb, 32)
        vals = jnp.moveaxis(jnp.moveaxis(vals, -3, -1), -3, -2)
        scale = jnp.swapaxes(scale, -1, -2)
    else:
        vals = jnp.moveaxis(vals, -3, -1)          # (..., d, nb, 32)
    w_f = vals * scale[..., None]
    return w_f.reshape(*w_f.shape[:-2], w_f.shape[-2] * 32)


def _pick_rows_i4(d: int, nb: int) -> int | None:
    """Row tile for the d-major int4 matvec: int4 operands carry a
    (64, 128) native tile, so the second-minor block dim (rows) must be a
    multiple of 64 (Mosaic: 'has tiling (64, 128)'), under the same
    VMEM-word budget as the u8 picker."""
    top = min(d, _matvec_cap(), max(64, 360_000 // nb))
    for cand in range(top - top % 64, 0, -64):
        if d % cand == 0:
            return cand
    return None


def _q40_matmul_i4(w, x, interpret, layer, block_rows):
    """Dispatch for the int4-plane layouts (chain-internal, T=1 hot path;
    anything else takes the dequantize-then-dot fallback)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb_major = isinstance(w, Q40KernelNbI4)
    d = w.logical_shape[-2]
    nb = (w.scale.shape[-2] if nb_major else w.scale.shape[-1])
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    if x2.shape[0] == 1:
        if nb_major:
            rows = block_rows or _pick_rows_nb(d, nb)
        else:
            rows = block_rows or _pick_rows_i4(d, nb)
        if rows:
            if layer is not None:
                out = (_q40_matvec_nb_i4_stacked if nb_major
                       else _q40_matvec_i4_stacked)(
                    jnp.asarray(layer, jnp.int32).reshape(1), w.qs4,
                    w.scale, x2, block_rows=rows, interpret=interpret)
            else:
                out = (_q40_matvec_nb_i4_2d if nb_major
                       else _q40_matvec_i4_2d)(
                    w.qs4, w.scale, x2, block_rows=rows,
                    interpret=interpret)
            return out.reshape(*lead, d)
    wf = _dequant_i4(w)
    if layer is not None:
        wf = wf[layer]
    return jnp.einsum("dn,tn->td", wf, x2.astype(jnp.float32),
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST) \
        .reshape(*lead, d)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matvec_i4_2d(qs4, scale, x, *, block_rows, interpret):
    nj2, d, nb = qs4.shape
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)   # (NJ, 1, nb)
    x32 = jnp.concatenate([xlo, xhi], axis=0)        # (32, 1, nb)
    out = pl.pallas_call(
        _kernel_matvec_i4,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((nj2, block_rows, nb), lambda i: (0, i, 0)),
            pl.BlockSpec((block_rows, nb), lambda i: (i, 0)),
            pl.BlockSpec((nj2, 1, nb), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((d, 1), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(qs4, scale, x32)
    return out.reshape(1, d)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matvec_i4_stacked(layer, qs4, scale, x, *, block_rows, interpret):
    _, nj2, d, nb = qs4.shape
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    x32 = jnp.concatenate([xlo, xhi], axis=0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((1, nj2, block_rows, nb),
                         lambda i, L: (L[0], 0, i, 0)),
            pl.BlockSpec((1, block_rows, nb), lambda i, L: (L[0], i, 0)),
            pl.BlockSpec((nj2, 1, nb), lambda i, L: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, 1), lambda i, L: (i, 0)),
    )
    out = pl.pallas_call(
        _kernel_matvec_i4_stacked, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((d, 1), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(layer, qs4, scale, x32)
    return out.reshape(1, d)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matvec_nb_i4_2d(qs4, scale, x, *, block_rows, interpret):
    nj2, nb, d = qs4.shape
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)   # (NJ, 1, nb)
    x32 = jnp.transpose(jnp.concatenate([xlo, xhi], axis=0),
                        (0, 2, 1))                   # (32, nb, 1)
    out = pl.pallas_call(
        _kernel_matvec_nb_i4,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((nj2, nb, block_rows), lambda i: (0, 0, i)),
            pl.BlockSpec((nb, block_rows), lambda i: (0, i)),
            pl.BlockSpec((nj2, nb, 1), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_rows), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(qs4, scale, x32)
    return out


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matvec_nb_i4_stacked(layer, qs4, scale, x, *, block_rows,
                              interpret):
    _, nj2, nb, d = qs4.shape
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    x32 = jnp.transpose(jnp.concatenate([xlo, xhi], axis=0), (0, 2, 1))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(d // block_rows,),
        in_specs=[
            pl.BlockSpec((1, nj2, nb, block_rows),
                         lambda i, L: (L[0], 0, 0, i)),
            pl.BlockSpec((1, nb, block_rows), lambda i, L: (L[0], 0, i)),
            pl.BlockSpec((nj2, nb, 1), lambda i, L: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_rows), lambda i, L: (0, i)),
    )
    return pl.pallas_call(
        _kernel_matvec_nb_i4_stacked, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, d), jnp.float32),
        compiler_params=_VMEM64_PARAMS, interpret=interpret,
    )(layer, qs4, scale, x32)


def q40_matmul(w: Q40Kernel | Q40Weight, x: jax.Array,
               block_rows: int | None = None,
               interpret: bool | None = None,
               layer: jax.Array | None = None) -> jax.Array:
    """out[..., d] = dequant(w)(d, n) @ x[..., n], packed weights end to end.

    x may be (n,) or (..., n); leading dims are flattened into T for the
    kernel and restored after. ``w`` should be a pre-tiled Q40Kernel on the
    hot path; a Q40Weight is accepted and re-tiled per call (tests only).

    ``layer``: when given, ``w`` holds stacked per-layer weights (qs_t
    (L, 16, d, nb)) and the kernel DMAs layer ``layer`` directly out of the
    stack via scalar prefetch — the zero-copy path for lax.scan over layers.
    """
    if isinstance(w, (Q40KernelI4PackedD, Q40KernelI4PackedNb)):
        # callers outside a prepped chain (prefill, tests): unpack per
        # call — the bitcast is a reinterpretation, not a weight copy
        w = unpack_i4_packed(w)
    if isinstance(w, (Q40KernelI4, Q40KernelNbI4)):
        return _q40_matmul_i4(w, x, interpret, layer, block_rows)
    if isinstance(w, Q40KernelNb):
        return _q40_matmul_nbmajor(w, x, interpret, layer, block_rows)
    if isinstance(w, Q40Weight):
        w = to_kernel_layout(w)
    qs_t, scale = w.qs_t, w.scale
    d, nb = qs_t.shape[-2], qs_t.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # read the trace-time precision flag HERE (q40_matmul is inlined in the
    # caller's trace) and thread it as a static arg — the inner jits below
    # cache traces and cannot see the contextvar
    from .linear import matmul_mode

    bf16 = matmul_mode() == "bf16"
    lead = x.shape[:-1]
    n = x.shape[-1]
    x2 = x.reshape(-1, n)
    t = x2.shape[0]
    if t > MULTI_T_MAX and _prefill_matmul_mode() == "dequant":
        # prefill-ladder experiment arm (tools/prefill_ladder.py): unpack the
        # weight ONCE into a bf16/f32 HBM temp and let XLA drive a plain MXU
        # dot, instead of the Pallas grid re-unpacking the weight tile per
        # T-tile. Decode (t==1) never takes this.
        return _dequant_matmul(w, x2, layer).reshape(*lead, d)
    if t > MULTI_T_MAX and t % 8 != 0:
        # pad to a multiple of 8 so the MXU path always has an under-cap
        # t-tile divisor (a full-t block of awkward length can exceed the
        # scoped-VMEM plane budget); the pad rows are zeros, sliced off below
        pad = (-t) % 8
        out = q40_matmul(w, jnp.pad(x2, ((0, pad), (0, 0))),
                         block_rows=block_rows, interpret=interpret,
                         layer=layer)
        return out[:t].reshape(*lead, d)
    block_t = _pick_block_t(t, nb)
    if block_rows is None:
        block_rows = _pick_block_rows(d, t, nb, block_t)
        if block_rows is None:
            # this (d, t) combo has no legal tiling (e.g. TP-shard dims with
            # no multiple-of-128 divisor at MXU T): dequantize-then-dot on
            # the packed weight — correctness everywhere, kernel speed on
            # the shapes that matter
            return _dequant_matmul(w, x2, layer).reshape(*lead, d)
    scratch = t > MULTI_T_MAX and _prefill_matmul_mode() == "scratch"
    # like bf16 above: the T<=8 body mode must be read at the CALLER's
    # trace point and threaded as a static arg, or a cached inner trace
    # silently serves the other body after the env flips
    extra = {} if scratch else {"multi_body": _multi_t_body()
                                if t <= MULTI_T_MAX else "vpu"}
    if layer is not None:
        if qs_t.ndim != 4:
            raise ValueError("layer= requires stacked (L, 16, d, nb) weights")
        lidx = jnp.asarray(layer, dtype=jnp.int32).reshape(1)
        call = _q40_matmul_stacked_scratch if scratch else _q40_matmul_stacked
        out = call(lidx, qs_t, scale, x2,
                   block_rows=block_rows, block_t=block_t,
                   interpret=interpret, bf16=bf16, **extra)
    else:
        call = _q40_matmul_2d_scratch if scratch else _q40_matmul_2d
        out = call(qs_t, scale, x2, block_rows=block_rows,
                   block_t=block_t, interpret=interpret, bf16=bf16, **extra)
    return out.reshape(*lead, d)
