"""Pallas TPU kernel: fused Q40 dequant + matmul.

The TPU analog of the reference's hot NEON kernel ``matmulQ40vQ80``
(src/funcs.cpp:185-260): weights stay packed in HBM (0.5625 bytes/value) and
the nibble-unpack + f16-delta scale happens in VMEM on the way into the dot —
HBM traffic per token is the packed bytes, not dequantized f32. This is what
makes single-token decode HBM-bound at the Q40 size instead of the f32 size
(the dequantize-then-dot XLA fallback in ops/linear.py materializes f32 tiles).

Layout in the kernel (see ops/quants.py for the wire format):
  qs2d (d, nb*16) uint8 — column c = b*16+j holds codes for values b*32+j
                           (low nibble) and b*32+j+16 (high nibble)
  d16  (d, nb) float16  — per-block deltas
  x is pre-split OUTSIDE the kernel into xlo/xhi (T, nb*16) matching the
  column order, so the kernel is: out[t, r] = sum_c (lo[r,c]-8)*s[r,c/16]*xlo[t,c]
                                            + (hi[r,c]-8)*s[r,c/16]*xhi[t,c]
  computed as two MXU dots against the unpacked row band.

Grid: one step per ``block_rows`` output rows; Pallas double-buffers the HBM
loads across steps automatically. Non-TPU backends run in interpret mode
(tests); the numerics are the exact Q40 value map, so parity with the XLA
path is bit-tight at f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.loader import Q40Weight

QK = 32


def _kernel(qs_ref, d16_ref, xlo_ref, xhi_ref, out_ref, *, block_rows, nb):
    q = qs_ref[...]                                   # (Rb, nb*16) uint8
    scales = d16_ref[...].astype(jnp.float32)         # (Rb, nb)
    lo = (q & 0xF).astype(jnp.int32) - 8
    hi = (q >> 4).astype(jnp.int32) - 8
    sc = jnp.broadcast_to(scales[:, :, None],
                          (block_rows, nb, 16)).reshape(block_rows, nb * 16)
    wlo = lo.astype(jnp.float32) * sc
    whi = hi.astype(jnp.float32) * sc
    acc = jnp.dot(xlo_ref[...], wlo.T, preferred_element_type=jnp.float32)
    acc += jnp.dot(xhi_ref[...], whi.T, preferred_element_type=jnp.float32)
    out_ref[...] = acc                                # (T, Rb)


def _split_x(x: jax.Array, nb: int) -> tuple[jax.Array, jax.Array]:
    """(T, n) f32 -> xlo/xhi (T, nb*16) in kernel column order."""
    t = x.shape[0]
    xb = x.reshape(t, nb, QK)
    return (xb[:, :, :16].reshape(t, nb * 16),
            xb[:, :, 16:].reshape(t, nb * 16))


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _q40_matmul_2d(qs2d, d16, x, *, block_rows, interpret):
    d, ncols = qs2d.shape
    nb = ncols // 16
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    grid = (d // block_rows,)
    out = pl.pallas_call(
        functools.partial(_kernel, block_rows=block_rows, nb=nb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, ncols), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, nb), lambda i: (i, 0)),
            pl.BlockSpec((t, ncols), lambda i: (0, 0)),
            pl.BlockSpec((t, ncols), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((t, block_rows), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        interpret=interpret,
    )(qs2d, d16, xlo, xhi)
    return out


def _pick_block_rows(d: int) -> int:
    for cand in (512, 256, 128):
        if d % cand == 0:
            return cand
    # largest multiple-of-8 divisor (TPU sublane alignment)
    top = (min(d, 1024) // 8) * 8
    for cand in range(top, 0, -8):
        if d % cand == 0:
            return cand
    raise ValueError(
        f"q40_matmul needs an output dim with a multiple-of-8 divisor, "
        f"got d={d}")


def q40_matmul(w: Q40Weight, x: jax.Array,
               block_rows: int | None = None,
               interpret: bool | None = None) -> jax.Array:
    """out[..., d] = dequant(w)(d, n) @ x[..., n], packed weights end to end.

    x may be (n,) or (..., n); leading dims are flattened into T for the
    kernel and restored after.
    """
    qs, d16 = w.qs, w.d16
    d, nb = qs.shape[-3], qs.shape[-2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_rows is None:
        block_rows = _pick_block_rows(d)
    lead = x.shape[:-1]
    n = x.shape[-1]
    x2 = x.reshape(-1, n)
    qs2d = qs.reshape(d, nb * 16)
    out = _q40_matmul_2d(qs2d, d16, x2, block_rows=block_rows,
                         interpret=interpret)
    return out.reshape(*lead, d)
