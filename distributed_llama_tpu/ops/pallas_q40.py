"""Pallas TPU kernel: fused Q40 dequant + matmul.

The TPU analog of the reference's hot NEON kernel ``matmulQ40vQ80``
(src/funcs.cpp:185-260): weights stay packed in HBM (0.5625 bytes/value) and
the nibble-unpack + f16-delta scale happens in VMEM on the way into the dot —
HBM traffic per token is the packed bytes, not dequantized f32. This is what
makes single-token decode HBM-bound at the Q40 size instead of the f32 size
(the dequantize-then-dot XLA fallback in ops/linear.py materializes f32 tiles).

Mosaic constraint that shapes this kernel: there is no supported way to
expand per-block scales (R, nb) to per-value (R, nb*16) inside the kernel
(minor-dim broadcast+reshape is an "unsupported shape cast"). So instead of
one wide dot over all 32 values per block, the grid carries the nibble
position j = 0..15 as its innermost axis and every step is pure 2D:

  qs_t   (16, d, nb) uint8  — qs_t[j, r, b] packs values x[b*32+j] (low
                               nibble) and x[b*32+j+16] (high nibble)
  scale  (d, nb) float32    — per-block deltas (f32: Mosaic has no f16
                               vectors; the f16->f32 upconvert is exact)
  xlo/xhi (16, t, nb) f32   — xlo[j, t, b] = x[t, b*32+j], xhi: +16

  step (ti, i, j):  out[ti, i] += xlo[j] @ ((lo(qs_t[j]) - 8) * scale).T
                               +  xhi[j] @ ((hi(qs_t[j]) - 8) * scale).T

The (16, d, nb) weight tiling is prepared ONCE at load time
(io.loader.to_kernel_layout); feeding a codec-layout Q40Weight works but
re-tiles on every call — fine under test, wrong for the per-token hot loop.

Grid: (t tiles, d tiles, 16); j innermost so the output tile stays resident
in VMEM across its 16 accumulation steps; Pallas double-buffers the packed
HBM loads across steps. Non-TPU backends run in interpret mode (tests); the
numerics are the exact Q40 value map, so parity with the XLA path is
bit-tight at f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..io.loader import Q40Kernel, Q40Weight, to_kernel_layout

QK = 32
NJ = 16  # nibble positions per block byte-plane


def _kernel(qs_ref, scale_ref, xlo_ref, xhi_ref, out_ref):
    j = pl.program_id(2)
    q = qs_ref[0].astype(jnp.int32)              # (R, nb)
    s = scale_ref[...]                           # (R, nb) f32
    wlo = ((q & 0xF) - 8).astype(jnp.float32) * s
    whi = ((q >> 4) - 8).astype(jnp.float32) * s
    dn = (((1,), (1,)), ((), ()))                # contract both minor dims
    # HIGHEST: true f32 MXU passes — the parity contract; decode is HBM-bound
    # on the packed weights, so the extra passes don't move the bottleneck
    acc = jax.lax.dot_general(xlo_ref[0], wlo, dn,
                              preferred_element_type=jnp.float32,
                              precision=jax.lax.Precision.HIGHEST)
    acc = acc + jax.lax.dot_general(xhi_ref[0], whi, dn,
                                    preferred_element_type=jnp.float32,
                                    precision=jax.lax.Precision.HIGHEST)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = acc

    @pl.when(j > 0)
    def _accumulate():
        out_ref[...] += acc


def _split_x(x: jax.Array, nb: int) -> tuple[jax.Array, jax.Array]:
    """(T, n) f32 -> xlo/xhi (16, T, nb) in kernel plane order."""
    t = x.shape[0]
    x3 = x.reshape(t, nb, QK)
    xlo = jnp.transpose(x3[:, :, :NJ], (2, 0, 1))
    xhi = jnp.transpose(x3[:, :, NJ:], (2, 0, 1))
    return xlo, xhi


@functools.partial(jax.jit,
                   static_argnames=("block_rows", "block_t", "interpret"))
def _q40_matmul_2d(qs_t, scale, x, *, block_rows, block_t, interpret):
    _, d, nb = qs_t.shape
    t = x.shape[0]
    xlo, xhi = _split_x(x.astype(jnp.float32), nb)
    grid = (t // block_t, d // block_rows, NJ)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_rows, nb), lambda ti, i, j: (j, i, 0)),
            pl.BlockSpec((block_rows, nb), lambda ti, i, j: (i, 0)),
            pl.BlockSpec((1, block_t, nb), lambda ti, i, j: (j, ti, 0)),
            pl.BlockSpec((1, block_t, nb), lambda ti, i, j: (j, ti, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, block_rows),
                               lambda ti, i, j: (ti, i)),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        interpret=interpret,
    )(qs_t, scale, xlo, xhi)
    return out


def _pick_block_rows(d: int) -> int | None:
    for cand in (512, 256, 128):
        if d % cand == 0:
            return cand
    # largest multiple-of-8 divisor (TPU sublane alignment)
    top = (min(d, 1024) // 8) * 8
    for cand in range(top, 0, -8):
        if d % cand == 0:
            return cand
    return None


def kernel_supports(d: int) -> bool:
    """Whether the fused kernel can tile this output dim (callers fall back
    to the XLA dequantize-then-dot path when not — see ops/linear.matmul)."""
    return _pick_block_rows(d) is not None


def _pick_block_t(t: int) -> int:
    if t <= 256:
        return t
    for cand in (256, 128, 64, 32, 16, 8):
        if t % cand == 0:
            return cand
    return t


def q40_matmul(w: Q40Kernel | Q40Weight, x: jax.Array,
               block_rows: int | None = None,
               interpret: bool | None = None) -> jax.Array:
    """out[..., d] = dequant(w)(d, n) @ x[..., n], packed weights end to end.

    x may be (n,) or (..., n); leading dims are flattened into T for the
    kernel and restored after. ``w`` should be a pre-tiled Q40Kernel on the
    hot path; a Q40Weight is accepted and re-tiled per call (tests only).
    """
    if isinstance(w, Q40Weight):
        w = to_kernel_layout(w)
    qs_t, scale = w.qs_t, w.scale
    _, d, nb = qs_t.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_rows is None:
        block_rows = _pick_block_rows(d)
        if block_rows is None:
            raise ValueError(
                f"q40_matmul needs an output dim with a multiple-of-8 "
                f"divisor, got d={d}")
    lead = x.shape[:-1]
    n = x.shape[-1]
    x2 = x.reshape(-1, n)
    block_t = _pick_block_t(x2.shape[0])
    out = _q40_matmul_2d(qs_t, scale, x2, block_rows=block_rows,
                         block_t=block_t, interpret=interpret)
    return out.reshape(*lead, d)
