"""Fused per-layer Pallas kernels: the launch-tax attack (VERDICT r2 #2).

STATUS: FROZEN as a documented negative (round 5, VERDICT r4 #9). Both
fusion modes lost rigorous end-to-end A/Bs on the real chip: the
megakernel by ~4.6 ms/token (r3: 9.30-9.50 unfused vs 13.92-14.21 fused
at 128-step chains) and the head/tail pair by ~1.1 ms/token (r4: 9.80 vs
10.91 at 64 steps, 9.08 vs 10.13 at 128) — the fused kernels' multi-weight
DMA pipelines stream at ~550-600 GB/s vs the standalone matvec kernels'
~650-670 on the same bytes, which eats more than the saved launches. The
r4 off-arm also re-measured the thing this attack targets: solving
s + C/steps from the 64/128-step pair gives a dispatch-free steady state
~8.36 ms/token against 8.1 ms of profiler op time, i.e. the inter-op
bubble budget is now ~0.25 ms/token. The remaining follow-up ideas
(2-layer grid, cross-kernel prefetch) cannot win against that budget even
at 100% efficiency, so no further fusion hypotheses are planned; the
hardware findings that shaped these kernels (Mosaic lane-split limits,
plane-conversion idioms, dynamic sublane stores, in-kernel RoPE) are
recorded below and in BASELINE.md. The kernels stay opt-in
(DLLAMA_LAYER_FUSION=on|headtail), parity-pinned either way by
tests/test_pallas_layer.py, as the reusable substrate for any future
layer-granularity work.

Single-token decode at 7B ran ~130 device ops/token; round 2's profiler
attribution showed ~2 ms/token of inter-op pipeline bubbles on top of
~8.1 ms of op time (a gap later closed by toolchain/runtime improvements,
see above). These kernels collapse each layer's matvec chain + glue into
TWO pallas_calls (plus the flash-attention kernel between them):

  head:  rmsnorm(x, rms_att) -> wqkv matvec -> RoPE(q, k)
  tail:  wo matvec -> +residual -> rmsnorm(rms_ffn) -> w13 matvec ->
         silu*mul -> w2 matvec -> +residual

Design (hardware-verified on v5e; the probe scripts that established
these constraints were retired with the freeze): Mosaic cannot
lane-split a (1, n) row vector into the matvec plane layout in-kernel, but
it CAN reshape (d, 1) -> (d/32, 32) and 2-D-transpose to (32, d/32). So
every intermediate vector lives in COLUMN form (d, 1):

  * each matvec phase streams row tiles of the packed weight over a 1-D
    grid and accumulates (R, 1) outputs into a column scratch at dynamic
    SUBLANE offsets (supported; dynamic lane offsets are not);
  * the first step of the next phase converts the finished column to the
    (32, nb) plane layout (reshape + transpose) and precomputes the
    per-block input sums for the factored -8 code offset — the same math
    as ops/pallas_q40._matvec_body, verbatim;
  * glue (rmsnorm reductions, silu, residual adds, RoPE pair rotation via
    a (d/2, 2) reshape and a precomputed frequency column) is elementwise
    or reduction work Mosaic handles directly. In-kernel iota is broken on
    this toolchain, so RoPE frequencies arrive as a constant input column.

The weights are the SAME stacked Q40Kernel tensors the unfused path uses
(wqkv/w13 load-time fusions included; w1 and w3 tiles are read from the
single w13 stack through two BlockSpecs at different row offsets), so
enabling fusion changes no load path. Scope: T=1 decode, f32 buffer mode,
unsharded d-major kernel weights (the 7B/70B-rank shapes; 13B's nb-major
layout keeps the unfused path). Value map: identical Q40 dequant and
factored accumulation as pallas_q40; rmsnorm/silu/RoPE are the same f32
formulas, so logits match the unfused path to float-associativity noise
(pinned in tests/test_pallas_layer.py).

Reference anchor: this replaces the per-layer task chain of
transformer-tasks.cpp:161-427 (rms+qkv+rope / att-out+ffn+w2 sequences)
with two device ops instead of ~10.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..io.loader import Q40Kernel

NJ = 16
_EPS = 1e-5
# Mosaic's default scoped-VMEM limit is 16 MB; the fused kernels' phase
# branches make its stack accounting conservative (the unrolled plane
# temporaries of _mv_tile are counted ~per-plane: measured 19.99M at a
# (768, 128) tile that the standalone matvec kernel runs fine). v5e has
# 128 MB of physical VMEM — raise the limit rather than starving the tiles.
_VMEM_LIMIT = 100 * 1024 * 1024
from ..utils.compat import pallas_tpu_compiler_params as _compiler_params

_PARAMS = _compiler_params(vmem_limit_bytes=_VMEM_LIMIT)


def fusion_mode() -> str:
    """'auto', 'on', 'headtail', or 'off' — DLLAMA_LAYER_FUSION. Read at
    trace/load time; already-built engines keep their mode. Unknown values
    raise (a typo would silently run the unfused path)."""
    mode = os.environ.get("DLLAMA_LAYER_FUSION") or "auto"  # '' = unset
    if mode not in ("auto", "on", "headtail", "off"):
        raise ValueError(f"DLLAMA_LAYER_FUSION={mode!r}: "
                         f"expected auto|on|headtail|off")
    return mode


def fusion_enabled() -> bool:
    """Whether T=1 decode builds the fused-layer program.

    'auto' currently resolves to OFF: at real 7B footprint the megakernel's
    multi-window DMA streams at ~550 GB/s vs the standalone kernels'
    ~670 GB/s (same bytes; measured tools/layer_kernel_bench +
    mega bisections, r3), so fusion does not yet beat the unfused path
    end-to-end. Opt in with DLLAMA_LAYER_FUSION=on (whole-layer megakernel
    when the spec supports it) or =headtail (the two-pallas_call pair with
    the flash-attention kernel between them — r4's launch-tax attempt #2:
    the r3 end-to-end A/B only ever exercised the megakernel). Parity is
    pinned by tests/test_pallas_layer.py for every mode."""
    return fusion_mode() in ("on", "headtail")


def fusion_cache_key() -> str:
    """'off' | 'headtail' | 'mega' — the value that decides the param
    TREE's contents (prepare_mega_params adds wo_mega only under 'mega'),
    for shape-manifest/executable cache keys."""
    if not fusion_enabled():
        return "off"
    return "mega" if fusion_mode() == "on" else "headtail"


def _pick_rows(d: int, cap: int) -> int | None:
    """Largest multiple-of-8 divisor of d up to ``cap`` (row-tile pick: the
    tile is (R, nb) with R on sublanes; the dynamic sublane store offset
    i*R stays 8-aligned)."""
    top = (min(d, cap) // 8) * 8
    for cand in range(top, 0, -8):
        if d % cand == 0:
            return cand
    return None


def _plan(spec):
    """Row tiles for the three phases, or None when the shapes don't fit
    the fused kernels (then the unfused path runs). The caps keep the
    double-buffered tile set + scratches well under the raised scoped-VMEM
    limit (_VMEM_LIMIT). DLLAMA_MEGA_R="r_qkv,r_wo,r_13,r_w2" overrides
    the picks (tile-size experiments; 0 keeps the auto pick)."""
    dim, hid = spec.dim, spec.hidden_dim
    if dim % 32 or hid % 32 or spec.head_size % 2:
        return None
    nb_d, nb_h = dim // 32, hid // 32
    r_wo = _pick_rows(dim, max(8, 130_000 // nb_d))
    r_13 = _pick_rows(hid, max(8, 65_000 // nb_d))
    r_w2 = _pick_rows(dim, max(8, 90_000 // nb_h))
    r_qkv = _pick_rows(dim + 2 * spec.kv_dim, max(8, 130_000 // nb_d))
    if None in (r_wo, r_13, r_w2, r_qkv):
        return None
    plan = dict(r_wo=r_wo, r_13=r_13, r_w2=r_w2, r_qkv=r_qkv,
                nb_d=nb_d, nb_h=nb_h)
    env = os.environ.get("DLLAMA_MEGA_R")
    if env:
        dims = {"r_qkv": dim + 2 * spec.kv_dim, "r_wo": dim, "r_13": hid,
                "r_w2": dim}
        for key, val in zip(("r_qkv", "r_wo", "r_13", "r_w2"),
                            env.split(",")):
            r = int(val)
            if not r:
                continue
            if r % 8 or dims[key] % r:
                raise ValueError(
                    f"DLLAMA_MEGA_R {key}={r} must be a multiple of 8 "
                    f"dividing {dims[key]} (a truncating grid would skip "
                    f"rows silently)")
            plan[key] = r
    return plan


def supports(spec, params) -> bool:
    """Fused path precondition: stacked d-major Q40Kernel weights for the
    whole layer chain (wqkv/w13 load-time fusions present) + plannable
    shapes + f32 buffers."""
    from ..ops.quants import FloatType

    if spec.buffer_float_type == FloatType.Q80:
        return False
    for key in ("wqkv", "wo", "w13", "w2"):
        w = params.get(key)
        if not (isinstance(w, Q40Kernel) and w.qs_t.ndim == 4):
            return False
    return _plan(spec) is not None


# ---------------------------------------------------------------------------
# shared in-kernel pieces
# ---------------------------------------------------------------------------


def _to_planes(col):
    """(d, 1) column -> (32, d/32) planes: value 32b+j lands at (j, b) —
    exactly ops/pallas_q40._split_x's layout, built from supported ops
    (reshape splitting sublanes, then a 2-D transpose)."""
    d = col.shape[0]
    return col.reshape(d // 32, 32).T


def _mv_tile(qs3, s, planes, xsum):
    """One (R, nb) output tile of the factored Q40 matvec: qs3 (NJ, R, nb)
    uint8 code planes, s (R, nb) f32 scales, planes (32, nb) input planes,
    xsum (1, nb) per-block input sums. Same math as _matvec_body."""
    acc = None
    for j in range(NJ):
        q = qs3[j].astype(jnp.int32)
        wlo = (q & 0xF).astype(jnp.float32)
        whi = (q >> 4).astype(jnp.float32)
        a = wlo * planes[j:j + 1] + whi * planes[j + 16:j + 17]
        acc = a if acc is None else acc + a
    acc = acc - 8.0 * xsum
    return jnp.sum(acc * s, axis=1, keepdims=True)  # (R, 1)


def _rms_col(col, w_col, n):
    """rmsnorm of a (d, 1) column against a (d, 1) weight column (eps after
    the mean — the reference's rms(), funcs.cpp:60-62)."""
    ss = jnp.sum(col * col) / n + _EPS
    return col * jax.lax.rsqrt(ss) * w_col


# ---------------------------------------------------------------------------
# tail kernel: wo -> +res -> rms_ffn -> w13 -> silu*mul -> w2 -> +res
# ---------------------------------------------------------------------------


def _tail_kernel(dims, sref, wo_qs, wo_s, w1_qs, w1_s, w3_qs, w3_s, w2_qs,
                 w2_s, ao_col, x_col, wffn_col, out_ref,
                 planes, xsum, planes_h, xsum_h, xnew, hb):
    dim, hid, r_wo, r_13, r_w2 = dims
    g_wo, g_13 = dim // r_wo, hid // r_13
    i = pl.program_id(0)

    # ---- phase starts: column -> planes conversions -----------------------
    @pl.when(i == 0)
    def _():
        p = _to_planes(ao_col[...])
        planes[...] = p
        xsum[...] = jnp.sum(p, axis=0, keepdims=True)

    @pl.when(i == g_wo)
    def _():
        xn = _rms_col(xnew[...], wffn_col[...], dim)
        p = _to_planes(xn)
        planes[...] = p
        xsum[...] = jnp.sum(p, axis=0, keepdims=True)

    @pl.when(i == g_wo + g_13)
    def _():
        p = _to_planes(hb[...])
        planes_h[...] = p
        xsum_h[...] = jnp.sum(p, axis=0, keepdims=True)

    # ---- phase bodies -----------------------------------------------------
    @pl.when(i < g_wo)
    def _():
        out = _mv_tile(wo_qs[0], wo_s[0], planes[...], xsum[...])
        xnew[pl.ds(i * r_wo, r_wo), :] = x_col[...] + out

    @pl.when((i >= g_wo) & (i < g_wo + g_13))
    def _():
        a = _mv_tile(w1_qs[0], w1_s[0], planes[...], xsum[...])
        b = _mv_tile(w3_qs[0], w3_s[0], planes[...], xsum[...])
        h = a / (1.0 + jnp.exp(-a)) * b
        hb[pl.ds((i - g_wo) * r_13, r_13), :] = h

    @pl.when(i >= g_wo + g_13)
    def _():
        k = i - g_wo - g_13
        out = _mv_tile(w2_qs[0], w2_s[0], planes_h[...], xsum_h[...])
        out_ref[...] = xnew[pl.ds(k * r_w2, r_w2), :] + out


@functools.partial(jax.jit, static_argnames=("r_wo", "r_13", "r_w2",
                                             "interpret"))
def _tail_call(layer, wo_qs, wo_s, w13_qs, w13_s, w2_qs, w2_s, ao_col,
               x_col, wffn_col, *, r_wo, r_13, r_w2, interpret):
    L, _, dim, nb_d = wo_qs.shape
    hid2 = w13_qs.shape[2]
    hid = hid2 // 2
    nb_h = w2_qs.shape[3]
    g_wo, g_13, g_w2 = dim // r_wo, hid // r_13, dim // r_w2

    kernel = functools.partial(_tail_kernel,
                               (dim, hid, r_wo, r_13, r_w2))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g_wo + g_13 + g_w2,),
        in_specs=[
            # wo tiles advance through phase 1, freeze elsewhere
            pl.BlockSpec((1, NJ, r_wo, nb_d),
                         lambda i, s: (s[0], 0, jnp.minimum(i, dim // r_wo
                                                            - 1), 0)),
            pl.BlockSpec((1, r_wo, nb_d),
                         lambda i, s: (s[0], jnp.minimum(i, dim // r_wo - 1),
                                       0)),
            # w1 rows: first half of the w13 stack
            pl.BlockSpec((1, NJ, r_13, nb_d),
                         lambda i, s: (s[0], 0,
                                       jnp.clip(i - dim // r_wo, 0,
                                                hid // r_13 - 1), 0)),
            pl.BlockSpec((1, r_13, nb_d),
                         lambda i, s: (s[0],
                                       jnp.clip(i - dim // r_wo, 0,
                                                hid // r_13 - 1), 0)),
            # w3 rows: second half of the SAME stack, offset by hid/r_13
            pl.BlockSpec((1, NJ, r_13, nb_d),
                         lambda i, s: (s[0], 0,
                                       hid // r_13
                                       + jnp.clip(i - dim // r_wo, 0,
                                                  hid // r_13 - 1), 0)),
            pl.BlockSpec((1, r_13, nb_d),
                         lambda i, s: (s[0],
                                       hid // r_13
                                       + jnp.clip(i - dim // r_wo, 0,
                                                  hid // r_13 - 1), 0)),
            # w2 tiles advance through phase 3
            pl.BlockSpec((1, NJ, r_w2, nb_h),
                         lambda i, s: (s[0], 0,
                                       jnp.clip(i - dim // r_wo
                                                - hid // r_13, 0,
                                                dim // r_w2 - 1), 0)),
            pl.BlockSpec((1, r_w2, nb_h),
                         lambda i, s: (s[0],
                                       jnp.clip(i - dim // r_wo
                                                - hid // r_13, 0,
                                                dim // r_w2 - 1), 0)),
            pl.BlockSpec((dim, 1), lambda i, s: (0, 0)),   # ao_col
            # x residual rows, consumed during the wo phase
            pl.BlockSpec((r_wo, 1),
                         lambda i, s: (jnp.minimum(i, dim // r_wo - 1), 0)),
            pl.BlockSpec((dim, 1), lambda i, s: (0, 0)),   # rms_ffn col
        ],
        out_specs=pl.BlockSpec(
            (r_w2, 1),
            lambda i, s: (jnp.clip(i - dim // r_wo - hid // r_13, 0,
                                   dim // r_w2 - 1), 0)),
        scratch_shapes=[
            pltpu.VMEM((32, nb_d), jnp.float32),   # planes (ao, then x)
            pltpu.VMEM((1, nb_d), jnp.float32),    # xsum
            pltpu.VMEM((32, nb_h), jnp.float32),   # planes_h
            pltpu.VMEM((1, nb_h), jnp.float32),    # xsum_h
            pltpu.VMEM((dim, 1), jnp.float32),     # xnew (post-attn resid)
            pltpu.VMEM((hid, 1), jnp.float32),     # hb
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((dim, 1), jnp.float32),
        compiler_params=_PARAMS, interpret=interpret,
    )(jnp.asarray(layer, jnp.int32).reshape(1), wo_qs, wo_s, w13_qs, w13_s,
      w13_qs, w13_s, w2_qs, w2_s, ao_col, x_col, wffn_col)


def q40_tail_fused(spec, wo: Q40Kernel, w13: Q40Kernel, w2: Q40Kernel,
                   rms_ffn_col, ao_col, x_col, layer,
                   interpret: bool | None = None):
    """Fused layer tail: (dim,1) attention output + (dim,1) residual ->
    (dim,1) layer output. Weights are the stacked (L, ...) kernel tensors;
    ``layer`` is the traced scan index (scalar-prefetch DMA, zero-copy)."""
    p = _plan(spec)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _tail_call(layer, wo.qs_t, wo.scale, w13.qs_t, w13.scale,
                      w2.qs_t, w2.scale, ao_col, x_col, rms_ffn_col,
                      r_wo=p["r_wo"], r_13=p["r_13"], r_w2=p["r_w2"],
                      interpret=interpret)


# ---------------------------------------------------------------------------
# head kernel: rms_att -> wqkv -> RoPE(q, k)
# ---------------------------------------------------------------------------


def _rope_rot(seg, posf, freq, even):
    """Interleaved-pair RoPE rotation on a column segment, via sublane
    rolls + a parity mask: Mosaic cannot merge (n/2, 2) back to (n, 1)
    (unsupported shape cast — the failed first design,
    tools/mosaic_probe4.py), so
      even v: seg[v]*cos - seg[v+1]*sin   (up-roll partner)
      odd  v: seg[v-1]*sin + seg[v]*cos   (down-roll partner)
    cos/sin come from a per-VALUE frequency column (in-kernel iota is
    broken on this toolchain); the roll wrap-around contributions are
    killed by the mask. Shared by the head kernel and the megakernel."""
    ang = posf * freq
    c, s = jnp.cos(ang), jnp.sin(ang)
    up = pltpu.roll(seg, seg.shape[0] - 1, 0)   # up[v] = seg[v+1]
    down = pltpu.roll(seg, 1, 0)                # down[v] = seg[v-1]
    return seg * c + (-up * s) * even + down * s * (1.0 - even)


def _head_kernel(dims, sref, qkv_qs, qkv_s, x_col, watt_col, freq_col,
                 even_col, out_ref, planes, xsum, qkv):
    dim, kv_dim, dqkv, r_qkv = dims
    g = dqkv // r_qkv
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        xn = _rms_col(x_col[...], watt_col[...], dim)
        p = _to_planes(xn)
        planes[...] = p
        xsum[...] = jnp.sum(p, axis=0, keepdims=True)

    out = _mv_tile(qkv_qs[0], qkv_s[0], planes[...], xsum[...])
    qkv[pl.ds(i * r_qkv, r_qkv), :] = out

    @pl.when(i == g - 1)
    def _():
        # RoPE via _rope_rot; pos arrives via SMEM scalar prefetch.
        pos = sref[1].astype(jnp.float32)
        q = _rope_rot(qkv[pl.ds(0, dim), :], pos, freq_col[0:dim, :],
                      even_col[0:dim, :])
        k = _rope_rot(qkv[pl.ds(dim, kv_dim), :], pos,
                      freq_col[0:kv_dim, :], even_col[0:kv_dim, :])
        out_ref[pl.ds(0, dim), :] = q
        out_ref[pl.ds(dim, kv_dim), :] = k
        out_ref[pl.ds(dim + kv_dim, kv_dim), :] = qkv[
            pl.ds(dim + kv_dim, kv_dim), :]


@functools.partial(jax.jit, static_argnames=("dim", "kv_dim", "r_qkv",
                                             "interpret"))
def _head_call(layer_pos, qkv_qs, qkv_s, x_col, watt_col, freq_col,
               even_col, *, dim, kv_dim, r_qkv, interpret):
    dqkv = qkv_qs.shape[2]
    nb_d = qkv_qs.shape[3]
    g = dqkv // r_qkv
    kernel = functools.partial(_head_kernel, (dim, kv_dim, dqkv, r_qkv))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((1, NJ, r_qkv, nb_d),
                         lambda i, s: (s[0], 0, i, 0)),
            pl.BlockSpec((1, r_qkv, nb_d), lambda i, s: (s[0], i, 0)),
            pl.BlockSpec((dim, 1), lambda i, s: (0, 0)),
            pl.BlockSpec((dim, 1), lambda i, s: (0, 0)),
            pl.BlockSpec((dim, 1), lambda i, s: (0, 0)),
            pl.BlockSpec((dim, 1), lambda i, s: (0, 0)),
        ],
        out_specs=pl.BlockSpec((dqkv, 1), lambda i, s: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((32, nb_d), jnp.float32),
            pltpu.VMEM((1, nb_d), jnp.float32),
            pltpu.VMEM((dqkv, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((dqkv, 1), jnp.float32),
        compiler_params=_PARAMS, interpret=interpret,
    )(layer_pos, qkv_qs, qkv_s, x_col, watt_col, freq_col, even_col)


# ---------------------------------------------------------------------------
# whole-layer megakernel: rms+wqkv+rope -> flash attention + cache write ->
# wo -> +res -> rms+w13 -> silu*mul -> w2 -> +res, ONE pallas_call per layer
# ---------------------------------------------------------------------------


def wo_block_perm(n_heads: int, head_size: int) -> np.ndarray:
    """Column-BLOCK permutation for wo inside the megakernel: kernel block
    b reads original block sigma(b) = (head_size/32)*h + dhi with
    h = b mod n_heads, dhi = b div n_heads. Why: the attention output is
    assembled in VMEM as (n_q, hs); transposing it (supported) and
    lane-concatenating its hs/32 sublane strips yields EXACTLY the plane
    layout of the sigma-permuted blocks — no unsupported sublane/lane merge
    needed. Permuting whole 32-column blocks keeps every Q40 scale group
    intact, so the value map is unchanged."""
    nb = n_heads * head_size // 32
    pieces = head_size // 32
    b = np.arange(nb)
    return (b % n_heads) * pieces + b // n_heads


def permute_wo_blocks(wo: Q40Kernel, n_heads: int,
                      head_size: int) -> Q40Kernel:
    """Reorder wo's column blocks by wo_block_perm (host side, at pack —
    the fancy index + ascontiguousarray is the one conversion point)."""
    sigma = wo_block_perm(n_heads, head_size)
    return Q40Kernel(np.ascontiguousarray(wo.qs_t[..., sigma]),
                     np.ascontiguousarray(wo.scale[..., sigma]))


def _ao_to_planes(ao, n_heads: int, hs: int):
    """(n_q, hs) attention output -> (32, nb) planes matching the
    sigma-permuted wo blocks: transpose to (hs, n_heads), then lane-concat
    the hs/32 sublane strips."""
    ao_t = ao.T  # (hs, n_heads)
    strips = [ao_t[k * 32:(k + 1) * 32, :] for k in range(hs // 32)]
    return jnp.concatenate(strips, axis=1)  # (32, n_heads * hs/32)


def _mega_kernel(cfg, sref, qkv_qs, qkv_s, wo_qs, wo_s, w1_qs, w1_s,
                 w3_qs, w3_s, w2_qs, w2_s, x_rows, x_full, watt_col,
                 wffn_col, freq_col, even_col, k_hbm, v_hbm,
                 out_ref, k_out, v_out,
                 planes, xsum, planes_h, xsum_h, qkv, xnew, hb,
                 k_buf, v_buf, kv_wr, sems, wsem):
    (dim, kv_dim, hid, n_kv, kv_mul, hs, chunk,
     r_qkv, r_wo, r_13, r_w2, skip) = cfg
    dqkv = dim + 2 * kv_dim
    g_qkv = dqkv // r_qkv
    att = g_qkv            # the dedicated attention step
    wo0 = att + 1
    w130 = wo0 + dim // r_wo
    w20 = w130 + hid // r_13
    n_heads = n_kv * kv_mul
    i = pl.program_id(0)
    layer = sref[0]
    pos = sref[1]
    # bisection knob (DLLAMA_MEGA_SKIP): skip named phase BODIES — DMA
    # still streams (index maps drive it), so compute cost isolates from
    # DMA cost. Threaded through cfg (a STATIC jit arg read in
    # q40_layer_mega) so changing the env between calls re-traces instead
    # of silently reusing the previous kernel.
    _skip = set(skip.split(","))

    # ---- phase 1: rms_att -> wqkv tiles -> (last step) RoPE ---------------
    if "qkv" not in _skip:
        @pl.when(i == 0)
        def _():
            xn = _rms_col(x_full[...], watt_col[...], dim)
            p = _to_planes(xn)
            planes[...] = p
            xsum[...] = jnp.sum(p, axis=0, keepdims=True)

        @pl.when(i < g_qkv)
        def _():
            out = _mv_tile(qkv_qs[0], qkv_s[0], planes[...], xsum[...])
            qkv[pl.ds(i * r_qkv, r_qkv), :] = out

    @pl.when(jnp.logical_and(i == g_qkv - 1, "rope" not in _skip))
    def _():
        posf = pos.astype(jnp.float32)
        qkv[pl.ds(0, dim), :] = _rope_rot(qkv[pl.ds(0, dim), :], posf,
                                          freq_col[0:dim, :],
                                          even_col[0:dim, :])
        kseg = _rope_rot(qkv[pl.ds(dim, kv_dim), :], posf,
                         freq_col[0:kv_dim, :], even_col[0:kv_dim, :])
        qkv[pl.ds(dim, kv_dim), :] = kseg
        # stage the new K/V rows in cache layout and LAUNCH the cache
        # writes now — they land while the attention walk below runs
        # (positions <= pos-1 only are read from HBM; the pos term comes
        # from VMEM, so the in-flight write cannot race anything read)
        kv_wr[0] = kseg.reshape(n_kv, hs).astype(k_out.dtype)
        kv_wr[1] = qkv[pl.ds(dim + kv_dim, kv_dim), :].reshape(
            n_kv, hs).astype(v_out.dtype)
        pltpu.make_async_copy(kv_wr.at[0], k_out.at[layer, pos],
                              wsem.at[0]).start()
        pltpu.make_async_copy(kv_wr.at[1], v_out.at[layer, pos],
                              wsem.at[1]).start()

    # ---- phase 2 (one step): flash attention over the live prefix ---------
    @pl.when(jnp.logical_and(i == att, "att" not in _skip))
    def _():
        q2 = qkv[pl.ds(0, dim), :].reshape(n_heads, hs)
        scale = 1.0 / jnp.sqrt(jnp.float32(hs))
        n_chunks = jnp.where(pos > 0, (pos - 1) // chunk + 1, 0)

        def k_dma(slot, c):
            return pltpu.make_async_copy(
                k_hbm.at[layer, pl.ds(c * chunk, chunk)], k_buf.at[slot],
                sems.at[slot, 0])

        def v_dma(slot, c):
            return pltpu.make_async_copy(
                v_hbm.at[layer, pl.ds(c * chunk, chunk)], v_buf.at[slot],
                sems.at[slot, 1])

        @pl.when(n_chunks > 0)
        def _():
            k_dma(0, 0).start()
            v_dma(0, 0).start()

        if kv_mul == 1:
            qg = [q2]
        else:  # GQA: group m's query rows are m, kv_mul+m, ... (stride)
            qg = [jnp.concatenate(
                [q2[g * kv_mul + m:g * kv_mul + m + 1, :]
                 for g in range(n_kv)], axis=0) for m in range(kv_mul)]

        def body(c, carry):
            slot = jax.lax.rem(c, 2)

            @pl.when(c + 1 < n_chunks)
            def _():
                nxt = jax.lax.rem(c + 1, 2)
                k_dma(nxt, c + 1).start()
                v_dma(nxt, c + 1).start()

            k_dma(slot, c).wait()
            v_dma(slot, c).wait()
            k = k_buf[slot].astype(jnp.float32)   # (chunk, n_kv, hs)
            v = v_buf[slot].astype(jnp.float32)
            key_pos = c * chunk + jax.lax.broadcasted_iota(
                jnp.int32, (chunk, n_kv), 0)
            valid = key_pos < pos                 # strict: pos rides VMEM
            out = []
            for m in range(kv_mul):
                m_old, l_old, o_old = carry[m]
                s = jnp.sum(k * qg[m][None, :, :], axis=-1) * scale
                s = jnp.where(valid, s, NEG_INF)
                m_new = jnp.maximum(m_old,
                                    jnp.max(s, axis=0, keepdims=True))
                p = jnp.exp(s - m_new)
                corr = jnp.exp(m_old - m_new)
                l_new = l_old * corr + jnp.sum(p, axis=0, keepdims=True)
                po = jnp.sum(p[:, :, None] * v, axis=0)
                out.append((m_new, l_new, o_old * corr.T + po))
            return tuple(out)

        init = tuple((jnp.full((1, n_kv), NEG_INF, jnp.float32),
                      jnp.zeros((1, n_kv), jnp.float32),
                      jnp.zeros((n_kv, hs), jnp.float32))
                     for _ in range(kv_mul))
        fin = jax.lax.fori_loop(0, n_chunks, body, init)

        # the pos term from VMEM (never read back from HBM)
        k_self = kv_wr[0].astype(jnp.float32)     # (n_kv, hs)
        v_self = kv_wr[1].astype(jnp.float32)
        rows = []
        for m in range(kv_mul):
            m_old, l_old, o_old = fin[m]
            s = jnp.sum(k_self * qg[m], axis=-1,
                        keepdims=True).T * scale  # (1, n_kv)
            m_new = jnp.maximum(m_old, s)
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_old - m_new)
            l_new = l_old * corr + p
            o_new = o_old * corr.T + p.T * v_self
            rows.append(o_new / l_new.T)          # (n_kv, hs)
        if kv_mul == 1:
            ao = rows[0]
        else:  # interleave groups back to head order g*kv_mul+m
            ao = jnp.concatenate(
                [rows[m][g:g + 1, :] for g in range(n_kv)
                 for m in range(kv_mul)], axis=0)
        p = _ao_to_planes(ao, n_heads, hs)        # sigma-permuted planes
        planes[...] = p
        xsum[...] = jnp.sum(p, axis=0, keepdims=True)

    # the cache-write DMAs started in the RoPE step must land before the
    # kernel ends — waited whenever they were STARTED ("rope" ran), in a
    # block independent of the "att" bisection skip (an "att"-skipped run
    # would otherwise finish with outstanding DMA semaphores and fault)
    @pl.when(jnp.logical_and(i == att, "rope" not in _skip))
    def _():
        pltpu.make_async_copy(kv_wr.at[0], k_out.at[layer, pos],
                              wsem.at[0]).wait()
        pltpu.make_async_copy(kv_wr.at[1], v_out.at[layer, pos],
                              wsem.at[1]).wait()

    # ---- phase 3: wo (sigma-permuted blocks) + residual -------------------
    @pl.when((i >= wo0) & (i < w130) & ("wo" not in _skip))
    def _():
        k = i - wo0
        out = _mv_tile(wo_qs[0], wo_s[0], planes[...], xsum[...])
        xnew[pl.ds(k * r_wo, r_wo), :] = x_rows[...] + out

    # ---- phase 4: rms_ffn -> w13 -> silu*mul ------------------------------
    @pl.when(jnp.logical_and(i == w130, "w13" not in _skip))
    def _():
        xn = _rms_col(xnew[...], wffn_col[...], dim)
        p = _to_planes(xn)
        planes[...] = p
        xsum[...] = jnp.sum(p, axis=0, keepdims=True)

    @pl.when((i >= w130) & (i < w20) & ("w13" not in _skip))
    def _():
        k = i - w130
        a = _mv_tile(w1_qs[0], w1_s[0], planes[...], xsum[...])
        b = _mv_tile(w3_qs[0], w3_s[0], planes[...], xsum[...])
        hb[pl.ds(k * r_13, r_13), :] = a / (1.0 + jnp.exp(-a)) * b

    # ---- phase 5: w2 + residual -------------------------------------------
    @pl.when(jnp.logical_and(i == w20, "w2" not in _skip))
    def _():
        p = _to_planes(hb[...])
        planes_h[...] = p
        xsum_h[...] = jnp.sum(p, axis=0, keepdims=True)

    @pl.when((i >= w20) & ("w2" not in _skip))
    def _():
        k = i - w20
        out = _mv_tile(w2_qs[0], w2_s[0], planes_h[...], xsum_h[...])
        out_ref[...] = xnew[pl.ds(k * r_w2, r_w2), :] + out


NEG_INF = float("-inf")


def _att_chunk(seq_len: int, n_kv: int, hs: int, itemsize: int) -> int | None:
    """Cache chunk for the in-kernel flash walk: 2 slots x {K,V} within a
    few MB next to the weight windows."""
    for c in (256, 128, 64, 32, 16, 8):
        if seq_len % c == 0 and 4 * c * n_kv * hs * itemsize <= 8 << 20:
            return min(c, seq_len)
    return None


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def _mega_call(layer_pos, qkv_qs, qkv_s, wo_qs, wo_s, w13_qs, w13_s,
               w2_qs, w2_s, x_col, watt_col, wffn_col, freq_col, even_col,
               k_cache, v_cache, *, cfg, interpret):
    (dim, kv_dim, hid, n_kv, kv_mul, hs, chunk,
     r_qkv, r_wo, r_13, r_w2, skip) = cfg
    dqkv = dim + 2 * kv_dim
    nb_d, nb_h = dim // 32, hid // 32
    g_qkv, g_wo, g_13, g_w2 = (dqkv // r_qkv, dim // r_wo, hid // r_13,
                               dim // r_w2)
    att = g_qkv
    wo0, w130 = att + 1, att + 1 + g_wo
    w20 = w130 + g_13
    grid = w20 + g_w2

    def frozen(start, g):
        return lambda i, s: (s[0], 0, jnp.clip(i - start, 0, g - 1), 0)

    def frozen_s(start, g):
        return lambda i, s: (s[0], jnp.clip(i - start, 0, g - 1), 0)

    def frozen_off(start, g, off):
        return lambda i, s: (s[0], 0, off + jnp.clip(i - start, 0, g - 1),
                             0)

    def frozen_s_off(start, g, off):
        return lambda i, s: (s[0], off + jnp.clip(i - start, 0, g - 1), 0)

    col = lambda i, s: (0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1, NJ, r_qkv, nb_d), frozen(0, g_qkv)),
            pl.BlockSpec((1, r_qkv, nb_d), frozen_s(0, g_qkv)),
            pl.BlockSpec((1, NJ, r_wo, nb_d), frozen(wo0, g_wo)),
            pl.BlockSpec((1, r_wo, nb_d), frozen_s(wo0, g_wo)),
            pl.BlockSpec((1, NJ, r_13, nb_d), frozen(w130, g_13)),
            pl.BlockSpec((1, r_13, nb_d), frozen_s(w130, g_13)),
            pl.BlockSpec((1, NJ, r_13, nb_d),
                         frozen_off(w130, g_13, hid // r_13)),
            pl.BlockSpec((1, r_13, nb_d),
                         frozen_s_off(w130, g_13, hid // r_13)),
            pl.BlockSpec((1, NJ, r_w2, nb_h), frozen(w20, g_w2)),
            pl.BlockSpec((1, r_w2, nb_h), frozen_s(w20, g_w2)),
            pl.BlockSpec((r_wo, 1),
                         lambda i, s: (jnp.clip(i - wo0, 0, g_wo - 1), 0)),
            pl.BlockSpec((dim, 1), col),  # x_full (rms input)
            pl.BlockSpec((dim, 1), col),  # rms_att
            pl.BlockSpec((dim, 1), col),  # rms_ffn
            pl.BlockSpec((dim, 1), col),  # rope freq
            pl.BlockSpec((dim, 1), col),  # rope parity
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec((r_w2, 1),
                         lambda i, s: (jnp.clip(i - w20, 0, g_w2 - 1), 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((32, nb_d), jnp.float32),   # planes (x, then ao, x)
            pltpu.VMEM((1, nb_d), jnp.float32),
            pltpu.VMEM((32, nb_h), jnp.float32),
            pltpu.VMEM((1, nb_h), jnp.float32),
            pltpu.VMEM((dqkv, 1), jnp.float32),    # qkv column
            pltpu.VMEM((dim, 1), jnp.float32),     # xnew
            pltpu.VMEM((hid, 1), jnp.float32),     # hb
            pltpu.VMEM((2, chunk, n_kv, hs), k_cache.dtype),
            pltpu.VMEM((2, chunk, n_kv, hs), v_cache.dtype),
            pltpu.VMEM((2, n_kv, hs), k_cache.dtype),  # staged new K/V
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    kernel = functools.partial(_mega_kernel, cfg)
    x_out, k_new, v_new = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((dim, 1), jnp.float32),
            jax.ShapeDtypeStruct(k_cache.shape, k_cache.dtype),
            jax.ShapeDtypeStruct(v_cache.shape, v_cache.dtype),
        ],
        # cache in/out aliasing: operand indices count the scalar-prefetch
        # arg and every input in call order — k_cache is operand 17,
        # v_cache 18 (asserted by the cache-content parity test)
        input_output_aliases={17: 1, 18: 2},
        compiler_params=_PARAMS, interpret=interpret,
    )(layer_pos, qkv_qs, qkv_s, wo_qs, wo_s, w13_qs, w13_s,
      w13_qs, w13_s, w2_qs, w2_s, x_col, x_col, watt_col, wffn_col,
      freq_col, even_col, k_cache, v_cache)
    return x_out, k_new, v_new


def _mega_shapes_ok(spec) -> bool:
    return (spec.head_size == 128
            and _att_chunk(spec.seq_len, spec.n_kv_heads, spec.head_size,
                           4) is not None)


def mega_supported(spec, params) -> bool:
    """Whole-layer megakernel preconditions: the head/tail plan + an
    attention chunking + lane-width head size (the flash walk's layout) +
    the sigma-permuted wo stack prepared at load (prepare_mega_params)."""
    return (supports(spec, params) and _mega_shapes_ok(spec)
            and isinstance(params.get("wo_mega"), Q40Kernel))


def prepare_mega_params(spec, params: dict) -> dict:
    """Host-side load step: when the megakernel can serve this spec, add
    the sigma-permuted wo stack as ``wo_mega`` (the megakernel's attention-
    output plane layout — see wo_block_perm). ``wo`` stays for the T>1
    prefill path, which runs the unfused kernels."""
    if not (fusion_mode() == "on" and supports(spec, params)
            and _mega_shapes_ok(spec)):
        return params
    out = dict(params)
    out["wo_mega"] = permute_wo_blocks(params["wo"], spec.n_heads,
                                       spec.head_size)
    return out


def q40_layer_mega(spec, wqkv: Q40Kernel, wo_perm: Q40Kernel,
                   w13: Q40Kernel, w2: Q40Kernel, rms_att_col, rms_ffn_col,
                   freq_col, even_col, x_col, k_cache, v_cache, layer, pos,
                   interpret: bool | None = None):
    """ONE device op for a whole decode layer (VERDICT r2 #2's endgame):
    returns (x_out_col, k_cache, v_cache) with the new K/V written at
    (layer, pos) in the (donated) caches. ``wo_perm`` must be the
    sigma-permuted wo (permute_wo_blocks)."""
    p = _plan(spec)
    chunk = _att_chunk(spec.seq_len, spec.n_kv_heads, spec.head_size,
                       jnp.dtype(k_cache.dtype).itemsize)
    cfg = (spec.dim, spec.kv_dim, spec.hidden_dim, spec.n_kv_heads,
           spec.kv_mul, spec.head_size, chunk,
           p["r_qkv"], p["r_wo"], p["r_13"], p["r_w2"],
           os.environ.get("DLLAMA_MEGA_SKIP", ""))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    layer_pos = jnp.stack([jnp.asarray(layer, jnp.int32),
                           jnp.asarray(pos, jnp.int32)])
    return _mega_call(layer_pos, wqkv.qs_t, wqkv.scale, wo_perm.qs_t,
                      wo_perm.scale, w13.qs_t, w13.scale, w2.qs_t, w2.scale,
                      x_col, rms_att_col, rms_ffn_col, freq_col, even_col,
                      k_cache, v_cache, cfg=cfg, interpret=interpret)


def rope_freq_cols(spec) -> tuple[np.ndarray, np.ndarray]:
    """Per-VALUE RoPE columns for the roll-based in-kernel rotation:
    freq (dim, 1) — value v rotates by pos * 10000^-((v - v%2 mod
    head_size)/head_size), the reference's per-element loop
    (transformer-tasks.cpp:228-242) with each pair's angle repeated for
    both members — and the even-parity mask (dim, 1). The k segment uses
    the first kv_dim rows (the pattern repeats per head)."""
    v = np.arange(spec.dim, dtype=np.float32)
    head_dim = np.mod(v - np.mod(v, 2), spec.head_size)
    freq = (1.0 / np.power(np.float32(10000.0),
                           head_dim / spec.head_size)).reshape(-1, 1)
    even = (np.arange(spec.dim) % 2 == 0).astype(np.float32).reshape(-1, 1)
    return freq, even


def q40_head_fused(spec, wqkv: Q40Kernel, rms_att_col, freq_col, even_col,
                   x_col, layer, pos, interpret: bool | None = None):
    """Fused layer head: (dim,1) residual stream -> (dim+2*kv_dim, 1)
    RoPE-rotated qkv column."""
    p = _plan(spec)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    layer_pos = jnp.stack([jnp.asarray(layer, jnp.int32),
                           jnp.asarray(pos, jnp.int32)])
    return _head_call(layer_pos, wqkv.qs_t, wqkv.scale, x_col, rms_att_col,
                      freq_col, even_col, dim=spec.dim, kv_dim=spec.kv_dim,
                      r_qkv=p["r_qkv"], interpret=interpret)
