"""Token sampler with reference semantics (src/tokenizer.cpp:206-319).

temperature == 0 -> argmax; else logits/temp -> max-subtracted softmax -> coin
from xorshift64* -> nucleus (top-p) with the (1-p)/(n-1) cutoff pre-filter and
stable descending sort, or plain multinomial CDF walk when topp is outside
(0, 1). All float math in float32, like the reference.
"""

from __future__ import annotations

import numpy as np

from ..utils.rng import Xorshift64


def softmax_f32(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.float32)
    e = np.exp(x - x.max(), dtype=np.float32)
    return e / np.float32(e.sum(dtype=np.float32))


def sample_argmax(probs: np.ndarray) -> int:
    return int(np.argmax(probs))


def sample_mult(probs: np.ndarray, coin: float) -> int:
    cdf = np.cumsum(probs.astype(np.float32))
    idx = int(np.searchsorted(cdf, coin, side="right"))
    return min(idx, len(probs) - 1)


def sample_topp(probs: np.ndarray, topp: float, coin: float) -> int:
    n = len(probs)
    if n == 1:
        return 0
    cutoff = np.float32(1.0 - topp) / np.float32(n - 1)
    idx = np.nonzero(probs >= cutoff)[0]
    if len(idx) == 0:
        # degenerate nucleus (topp < 1/n with near-uniform probs): keep the
        # single most-probable token (native sample_logits does the same)
        return int(np.argmax(probs))
    # descending by prob; stable so equal probs keep index order (qsort with
    # strict compare leaves equal elements in scan order)
    order = idx[np.argsort(-probs[idx], kind="stable")]
    p_sorted = probs[order].astype(np.float32)
    cum = np.float32(0.0)
    last = len(order) - 1
    for i, p in enumerate(p_sorted):
        cum += p
        if cum > topp:
            last = i
            break
    r = np.float32(coin) * cum
    cdf = np.float32(0.0)
    for i in range(last + 1):
        cdf += p_sorted[i]
        if r < cdf:
            return int(order[i])
    return int(order[last])


class Sampler:
    """Reference Sampler (tokenizer.cpp:283-319). Mutates logits like it.

    The hot select runs in the native host library when available (csrc
    sample_logits — the C++ equivalent of the reference's C++ sampler); the
    numpy implementation above is the fallback and the semantics of record
    (tests pin native == numpy on the same logits/coin). Caveat: the two can
    diverge by float ulps across libm/numpy builds at CDF boundaries, so
    flows that need bit-identical streams on EVERY machine (multi-host SPMD)
    should pass use_native=False (cli.py does).
    """

    def __init__(self, vocab_size: int, temperature: float, topp: float,
                 seed: int, use_native: bool = True):
        self.vocab_size = vocab_size
        self.temperature = float(temperature)
        self.topp = float(topp)
        self.rng = Xorshift64(seed)
        self.use_native = use_native

    def sample(self, logits: np.ndarray) -> int:
        # dlint: allow[D001] the host sampler's contract is host logits
        logits = np.asarray(logits, dtype=np.float32)[:self.vocab_size]
        if self.temperature == 0.0:
            return sample_argmax(logits)
        coin = self.rng.f32()
        if self.use_native:
            from ..utils import native

            idx = native.sample_logits(logits, self.temperature, self.topp,
                                       coin)
            if idx is not None:
                return idx
        probs = softmax_f32(logits / np.float32(self.temperature))
        if self.topp <= 0 or self.topp >= 1:
            return sample_mult(probs, coin)
        return sample_topp(probs, self.topp, coin)
