"""Continuous batching: per-slot position clocks + mid-flight admission.

The lockstep batch path (runtime/decode.make_batch_decode_loop) shares one
position clock across rows, so the batch finishes at the pace of its slowest
row and new work waits for the whole batch. This engine removes both limits —
the TPU analog of vLLM-style continuous batching, far beyond the reference's
strict batch=1 loop (tokenizer.cpp:321-394):

* a fixed pool of B cache slots, each with its OWN position clock
  (models/llama.forward_batch_ragged: per-row RoPE, per-row cache column,
  per-row attention visibility);
* a host-side scheduler that retires a row the moment it stops (BOS or step
  budget) and admits the next queued request into the freed slot at pos 0
  while the other rows keep decoding.

Prompt tokens are forced through the same decode step (one per iteration,
the reference's own prompt handling); each request samples from its own
xorshift stream seeded ``seed + request_index`` with reference Sampler
semantics, so a request's token stream is IDENTICAL to running it alone
through generate() with that seed — the scheduling is invisible in the
output (the parity gate of tests/test_continuous.py).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
from typing import Any

import numpy as np

from ..io.tokenizer import BOS
from ..models.spec import TransformerSpec
from ..obs import tracectx
from ..obs.ledger import CensusRing, LedgerBook
from .sampling import Sampler


@dataclasses.dataclass
class Request:
    """One generation request flowing through the slot pool.

    ``tokens`` is the encoded prompt (BOS included, non-empty); optional
    per-request sampling overrides fall back to the engine defaults. The
    engine fills ``out`` and sets ``done`` when the request retires —
    online callers (runtime/server.py) wait on it.
    """
    tokens: list
    steps: int
    temperature: float | None = None
    topp: float | None = None
    seed: int | None = None
    out: list = dataclasses.field(default_factory=list)
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    index: int = -1  # submission order; assigned by submit()
    error: str | None = None  # set (before done) if the engine failed it
    cancelled: bool = False  # consumer gone: retire at the next step
    # SLO priority class (obs/slo.py): None = the policy's default class;
    # the tracker resolves it at retire. Ignored on engines without a
    # policy.
    slo_class: str | None = None
    # crash recovery (runtime/journal.py): coins the request's sampler
    # already consumed in a previous life — admission fast-forwards the
    # xorshift stream by exactly this many draws so the continuation is
    # bitwise the uninterrupted stream. 0 for fresh requests.
    coin_cursor: int = 0
    # journal id of the previous life this request replays (recover()
    # sets it): the admit record carries it as ``recovers`` so ONE
    # append atomically opens the new life and closes the old — a crash
    # can never leave both live. None for fresh requests.
    recovered_from: int | None = None
    # DCN handoff durability (ISSUE 14): True when
    # ContinuousEngine.prejournal already assigned this request's index
    # and journaled its admit record — submit() then only queues it
    # (appending a second admit would corrupt the journal)
    prejournaled: bool = False
    # distributed-trace identity (ISSUE 15, obs/tracectx.TraceContext):
    # minted at request ingress (runtime/server.py) or by submit() when
    # absent; carried into every span this request produces, the journal
    # admit record, and the handoff wire form — a recovered/handed-off
    # continuation keeps the SAME trace_id with a recovers/handoff link
    trace: Any = None
    # streaming hook: called from the scheduler thread with each token as it
    # lands in ``out`` (prompt echoes included, prefill echoes in one burst);
    # must be fast and must not raise — it runs inside the decode loop
    on_token: Any = None
    # lifecycle timestamps (time.monotonic; 0.0 = not reached): queue wait =
    # t_admit - t_enqueue, TTFT = t_first_token - t_enqueue. t_first_token
    # marks the first SAMPLED token — forced prompt echo is input replay,
    # not generation. obs/trace.EngineMetrics derives histograms from these.
    t_enqueue: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0
    n_sampled: int = 0  # sampled (non-forced) tokens emitted
    # cost accounting (ISSUE 16, obs/ledger.py): the live RequestLedger
    # handle submit() opens (seam code — the DCN handoff — charges
    # through it without a book lookup), and the snapshot a previous
    # life carried across a recovery/handoff seam (the journal record's
    # ``ledger`` field) — merged into this life's snapshot so the bill
    # stays whole across seams
    ledger: Any = None
    carried_cost: dict | None = None


_PROGRAM_MEMO: dict = {}


def _shared_program(key: tuple, build):
    """Process-wide memo for the engine's jitted step programs.

    Every engine build used to re-jit its own ``functools.partial`` /
    sharded-builder closure, so two engines with EQUAL (spec, mesh,
    scheme, page_size, kv_quant, ...) each paid a full XLA compile for
    byte-identical programs — the dominant cost of multi-engine
    processes (the disagg two-pool topology, loadgen sweeps, every
    stream-parity test). Sharing the jitted callable itself is
    deterministic by construction: callers get the SAME executable
    object, not a deserialized copy, so bitwise pins only get stronger.
    (jax's persistent disk cache is NOT a substitute — measured on the
    test suite, deserialized executables are not always bit-identical
    to fresh compiles of the same HLO.) Donation is per-call state, so
    sharing across engines is safe; nothing here is ever evicted — keys
    are bounded by the distinct engine configurations of the process."""
    fn = _PROGRAM_MEMO.get(key)
    if fn is None:
        fn = _PROGRAM_MEMO[key] = build()
    return fn


def _maybe_bf16(fn, enable: bool, jax_mod, jit: bool = False):
    """Route a prefill forward through the shared fast-prefill wrapper
    (ops/linear.bf16_prefill) when enabled. Unlike Engine.prefill's T>8
    gate, admission prefill runs ALL its chunks (tail included) through
    this one dedicated program — the whole prefilled prefix shares one
    documented tolerance."""
    if enable:
        from ..ops.linear import bf16_prefill

        fn = bf16_prefill(fn)
    return jax_mod.jit(fn, donate_argnums=1) if jit else fn


_q8_fallback_warned = False


def _warn_q8_xla_fallback(spec: TransformerSpec, page_size: int,
                          n_slices: int) -> None:
    """One-time loud note when --kv-quant q8 is requested but the paged
    flash kernel cannot take this layout for the DECODE shape (t_len=1,
    the per-token hot path), so attention runs the XLA gather fallback
    (which dequantizes the WHOLE gathered plane per step). Mirrors the
    explicit prefill-flash degrade warning: the fallback computes the
    same attention, just slower — a warning, not a raise. Silent on
    CPU/interpret engines (kernel mode 'xla' is the documented default
    there, not a degrade). A spec_k window past the kernel's bound only
    degrades the verify dispatch, not decode — that case stays quiet."""
    global _q8_fallback_warned
    if _q8_fallback_warned:
        return
    from ..ops.pallas_attention import attn_kernel_mode
    from ..ops.pallas_paged_attention import would_use_paged_kernel

    kv_loc = spec.n_kv_heads // n_slices
    if (attn_kernel_mode() != "pallas"
            or would_use_paged_kernel(page_size, kv_loc, spec.head_size,
                                      1, itemsize=1, q8=True)):
        return
    _q8_fallback_warned = True
    import sys

    print(f"⚠️  --kv-quant q8 requested but the paged flash-decode Pallas "
          f"kernel does not apply to this layout (page_size {page_size}, "
          f"n_kv/tp {kv_loc}, head_size {spec.head_size}); decode "
          f"attention takes the XLA gather fallback, which dequantizes "
          f"the whole gathered plane every step — the HBM saving stands "
          f"but the per-token attention cost does not improve. Use a "
          f"head_size multiple of 128 and a page size whose K/V planes "
          f"fit the kernel's VMEM scratch budget "
          f"(ops/pallas_paged_attention.supports_paged).",
          file=sys.stderr)


@dataclasses.dataclass
class _Slot:
    req: Request | None = None   # None = free
    pos: int = 0                 # this row's position clock
    token: int = 0               # next input token
    forced: list = dataclasses.field(default_factory=list)
    budget: int = 0              # max positions for this request
    sampler: Sampler | None = None
    # paged KV mode only: physical page ids in logical order (position p
    # lives in pages[p // page_size]); the first ``shared`` entries came
    # from the radix tree (prefix sharing) — refcounted, never written by
    # this slot (decode writes start at the page-aligned share boundary)
    pages: list = dataclasses.field(default_factory=list)
    shared: int = 0
    # KV tiering (ISSUE 12): True while the slot's shared-prefix pages
    # await an async promotion upload — admission prefill is deferred and
    # the slot rides dispatches masked inactive (pages-starved semantics)
    # until the payload lands at a step boundary (_settle_promotions)
    await_promo: bool = False
    # chunk-boundary prefill preemption (ISSUE 14): True when admission
    # prefill parked at a page-aligned chunk boundary (a higher-priority
    # arrival preempted it) — the scheduler re-enters _maybe_prefill_slot
    # for this slot on later iterations until the prompt is covered
    prefill_pending: bool = False

    @property
    def free(self) -> bool:
        return self.req is None


@dataclasses.dataclass
class ContinuousStats:
    tokens: int = 0          # generated (emitted) tokens
    steps: int = 0           # device steps executed
    total_ms: float = 0.0
    max_active: int = 0
    sum_active: int = 0      # sum of active slots over device steps
    # speculative decoding (spec_k > 0): drafter proposals fed to verify
    # dispatches and how many the model accepted — the accept-rate /
    # ms-per-accepted-token bench columns (ISSUE 7)
    spec_proposed: int = 0
    spec_accepted: int = 0
    # admission-pressure accounting (ISSUE 8): page-starved slot pauses
    # (a slot rode one dispatch masked inactive) and head-of-queue
    # requeues (paged admission found the pool dry) — kept on stats so
    # metric-less engines (the loadgen driver) still see them
    pauses: int = 0
    requeues: int = 0
    # admission-prefill forward passes executed (one per chunk window /
    # per-token tail dispatch): the virtual-clock cost term the two-pool
    # sweep charges prefill with (ISSUE 14) — without it a colocated
    # engine's prefill interference would be invisible to the clock.
    # Counted at DISPATCH (inside the per-window fwd closure), so a chunk
    # that parks at a boundary and resumes there is charged exactly once.
    prefill_chunks: int = 0
    # token-budget mixed dispatches (ISSUE 18): virtual EXTRA device
    # steps a dispatch would have cost had its total span honored the
    # budget — ceil(sum(span) / budget) - 1 per dispatch, 0 in healthy
    # runs. Nonzero only under the overrun-budget chaos mutation (the
    # prefill slice ignores the remaining budget); the virtual clock
    # charges it as real step time so loadcheck's gate catches the
    # overrun as inflated decode latency.
    overrun_steps: int = 0

    @property
    def tokens_per_s(self) -> float:
        return self.tokens / max(self.total_ms / 1000, 1e-9)

    @property
    def avg_active(self) -> float:
        """Sustained concurrency: mean active slots per device step (rows
        entering a fused chain count for its whole span) — the
        continuous_bench column paged KV exists to move."""
        return self.sum_active / max(self.steps, 1)

    @property
    def spec_accept_rate(self) -> float:
        """Accepted / proposed drafts (0.0 before any proposal)."""
        return self.spec_accepted / max(self.spec_proposed, 1)


class ContinuousEngine:
    """Owns the slot cache + jitted ragged step; schedules requests.

    ``slots`` bounds concurrent sequences (cache memory = slots x seq_len);
    any number of requests stream through the pool.
    """

    def __init__(self, spec: TransformerSpec, params: dict[str, Any],
                 slots: int, temperature: float, topp: float, seed: int,
                 cache_dtype=None, mesh=None, prefill_chunk: int = 0,
                 block_steps: int = 1, use_native_sampler: bool = True,
                 fast_prefill: bool = False, metrics=None,
                 page_size: int = 0, kv_pages: int = 0,
                 prefix_share: bool = True, spec_k: int = 0,
                 spec_ngram: int = 3, dispatch_tokens: int = 0,
                 slo=None, chaos=None,
                 journal=None, watchdog=None, kv_quant: str = "f32",
                 kv_host_pages: int = 0, kv_disk_dir: str | None = None,
                 kv_disk_bytes: int = 0, kv_tier_async: bool = True,
                 remote_pages: bool = False, slo_priority: bool = False):
        import functools

        import jax
        import jax.numpy as jnp

        from ..models.llama import (forward_batch_mixed_paged,
                                    forward_batch_paged,
                                    forward_batch_ragged,
                                    forward_batch_spec_paged, gather_pages,
                                    gather_pages_q8, init_cache_batch,
                                    init_cache_paged, init_cache_paged_q8,
                                    params_to_device, scatter_pages,
                                    scatter_pages_q8)

        self.spec = spec
        self.slots = slots
        self.temperature = temperature
        self.topp = topp
        self.seed = seed
        self.jnp = jnp
        self.prefill_chunk = prefill_chunk
        # deterministic fault injection (runtime/chaos.py ChaosMonkey):
        # consulted pre-dispatch (latency spikes), at page allocation
        # (transient starvation), and on cancelled-release (the seeded
        # leak mutation). None = zero overhead, like the metrics handle.
        self._chaos = chaos
        # paged KV mode (page_size > 0): the cache becomes a fixed pool of
        # (page_size)-position pages shared by all slots through per-slot
        # page tables, with radix-tree prefix sharing on admission
        # (runtime/paging.py). page_size == 0 keeps the contiguous
        # slots x seq_len layout. ``kv_pages`` sizes the pool (default:
        # slots * seq_len/page_size — byte-parity with contiguous; pass
        # fewer pages to oversubscribe slots at equal HBM, the
        # continuous_bench concurrency lever).
        self.page_size = page_size
        self._alloc = None
        if kv_pages and page_size <= 0:
            raise ValueError("kv_pages requires page_size > 0 (pass "
                             "--kv-page-size with --kv-pages)")
        # KV page quantization (ISSUE 11): 'q8' stores pool pages in the
        # Q80 int8+scale wire layout (models/llama.PagedKVQ8) — ~1/3.8 of
        # the f32 page bytes, so the same HBM holds ~3.8x pages. Decode
        # quantizes on write; attention dequantizes on read (inside the
        # paged flash kernel's page loop, or in the XLA gather fallback).
        self.kv_quant = kv_quant
        if kv_quant not in ("f32", "q8"):
            raise ValueError(f"kv_quant={kv_quant!r}: expected f32|q8")
        if kv_quant == "q8" and page_size <= 0:
            raise ValueError("kv_quant='q8' quantizes PAGE planes; pass "
                             "page_size > 0 (--kv-page-size with "
                             "--kv-quant q8)")
        if kv_quant == "q8":
            from ..parallel.tp import validate_kv_quant

            validate_kv_quant(spec, (mesh.shape["tp"] if mesh is not None
                                     else 1), kv_quant)
            _warn_q8_xla_fallback(spec, page_size,
                                  mesh.shape["tp"] if mesh is not None
                                  else 1)
        if (kv_host_pages or kv_disk_dir) and page_size <= 0:
            raise ValueError("KV tiering spills PAGES: pass page_size > 0 "
                             "(--kv-page-size with --kv-host-pages/"
                             "--kv-disk-dir)")
        # DCN handoff ingestion (ISSUE 14): the decode pool of a
        # disaggregated topology adopts remotely-prefilled KV pages — the
        # transfer unit is the PAGE, so the paged pool is mandatory
        if remote_pages and page_size <= 0:
            raise ValueError("remote_pages ingests KV PAGES: pass "
                             "page_size > 0 (--kv-page-size with "
                             "--disagg-role decode)")
        if slo_priority and slo is None:
            raise ValueError("slo_priority orders admission by SLO class: "
                             "pass an SLO policy (slo=...)")
        if kv_disk_bytes and not kv_disk_dir:
            raise ValueError("kv_disk_bytes without kv_disk_dir: the disk "
                             "tier needs a directory (--kv-disk-dir)")
        if page_size > 0:
            from .paging import PagedAllocator

            if spec.seq_len % page_size:
                raise ValueError(f"page_size={page_size} must divide "
                                 f"seq_len={spec.seq_len}")
            self._max_pages = spec.seq_len // page_size
            n_pages = kv_pages or slots * self._max_pages
            self._alloc = PagedAllocator(n_pages, page_size,
                                         prefix_share=prefix_share,
                                         host_pages=kv_host_pages,
                                         disk_dir=kv_disk_dir,
                                         disk_bytes=kv_disk_bytes)
            # persistent page-table staging row block (dlint D004): one
            # int32 (slots, max_pages) buffer, rewritten host-side per
            # step and shipped as ONE upload; free/short rows park their
            # tail on the scrap page
            self._stage_tbl = np.zeros((slots, self._max_pages), np.int32)
        # self-speculative decoding (ISSUE 7): each scheduler iteration
        # drafts up to spec_k - 1 tokens per row (runtime/speculative.py
        # n-gram lookup) and verifies them with current-token + drafts in
        # ONE K-query dispatch — the per-dispatch collective schedule is
        # paid once for up to spec_k emitted tokens. Needs the paged cache:
        # rejected-suffix KV rolls back by truncating the page table.
        self.spec_k = spec_k
        self.spec_ngram = spec_ngram
        if spec_k:
            if spec_k < 2:
                raise ValueError(f"spec_k={spec_k}: the verify window is "
                                 f"current token + K-1 drafts, so K >= 2 "
                                 f"(K=0 disables)")
            if page_size <= 0:
                raise ValueError(
                    "spec_k requires the paged KV cache (pass "
                    "--kv-page-size with --spec-k): acceptance rollback "
                    "truncates the page-table logical length")
            # persistent (slots, K) verify-window staging block: the K
            # input tokens per row ride ONE int32 upload per dispatch
            # (dlint D004), exactly like the chain's staged_i32 rows
            self._stage_spec = np.zeros((slots, spec_k), np.int32)
        # token-budget mixed dispatches (ISSUE 18): every dispatch carries
        # a fixed budget of ``dispatch_tokens`` query positions filled
        # with all active decode rows (1 token each) plus ONE prefill
        # slice cut to the remaining budget, in a single fused forward
        # (models/llama.forward_batch_mixed_paged). -1 = auto: sized from
        # the chunk knob — room for every slot's decode token plus a
        # chunk-wide slice.
        if dispatch_tokens == -1:
            dispatch_tokens = slots - 1 + max(prefill_chunk, 2)
        self.dispatch_tokens = dispatch_tokens
        if dispatch_tokens:
            if dispatch_tokens < 2:
                raise ValueError(
                    f"dispatch_tokens={dispatch_tokens}: the budget holds "
                    f"decode rows plus a prefill slice, so it must be "
                    f">= 2 (0 disables, -1 sizes from the chunk knob)")
            if page_size <= 0:
                raise ValueError(
                    "dispatch_tokens requires the paged KV cache (pass "
                    "--kv-page-size with --dispatch-tokens): the mixed "
                    "window writes through per-row page tables")
            if spec_k:
                raise ValueError(
                    "dispatch_tokens is incompatible with spec_k: the "
                    "verify window and the prefill slice both claim the "
                    "per-row span (unifying them is follow-up work)")
            # persistent (slots, budget + 2) mixed staging block: per row
            # [span, pos, token window...] — ONE int32 upload per dispatch
            # (dlint D004); the jitted program splits device-side
            self._stage_mixed = np.zeros((slots, dispatch_tokens + 2),
                                         np.int32)
            # rotating fairness cursor: when active decode rows exceed the
            # budget, deferral rotates so no row starves (budget_wait)
            self._mixed_rr = 0
        # multi-host SPMD runs MUST pin the numpy sampler: native and numpy
        # can differ by float ulps across libm builds (sampling.Sampler
        # docstring), and divergent hosts feed different tokens into the
        # lockstep step — silent corruption. cli.py passes False whenever
        # --coordinator is set, mirroring the single-sequence Engine path.
        self.use_native_sampler = use_native_sampler
        self.block_steps = block_steps  # >1: fused K-step chains (step_many)
        dtype = cache_dtype or jnp.float32
        self._cache_dtype = dtype
        from ..models.llama import KVCache, forward, init_cache

        def _insert(cache_b, c1, b):
            # write sequence-cache planes (L, S, kv, hs) into row b of the
            # batched (L, B, S, kv, hs) cache, in place (the sharded case
            # is pure per-shard work: the two caches share the S/kv-head
            # sharding axes, and the batch axis is unsharded)
            return KVCache(
                jax.lax.dynamic_update_slice(
                    cache_b.k, c1.k[:, None], (0, b, 0, 0, 0)),
                jax.lax.dynamic_update_slice(
                    cache_b.v, c1.v[:, None], (0, b, 0, 0, 0)))

        if mesh is not None and (mesh.shape["tp"] > 1
                                 or mesh.shape.get("sp", 1) > 1):
            # sharded step: same program as the lockstep batch path, driven
            # with a (B,) position vector
            from ..parallel import (make_sharded_forward,
                                    make_sharded_forward_batch,
                                    make_sharded_forward_batch_paged,
                                    make_sharded_mixed,
                                    make_sharded_verify, shard_cache,
                                    shard_cache_batch, shard_cache_paged,
                                    shard_params, validate_sharding)
            from ..parallel.comm_stats import tp_scheme

            scheme = tp_scheme()  # one resolution: decode + prefill +
            #                       params all run the same schedule
            validate_sharding(spec, mesh)
            self.params = shard_params(params, mesh, scheme=scheme)
            if self._alloc is not None:
                # +1 physical page: the reserved scrap page 0
                self._step = _shared_program(
                    ("sh_step_paged", spec, mesh, page_size, scheme,
                     kv_quant),
                    lambda: make_sharded_forward_batch_paged(
                        spec, mesh, page_size, scheme=scheme,
                        kv_quant=kv_quant))  # rejects sp>1
                if spec_k:
                    self._verify_base = _shared_program(
                        ("sh_verify", spec, mesh, page_size, scheme,
                         kv_quant),
                        lambda: make_sharded_verify(
                            spec, mesh, page_size, scheme=scheme,
                            kv_quant=kv_quant))
                if dispatch_tokens:
                    self._mixed_base = _shared_program(
                        ("sh_mixed", spec, mesh, page_size, scheme,
                         kv_quant),
                        lambda: make_sharded_mixed(
                            spec, mesh, page_size, scheme=scheme,
                            kv_quant=kv_quant))
                self.cache = shard_cache_paged(
                    init_cache_paged_q8(spec, self._alloc.n_pages + 1,
                                        page_size)
                    if kv_quant == "q8" else
                    init_cache_paged(spec, self._alloc.n_pages + 1,
                                     page_size, dtype), mesh)
            else:
                self.cache = shard_cache_batch(
                    init_cache_batch(spec, slots, dtype), mesh)
                self._step = _shared_program(
                    ("sh_step_batch", spec, mesh, scheme),
                    lambda: make_sharded_forward_batch(spec, mesh,
                                                       scheme=scheme))
            if prefill_chunk > 1:
                # admission prefill: the sharded single-sequence forward
                # (T=chunk under sp/tp) fills a sharded scratch cache
                self._prefill_fwd = _shared_program(
                    ("sh_prefill", spec, mesh, scheme, fast_prefill),
                    lambda: _maybe_bf16(
                        make_sharded_forward(spec, mesh, scheme=scheme),
                        fast_prefill, jax))
                self._scratch_cache = lambda: shard_cache(
                    init_cache(spec, dtype), mesh)
        else:
            self.params = params_to_device(params)
            if self._alloc is not None:
                self.cache = (
                    init_cache_paged_q8(spec, self._alloc.n_pages + 1,
                                        page_size)
                    if kv_quant == "q8" else
                    init_cache_paged(spec, self._alloc.n_pages + 1,
                                     page_size, dtype))
                self._step = _shared_program(
                    ("step_paged", spec, page_size, kv_quant),
                    lambda: jax.jit(
                        functools.partial(forward_batch_paged, spec,
                                          page_size, kv_quant=kv_quant),
                        donate_argnums=1))
                if spec_k:
                    self._verify_base = _shared_program(
                        ("verify", spec, page_size, kv_quant),
                        lambda: jax.jit(
                            functools.partial(forward_batch_spec_paged,
                                              spec, page_size,
                                              kv_quant=kv_quant),
                            donate_argnums=1))
                if dispatch_tokens:
                    self._mixed_base = _shared_program(
                        ("mixed", spec, page_size, kv_quant),
                        lambda: jax.jit(
                            functools.partial(forward_batch_mixed_paged,
                                              spec, page_size,
                                              kv_quant=kv_quant),
                            donate_argnums=1))
            else:
                self.cache = init_cache_batch(spec, slots, dtype)
                self._step = _shared_program(
                    ("step_ragged", spec),
                    lambda: jax.jit(
                        functools.partial(forward_batch_ragged, spec),
                        donate_argnums=1))
            if prefill_chunk > 1:
                # admission prefill: single-sequence T=chunk forward into a
                # scratch cache + plane insert
                self._prefill_fwd = _shared_program(
                    ("prefill", spec, fast_prefill),
                    lambda: _maybe_bf16(
                        functools.partial(forward, spec), fast_prefill,
                        jax, jit=True))
                self._scratch_cache = lambda: init_cache(spec, dtype)
        if prefill_chunk > 1:
            # donate only the batched cache (updated in place); the scratch
            # sequence cache can't alias the rank-5 output
            self._insert = _shared_program(
                ("insert",), lambda: jax.jit(_insert, donate_argnums=0))
            if self._alloc is not None:
                # paged prefill plumbing: gather the slot's pages into a
                # virtual contiguous sequence cache (shared prefix k/v
                # included — suffix chunks must attend over it), prefill
                # into that, scatter back into the pool in place. Q8
                # pools dequantize on gather and re-quantize on scatter
                # (the engine redirects SHARED entries of the scatter
                # table to the scrap page — quantize∘dequantize is not
                # byte-idempotent, and a shared page must keep the bytes
                # its first prefiller published).
                gp = gather_pages_q8 if kv_quant == "q8" else gather_pages
                sp_ = (scatter_pages_q8 if kv_quant == "q8"
                       else scatter_pages)
                self._gather_pages = _shared_program(
                    ("gather", kv_quant, page_size),
                    lambda: jax.jit(lambda c, t, gp=gp: gp(c, t,
                                                           page_size)))
                self._scatter_pages = _shared_program(
                    ("scatter", kv_quant, page_size),
                    lambda: jax.jit(
                        lambda c, s, t, sp_=sp_: sp_(c, s, t, page_size),
                        donate_argnums=0))
        # KV tiering (ISSUE 12): bind the allocator's device I/O — the
        # demotion read (pool page planes -> host numpy, models/llama.
        # fetch_page_planes), the promotion stage (host payload ->
        # device(-sharded) arrays, run by a background PageUploader so
        # the host->device copy hides behind decode steps), and the
        # donated apply jit the scheduler runs at step boundaries
        # (_settle_promotions). kv_tier_async=False stages inline at
        # promotion time — the deterministic mode the virtual-clock
        # bench/tests drive.
        self._uploader = None
        self._tier_write = None
        self._tier_seen = {"prom": 0, "dem": 0, "hbm": 0, "host": 0,
                           "disk": 0}
        if self._alloc is not None and (self._alloc.tiered or remote_pages):
            from ..models.llama import fetch_page_planes, write_page_planes
            from .paging import PageUploader

            if mesh is not None:
                from ..parallel.tp import stage_page_planes

                q8 = kv_quant == "q8"
                stage = lambda planes: stage_page_planes(  # noqa: E731
                    planes, mesh, q8=q8)
            else:
                stage = lambda planes: tuple(  # noqa: E731
                    jax.device_put(p) for p in planes)
            if self._alloc.tiered:
                if kv_tier_async:
                    self._uploader = PageUploader(stage=stage)
                self._alloc.bind_device_io(
                    lambda pid: fetch_page_planes(self.cache, pid),
                    stage=stage, uploader=self._uploader)
                if chaos is not None:
                    # hook consulted per demotion; the monkey's
                    # drop_on_demote flag decides (like deny_page)
                    self._alloc.corrupt_demote = chaos.demote_drop
            else:
                # remote-only (DCN decode pool): no demotion reads — just
                # the promotion stage + apply for adopted handoff pages
                self._alloc.bind_device_io(None, stage=stage)
            if remote_pages:
                self._alloc.remote = True
            self._tier_write = _shared_program(
                ("tier_write",),
                lambda: jax.jit(write_page_planes, donate_argnums=0))
        # write-ahead request journal (runtime/journal.py, ISSUE 9): every
        # submit/sampled-token/retire appends a record; recover() replays
        # incomplete requests after a crash. None = zero overhead, like
        # the chaos and metrics handles. New request ids start past the
        # journal's highest so appended records never alias old requests.
        self._journal = journal
        self._suspending = False  # drain: retire without journaling
        # per-dispatch hang detection (runtime/supervisor.StepWatchdog):
        # armed around every device call — decode steps, fused chains,
        # verify dispatches, and admission prefill
        self._watchdog = watchdog
        # SLO-aware admission (ISSUE 14): with slo_priority on, _pop_request
        # takes the best-ranked class first (rank = position in the policy's
        # class order, FIFO within a class) instead of plain FIFO — the
        # prefill pool's routing-by-class lever. Scheduling never changes a
        # request's own stream, so priority is stream-invisible.
        self._prio = slo.rank if slo_priority else None
        # chunk-boundary prefill preemption hook (ISSUE 14): a callable
        # consulted at page-aligned chunk boundaries of admission prefill;
        # True parks the slot there (s.prefill_pending) so a higher-priority
        # arrival's prefill runs first. Paged engines only (the contiguous
        # scratch-cache prefill is not resumable). None = never preempt.
        self.prefill_hold = None
        # DCN handoff intake (decode pool): handler threads queue
        # (tokens, planes, request) triples here; the SCHEDULER thread
        # adopts + submits at its next iteration — the radix tree is
        # scheduler-owned and must never be mutated from a handler
        self._remote_inbox: list = []
        # ... and the prefill-pool twin: handler threads queue export
        # requests (tokens, box) and the scheduler fulfils them with the
        # tree-held prompt pages' wire payloads (same ownership rule)
        self._export_inbox: list = []
        self._pool = [_Slot() for _ in range(slots)]
        # persistent host-side staging buffers (dlint D004): the per-step
        # pool scan writes rows here and each step ships ONE upload per
        # buffer instead of B-element Python lists boxed into fresh arrays
        # on every step. Rows: i32 = (token, pos, budget); f32 = (temp,
        # topp). jnp.asarray COPIES host memory into the device buffer at
        # dispatch, so reusing the staging arrays across steps is safe.
        self._stage_i32 = np.zeros((3, slots), np.int32)
        self._stage_f32 = np.zeros((2, slots), np.float32)
        self._stage_active = np.zeros((slots,), np.bool_)
        self._queue: list[Request] = []
        self._lock = threading.Lock()
        self._submitted = 0 if journal is None else journal.next_id
        self._chains: dict = {}  # (k, greedy_only) -> fused chain program
        self.stats = ContinuousStats()
        # request-cost accounting + dispatch census (ISSUE 16, obs/
        # ledger.py): always on like stats and the SLOTracker — pure
        # host bookkeeping charged once per DISPATCH, not per token; the
        # Prometheus pushes stay behind the self._obs guard below
        self._book = LedgerBook()
        self._census = CensusRing(slots)
        self._ici_row_bytes = 0.0  # per-row ICI bytes per device step
        # telemetry is opt-in: ``metrics`` is an obs.metrics.Registry; when
        # None (the default) self._obs stays None and every guarded call
        # site below is skipped — the hot path makes ZERO registry calls
        # (the off-unless-enabled contract, tests/test_obs.py)
        if metrics is not None:
            from ..obs.spans import SpanTracer
            from ..obs.trace import EngineMetrics

            self._obs = EngineMetrics(metrics)
            if self._alloc is not None:
                # a fresh paged server must scrape as fully free, not as
                # exhausted (the gauge default 0)
                self._obs.kv_pages_free.set(self._alloc.n_free)
                # pool byte accounting (ISSUE 11): the GLOBAL logical
                # bytes of the allocated page planes (scrap included;
                # whole pool across tp shards — per-device is /tp) +
                # the KV-quant info series, so a dashboard can prove the
                # equal-HBM capacity claim from the scrape alone
                pool_bytes = sum(int(a.nbytes) for a in self.cache)
                self._obs.bind_kv_pool(kv_quant, pool_bytes,
                                       self._alloc.n_pages + 1)
            # the span timeline (GET /debug/timeline) rides the same
            # opt-in: a disabled engine records nothing. Ring overflow
            # feeds dllama_spans_dropped_total (ISSUE 15 satellite).
            self._spans = SpanTracer(on_drop=self._obs.spans_dropped.inc)
            if mesh is not None and mesh.shape["tp"] > 1:
                # export the analytic collective schedule as labeled
                # /metrics series — the budget the drift gate (obs/drift)
                # reconciles measurements against. Bytes scale by the slot
                # count: every batched collective moves B rows.
                from ..parallel.comm_stats import tp_collective_budget

                self._obs.bind_collectives(
                    tp_collective_budget(spec, mesh.shape["tp"], scheme),
                    scheme, rows=slots)
                # per-row share of the budget's per-step bytes — the
                # ledger's pro-rated ICI attribution (ISSUE 16)
                self._ici_row_bytes = (self._obs.ici_bytes_per_step
                                       / max(slots, 1))
        else:
            self._obs = None
            self._spans = None
        if journal is not None and self._obs is not None:
            journal.bind_metrics(self._obs.journal_records)
        # SLO verdict tracking (obs/slo.py, ISSUE 8): independent of the
        # metrics toggle — a policy without a registry still tallies
        # (loadcheck's virtual-clock engines), a registry without a
        # policy exposes no SLO series. The tracker is written only at
        # retire, off the per-token hot path.
        if slo is not None:
            from ..obs.slo import SLOTracker

            self._slo = SLOTracker(slo, metrics)
        else:
            self._slo = None

    @property
    def slo_tracker(self):
        """The obs.slo.SLOTracker when a policy was configured, else None
        — the server's /health "slo" block reads snapshot() here."""
        return self._slo

    @property
    def ledger_book(self):
        """The obs.ledger.LedgerBook (always constructed) — the server's
        /health "sched" block and GET /debug/sched read it."""
        return self._book

    @property
    def sched_census(self):
        """The obs.ledger.CensusRing of per-dispatch composition records
        (always constructed) — exported at GET /debug/sched."""
        return self._census

    def close(self) -> None:
        """Release engine-owned background resources — today the KV-tier
        PageUploader thread (ISSUE 12). Idempotent; the engine must not
        step after close(). Server shutdown (runtime/server.InferenceServer
        .stop) and the bench arms call this; short-lived engines may rely
        on the thread being a daemon instead."""
        if self._uploader is not None:
            self._uploader.close()
            self._uploader = None

    def audit_pages(self) -> list[str]:
        """Page-accounting invariant check (paging.PagedAllocator.audit
        over the live slot tables) — the chaos-drill oracle; [] on
        contiguous engines and clean pools."""
        if self._alloc is None:
            return []
        return self._alloc.audit([s.pages for s in self._pool])

    @property
    def allocator(self):
        """The paging.PagedAllocator when page_size > 0, else None — the
        bench and server read pool occupancy / prefix-hit counters here."""
        return self._alloc

    def _chain(self, k: int, greedy_only: bool):
        """Build (and cache) the fused K-step device program: K ragged
        decode steps in ONE dispatch, with per-row active masks so rows
        freeze in place the moment they hit BOS or their budget (a frozen
        row keeps rewriting the same k/v at its frozen position — identical
        values, harmless). Admission/retirement happen on the host BETWEEN
        chains (admission latency <= k steps, the documented trade for
        k fewer host round-trips)."""
        import jax
        import jax.numpy as jnp

        key = (k, greedy_only)
        if key in self._chains:
            return self._chains[key]
        if self._obs is not None:  # step-shape cache miss: a new trace
            self._obs.compile_events.inc()

        from .decode import sample_device_dynamic

        step = self._step
        paged = self._alloc is not None

        def chain(params, cache, staged_i32, active, forced, coins,
                  staged_f32, table):
            # staged_i32 (3, B) = token/pos/budget rows, staged_f32 (2, B)
            # = temp/topp rows — each ONE host->device upload per chain
            # (dlint D004); the splits below are device-side slices.
            # ``table`` (B, max_pages) is the paged page-table block (a
            # zero-width dummy in contiguous mode): constant across the K
            # steps — step_many pre-allocates page coverage for the whole
            # chain, so no page boundary can strand a mid-chain write
            tokens, pos, budget = (staged_i32[0], staged_i32[1],
                                   staged_i32[2])
            temps, topps = staged_f32[0], staged_f32[1]

            def body(carry, xs):
                tokens, pos, active, cache = carry
                forced_i, coins_i = xs                      # (B,), (B,)
                if paged:
                    logits, cache = step(params, cache, tokens, pos, table)
                else:
                    logits, cache = step(params, cache, tokens, pos)
                if greedy_only:
                    sampled = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    sampled = jax.vmap(sample_device_dynamic)(
                        logits, coins_i, temps, topps)
                nxt = jnp.where(forced_i >= 0, forced_i, sampled)
                rec_active = active
                new_active = (active & (nxt != BOS)
                              & (pos + 1 < budget))
                pos = jnp.where(new_active, pos + 1, pos)
                tokens = jnp.where(new_active, nxt, tokens)
                return (tokens, pos, new_active, cache), (nxt, rec_active)

            (_, _, _, cache), (toks, acts) = jax.lax.scan(
                body, (tokens, pos, active, cache), (forced, coins))
            return cache, toks, acts                       # ys: (K, B)

        # keyed on the step program OBJECT (identity): equal-config
        # engines share a memoized step, so their chains collapse to one
        # compile; a patched step (chaos proxies) gets its own chain
        self._chains[key] = _shared_program(
            ("chain", step, k, greedy_only, paged),
            lambda: jax.jit(chain, donate_argnums=1))
        return self._chains[key]

    # -- speculative decoding (spec_k > 0) ----------------------------------

    def _verify_program(self, greedy_only: bool):
        """The jitted K-query verify dispatch (built once per variant).
        The base program scores all K window positions; when EVERY active
        row is greedy the wrapper argmaxes ON DEVICE and ships a (B, K)
        int32 block instead of the f32 logit cube (decode.
        greedy_verify_tokens) — the same transfer cut the fused chain's
        greedy_only branch makes. Mixed/sampled pools ship full logits:
        rejection-sampling acceptance needs whole distributions with the
        host Sampler's exact semantics."""
        import jax

        key = ("spec", greedy_only)
        if key in self._chains:
            return self._chains[key]
        if self._obs is not None:  # verify-shape cache miss: a new trace
            self._obs.compile_events.inc()
        base = self._verify_base

        from .decode import greedy_verify_tokens

        def run(params, cache, tokens, pos, table):
            logits, cache = base(params, cache, tokens, pos, table)
            out = greedy_verify_tokens(logits) if greedy_only else logits
            return out, cache

        self._chains[key] = _shared_program(
            ("verify_prog", base, greedy_only),
            lambda: jax.jit(run, donate_argnums=1))
        return self._chains[key]

    def _mixed_program(self, greedy_only: bool):
        """The jitted token-budget mixed dispatch (built once per
        variant). The staged (slots, budget + 2) block splits DEVICE-side
        into [span | pos | token window] so the host ships ONE int32
        upload per dispatch (dlint D004, _verify_program's transfer
        shape). All-greedy pools argmax on device and ship a (B, T) int32
        block instead of the f32 logit cube (decode.greedy_verify_tokens
        — the same cut as the verify program); sampled pools ship full
        logits for the host Sampler's exact semantics."""
        import jax

        key = ("mixed", greedy_only)
        if key in self._chains:
            return self._chains[key]
        if self._obs is not None:  # mixed-shape cache miss: a new trace
            self._obs.compile_events.inc()
        base = self._mixed_base

        from .decode import greedy_verify_tokens

        def run(params, cache, blk, table):
            span, pos, tokens = blk[:, 0], blk[:, 1], blk[:, 2:]
            logits, cache = base(params, cache, tokens, pos, span, table)
            out = greedy_verify_tokens(logits) if greedy_only else logits
            return out, cache

        self._chains[key] = _shared_program(
            ("mixed_prog", base, greedy_only),
            lambda: jax.jit(run, donate_argnums=1))
        return self._chains[key]

    def step_mixed(self, quiet: bool = True) -> int:
        """One token-budget mixed dispatch over the pool (ISSUE 18):
        every active decode row contributes its 1 pending token and ONE
        row with forced prompt tokens left (the prefill slice — the
        best-SLO-ranked such row, FIFO within a class) contributes up to
        the remaining budget, all in a single fused forward
        (forward_batch_mixed_paged). Prefill therefore never stalls
        in-flight decodes behind a separate chunk dispatch, and PR 14's
        chunk-boundary preemption collapses into slice selection: a
        higher-priority arrival simply wins the next dispatch's slice
        (no parked-slot bookkeeping on this path — _maybe_prefill_slot
        is gated off entirely).

        When active decode rows exceed the budget, the overflow rides
        this dispatch deferred (span 0, masked junk, ledger/census cause
        ``budget_wait``) under a rotating fairness cursor. The host
        replay applies exactly step_once's per-token bookkeeping (forced
        pops, sampler/argmax, BOS + budget stops via _advance), and
        window construction guarantees a row's sampler is consulted only
        at its LAST window position (span <= 1 + len(forced)), so the
        emitted stream is token-for-token the separate-dispatch engine's
        (greedy and seeded-sampled — the tests/test_mixed_batch.py
        parity gates). Returns active slots after the iteration."""
        jnp = self.jnp
        T = self.dispatch_tokens
        self._drain_remote_inbox()
        self._sweep_cancelled()
        self._admit()
        self._settle_promotions(quiet)
        pool = self._pool
        # span assignment BEFORE page growth: every candidate decode row
        # wants 1 position; the slice row wants its span. Deferral
        # (budget_wait) happens here too — a deferred row needs no pages.
        candidates = [b for b, s in enumerate(pool) if not s.free]
        spans: dict[int, int] = {}
        deferred: set = set()
        if len(candidates) > T:
            order = sorted(candidates,
                           key=lambda b: (b - self._mixed_rr) % self.slots)
            deferred = set(order[T:])
            self._mixed_rr = (self._mixed_rr + T) % self.slots
            for b in order[:T]:
                spans[b] = 1
        else:
            for b in candidates:
                spans[b] = 1
            room = T - len(candidates)
            # ONE prefill slice: among rows with forced tokens pending,
            # the best SLO rank wins (FIFO within a class) — arrival
            # priority replaces the parked-slot preemption machinery
            slice_rows = [b for b in candidates if pool[b].forced]
            if room > 0 and slice_rows:
                rank = self._prio or (lambda cls: 0)
                win = min(slice_rows,
                          key=lambda b: (rank(pool[b].req.slo_class),
                                         pool[b].req.index))
                s = pool[win]
                extra = min(len(s.forced), room)
                if (self._chaos is not None
                        and self._chaos.budget_overrun()):
                    # mutation arm: the slice ignores the remaining
                    # budget and takes the whole staging width
                    extra = min(len(s.forced), T - 1)
                spans[win] = 1 + extra
        paused = self._grow_pages(pool, 1, quiet, spans=spans)
        if all(s.free for s in pool):
            self._journal_sync()  # cover sweep/admit records this iteration
            return self._n_outstanding()
        blk = self._stage_mixed
        greedy_only = True
        for b, s in enumerate(pool):
            span = 0 if (s.free or b in paused or b in deferred) \
                else spans.get(b, 0)
            spans[b] = span
            blk[b, 0] = span
            blk[b, 1] = s.pos
            blk[b, 2:] = 0
            if span <= 0:
                continue
            if s.sampler.temperature != 0.0:
                greedy_only = False
            blk[b, 2] = s.token
            for i, t in enumerate(s.forced[:span - 1]):
                blk[b, 3 + i] = t
        n_active0 = sum(1 for v in spans.values() if v > 0)
        total_span = sum(spans.values())
        # virtual overrun charge: a healthy dispatch fits the budget
        # (sum(span) <= T); the overrun-budget mutation does not, and the
        # virtual clock must see the extra device time it would cost
        self.stats.overrun_steps += max(0, -(-total_span // T) - 1)
        table = self._stage_tables()
        run = self._mixed_program(greedy_only)
        t0 = time.monotonic()  # census/ledger wall charges need it even
        #                        when the engine runs metrics-dark
        with self._span("mixed", "decode", budget=T, tokens=total_span,
                        active=n_active0), self._watch():
            if self._chaos is not None:
                self._chaos.on_dispatch()  # inside the armed window (the
                #   injected stall IS the hang the watchdog must detect)
            out, cache = run(self.params, self.cache, jnp.asarray(blk),
                             table)
            self.cache = cache
            out = np.asarray(out)  # dlint: allow[D001] host replay reads ids/logits
            if self._obs is not None:
                # the sync flag additionally drains the donated cache
                # write (obs/trace.sync_device_timing)
                if self._obs.sync:
                    import jax

                    jax.block_until_ready(self.cache)  # dlint: allow[D001] opt-in timing drain
                self._obs.record_step(time.monotonic() - t0, n_active0)
                if self._alloc is not None:
                    self._obs.kv_pages_free.set(self._alloc.n_free)
        self.stats.steps += 1
        self.stats.sum_active += n_active0
        self.stats.max_active = max(self.stats.max_active, n_active0)
        self._census_dispatch("mixed", 1, paused, n_active0,
                              time.monotonic() - t0, deferred=deferred)
        # host replay: exactly step_once's per-token bookkeeping over each
        # row's live window (forced pops first; the sampler is consulted
        # only at the last position, where the fed inputs ran out)
        for b, s in enumerate(pool):
            if s.free:
                continue
            if s.req.cancelled:  # consumer vanished during the dispatch
                self._retire(s, quiet)
                continue
            span = spans.get(b, 0)
            if span <= 0:
                continue
            for i in range(span):
                if s.forced:
                    nxt, sampled = s.forced.pop(0), False
                elif greedy_only:
                    nxt, sampled = int(out[b, i]), True
                else:
                    nxt, sampled = int(s.sampler.sample(out[b, i])), True
                if self._advance(s, nxt, quiet, sampled=sampled):
                    break
        self._admit()
        self._journal_sync()
        return self._n_outstanding()

    def step_spec(self, quiet: bool = True) -> int:
        """One draft → verify → accept iteration over the pool (ISSUE 7).

        Each active row feeds [current token | window] where the window is
        its pending FORCED tokens first (prompt replay — guaranteed to
        match, so the dispatch doubles as K-wide prompt chunking), then up
        to K-1 n-gram drafts (runtime/speculative.draft_tokens). The
        K-query verify forward scores every window position in ONE
        dispatch; the host replay applies exactly step_once's bookkeeping
        per position (forced pops, sampler/argmax, BOS + budget stops via
        _advance) and stops at the first position whose outcome differs
        from the fed input — later logits were conditioned on a wrong
        token. Greedy rows accept drafts by exact argmax match, so the
        emitted stream is BITWISE the spec-off stream; sampled rows run
        Leviathan rejection sampling (speculative.accept_or_resample) —
        coin-stream alignment: each resolved draft position draws its
        accept coin (plus one residual-resample coin on rejection), and
        positions never reached consume NO coin, so a seeded engine
        replays deterministically. Rejected-suffix KV is discarded by
        rolling the page table back to the accepted length (_trim_pages)
        — pages whose only content was rejected tokens return to the
        pool. Returns active slots after the iteration."""
        jnp = self.jnp
        K = self.spec_k
        from .speculative import accept_or_resample, draft_tokens

        self._drain_remote_inbox()
        self._sweep_cancelled()
        self._admit()
        self._settle_promotions(quiet)
        self._resume_prefills()
        pool = self._pool
        paused = self._grow_pages(pool, K, quiet)
        if all(s.free for s in pool):
            self._journal_sync()  # cover sweep/admit records this iteration
            return self._n_outstanding()
        st = self._stage_spec
        st_pos = self._stage_i32  # row 1 = per-slot positions, as ever
        active0 = self._stage_active
        kinds: list = [()] * self.slots  # window entry i (= input i+1):
        #                                   'f' forced | 'd' drafted
        greedy_only = True
        for b, s in enumerate(pool):
            active0[b] = not s.free and b not in paused
            st[b, 0] = s.token
            st[b, 1:] = 0
            st_pos[1, b] = s.pos
            if not active0[b]:
                continue
            if s.sampler.temperature != 0.0:
                greedy_only = False
            window = list(s.forced[:K - 1])
            row_kinds = ["f"] * len(window)
            room = K - 1 - len(window)
            if room > 0 and not s.forced[K - 1:]:
                # drafting starts only past the forced prompt; the lookup
                # history is the emitted stream plus the forced tokens fed
                # ahead of the drafts in THIS window
                history = [s.req.tokens[0]] + s.req.out + window
                drafts = draft_tokens(history, room, max_n=self.spec_ngram)
                self.stats.spec_proposed += len(drafts)
                if drafts:
                    self._census.count_tokens("spec", len(drafts))
                    if s.req.ledger is not None:
                        s.req.ledger.charge_spec(len(drafts), 0)
                if self._obs is not None:
                    self._obs.spec_proposed.inc(len(drafts))
                    if drafts:
                        self._obs.count_dispatch_tokens("spec",
                                                        len(drafts))
                window += [int(t) for t in drafts]
                row_kinds += ["d"] * len(drafts)
            for i, t in enumerate(window):
                st[b, 1 + i] = t
            kinds[b] = tuple(row_kinds)
        n_active0 = int(active0.sum())
        table = self._stage_tables()
        run = self._verify_program(greedy_only)
        t0 = time.monotonic()  # census/ledger wall charges need it even
        #                        when the engine runs metrics-dark
        with self._span("verify", "decode", k=K, active=n_active0), \
                self._watch():
            if self._chaos is not None:
                self._chaos.on_dispatch()  # inside the armed window: an
                #   injected stall is device work as far as the watchdog
                #   can tell — exactly the hang it must detect
            out, cache = run(self.params, self.cache, jnp.asarray(st),
                             jnp.asarray(st_pos[1]), table)
            self.cache = cache
            out = np.asarray(out)  # dlint: allow[D001] host replay reads ids/logits
            if self._obs is not None:
                # the sync flag additionally drains the donated cache
                # write (obs/trace.sync_device_timing)
                if self._obs.sync:
                    import jax

                    jax.block_until_ready(self.cache)  # dlint: allow[D001] opt-in timing drain
                self._obs.record_step(time.monotonic() - t0, n_active0)
                if self._alloc is not None:
                    self._obs.kv_pages_free.set(self._alloc.n_free)
        self.stats.steps += 1
        self.stats.sum_active += n_active0
        self.stats.max_active = max(self.stats.max_active, n_active0)
        self._census_dispatch("spec", 1, paused, n_active0,
                              time.monotonic() - t0)
        # host replay: exactly step_once's per-position bookkeeping over
        # the accepted prefix of each row's window
        for b, s in enumerate(pool):
            if s.free:
                continue
            if s.req.cancelled:  # consumer vanished during the dispatch
                self._retire(s, quiet)
                continue
            if not active0[b]:
                continue
            row_kinds = kinds[b]
            retired = False
            for i in range(K):
                accepted_draft = False
                if s.forced:
                    nxt, sampled = s.forced.pop(0), False
                elif s.sampler.temperature == 0.0:
                    nxt = (int(out[b, i]) if greedy_only
                           else int(np.argmax(
                               out[b, i][:self.spec.vocab_size])))
                    sampled = True
                    accepted_draft = (i < len(row_kinds)
                                      and row_kinds[i] == "d"
                                      and nxt == int(st[b, i + 1]))
                elif i < len(row_kinds) and row_kinds[i] == "d":
                    nxt, accepted_draft = accept_or_resample(
                        out[b, i], int(st[b, i + 1]), s.sampler)
                    sampled = True
                else:  # no draft fed here: the plain sampler path
                    nxt, sampled = int(s.sampler.sample(out[b, i])), True
                if accepted_draft:
                    self.stats.spec_accepted += 1
                    if s.req.ledger is not None:
                        s.req.ledger.charge_spec(0, 1)
                    if self._obs is not None:
                        self._obs.spec_accepted.inc()
                if self._advance(s, nxt, quiet, sampled=sampled):
                    retired = True
                    break
                if (i + 1 >= K or i >= len(row_kinds)
                        or nxt != int(st[b, i + 1])):
                    break  # window exhausted, or the fed input was wrong —
                #            logits[i+1] were conditioned on a bad token
            if not retired:
                self._trim_pages(s)
        self._admit()
        self._journal_sync()
        return self._n_outstanding()

    def _trim_pages(self, s: _Slot) -> None:
        """Speculative rollback: drop a slot's trailing pages past the
        accepted position. After a verify dispatch, positions >= s.pos may
        hold rejected-draft KV; positions 0..s.pos-1 are live and position
        s.pos is rewritten by the next dispatch before anything reads it,
        so pages covering ONLY positions >= s.pos return to the pool
        (refcounted: a page the radix tree also holds just drops this
        slot's ref). The shared prefix always survives — s.pos never
        rolls below the share boundary."""
        keep = max(self._alloc.pages_for(s.pos), s.shared)
        if len(s.pages) > keep:
            self._alloc.release_pages(s.pages[keep:])
            del s.pages[keep:]
            if self._obs is not None:
                self._obs.kv_pages_free.set(self._alloc.n_free)

    # -- paged-KV bookkeeping (page_size > 0) -------------------------------

    def _settle_promotions(self, quiet: bool = True) -> None:
        """Step-boundary promotion apply (KV tiering, ISSUE 12): write
        every staged promotion payload into its target pool page (ONE
        donated jit per page — in place), then release slots that were
        waiting on those pages: their deferred admission prefill runs now
        (suffix-only, exactly as for an HBM-resident prefix) and they
        dispatch on the next step. Scheduler thread only — the pool cache
        must never be written concurrently with a dispatch."""
        alloc = self._alloc
        if alloc is None or not alloc.pending_capable:
            return
        jobs = alloc.take_staged_promotions()
        for job in jobs:
            self.cache = self._tier_write(self.cache,
                                          self.jnp.int32(job.page),
                                          tuple(job.staged))
            alloc.promotion_applied(job)
        for b, s in enumerate(self._pool):
            if s.free or not s.await_promo:
                continue
            if alloc.slot_pending(s.pages):
                continue  # still uploading: stays paused
            s.await_promo = False
            self._maybe_prefill_slot(b, s)
            if s.req.cancelled:
                self._retire(s, quiet)
        if jobs:
            self._update_tier_obs()

    def _update_tier_obs(self) -> None:
        """Push the allocator's tier ledger into the Prometheus series
        (delta-tracked: obs counters only move forward)."""
        if self._obs is None or self._alloc is None \
                or not self._alloc.tiered:
            return
        a = self._alloc
        for tier, gauge in self._obs.tier_pages.items():
            gauge.set(a.tier_pages.get(tier, 0))
        seen = self._tier_seen

        def push(key, got, counter):
            # cumulative < seen means allocator.reset_counters() ran (the
            # bench warm-up boundary): re-base without incrementing, so
            # the Prometheus counters keep moving instead of stalling
            # until the count re-exceeds its pre-reset high-water mark
            if got > seen[key]:
                counter.inc(got - seen[key])
            seen[key] = got

        push("prom", sum(a.promotions.values()), self._obs.tier_promotions)
        push("dem", sum(a.demotions.values()), self._obs.tier_demotions)
        for tier, counter in self._obs.tier_saved.items():
            push(tier, a.tokens_saved_by_tier.get(tier, 0), counter)

    def _ensure_pages(self, s: _Slot, n_positions: int) -> bool:
        """Grow a slot's page list to cover ``n_positions`` sequence
        positions, evicting idle radix leaves when the free list is dry
        (paging.PagedAllocator.alloc_page). False = the pool cannot cover
        it even after eviction — the caller fails or requeues the
        request. Never shrinks here: pages free at retire, or via the
        speculative rollback (_trim_pages) when a verify dispatch rejects
        a drafted suffix."""
        need = self._alloc.pages_for(min(n_positions, self.spec.seq_len))
        while len(s.pages) < need:
            if self._chaos is not None and self._chaos.deny_page():
                return False  # injected transient starvation (chaos drill)
            pid = self._alloc.alloc_page()
            if pid is None:
                return False
            s.pages.append(pid)
        return True

    def _grow_pages(self, pool, k: int, quiet: bool,
                    spans: dict | None = None) -> set:
        """Pre-chain page coverage: every active slot gets pages for the
        next ``k`` positions (ONE host round per chain — mid-chain writes
        can then never cross into an unmapped page). ``spans`` (the mixed
        path) overrides k per slot — a deferred row (span 0) needs no new
        pages this dispatch. A slot the pool
        cannot serve yet is PAUSED for this chain (returned in the paused
        set): it rides through the device step masked inactive — its dead
        rewrite lands on the scrap page, its replay is skipped, and its
        sampler consumes nothing, so the eventual stream is untouched —
        and retries once a retirement frees pages. Only when EVERY active
        slot is starved (a true deadlock: no retirement can ever free a
        page) does the youngest request fail; preemption/swap-out is the
        ROADMAP item-4 follow-up."""
        while True:
            paused = set()
            promo = set()
            active = 0
            for b, s in enumerate(pool):
                if s.free:
                    continue
                active += 1
                if s.await_promo or (
                        self._alloc.pending_capable
                        and self._alloc.slot_pending(s.pages)):
                    # shared-prefix pages still riding a promotion upload
                    # (KV tiering): the slot pauses like a page-starved
                    # one, but resolves by itself when the upload lands —
                    # never a deadlock, so the breaker must not see it
                    promo.add(b)
                    continue
                if s.prefill_pending:
                    # parked (preempted) admission prefill: the slot makes
                    # progress only through _resume_prefills — masking it
                    # out of dispatches keeps its position clock at the
                    # page-aligned park point (load-bearing for q8: a
                    # forced step advancing mid-page would force the next
                    # scatter to re-quantize a partially-written page).
                    # Self-resolving, so the deadlock breaker skips it.
                    promo.add(b)
                    continue
                need = k if spans is None else spans.get(b, 0)
                if not self._ensure_pages(s, min(s.pos + need, s.budget)):
                    paused.add(b)
            if promo or not paused or len(paused) < active:
                if paused or promo:
                    self.stats.pauses += len(paused) + len(promo)
                    if self._obs is not None:
                        self._obs.pauses.inc(len(paused) + len(promo))
                return paused | promo
            victim = max(paused, key=lambda b: pool[b].req.index)
            s = pool[victim]
            if self._obs is not None:
                self._obs.reject("deadlock")
            s.req.error = (
                f"kv page pool exhausted: {self._alloc.n_pages} pages of "
                f"{self.page_size} positions, all pinned by concurrent "
                f"requests (deadlock broken by failing the youngest)")
            self._retire(s, quiet)  # frees its pages; survivors retry
            #                        (record_retire counts the failure)

    def _stage_tables(self):
        """Rewrite the persistent page-table staging block from the pool
        state and ship it as ONE int32 upload (dlint D004). Free slots and
        unmapped tail entries park on the scrap page — their dead writes
        and masked gathers land on page 0 by construction."""
        from .paging import SCRAP_PAGE

        tbl = self._stage_tbl
        for b, s in enumerate(self._pool):
            n = len(s.pages)
            tbl[b, :n] = s.pages
            tbl[b, n:] = SCRAP_PAGE
        return self.jnp.asarray(tbl)

    def step_many(self, k: int, quiet: bool = True) -> int:
        """Like ``k`` step_once calls in ONE device dispatch. Per-request
        token streams are identical to the per-step path (the parity gate);
        only scheduling differs: a slot freed mid-chain re-admits at the
        chain boundary. Returns active slots after the chain.

        Parity caveat (same class of contract as PARITY.md's native==numpy
        note): the chain samples on DEVICE (decode.sample_device_dynamic)
        while step_once samples on HOST, so token-for-token equality at
        temperature > 0 holds only while the two softmax/CDF implementations
        agree to the ulp at every CDF boundary — pinned by tests on the
        shipped configs, but an XLA or libm change could flip a
        knife-edge coin. temperature == 0 (argmax) is exact by
        construction."""
        if self.dispatch_tokens:
            # token-budget mode (ISSUE 18): every scheduler iteration IS
            # a mixed dispatch (decode rows + one prefill slice under one
            # budget), superseding both per-step and block-step chaining
            return self.step_mixed(quiet=quiet)
        if self.spec_k:
            # speculative mode: every scheduler iteration IS a fused
            # multi-position dispatch (draft → one K-query verify), so the
            # spec path supersedes block-step chaining — chaining verifies
            # would stack drafts on unverified drafts
            return self.step_spec(quiet=quiet)
        if k <= 1:
            return self.step_once(quiet=quiet)
        jnp = self.jnp
        self._drain_remote_inbox()
        self._sweep_cancelled()
        self._admit()
        self._settle_promotions(quiet)
        self._resume_prefills()
        pool = self._pool
        paused = (self._grow_pages(pool, k, quiet)
                  if self._alloc is not None else ())
        if all(s.free for s in pool):
            self._journal_sync()  # cover sweep/admit records this iteration
            return self._n_outstanding()
        B = self.slots
        st_i32, st_f32 = self._stage_i32, self._stage_f32
        active0 = self._stage_active
        forced = np.full((k, B), -1, dtype=np.int32)
        coins = np.zeros((k, B), dtype=np.float32)
        for b, s in enumerate(pool):
            active0[b] = not s.free and b not in paused
            st_i32[0, b] = s.token
            st_i32[1, b] = s.pos
            st_i32[2, b] = 0 if s.free else s.budget
            st_f32[0, b] = 0.0 if s.free else s.sampler.temperature
            st_f32[1, b] = 0.9 if s.free else s.sampler.topp
            if s.free:
                continue
            for i, t in enumerate(s.forced[:k]):
                forced[i, b] = t
            if s.sampler.temperature != 0.0:
                # pre-draw on a THROWAWAY copy; the real stream advances
                # during replay by exactly the coins the per-step loop
                # would consume. Coin alignment: forced steps draw NO coin,
                # so chain step i uses draw #(i - n_forced) — the stream
                # position the per-step loop would be at
                n_forced = min(len(s.forced), k)
                if n_forced < k:
                    coins[n_forced:, b] = s.sampler.rng.clone().f32_array(
                        k - n_forced)

        n_active0 = int(active0.sum())
        table = (self._stage_tables() if self._alloc is not None
                 else jnp.zeros((B, 0), jnp.int32))
        run = self._chain(k, greedy_only=not st_f32[0].any())
        t0 = time.monotonic()  # census/ledger wall charges need it even
        #                        when the engine runs metrics-dark
        with self._span("chain", "decode", steps=k, active=n_active0), \
                self._watch():
            if self._chaos is not None:
                self._chaos.on_dispatch()  # inside the armed window (the
                #   injected stall IS the hang the watchdog must detect)
            cache, toks, acts = run(
                self.params, self.cache, jnp.asarray(st_i32),
                jnp.asarray(active0), jnp.asarray(forced),
                jnp.asarray(coins), jnp.asarray(st_f32), table)
            self.cache = cache
            toks = np.asarray(toks)  # dlint: allow[D001] chain outputs drive
            acts = np.asarray(acts)  # dlint: allow[D001] the host replay below
            if self._obs is not None:
                # toks/acts above already synced the chain's host outputs;
                # the sync flag additionally drains the donated cache write
                # so the histogram sees pure device time
                # (obs/trace.sync_device_timing)
                if self._obs.sync:
                    import jax

                    jax.block_until_ready(self.cache)  # dlint: allow[D001] opt-in timing drain
                self._obs.record_step(time.monotonic() - t0, n_active0,
                                      steps=k)
                if self._alloc is not None:
                    self._obs.kv_pages_free.set(self._alloc.n_free)
        self.stats.steps += k
        self.stats.sum_active += n_active0 * k
        self.stats.max_active = max(self.stats.max_active, n_active0)
        self._census_dispatch("decode", k, paused, n_active0,
                              time.monotonic() - t0)
        # host replay: apply the recorded per-step outcomes with exactly
        # step_once's bookkeeping (forced pops, RNG draws, BOS/budget stops)
        for b, s in enumerate(pool):
            if s.free:
                continue
            if s.req.cancelled:  # consumer vanished during the chain
                self._retire(s, quiet)  # paused rows free their pages too
                continue
            if not active0[b]:
                continue
            for i in range(k):
                if not acts[i, b]:
                    break
                sampled = not s.forced
                if s.forced:
                    s.forced.pop(0)
                elif s.sampler.temperature != 0.0:
                    s.sampler.rng.f32()  # the coin the chain consumed
                if self._advance(s, int(toks[i, b]), quiet, sampled=sampled):
                    break
        self._admit()
        self._journal_sync()
        return self._n_outstanding()

    def _span(self, name: str, cat: str, **meta):
        """A timeline span when tracing is on; a free nullcontext when the
        engine runs dark (the zero-calls-when-disabled contract covers the
        span tracer too)."""
        if self._spans is None:
            return contextlib.nullcontext()
        return self._spans.span(name, cat, **meta)

    def _watch(self):
        """Arm the step watchdog around a device dispatch (supervisor.
        StepWatchdog context manager); free when no watchdog is set."""
        if self._watchdog is None:
            return contextlib.nullcontext()
        return self._watchdog

    def _journal_sync(self) -> None:
        """Step-boundary journal durability point: one fsync covering the
        iteration's records (batch policy), plus the compaction rotation
        check. Called at the end of every step path."""
        if self._journal is None:
            return
        self._journal.sync()
        self._journal.maybe_compact()

    # -- cost accounting (ISSUE 16) -----------------------------------------

    def _census_dispatch(self, kind: str, k: int, paused, active: int,
                         dt_s: float, deferred=()) -> None:
        """Charge BOTH accounting halves from one pool walk after a
        decode/spec dispatch: per-slot ledger charges (row steps, page
        steps, stalls by cause, pro-rated ICI bytes) and the whole-
        dispatch census record. The two sides take independent
        arithmetic paths — tools/costcheck.py verifies they agree
        EXACTLY, and the chaos ``double_count_dispatch`` mutation
        multiplies only the ledger side (``reps``) so that check must
        catch it. The census stays mutation-clean by construction."""
        reps = 2 if (self._chaos is not None
                     and self._chaos.dispatch_double()) else 1
        alloc = self._alloc
        dt_share = dt_s / max(active, 1)
        pages_held = 0
        parked: dict = {}
        class_page_s: dict = {}
        for b, s in enumerate(self._pool):
            if s.free:
                continue
            led = s.req.ledger
            npages = len(s.pages)
            if npages:
                pages_held += npages
                if led is not None:
                    led.charge_pages(npages, k, dt_s, reps)
                cls = self._bill_class(s.req.slo_class)
                class_page_s[cls] = (class_page_s.get(cls, 0.0)
                                     + npages * dt_s)
            if b in paused:
                # re-distinguish what _grow_pages lumped into one set:
                # promo/prefill parks are self-resolving; pool_dry waits
                # on a retirement to free pages
                if s.await_promo or (alloc is not None
                                     and alloc.pending_capable
                                     and alloc.slot_pending(s.pages)):
                    cause = "promo_pending"
                elif s.prefill_pending:
                    cause = "prefill_hold"
                else:
                    cause = "pool_dry"
                parked[cause] = parked.get(cause, 0) + 1
                if led is not None:
                    led.charge_stall(cause, k, dt_s, reps)
            elif b in deferred:
                # mixed path (ISSUE 18): more active rows than the token
                # budget holds — this row rode the dispatch deferred
                # (span 0) and retries under the rotating cursor
                parked["budget_wait"] = parked.get("budget_wait", 0) + 1
                if led is not None:
                    led.charge_stall("budget_wait", k, dt_s, reps)
            elif led is not None:
                led.charge_rows(k, dt_share, reps)
                if self._ici_row_bytes:
                    led.charge_ici(self._ici_row_bytes * k, reps)
        with self._lock:
            queued = list(self._queue)
        for req in queued:
            if req.ledger is not None:
                req.ledger.charge_stall("queue_wait", k, dt_s, reps)
        tier = (alloc.tier_page_counts()
                if alloc is not None and alloc.tiered else None)
        self._census.record(kind, k, active, parked, len(queued),
                            pages_held, tier_pages=tier)
        if self._obs is not None:
            for cause, n in parked.items():
                self._obs.add_stall_seconds(cause, n * dt_s)
            if queued:
                self._obs.add_stall_seconds("queue_wait",
                                            len(queued) * dt_s)
            for cls, page_s in class_page_s.items():
                self._obs.add_page_seconds(cls, page_s)
            self._obs.set_class_queue_depth(
                collections.Counter(self._bill_class(r.slo_class)
                                    for r in queued))

    def _close_ledger(self, rid: int, status: str) -> None:
        """Close a request's cost ledger at its terminal event and export
        the per-class cost histograms. The chaos ``leak_ledger`` mutation
        skips the close — tools/costcheck.py's orphaned-ledger check must
        flag it."""
        if self._chaos is not None and self._chaos.ledger_leak():
            return
        snap = self._book.close_request(rid, status)
        if snap is not None and self._obs is not None:
            self._obs.observe_request_cost(snap)

    def prejournal(self, req: Request) -> Request:
        """Assign a request's index and journal its admit record NOW
        without queueing it — the decode pool's durability point BEFORE
        a DCN page transfer (ISSUE 14): a crash between here and
        submit() recovers the request from the journal exactly like a
        crash mid-decode would. The caller must eventually submit() (the
        flag makes that append-free) or retire the journaled life
        (``abandon_prejournaled``) — leaving it dangling re-admits it on
        the next recovery, which is the safe failure mode, not the
        intended one."""
        if not req.tokens:
            raise ValueError("request has no prompt tokens")
        if self._journal is None:
            raise ValueError("prejournal() without a journal has no "
                             "durability to offer; call submit()")
        req.t_enqueue = time.monotonic()
        with self._lock:
            req.index = self._submitted
            self._submitted += 1
        if req.trace is None:
            # mint BEFORE the admit lands: the durable record must carry
            # the trace identity a post-crash recovery continues
            req.trace = tracectx.mint()
        self._journal_admit(req)
        self._journal.sync(force=True)  # durable BEFORE any page moves
        req.prejournaled = True
        return req

    def abandon_prejournaled(self, req: Request) -> None:
        """Retire a prejournaled life that will never be submitted (the
        handoff fell back to local serving): without this, the next
        recovery would replay the request AND the fallback would serve
        it — twice the work, twice the stream."""
        if self._journal is not None and req.prejournaled:
            self._journal.retire(req.index, "cancelled")
            self._journal.sync(force=True)

    def _journal_admit(self, req: Request) -> None:
        """The one admit-record append (submit/prejournal share it)."""
        self._journal.admit(
            req.index, req.tokens, steps=req.steps,
            temperature=(req.temperature if req.temperature is not None
                         else self.temperature),
            topp=req.topp if req.topp is not None else self.topp,
            seed=(req.seed if req.seed is not None
                  else self.seed + req.index),
            slo=req.slo_class, cursor=req.coin_cursor,
            recovers=req.recovered_from,
            trace=(req.trace.to_header() if req.trace is not None
                   else None),
            ledger=req.carried_cost)

    def _trace_admit(self, req: Request) -> None:
        """Trace bookkeeping at the one request entry point (ISSUE 15):
        mint a root context for requests that arrived without one (the
        server minted at HTTP ingress; offline/test paths mint here),
        and materialize a continuation LINK span — zero-duration, cat
        'link' — when this life crossed a seam (recovers/handoff), so
        the joined timeline shows WHERE the trace changed processes."""
        if req.trace is None:
            req.trace = tracectx.mint()
        if self._spans is not None and req.trace.link:
            self._spans.add(req.trace.link, "link", time.perf_counter(),
                            0.0, index=req.index,
                            **tracectx.span_fields(req.trace))

    def _bill_class(self, name: str | None) -> str:
        """The accounting class for a request: None resolves through the
        SLO policy's default class (so ``cost_by_class`` joins the
        ``slo`` block 1:1 — an unlabeled request must not bill under a
        phantom "default" row while its verdict lands on "interactive");
        the literal "default" only exists when no policy is configured."""
        if self._slo is not None:
            return name or self._slo.policy.default_class
        return name or "default"

    def submit(self, req: Request) -> Request:
        """Queue a request (thread-safe; HTTP handler threads call this while
        the scheduler thread steps). ``req.done`` fires when it retires."""
        if not req.tokens:
            raise ValueError("request has no prompt tokens")
        if req.prejournaled:
            self._trace_admit(req)
            if req.ledger is None:
                req.ledger = self._book.open_request(
                    req.index, self._bill_class(req.slo_class),
                    carried=req.carried_cost)
            # index + admit record already durable (prejournal): queue
            with self._lock:
                self._queue.append(req)
                if self._obs is not None:
                    self._obs.set_queue_depth(len(self._queue))
            return req
        req.t_enqueue = time.monotonic()
        with self._lock:
            req.index = self._submitted
            self._submitted += 1
        # open the cost ledger at the id assignment (ISSUE 16): every
        # charge from here to the terminal close lands on this handle; a
        # recovered/handed-off life seeds its previous bill as `carried`
        req.ledger = self._book.open_request(req.index,
                                             self._bill_class(req.slo_class),
                                             carried=req.carried_cost)
        self._trace_admit(req)  # before the journal admit: the durable
        #                         record carries the trace identity
        if self._journal is not None:
            # write-AHEAD means ahead of the SCHEDULER ever seeing the
            # request: the admit record (with the RESOLVED sampler config
            # — the engine-default seed is `seed + index`, which a
            # restarted process would re-derive differently) must be
            # journaled before the queue insert below, or a fast
            # scheduler could sample a token for an id the journal has
            # never admitted. Outside the engine lock: fsync=always
            # blocks on disk here, and the id counter above already
            # reserved our index.
            self._journal_admit(req)
        with self._lock:
            self._queue.append(req)
            if self._obs is not None:
                self._obs.set_queue_depth(len(self._queue))
        return req

    def cancel(self, req: Request) -> None:
        """Cancel a request NOW, from any thread (the server's
        mid-stream-disconnect path). A still-queued request is removed
        and completed immediately; an in-flight one is marked and the
        scheduler's pre-dispatch sweep (_sweep_cancelled) retires it —
        freeing its slot AND its KV pages — before the next chain
        launches, instead of letting a long fused chain decode its whole
        span for a consumer that is gone."""
        req.on_token = None
        req.cancelled = True
        with self._lock:
            if req in self._queue:
                self._queue.remove(req)
                if self._obs is not None:
                    self._obs.set_queue_depth(len(self._queue))
            else:
                return  # in flight (or already done): the sweep owns it
        if self._journal is not None:
            self._journal.retire(req.index, "cancelled")
        if self._obs is not None:
            self._obs.cancelled.inc()
        self._close_ledger(req.index, "cancelled")
        req.done.set()

    def recover(self, quiet: bool = True) -> int:
        """Re-admit every incomplete journaled request (crash recovery,
        ISSUE 9). Each entry re-enters through the NORMAL submit path as a
        fresh request whose prompt is the original prompt PLUS the tokens
        already sampled in the previous life: they ride the forced-token
        window (the PR 7 prompt-chunking path), so prefill re-derives
        their KV — mostly through the radix tree once siblings re-admit —
        and the sampler fast-forwards to the journaled coin cursor
        (_admit), making the continued stream BITWISE the uninterrupted
        run's. The new admit record carries ``recovers=<old rid>``, so
        ONE atomic append opens the new life and retires the old — a
        crash at any point (mid-recovery included) replays exactly one
        live entry per request. Returns the number of requests
        re-admitted."""
        journal = self._journal
        if journal is None:
            raise ValueError("recover() needs a journal (construct the "
                             "engine with journal=...): the atomic "
                             "old-life handoff must land in the journal "
                             "new records are written to")
        # config guard (PR 10): a journal recorded under different model
        # dims / quant types / tp scheme / seed policy / weights would
        # replay bitwise-DETERMINISTIC but bitwise-WRONG streams — refuse
        # before re-admitting anything (JournalConfigMismatch; legacy
        # headers without a fingerprint recover unchecked). With NOTHING
        # live there is nothing a config change could corrupt: adopt the
        # serving config instead of stranding the deployment on an
        # upgrade (e.g. a tp-scheme switch over a fully-retired journal).
        entries = journal.incomplete()
        if entries:
            journal.check_config()
        else:
            journal.adopt_config()
        for e in entries:
            trace = None
            if e.trace:
                try:
                    # continue the SAME trace: new span parented on the
                    # journaled one, linked 'recovers' (ISSUE 15)
                    trace = tracectx.from_header(
                        e.trace, link=tracectx.LINK_RECOVERS)
                except ValueError:
                    trace = None  # a damaged header never blocks recovery
            req = Request(tokens=e.replay_tokens, steps=e.steps,
                          temperature=e.temperature, topp=e.topp,
                          seed=e.seed, slo_class=e.slo,
                          coin_cursor=e.cursor, recovered_from=e.rid,
                          trace=trace, carried_cost=e.ledger)
            self.submit(req)
            if self._obs is not None:
                self._obs.recoveries.inc()
            if not quiet:
                print(f"[recover] request {e.rid} -> {req.index}: "
                      f"{len(e.tokens)} prompt + {len(e.sampled)} sampled "
                      f"tokens, coin cursor {e.cursor}")
        journal.sync(force=True)
        return len(entries)

    def suspend(self, message: str = "draining: request journaled for "
                                     "recovery") -> int:
        """Graceful-drain wrap-up (runtime/server.py SIGTERM path): give
        up on every still-outstanding request WITHOUT retiring it in the
        journal — their admit + token records stay live, so the next
        process recovers them with recover(). Waiters wake with ``error``
        set (the stream handler ends the response; the client retries or
        reconnects after restart). Requires a journal: suspending without
        one would silently drop work — that is fail_all's job, and it
        says "failed". Returns the number of requests left journaled."""
        if self._journal is None:
            raise ValueError("suspend() without a journal would drop "
                             "in-flight work on the floor; use fail_all")
        n = self._n_outstanding()
        self._suspending = True
        try:
            self.fail_all(message)
        finally:
            self._suspending = False
        self._journal.sync(force=True)
        return n

    def ingest_remote(self, tokens, planes, req: Request) -> None:
        """Thread-safe DCN handoff intake (ISSUE 14, decode pool): queue
        shipped page payloads plus the re-admission request for the
        scheduler thread to adopt at its next iteration. ``planes`` is
        the CRC-verified plane tuples in full-prompt-page window order
        (None entries mark pages that never arrived — adoption stops at
        the gap and prefill re-derives)."""
        if self._alloc is None or not self._alloc.remote:
            raise ValueError("ingest_remote needs a remote_pages=True "
                             "paged engine (the decode pool role)")
        with self._lock:
            self._remote_inbox.append((tokens, planes, req))

    def export_prefix_sync(self, tokens, timeout: float = 30.0) -> list:
        """Thread-safe prefill-pool page export (ISSUE 14): ask the
        scheduler thread for the wire payloads of the tree-held full
        prompt pages of ``tokens`` and wait for the answer (the server's
        POST /prefill handler calls this — it must never walk the tree
        itself). [] when nothing is shared (or the scheduler never
        answered inside ``timeout``) — the handoff then ships nothing
        and the decode pool re-derives via prefill."""
        box = {"ev": threading.Event(), "planes": None}
        with self._lock:
            self._export_inbox.append((list(tokens), box))
        box["ev"].wait(timeout)
        return box["planes"] or []

    def _drain_remote_inbox(self) -> None:
        """Scheduler-thread half of ingest_remote/export_prefix_sync:
        adopt shipped pages into the radix tree (promotion-pending) and
        submit their requests so admission finds the prefix already
        published; fulfil pending page exports from the tree."""
        with self._lock:
            if not (self._remote_inbox or self._export_inbox):
                return
            items, self._remote_inbox = self._remote_inbox, []
            exports, self._export_inbox = self._export_inbox, []
        for tokens, planes, req in items:
            self._alloc.adopt_remote_pages(tokens, planes)
            self.submit(req)
        if exports:
            from .disagg import export_prefix_pages

            for tokens, box in exports:
                try:
                    box["planes"] = export_prefix_pages(self, tokens)
                finally:
                    box["ev"].set()

    def _sweep_cancelled(self) -> None:
        """Retire every cancelled in-flight request BEFORE the next
        dispatch (scheduler thread only): pages and slots free at the
        sweep, not after another full chain. The post-dispatch checks in
        the step paths still catch cancellations that land mid-chain."""
        for s in self._pool:
            if not s.free and s.req.cancelled:
                self._retire(s, quiet=True)

    def _n_outstanding(self) -> int:
        """Active slots + queued requests — the step functions' return
        value. Counting the QUEUE matters when admission could not place
        anything (dry pool / injected starvation) while the pool sits
        empty: a bare active count would read 0 and the caller's drive
        loop (run(), the server scheduler) would stop with work still
        waiting."""
        with self._lock:
            queued = len(self._queue) + len(self._remote_inbox)
        return sum(not s.free for s in self._pool) + queued

    def step_once(self, quiet: bool = True) -> int:
        """Admit queued requests, run ONE device step over the pool, and
        retire finished rows. Returns the number of active slots after the
        step (0 = idle: nothing queued, nothing in flight). Must be called
        from a single scheduler thread; submit() may race freely."""
        jnp = self.jnp
        self._drain_remote_inbox()
        self._sweep_cancelled()
        self._admit()
        self._settle_promotions(quiet)
        self._resume_prefills()
        pool = self._pool
        paused = (self._grow_pages(pool, 1, quiet)
                  if self._alloc is not None else ())
        if all(s.free for s in pool):
            self._journal_sync()  # cover sweep/admit records this iteration
            return self._n_outstanding()
        # paused (page-starved) rows make no progress this step — exclude
        # them from occupancy exactly as step_many's active mask does
        active0 = sum(not s.free and b not in paused
                      for b, s in enumerate(pool))
        t0 = time.monotonic()  # census/ledger wall charges need it even
        #                        when the engine runs metrics-dark
        st = self._stage_i32
        for b, s in enumerate(pool):
            st[0, b] = s.token
            st[1, b] = s.pos
        with self._span("step", "decode", active=active0), self._watch():
            if self._chaos is not None:
                self._chaos.on_dispatch()  # inside the armed window (the
                #   injected stall IS the hang the watchdog must detect)
            # one staged upload; the row splits are lazy device-side
            # slices, so the shared step program keeps its (tokens, pos)
            # signature
            staged = jnp.asarray(st[:2])
            if self._alloc is not None:
                logits, self.cache = self._step(
                    self.params, self.cache, staged[0], staged[1],
                    self._stage_tables())
            else:
                logits, self.cache = self._step(self.params, self.cache,
                                                staged[0], staged[1])
            logits = np.asarray(logits)  # dlint: allow[D001] host sampler needs logits
            if self._obs is not None:
                # np.asarray synced the logits; the sync flag also drains
                # the donated cache write (obs/trace.sync_device_timing)
                if self._obs.sync:
                    import jax

                    jax.block_until_ready(self.cache)  # dlint: allow[D001] opt-in timing drain
                self._obs.record_step(time.monotonic() - t0, active0)
                if self._alloc is not None:
                    self._obs.kv_pages_free.set(self._alloc.n_free)
        self.stats.steps += 1
        self.stats.sum_active += active0
        self.stats.max_active = max(self.stats.max_active, active0)
        self._census_dispatch("decode", 1, paused, active0,
                              time.monotonic() - t0)
        for i, s in enumerate(pool):
            if s.free:
                continue
            if s.req.cancelled:  # consumer gone: free the slot now
                self._retire(s, quiet)
                continue
            if i in paused:  # starved of pages: frozen, retries next step
                continue
            if s.forced:
                nxt = s.forced.pop(0)
                self._advance(s, nxt, quiet)
            else:
                nxt = int(s.sampler.sample(logits[i]))
                self._advance(s, nxt, quiet, sampled=True)
        self._admit()
        self._journal_sync()
        return self._n_outstanding()

    def _advance(self, s: _Slot, nxt: int, quiet: bool,
                 sampled: bool = False) -> bool:
        """Apply one decode outcome to a slot — the per-token bookkeeping
        (position clock, BOS stop, output append/notify/count, budget stop)
        shared by step_once and step_many's replay so the two paths cannot
        drift. ``sampled`` marks a token the sampler produced (vs forced
        prompt replay) — the TTFT anchor. Returns True when the slot
        retired."""
        s.pos += 1
        if sampled:
            s.req.n_sampled += 1
            if not s.req.t_first_token:
                s.req.t_first_token = time.monotonic()
        if nxt == BOS:  # reference stop: BOS before decoding it
            self._retire(s, quiet)
            return True
        s.req.out.append(nxt)
        if sampled and self._journal is not None:
            # journal SAMPLED tokens only (forced echoes re-derive from
            # the admit record) with the cumulative coin cursor — the
            # sampler drew its coins before _advance ran, so rng.draws is
            # already the post-token cursor (speculative accept/resample
            # double-draws included)
            self._journal.token(s.req.index, nxt, s.sampler.rng.draws)
        self._notify(s.req, nxt)
        self.stats.tokens += 1
        self._census.count_tokens("decode")
        if s.req.ledger is not None:
            s.req.ledger.charge_tokens()
        if self._obs is not None:
            self._obs.generated.inc()
            self._obs.count_dispatch_tokens("decode")
        s.token = nxt
        if s.pos >= s.budget:
            self._retire(s, quiet)
            return True
        return False

    def _pop_request(self) -> Request | None:
        """Next live queued request (cancelled-before-admission ones are
        completed and skipped), or None when the queue is empty. With
        slo_priority, the best-ranked SLO class pops first (FIFO within a
        class — stable, so batch work still drains in order)."""
        while True:
            with self._lock:
                if not self._queue:
                    return None
                at = 0
                if self._prio is not None and len(self._queue) > 1:
                    rank = self._prio
                    at = min(range(len(self._queue)),
                             key=lambda i: (rank(self._queue[i].slo_class),
                                            i))
                req = self._queue.pop(at)
                if self._obs is not None:
                    self._obs.set_queue_depth(len(self._queue))
            if not req.cancelled:
                return req
            if self._journal is not None:
                self._journal.retire(req.index, "cancelled")
            self._close_ledger(req.index, "cancelled")
            req.done.set()  # consumer gone before admission

    def _requeue_front(self, s: _Slot) -> None:
        """Undo an admission the page pool could not serve: release any
        shared-prefix refs, park the slot free, and put the request back at
        the HEAD of the queue (FCFS — later smaller requests do not jump
        a starved one; preemption is the ROADMAP item-4 follow-up)."""
        req = s.req
        self._alloc.release_pages(s.pages)
        s.pages, s.shared, s.await_promo = [], 0, False
        s.prefill_pending = False
        s.req, s.pos, s.token, s.forced, s.sampler = None, 0, 0, [], None
        req.t_admit = 0.0
        self.stats.requeues += 1
        if self._obs is not None:
            self._obs.reject("pool_dry")
        with self._lock:
            self._queue.insert(0, req)
            if self._obs is not None:
                self._obs.set_queue_depth(len(self._queue))

    def _admit_paged(self, s: _Slot) -> str:
        """Paged admission: walk the radix tree for a shared page-aligned
        prompt prefix (copy-free: the slot's table maps the SAME physical
        pages, refcounted), then allocate fresh pages covering the rest of
        the prompt. Returns 'ok' or 'dry' (pool exhausted — requeue).

        A shared prefix of m positions parks the row at pos m with exactly
        the forced-echo bookkeeping the prefill path uses: the prompt
        tokens it skips still land in ``out`` (output meaning is
        toggle-invariant) and only tokens[m:] remain to process. The same
        gates as admission prefill apply (short prompts, budget overruns,
        mid-stream BOS) — sharing must never change a request's stream.
        """
        req = s.req
        tokens = req.tokens
        # the pool itself bounds a request's positions, exactly like the
        # seq_len clamp above: a 3-page pool can hold 3 pages of history,
        # so the budget caps there instead of letting the deadlock breaker
        # kill the request mid-stream at the pool edge
        s.budget = min(s.budget, self._alloc.n_pages * self.page_size)
        n_pre = len(tokens) - 1
        attempted = (self._alloc.prefix_share and n_pre >= 2
                     and n_pre < s.budget and BOS not in tokens[1:])
        if attempted:
            s.pages = self._alloc.match_prefix(tokens[:n_pre])
            s.shared = len(s.pages)
        if not self._ensure_pages(s, min(len(tokens), s.budget)):
            return "dry"
        if attempted:
            # counted only now that the admission sticks — a dry-pool
            # requeue above re-matches on every retry and must not inflate
            # the hit/saved figures (they are pinned equal to the
            # Prometheus series by tests/test_obs.py)
            self._alloc.record_admission(s.shared)
        m = s.shared * self.page_size
        if m:
            s.pos = m
            s.token = tokens[m]
            s.forced = list(tokens[m + 1:])
            req.out.extend(tokens[1:m + 1])
            for t in tokens[1:m + 1]:
                self._notify(req, t)
            self.stats.tokens += m
            # the shared-prefix echo is prefill-kind work: positions the
            # radix tree covered instead of a forward pass
            self._census.count_tokens("prefill", m)
            if req.ledger is not None:
                req.ledger.charge_tokens(m)
                req.ledger.charge_prefill(0, m, 0.0)
            if self._obs is not None:
                self._obs.generated.inc(m)
                self._obs.prefix_hits.inc()
                self._obs.prefill_saved.inc(m)
                self._obs.count_dispatch_tokens("prefill", m)
        return "ok"

    def _admit(self):
        spec = self.spec
        for slot_index, s in enumerate(self._pool):
            while s.free:
                req = self._pop_request()
                if req is None:
                    return
                req.t_admit = time.monotonic()
                s.req, s.pos = req, 0
                s.token = req.tokens[0]
                s.forced = list(req.tokens[1:])
                s.budget = min(req.steps, spec.seq_len)
                temp = (req.temperature if req.temperature is not None
                        else self.temperature)
                topp = req.topp if req.topp is not None else self.topp
                seed = (req.seed if req.seed is not None
                        else self.seed + req.index)
                s.sampler = Sampler(spec.vocab_size, temp, topp, seed,
                                    use_native=self.use_native_sampler)
                if req.coin_cursor:
                    # journal recovery: fast-forward the xorshift stream
                    # past the coins a previous life already consumed —
                    # the already-sampled tokens ride the forced window
                    # (no draws), so the first NEW sample uses exactly
                    # the coin the uninterrupted run would have
                    s.sampler.rng.skip(req.coin_cursor)
                if self._alloc is not None:
                    if self._admit_paged(s) == "dry":
                        self._requeue_front(s)
                        return
                    if self._alloc.pending_capable \
                            and self._alloc.slot_pending(s.pages):
                        # shared prefix promoting from host/disk (or
                        # riding a DCN handoff upload): defer
                        # admission prefill until the upload lands
                        # (_settle_promotions) — gathering now would
                        # read junk where the payload hasn't arrived
                        s.await_promo = True
                        break
                self._maybe_prefill_slot(slot_index, s)
                if s.req.cancelled:
                    # consumer vanished during admission/prefill: free the
                    # slot AND its pages NOW — a cancelled prefill must not
                    # pin pool pages until the next chain boundary
                    self._retire(s, quiet=True)
                    continue
                break  # slot filled

    def _maybe_prefill_slot(self, slot_index: int, s: _Slot):
        """Admission prefill: fill the slot's cache rows for the prompt
        prefix in T=chunk single-sequence passes (Engine.prefill's scheme:
        fixed chunks, pad-safe, junk-invisible) and park the slot at the
        last prompt token — long prompts stop crawling through per-token
        steps. On sharded engines the scratch cache and forward are the
        sharded single-sequence ones (same S/kv sharding axes as the
        batched cache, so the insert is pure per-shard work). Same gates
        as generate._prefill_prefix: off for short prompts, prompts that
        exceed the budget (the forced-echo output is load-bearing), or a
        mid-stream BOS (only the step loop reproduces that early stop)."""
        chunk = self.prefill_chunk
        tokens = s.req.tokens
        n_pre = len(tokens) - 1
        start = s.pos  # 0, the page-aligned prefix-share boundary, or a
        #                preemption park point (s.prefill_pending resume)
        if self.dispatch_tokens:
            # token-budget mode (ISSUE 18): the prompt rides mixed
            # dispatches as the per-dispatch prefill slice (step_mixed) —
            # no separate chunk dispatches, no parked-slot bookkeeping
            s.prefill_pending = False
            return
        if (getattr(self, "_prefill_fwd", None) is None or chunk <= 1
                or n_pre - start < 2 or n_pre >= s.budget
                or BOS in tokens[1:]):
            s.prefill_pending = False
            return
        from .generate import run_chunked_prefill

        t0 = time.monotonic()  # census/ledger wall charges need it even
        #                        when the engine runs metrics-dark
        chunks0 = self.stats.prefill_chunks
        jnp = self.jnp
        paged = self._alloc is not None
        # chunk-boundary preemption (ISSUE 14): paged f32 pools only —
        # the contiguous path's fresh scratch cache cannot resume
        # mid-prompt, and a q8 pool quantizes at every scatter, so a
        # resumed prompt would attend over DEQUANTIZED earlier positions
        # where the single-pass run attends f32: accumulated rounding
        # breaks the bitwise single-pool contract. q8 pools keep the
        # SLO-priority admission order; they just never park mid-prompt.
        hold = (self.prefill_hold
                if paged and self.kv_quant == "f32" else None)
        end = n_pre
        with self._span("prefill", "prefill", slot=slot_index,
                        tokens=n_pre - start,
                        **tracectx.span_fields(s.req.trace)):
            if paged:
                # seed a virtual contiguous sequence cache from the slot's
                # pages: the unshared-suffix chunks attend over the shared
                # prefix k/v, positions start.. are written before any
                # later chunk reads them, and the scatter puts everything
                # back in place (shared pages get byte-identical content)
                from .paging import SCRAP_PAGE

                tbl = np.full((self._max_pages,), SCRAP_PAGE, np.int32)
                tbl[:len(s.pages)] = s.pages
                tbl_dev = jnp.asarray(tbl)
                cache_box = [self._gather_pages(self.cache, tbl_dev)]
            else:
                cache_box = [self._scratch_cache()]

            def fwd(part, start_pos):
                self.stats.prefill_chunks += 1
                _, cache_box[0] = self._prefill_fwd(
                    self.params, cache_box[0], jnp.asarray(part, jnp.int32),
                    jnp.int32(start_pos))

            if hold is None:
                run_chunked_prefill(fwd, tokens[start:n_pre], start, chunk,
                                    self.spec.seq_len)
            else:
                # the same window schedule, one chunk at a time, yielding
                # at PAGE-ALIGNED chunk boundaries when hold(s) says a
                # higher-priority arrival should prefill first. Page
                # alignment is load-bearing for q8 pools: a park inside a
                # page would re-quantize that page's earlier positions on
                # resume (quantize∘dequantize moves bytes)
                lo = start
                while lo < n_pre:
                    hi = min(lo + chunk, n_pre)
                    run_chunked_prefill(fwd, tokens[lo:hi], lo, chunk,
                                        self.spec.seq_len)
                    lo = hi
                    if (lo < n_pre and lo % self.page_size == 0
                            and hold(s)):
                        end = lo
                        break
            if paged:
                if self.kv_quant == "q8":
                    # q8 scatter must NOT re-quantize pages whose bytes
                    # were published by an EARLIER encode (quantize∘
                    # dequantize moves bytes): the shared prefix keeps
                    # its first publisher's encoding, and a preemption
                    # resume keeps the pages its previous rounds already
                    # wrote — their scatter entries park on the scrap
                    # page. The gather above still reads them: suffix
                    # chunks attend over the dequantized prefix.
                    tbl_sc = tbl.copy()
                    tbl_sc[:max(s.shared, start // self.page_size)] = \
                        SCRAP_PAGE
                    tbl_scatter = jnp.asarray(tbl_sc)
                else:
                    tbl_scatter = tbl_dev
                self.cache = self._scatter_pages(self.cache, cache_box[0],
                                                 tbl_scatter)
                # publish the freshly prefilled full prompt pages NOW (not
                # just at retire): a same-system-prompt request admitted
                # into the next slot this very round already shares them
                self._alloc.insert_prefix(tokens[:end], s.pages)
            else:
                self.cache = self._insert(self.cache, cache_box[0],
                                          jnp.int32(slot_index))
        # echo the prefilled prompt tokens into the output AND the token
        # count (the step loop both appends forced tokens and counts them —
        # "Generated tokens" must not change meaning with the toggle)
        s.req.out.extend(tokens[start + 1:end + 1])
        for t in tokens[start + 1:end + 1]:
            self._notify(s.req, t)
        dt_prefill = time.monotonic() - t0
        self.stats.tokens += end - start
        # prefill census record: steps=0 so the step/stall/page-step
        # conservation totals (decode/spec currency) are untouched — the
        # record documents the dispatch's token composition only
        self._census.count_tokens("prefill", end - start)
        self._census.record("prefill", 0, 0, {}, 0, 0,
                            prefill_tokens=end - start)
        if s.req.ledger is not None:
            s.req.ledger.charge_tokens(end - start)
            s.req.ledger.charge_prefill(
                self.stats.prefill_chunks - chunks0, end - start,
                dt_prefill)
        if self._obs is not None:
            self._obs.generated.inc(end - start)
            self._obs.prefill.observe(dt_prefill)
            self._obs.count_dispatch_tokens("prefill", end - start)
        s.pos = end
        s.token = tokens[end]
        s.forced = list(tokens[end + 1:]) if end < n_pre else []
        s.prefill_pending = end < n_pre

    def _resume_prefills(self) -> None:
        """Continue chunk-preempted admission prefills (ISSUE 14): every
        slot parked at a page-aligned boundary re-enters
        _maybe_prefill_slot — which may park it again if the hold still
        fires — so a preempted batch prompt keeps making chunk progress
        instead of crawling through per-token forced steps."""
        for b, s in enumerate(self._pool):
            if s.free or not s.prefill_pending or s.await_promo \
                    or s.req.cancelled:
                continue
            self._maybe_prefill_slot(b, s)

    @staticmethod
    def _notify(req: Request, token: int):
        """Streaming hook dispatch — exceptions must never reach the
        scheduler loop (a broken client is that client's problem)."""
        if req.on_token is not None:
            try:
                req.on_token(token)
            except Exception:
                req.on_token = None  # stop notifying a broken consumer

    def _retire(self, s: _Slot, quiet: bool):
        if not quiet:
            print(f"[{s.req.index}] done: {len(s.req.out)} tokens "
                  f"(pos {s.pos}/{s.budget})")
        if self._alloc is not None and s.pages:
            # publish the request's FULL prompt pages into the radix tree
            # (positions 0..pos-1 hold prompt k/v up to min(pos, prompt));
            # cancelled/failed requests publish nothing. Then drop this
            # slot's refs — tree-held pages survive for prefix reuse until
            # LRU eviction reclaims them.
            if s.req.error is None and not s.req.cancelled:
                n_ins = min(s.pos, len(s.req.tokens))
                self._alloc.insert_prefix(s.req.tokens[:n_ins], s.pages)
            elif self._chaos is not None and s.req.cancelled:
                # chaos mutation arm (leak_on_cancel): deliberately drop a
                # page from the release so the drill audit must flag it
                s.pages = self._chaos.filter_release(s.pages)
            self._alloc.release_pages(s.pages)
            s.pages, s.shared, s.await_promo = [], 0, False
            if self._obs is not None:
                self._obs.kv_pages_free.set(self._alloc.n_free)
                self._update_tier_obs()
        s.prefill_pending = False
        s.req.t_finish = time.monotonic()
        if self._journal is not None and not self._suspending:
            # a drain-suspended request writes NO retirement: its admit +
            # token records stay live, so the next process recovers it
            self._journal.retire(
                s.req.index,
                "cancelled" if s.req.cancelled
                else "failed" if s.req.error is not None else "done")
        if self._obs is not None:
            self._obs.record_retire(s.req, s.req.t_finish)
        if self._slo is not None:
            # verdict at retire (obs/slo.py): met/violated from the wall
            # lifecycle stamps, failed on engine error; cancelled
            # requests record nothing (client-side, not a serving SLO)
            self._slo.observe_request(s.req, s.req.t_finish)
        if self._spans is not None and s.req.t_admit:
            # request lifecycle timestamps are time.monotonic; re-anchor the
            # admit→finish window onto the tracer's perf_counter timeline
            # (the two clocks share a rate, not necessarily an epoch)
            dur = s.req.t_finish - s.req.t_admit
            start = time.perf_counter() - (time.monotonic() - s.req.t_admit)
            self._spans.add("request", "request", start, dur,
                            index=s.req.index, tokens=len(s.req.out),
                            sampled=s.req.n_sampled,
                            cancelled=s.req.cancelled,
                            **tracectx.span_fields(s.req.trace))
        self._close_ledger(
            s.req.index,
            "cancelled" if s.req.cancelled
            else "failed" if s.req.error is not None else "done")
        s.req.done.set()
        s.req = None
        # park the freed slot at pos 0: a retired row's clock can equal
        # seq_len, and feeding that to the flash kernel would DMA one
        # chunk past the end of the cache row (free slots still ride
        # through the fixed-B step; their writes at pos 0 are dead until
        # the slot is re-admitted, which restarts at pos 0 anyway)
        s.pos, s.token = 0, 0

    def fail_all(self, message: str):
        """Fail every queued and in-flight request (scheduler error path —
        runtime/server.py): sets ``error`` then ``done`` so waiters wake."""
        with self._lock:
            pending = self._queue
            self._queue = []
            if self._obs is not None:
                self._obs.set_queue_depth(0)
        for req in pending:
            req.error = message
            if self._journal is not None and not self._suspending:
                self._journal.retire(req.index, "failed")
            if self._obs is not None:
                self._obs.failed.inc()
            if self._slo is not None:
                # never admitted, but attempted: a failed attempt in its
                # class (queue-killed work is an SLO event)
                self._slo.observe(req.slo_class, None, None, 0,
                                  failed=True)
            self._close_ledger(req.index, "failed")
            req.done.set()
        for s in self._pool:
            if not s.free:
                s.req.error = message
                self._retire(s, quiet=True)
        if self._alloc is not None:
            # tear the radix tree down with the rest of the engine state:
            # a post-fault serving loop restarts from an empty, fully-free
            # pool instead of silently inheriting published prefixes
            self._alloc.tree.clear()
            if self._obs is not None:
                self._obs.kv_pages_free.set(self._alloc.n_free)

    def run(self, requests: list[list[int]], steps: int,
            quiet: bool = True) -> tuple[list[list[int]], ContinuousStats]:
        """Offline entry: decode every request (a non-empty prompt token
        list, BOS included) to BOS or ``steps`` positions; returns outputs
        in request order."""
        for i, r in enumerate(requests):
            if not r:
                raise ValueError(f"request {i} has no prompt tokens")
        self.stats = ContinuousStats()
        with self._lock:
            # per-run request indices: request i samples from seed + i, so a
            # re-used engine reproduces the same streams run after run (the
            # solo-parity contract in the module docstring); the counter
            # keeps advancing monotonically in online mode (server) and
            # whenever a journal is bound — resetting would alias new
            # journal records onto already-journaled request ids
            if self._journal is None:
                self._submitted = 0
        reqs = [self.submit(Request(tokens=list(r), steps=steps))
                for r in requests]
        t0 = time.perf_counter()
        while self.step_many(self.block_steps, quiet=quiet):
            pass
        self.stats.total_ms = (time.perf_counter() - t0) * 1000
        assert all(r.done.is_set() for r in reqs)
        return [r.out for r in reqs], self.stats


def decode_stream(tokenizer, first_token: int, tokens: list[int]) -> str:
    """Decode a generated token stream to text, chaining decode_piece's
    prev-token context from the prompt's first token — the ONE decode loop
    shared by the CLI row printer and the HTTP server."""
    prev, text = first_token, b""
    for t in tokens:
        text += tokenizer.decode_piece(prev, t)
        prev = t
    return text.decode("utf-8", errors="replace")


def generate_continuous(spec: TransformerSpec, params: dict[str, Any],
                        tokenizer, prompts: list[str], steps: int,
                        temperature: float, topp: float, seed: int,
                        slots: int = 0, cache_dtype=None, mesh=None,
                        prefill_chunk: int = 0, block_steps: int = 1,
                        quiet: bool = False, use_native_sampler: bool = True,
                        fast_prefill: bool = False, metrics=None,
                        page_size: int = 0, kv_pages: int = 0,
                        spec_k: int = 0, spec_ngram: int = 3,
                        dispatch_tokens: int = 0,
                        kv_quant: str = "f32", kv_host_pages: int = 0,
                        kv_disk_dir: str | None = None,
                        kv_disk_bytes: int = 0):
    """CLI entry: encode prompts, stream them through a slot pool, print
    rows in the --prompts-file format ("[i] 'text'")."""
    reqs = [tokenizer.encode(p or "", bos=True, eos=False) for p in prompts]
    slots = slots or min(len(reqs), 8)
    eng = ContinuousEngine(spec, params, slots, temperature, topp, seed,
                           cache_dtype=cache_dtype, mesh=mesh,
                           prefill_chunk=prefill_chunk,
                           block_steps=block_steps,
                           use_native_sampler=use_native_sampler,
                           fast_prefill=fast_prefill, metrics=metrics,
                           page_size=page_size, kv_pages=kv_pages,
                           spec_k=spec_k, spec_ngram=spec_ngram,
                           dispatch_tokens=dispatch_tokens,
                           kv_quant=kv_quant, kv_host_pages=kv_host_pages,
                           kv_disk_dir=kv_disk_dir,
                           kv_disk_bytes=kv_disk_bytes)
    outs, stats = eng.run(reqs, steps, quiet=quiet)
    for b, (req, row) in enumerate(zip(reqs, outs)):
        if not quiet:
            print(f"[{b}] {decode_stream(tokenizer, req[0], row)!r}")
    if not quiet:
        print(f"Generated tokens:    {stats.tokens} across {len(reqs)} "
              f"requests ({slots} slots, {stats.steps} steps)")
        print(f"Avg generation time: "
              f"{stats.total_ms / max(1, stats.tokens):.2f} ms/token "
              f"({stats.tokens_per_s:.1f} tok/s)")
        if eng.allocator is not None:
            a = eng.allocator
            print(f"Paged KV:            {a.n_pages} pages x "
                  f"{a.page_size} positions ({eng.kv_quant}), "
                  f"{a.n_free} free; prefix hit "
                  f"rate {a.hit_rate:.0%}, {a.tokens_saved} prefill "
                  f"tokens saved, {a.evictions} evictions")
            if a.tiered:
                counts = a.tier_page_counts()
                saved = a.tokens_saved_by_tier
                print(f"KV tiers:            hbm {counts['hbm']} / host "
                      f"{counts['host']} / disk {counts['disk']} pages; "
                      f"{sum(a.demotions.values())} demotions, "
                      f"{sum(a.promotions.values())} promotions; "
                      f"{saved['host'] + saved['disk']} prefill tokens "
                      f"rescued from spilled tiers")
        if eng.dispatch_tokens:
            print(f"Token budget:        {eng.dispatch_tokens} "
                  f"tokens/dispatch over {stats.steps} mixed dispatches")
        if eng.spec_k:
            print(f"Speculative:         K={eng.spec_k}, "
                  f"{stats.spec_accepted}/{stats.spec_proposed} drafts "
                  f"accepted ({stats.spec_accept_rate:.0%}); "
                  f"{stats.total_ms / max(1, stats.tokens):.2f} "
                  f"ms/accepted token over {stats.steps} verify dispatches")
    return outs, stats
