"""Continuous batching: per-slot position clocks + mid-flight admission.

The lockstep batch path (runtime/decode.make_batch_decode_loop) shares one
position clock across rows, so the batch finishes at the pace of its slowest
row and new work waits for the whole batch. This engine removes both limits —
the TPU analog of vLLM-style continuous batching, far beyond the reference's
strict batch=1 loop (tokenizer.cpp:321-394):

* a fixed pool of B cache slots, each with its OWN position clock
  (models/llama.forward_batch_ragged: per-row RoPE, per-row cache column,
  per-row attention visibility);
* a host-side scheduler that retires a row the moment it stops (BOS or step
  budget) and admits the next queued request into the freed slot at pos 0
  while the other rows keep decoding.

Prompt tokens are forced through the same decode step (one per iteration,
the reference's own prompt handling); each request samples from its own
xorshift stream seeded ``seed + request_index`` with reference Sampler
semantics, so a request's token stream is IDENTICAL to running it alone
through generate() with that seed — the scheduling is invisible in the
output (the parity gate of tests/test_continuous.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from ..io.tokenizer import BOS
from ..models.spec import TransformerSpec
from .sampling import Sampler


@dataclasses.dataclass
class _Slot:
    req: int = -1            # request index, -1 = free
    pos: int = 0             # this row's position clock
    token: int = 0           # next input token
    forced: list = dataclasses.field(default_factory=list)
    out: list = dataclasses.field(default_factory=list)
    budget: int = 0          # max positions for this request
    sampler: Sampler | None = None

    @property
    def free(self) -> bool:
        return self.req < 0


@dataclasses.dataclass
class ContinuousStats:
    tokens: int = 0          # generated (emitted) tokens
    steps: int = 0           # device steps executed
    total_ms: float = 0.0
    max_active: int = 0

    @property
    def tokens_per_s(self) -> float:
        return self.tokens / max(self.total_ms / 1000, 1e-9)


class ContinuousEngine:
    """Owns the slot cache + jitted ragged step; schedules requests.

    ``slots`` bounds concurrent sequences (cache memory = slots x seq_len);
    any number of requests stream through the pool.
    """

    def __init__(self, spec: TransformerSpec, params: dict[str, Any],
                 slots: int, temperature: float, topp: float, seed: int,
                 cache_dtype=None, mesh=None):
        import functools

        import jax
        import jax.numpy as jnp

        from ..models.llama import (forward_batch_ragged, init_cache_batch,
                                    params_to_device)

        self.spec = spec
        self.slots = slots
        self.temperature = temperature
        self.topp = topp
        self.seed = seed
        self.jnp = jnp
        dtype = cache_dtype or jnp.float32
        if mesh is not None and (mesh.shape["tp"] > 1
                                 or mesh.shape.get("sp", 1) > 1):
            # tensor-parallel step: same sharded program as the lockstep
            # batch path, driven with a (B,) position vector
            from ..parallel import (make_sharded_forward_batch,
                                    shard_cache_batch, shard_params,
                                    validate_sharding)

            validate_sharding(spec, mesh)
            self.params = shard_params(params, mesh)
            self.cache = shard_cache_batch(
                init_cache_batch(spec, slots, dtype), mesh)
            self._step = make_sharded_forward_batch(spec, mesh)
        else:
            self.params = params_to_device(params)
            self.cache = init_cache_batch(spec, slots, dtype)
            self._step = jax.jit(
                functools.partial(forward_batch_ragged, spec),
                donate_argnums=1)

    def run(self, requests: list[list[int]], steps: int,
            quiet: bool = True) -> tuple[list[list[int]], ContinuousStats]:
        """Decode every request (a non-empty prompt token list, BOS included)
        to BOS or ``steps`` positions; returns outputs in request order."""
        jnp = self.jnp
        spec = self.spec
        for i, r in enumerate(requests):
            if not r:
                raise ValueError(f"request {i} has no prompt tokens")
        queue = list(range(len(requests)))
        pool = [_Slot() for _ in range(self.slots)]
        outs: list[list[int] | None] = [None] * len(requests)
        stats = ContinuousStats()
        t0 = time.perf_counter()

        def admit():
            for s in pool:
                if s.free and queue:
                    ri = queue.pop(0)
                    prompt = requests[ri]
                    s.req, s.pos = ri, 0
                    s.token = prompt[0]
                    s.forced = list(prompt[1:])
                    s.out = []
                    s.budget = min(steps, spec.seq_len)
                    s.sampler = Sampler(spec.vocab_size, self.temperature,
                                        self.topp, self.seed + ri)

        def retire(s: _Slot):
            outs[s.req] = s.out
            if not quiet:
                print(f"[{s.req}] done: {len(s.out)} tokens "
                      f"(pos {s.pos}/{s.budget})")
            s.req = -1
            # park the freed slot at pos 0: a retired row's clock can equal
            # seq_len, and feeding that to the flash kernel would DMA one
            # chunk past the end of the cache row (free slots still ride
            # through the fixed-B step; their writes at pos 0 are dead until
            # the slot is re-admitted, which restarts at pos 0 anyway)
            s.pos, s.token = 0, 0

        admit()
        while any(not s.free for s in pool):
            tokens = jnp.asarray([s.token for s in pool], jnp.int32)
            pos_vec = jnp.asarray([s.pos for s in pool], jnp.int32)
            logits, self.cache = self._step(self.params, self.cache, tokens,
                                            pos_vec)
            logits = np.asarray(logits)
            stats.steps += 1
            stats.max_active = max(stats.max_active,
                                   sum(not s.free for s in pool))
            for i, s in enumerate(pool):
                if s.free:
                    continue
                if s.forced:
                    nxt = s.forced.pop(0)
                else:
                    nxt = int(s.sampler.sample(logits[i]))
                s.pos += 1
                if nxt == BOS:  # reference stop: BOS before decoding it
                    retire(s)
                    continue
                s.out.append(nxt)
                stats.tokens += 1
                s.token = nxt
                if s.pos >= s.budget:
                    retire(s)
            admit()

        stats.total_ms = (time.perf_counter() - t0) * 1000
        assert all(o is not None for o in outs)
        return outs, stats


def generate_continuous(spec: TransformerSpec, params: dict[str, Any],
                        tokenizer, prompts: list[str], steps: int,
                        temperature: float, topp: float, seed: int,
                        slots: int = 0, cache_dtype=None, mesh=None,
                        quiet: bool = False):
    """CLI entry: encode prompts, stream them through a slot pool, print
    rows in the --prompts-file format ("[i] 'text'")."""
    reqs = [tokenizer.encode(p or "", bos=True, eos=False) for p in prompts]
    slots = slots or min(len(reqs), 8)
    eng = ContinuousEngine(spec, params, slots, temperature, topp, seed,
                           cache_dtype=cache_dtype, mesh=mesh)
    outs, stats = eng.run(reqs, steps, quiet=quiet)
    for b, (req, row) in enumerate(zip(reqs, outs)):
        if not quiet:
            prev, text = req[0], b""
            for t in row:
                text += tokenizer.decode_piece(prev, t)
                prev = t
            print(f"[{b}] {text.decode('utf-8', errors='replace')!r}")
    if not quiet:
        print(f"Generated tokens:    {stats.tokens} across {len(reqs)} "
              f"requests ({slots} slots, {stats.steps} steps)")
        print(f"Avg generation time: "
              f"{stats.total_ms / max(1, stats.tokens):.2f} ms/token "
              f"({stats.tokens_per_s:.1f} tok/s)")
    return outs, stats
