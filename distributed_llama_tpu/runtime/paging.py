"""Paged KV allocation + radix prefix sharing — the host half of paged KV.

The continuous engine's contiguous layout charges every slot a full
``seq_len`` KV stripe (analysis/memory_model.kv_cache_device_bytes), so a
12-token chat request strands >99% of its stripe and the slot count — not
compute — caps concurrency. This module manages the replacement: a fixed
pool of fixed-size pages (vLLM's PagedAttention unit, Kwon et al. 2023)
plus a prefix tree over full pages (SGLang's RadixAttention, Zheng et al.
2023) so requests sharing a system prompt map the SAME physical prefill
pages instead of recomputing them.

Everything here is host-side bookkeeping over small Python ints — the
device never sees this module. The device-visible artifacts are the page
TABLE rows (int32 physical page ids per slot, staged by the engine into
one persistent numpy buffer — dlint D004) that models/llama.
forward_batch_paged walks, and the page-pool planes it indexes.

Invariants the unit tests pin (tests/test_paging.py):

* a page's refcount = (# slots mapping it) + (1 if the tree holds it);
  it returns to the free list exactly when that count reaches zero;
* page id 0 is RESERVED as the scrap page (parked/free slot rows write
  their dead k/v there); the pool never hands it out;
* the tree only shares FULL pages (``page_size`` tokens each): a
  partially-filled tail page is private to its request, so decode writes
  never land in a shared page;
* eviction frees least-recently-used tree LEAVES whose pages no live slot
  maps — interior nodes only become evictable once their children are
  gone (a child is unreachable without its prefix chain).

Hierarchical KV tiering (ISSUE 12): at fleet scale the radix tree's
shareable working set dwarfs HBM, and dropping a cold leaf burns the exact
prefill tokens the tree exists to save. With tiering enabled the pool
becomes the TOP of a three-tier hierarchy — HBM pages ⇄ a pinned host-RAM
pool (``HostPagePool``: numpy planes in the page wire layout, f32 or Q8)
⇄ append-only disk segments (``DiskPageStore``: CRC32-sidecar'd records
via io/stream's verified-read-back machinery) — and eviction becomes
WRITE-BEHIND DEMOTION: LRU pressure moves a cold page's bytes down a
tier instead of killing it (AttentionStore/Mooncake lineage; PAPER.md's
root/worker design already treats the host as the KV home). A radix hit
on a spilled prefix starts an ASYNC PROMOTION — payload read (disk CRC-
verified), HBM target page allocated, host→device staging handed to a
background ``PageUploader`` — and the engine PAUSEs the request with the
pages-starved semantics until the upload lands at a step boundary, so
the cold-hit cost is a page upload hidden behind decode steps, not a
full prefill recompute. Tier invariants the audit pins: a page's payload
is owned by EXACTLY one tier; host/disk copies map 1:1 to tree nodes;
disk records verify against their read-back CRCs; a CRC-damaged disk
page is dropped (with its now-unreachable subtree) and silently
re-derives through prefill on the next miss.
"""

from __future__ import annotations

import dataclasses
import os

SCRAP_PAGE = 0  # physical page 0: dead-write target for parked slots

TIER_HBM = "hbm"    # payload lives in the device page pool (node.page)
TIER_HOST = "host"  # payload lives in the pinned host pool (node.host_id)
TIER_DISK = "disk"  # payload lives in a disk segment (node.disk_ref)


class PagePool:
    """Free-list + refcount accounting over physical page ids 1..n_pages.

    ``alloc`` hands out the lowest free id (deterministic schedules make
    the paged==contiguous parity tests reproducible); ``retain``/
    ``release`` move the per-page refcount, and a page re-enters the free
    list exactly at refcount zero.
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"page pool needs >= 1 page, got {n_pages}")
        self.n_pages = n_pages
        # lowest-id-first allocation order; ids 1..n_pages (0 = scrap)
        self._free = list(range(n_pages, 0, -1))
        self._ref: dict[int, int] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self) -> int | None:
        """One page at refcount 1, or None when the pool is dry (the
        caller decides whether to evict or fail the request)."""
        if not self._free:
            return None
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def retain(self, pid: int) -> None:
        if pid not in self._ref:
            raise ValueError(f"retain of unallocated page {pid}")
        self._ref[pid] += 1

    def release(self, pid: int) -> None:
        n = self._ref.get(pid)
        if n is None:
            raise ValueError(f"release of unallocated page {pid}")
        if n == 1:
            del self._ref[pid]
            self._free.append(pid)
            # keep lowest-first order without re-sorting the whole list on
            # every release: append high, pop low via sort-on-alloc would be
            # O(n log n) per step — a lazy sort only when order broke
            if len(self._free) > 1 and self._free[-1] > self._free[-2]:
                self._free.sort(reverse=True)
        else:
            self._ref[pid] = n - 1

    def refcount(self, pid: int) -> int:
        return self._ref.get(pid, 0)

    def refcounts(self) -> dict[int, int]:
        """Copy of the live refcount table — the chaos-drill audit's view
        (runtime/chaos.py); mutating the copy touches nothing."""
        return dict(self._ref)

    def free_ids(self) -> list[int]:
        """Copy of the free list (drill introspection)."""
        return list(self._free)


# the page wire codec lives in runtime/pagewire.py (ISSUE 14): the disk
# tier's on-disk records and the DCN page channel's in-flight frames are
# the SAME bytes for the same page, produced by the one shared pack —
# two private copies of this pair is exactly how wire layouts drift
from .pagewire import pack_planes as _pack_planes
from .pagewire import unpack_planes as _unpack_planes


class HostPagePool:
    """The middle tier: up to ``n_pages`` page payloads pinned in host
    RAM, with the device pool's free-list/ownership invariants — ids hand
    out lowest-first, every live id is owned by exactly one tree node,
    and free + live always covers the capacity (the tier audit pins it).
    Payloads are numpy plane tuples in the page WIRE layout (f32 planes,
    or PR 11's Q8 codes+deltas), so demote→promote round-trips are
    byte-identical by construction."""

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"host page pool needs >= 1 page, "
                             f"got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, -1, -1))  # pop() = lowest id
        self._store: dict[int, tuple] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        return len(self._store)

    def store(self, payload) -> int | None:
        """Adopt one payload; returns its host id, or None when the pool
        is full (the caller spills its LRU entry to disk, or drops)."""
        if not self._free:
            return None
        hid = self._free.pop()
        self._store[hid] = payload
        return hid

    def load(self, hid: int):
        """The payload at ``hid`` (still owned by the pool)."""
        return self._store[hid]

    def live(self, hid: int) -> bool:
        return hid in self._store

    def free(self, hid: int):
        """Release ``hid`` and return its payload (a promotion takes the
        bytes with it — exactly-one-tier ownership)."""
        payload = self._store.pop(hid)
        self._free.append(hid)
        if len(self._free) > 1 and self._free[-1] > self._free[-2]:
            self._free.sort(reverse=True)  # keep lowest-first handout
        return payload

    def live_ids(self) -> list[int]:
        return sorted(self._store)

    def audit(self) -> list[str]:
        problems = []
        if len(set(self._free)) != len(self._free):
            problems.append("host pool free list has duplicate ids")
        for hid in self._free:
            if hid in self._store:
                problems.append(f"host page {hid} is both free and live")
        if len(self._free) + len(self._store) != self.n_pages:
            problems.append(
                f"host pool accounting: {len(self._free)} free + "
                f"{len(self._store)} live != {self.n_pages} pages")
        return problems


class DiskPageStore:
    """The bottom tier: page payloads appended to segment files, each
    record CRC32'd by READ-BACK into the segment's ``.slices`` sidecar
    (io/stream.append_record_verified — the weight-cache machinery
    reused verbatim) and verified again on every load. A record that
    fails its CRC loads as None: the caller drops the page and prefill
    re-derives it — disk damage degrades to recompute, never to wrong
    bytes. ``budget_bytes`` caps LIVE bytes (0 = uncapped); fully-dead
    segments are unlinked, which bounds append-only growth."""

    SEGMENT_BYTES = 8 << 20

    def __init__(self, directory: str, budget_bytes: int = 0):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.budget_bytes = int(budget_bytes)
        self._seg_path: str | None = None
        self._seg_n = 0
        self._seg_bytes = 0
        self._seg_live: dict[str, int] = {}   # path -> live record count
        self._seg_entries: dict[str, list] = {}  # path -> sidecar ranges
        self._live: dict[tuple, int] = {}     # (path, off) -> length
        self.live_bytes = 0
        self.stores = 0
        self.loads = 0
        self.crc_failures = 0
        # the disk tier is a CACHE: a previous process's segments are
        # orphans (their index lived in that process's radix tree), so
        # they are reclaimed here — without this, every restart would
        # stack a dead budget's worth of segment files next to the live
        # one and real disk usage would creep past --kv-disk-gb
        for name in sorted(os.listdir(directory)):
            if name.startswith("kvpages-"):
                try:
                    os.unlink(os.path.join(directory, name))
                except OSError:
                    pass

    @property
    def n_live(self) -> int:
        return len(self._live)

    def has_room(self, nbytes: int) -> bool:
        return (not self.budget_bytes
                or self.live_bytes + nbytes <= self.budget_bytes)

    def _flush_sidecar(self, path: str) -> None:
        """Write the segment's accumulated record ranges to its sidecar
        (deferred from per-append — io/stream.append_record_verified
        still read-back-CRCs every record at append time; this just
        persists the entries for verified_ranges/audit)."""
        from ..io.stream import write_record_sidecar

        entries = self._seg_entries.get(path)
        if entries:
            write_record_sidecar(path, entries[-1][0] + entries[-1][1],
                                 entries)

    def _reclaim_if_dead(self, path: str) -> None:
        """Unlink a SEALED segment with zero live records (free() and the
        seal-time check both call this — a segment whose last record
        dies while it is still the write target reclaims at rotation)."""
        if path == self._seg_path or self._seg_live.get(path, 1) != 0:
            return
        from ..io.stream import _sidecar_path

        self._seg_live.pop(path, None)
        self._seg_entries.pop(path, None)
        for victim in (path, _sidecar_path(path)):
            try:
                os.unlink(victim)
            except OSError:
                pass

    def _segment(self, nbytes: int) -> str:
        if (self._seg_path is None
                or self._seg_bytes + nbytes > self.SEGMENT_BYTES):
            sealed = self._seg_path
            self._seg_n += 1
            self._seg_path = os.path.join(
                self.dir, f"kvpages-{self._seg_n:05d}.seg")
            open(self._seg_path, "wb").close()
            self._seg_bytes = 0
            self._seg_live[self._seg_path] = 0
            self._seg_entries[self._seg_path] = []
            if sealed is not None:
                self._flush_sidecar(sealed)
                self._reclaim_if_dead(sealed)
        return self._seg_path

    def store(self, payload) -> tuple | None:
        """Append one payload; returns an opaque record ref, or None when
        the live-byte budget cannot take it (the caller evicts LRU disk
        pages first, or drops)."""
        from ..io.stream import append_record_verified

        blob, metas = _pack_planes(payload)
        if not self.has_room(len(blob)):
            return None
        path = self._segment(len(blob))
        off, length, crc = append_record_verified(
            path, blob, entries=self._seg_entries[path])
        self._seg_bytes += length
        self._seg_live[path] += 1
        self._live[(path, off)] = length
        self.live_bytes += length
        self.stores += 1
        return (path, off, length, crc, metas)

    def live(self, ref) -> bool:
        return ref is not None and (ref[0], ref[1]) in self._live

    def load(self, ref):
        """The payload at ``ref``, CRC-verified — None on any damage."""
        from ..io.stream import read_record_verified

        path, off, length, crc, metas = ref
        blob = read_record_verified(path, off, length, crc)
        if blob is None:
            self.crc_failures += 1
            return None
        self.loads += 1
        return _unpack_planes(blob, metas)

    def free(self, ref) -> None:
        path, off, length = ref[0], ref[1], ref[2]
        if self._live.pop((path, off), None) is None:
            return
        self.live_bytes -= length
        self._seg_live[path] -= 1
        self._reclaim_if_dead(path)

    def live_refs(self) -> list[tuple]:
        return sorted(self._live)

    def audit(self) -> list[str]:
        """Verify every live record against its segment's read-back CRC
        sidecar (io/stream.verified_ranges) — the disk half of the
        three-tier audit."""
        from ..io.stream import verified_ranges

        problems = []
        if self._seg_path is not None:
            # the live segment's sidecar is write-deferred (store()
            # appends entries in memory): persist before verifying
            self._flush_sidecar(self._seg_path)
        by_path: dict[str, list] = {}
        for (path, off), length in self._live.items():
            by_path.setdefault(path, []).append((off, length))
        for path, records in by_path.items():
            ok = verified_ranges(path)
            ok_set = set(ok or ())
            for off, length in sorted(records):
                if (off, length) not in ok_set:
                    problems.append(
                        f"disk tier: record [{off}, {off + length}) of "
                        f"{os.path.basename(path)} fails its read-back "
                        f"CRC (or lost its sidecar entry)")
        return problems


@dataclasses.dataclass
class _PromotionJob:
    """One spilled page being raised back to HBM: ``payload`` is the host
    numpy planes, ``staged`` the device-ready arrays the PageUploader (or
    a lazy inline stage) produces — the engine applies staged jobs to the
    pool cache at step boundaries. ``node.pending`` stays True until the
    write lands; a job whose node was dropped or re-paged in the meantime
    is dead and silently discarded."""

    node: "_Node"
    page: int
    payload: tuple
    staged: tuple | None = None


class PageUploader:
    """Background host→device staging thread: promotion payloads are
    device_put OFF the scheduler thread (the slow host→device copy hides
    behind decode steps; the scheduler only applies already-staged planes
    at step boundaries). ``gate`` — when a test installs a threading
    Event — stalls staging so admission-PAUSE semantics can be pinned
    deterministically. Stage errors fall back to the raw numpy payload:
    the apply-side jit transfers it anyway, so a staging hiccup degrades
    to a synchronous upload instead of a wedged promotion."""

    def __init__(self, stage=None):
        import queue
        import threading

        self._stage = stage
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self.gate = None  # tests: threading.Event held = staging stalls
        self.staged_jobs = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dllama-kv-uploader")
        self._thread.start()

    def submit(self, job: _PromotionJob) -> None:
        self._q.put(job)

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            gate = self.gate
            if gate is not None:
                gate.wait()
            try:
                staged = (self._stage(job.payload) if self._stage
                          else job.payload)
            except Exception:  # noqa: BLE001 - degrade to sync upload
                staged = job.payload
            job.staged = staged
            self.staged_jobs += 1

    def close(self) -> None:
        self._q.put(None)


@dataclasses.dataclass
class _Node:
    """One FULL page of the prefix tree: ``key`` is its page_size-token
    window. Exactly ONE of the tier fields is live at a time (the audit
    pins it): ``page`` when tier == hbm (the tree retains a pool ref),
    ``host_id`` when tier == host, ``disk_ref`` when tier == disk.
    ``pending`` marks a promotion in flight: the node is back at tier
    hbm with ``page`` allocated, but the payload has not landed in the
    device pool yet — readers must wait (engine PAUSE semantics)."""
    key: tuple
    page: int
    parent: "_Node | None"
    children: dict = dataclasses.field(default_factory=dict)
    last_used: int = 0
    tier: str = TIER_HBM
    host_id: int = -1
    disk_ref: tuple | None = None
    pending: bool = False
    promoted_from: str | None = None  # transient match-walk attribution


class PrefixTree:
    """Page-granular radix tree over token ids.

    Each node spans exactly one full page (``page_size`` token ids — the
    radix alphabet is page windows, so depth = pages, not tokens), holding
    one tree-owned reference on its physical page. ``match`` walks the
    longest stored page-aligned prefix and RETAINS every matched page for
    the caller; ``insert`` adopts a request's full prompt pages;
    ``evict_lru`` frees idle leaves when the pool runs dry.
    """

    def __init__(self, pool: PagePool, page_size: int, owner=None):
        self.pool = pool
        self.page_size = page_size
        # ``owner`` (the PagedAllocator, when tiering can be in play)
        # routes node-drop resource release and spilled-node re-adoption
        # through the tier bookkeeping; a bare tree (owner None) keeps
        # the original pool-only semantics.
        self.owner = owner
        self._roots: dict[tuple, _Node] = {}
        self._clock = 0
        self._n_nodes = 0

    def __len__(self) -> int:
        return self._n_nodes

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _windows(self, tokens) -> list[tuple]:
        ps = self.page_size
        return [tuple(tokens[i:i + ps])
                for i in range(0, (len(tokens) // ps) * ps, ps)]

    def match(self, tokens, promote=None, on_match=None) -> list[int]:
        """Physical page ids of the longest stored page-aligned prefix of
        ``tokens``; each returned page carries a NEW reference the caller
        must eventually release (slot retire).

        Every touched node gets its OWN monotonic tick (not one shared
        walk timestamp): LRU ordering among victims is a strict total
        order — deterministic, wall-clock-free (dlint D005) — and a
        parent always reads more recent than the child the same walk
        touched before it... the walk descends, so each child's tick is
        newer; what matters is that no two nodes ever tie.

        ``promote`` (tiering): called with a node whose payload is NOT in
        HBM; it must raise the node back to tier hbm (allocating
        ``node.page``, possibly still promotion-pending) and return True,
        or return False to stop the match at the spill boundary.
        ``on_match`` observes every matched node (tier-source
        attribution)."""
        pages: list[int] = []
        children = self._roots
        for key in self._windows(tokens):
            node = children.get(key)
            if node is None:
                break
            if node.tier != TIER_HBM:
                if promote is None or not promote(node):
                    break
            node.last_used = self._tick()
            self.pool.retain(node.page)
            pages.append(node.page)
            if on_match is not None:
                on_match(node)
            children = node.children
        return pages

    def insert(self, tokens, pages) -> int:
        """Adopt the full pages of ``tokens`` (prompt positions only —
        ``len(pages)`` pages covering ``len(pages) * page_size`` token
        ids). The tree retains one ref per NEWLY adopted page; windows
        already present just refresh recency (their pages stay whichever
        physical id got there first — content is identical by the prefix
        key), EXCEPT a window whose node was demoted to host/disk: the
        inserting request just PREFILLED fresh HBM pages with that exact
        content, so the node re-adopts the fresh page and its spilled
        copy is freed (promotion by recompute — the natural warm-up path
        after a CRC drop or a failed promotion). Returns the number of
        pages adopted."""
        adopted = 0
        children, parent = self._roots, None
        for key, pid in zip(self._windows(tokens), pages):
            node = children.get(key)
            if node is None:
                node = _Node(key=key, page=pid, parent=parent,
                             last_used=self._tick())
                children[key] = node
                self.pool.retain(pid)
                if self.owner is not None:
                    self.owner._note_tier(None, TIER_HBM)
                self._n_nodes += 1
                adopted += 1
            else:
                node.last_used = self._tick()
                if node.tier != TIER_HBM and self.owner is not None:
                    self.owner._readopt(node, pid)
            children, parent = node.children, node
        return adopted

    def _leaves(self):
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                yield node

    def nodes(self):
        """Every node (drill introspection: each holds ONE tree ref on
        ``node.page``)."""
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def evict_lru(self, n_pages: int) -> int:
        """Drop up to ``n_pages`` least-recently-used leaf pages that no
        live slot maps (pool refcount 1 = tree-only). Walks repeatedly so
        an interior chain unwinds leaf by leaf. Returns pages freed.
        Spilled (host/disk) leaves hold no pool page and are skipped —
        with tiering on, HBM pressure goes through PagedAllocator's
        write-behind demotion instead. The per-touch ticks of match/
        insert make the ``min`` a strict LRU: no two nodes share a
        ``last_used``, so eviction order is a pure function of the touch
        history (pinned by tests/test_paging.py)."""
        freed = 0
        while freed < n_pages:
            victims = [n for n in self._leaves()
                       if n.tier == TIER_HBM and not n.pending
                       and self.pool.refcount(n.page) == 1]
            if not victims:
                break
            node = min(victims, key=lambda n: n.last_used)
            self._drop(node)
            freed += 1
        return freed

    def _drop(self, node: _Node) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._roots)
        del siblings[node.key]
        self._n_nodes -= 1
        if self.owner is not None:
            self.owner.release_node_storage(node)
        else:
            self.pool.release(node.page)

    def clear(self) -> int:
        """Release every tree-held page (engine shutdown / fail_all)."""
        freed = 0
        while self._n_nodes:
            for node in list(self._leaves()):
                self._drop(node)
                freed += 1
        return freed


class PagedAllocator:
    """The engine-facing facade: pool + tree + the share/evict policy.

    ``alloc_page`` transparently evicts idle tree leaves when the free
    list runs dry; ``match_prefix``/``insert_prefix`` are the admission
    and retire hooks. Counters feed the engine's Prometheus series
    (dllama_kv_pages_free / dllama_prefix_hits_total) and the bench's
    prefix-hit columns.
    """

    def __init__(self, n_pages: int, page_size: int,
                 prefix_share: bool = True, host_pages: int = 0,
                 disk_dir: str | None = None, disk_bytes: int = 0):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.n_pages = n_pages
        self.prefix_share = prefix_share
        self.pool = PagePool(n_pages)
        self.tree = PrefixTree(self.pool, page_size, owner=self)
        self.prefix_hits = 0       # admissions that mapped >= 1 shared page
        self.prefix_misses = 0     # admissions that mapped none
        self.tokens_saved = 0      # prefill positions skipped via sharing
        self.evictions = 0         # tree pages DROPPED (not demoted)
        # -- tier hierarchy (ISSUE 12) ------------------------------------
        self.host = HostPagePool(host_pages) if host_pages > 0 else None
        self.disk = (DiskPageStore(disk_dir, disk_bytes)
                     if disk_dir else None)
        self.tiered = self.host is not None or self.disk is not None
        # DCN handoff ingestion (ISSUE 14): the decode pool of a
        # disaggregated topology adopts remotely-prefilled page payloads
        # through the same promotion-pending machinery the tier hierarchy
        # uses; ``remote`` is set by the engine's remote_pages knob and
        # only widens the pending gates — untiered local engines keep the
        # zero-overhead path
        self.remote = False
        self.remote_adopted = 0   # pages adopted from a DCN handoff
        self.remote_rejected = 0  # shipped pages the pool could not place
        # tree-node population per tier, maintained incrementally at every
        # transition; the audit recounts from the tree and flags drift
        # ("counters consistent with the page ledger")
        self.tier_pages = {TIER_HBM: 0, TIER_HOST: 0, TIER_DISK: 0}
        self.demotions = {TIER_HOST: 0, TIER_DISK: 0}
        self.promotions = {TIER_HOST: 0, TIER_DISK: 0, "reprefill": 0}
        # prefill positions saved per SOURCE tier of the shared pages —
        # "disk"-sourced savings are the tokens tiering rescued from the
        # drop-on-evict recompute
        self.tokens_saved_by_tier = {TIER_HBM: 0, TIER_HOST: 0,
                                     TIER_DISK: 0}
        self.crc_drops = 0  # disk pages lost to CRC damage (re-derived)
        # device I/O, bound by the engine (bind_device_io): _fetch reads a
        # pool page's planes to host numpy (demotion), _stage device_puts
        # a payload (promotion; sharded under tp), _uploader stages async
        self._fetch = None
        self._stage = None
        self._uploader: PageUploader | None = None
        self.corrupt_demote = None  # chaos hook: True = drop the payload
        self._pending: dict[int, _Node] = {}  # target pid -> node
        self._jobs: list[_PromotionJob] = []
        self._match_sources: list[str] = []  # last match's per-page tiers

    def bind_device_io(self, fetch, stage=None, uploader=None) -> None:
        """Attach the engine's device callbacks: ``fetch(pid)`` -> host
        numpy planes of pool page ``pid`` (write-behind demotion reads
        through it), ``stage(payload)`` -> device-ready arrays
        (promotion; None = let the apply-side jit transfer raw numpy),
        ``uploader`` a PageUploader for async staging (None = stage
        inline at promotion time)."""
        self._fetch = fetch
        self._stage = stage
        self._uploader = uploader

    @property
    def n_free(self) -> int:
        return self.pool.n_free

    @property
    def pending_capable(self) -> bool:
        """True when pages can be promotion-PENDING (payload not yet in
        the device pool): the tier hierarchy is on, or remote (DCN
        handoff) adoption is — the engine's pause/settle gates consult
        this instead of ``tiered`` so both sources share one machinery."""
        return self.tiered or self.remote

    def pages_for(self, n_positions: int) -> int:
        """Pages needed to cover ``n_positions`` sequence positions."""
        return -(-n_positions // self.page_size)

    def alloc_page(self) -> int | None:
        pid = self.pool.alloc()
        if pid is None and len(self.tree):
            if self.tiered and self._fetch is not None:
                self.demote_cold(1)
            else:
                self.evictions += self.tree.evict_lru(1)
            pid = self.pool.alloc()
        return pid

    def match_prefix(self, tokens) -> list[int]:
        """Admission hook: shared FULL pages for the longest stored prefix
        of ``tokens`` (refs retained for the caller). Counting is
        deferred to ``record_admission`` — an admission the pool cannot
        serve yet gets requeued and re-matches every retry, and counting
        here would inflate the hit/saved figures by the retry count.

        With tiering, a matched node whose payload was spilled PROMOTES
        on the way through (async: the caller sees its page id now, the
        bytes land at a step boundary — pause until ``slot_pending`` is
        clear); a promotion the pool cannot place (or a CRC-dead disk
        page) stops the match at the spill boundary and the suffix
        prefills as a plain miss."""
        self._match_sources = []
        if not self.prefix_share:
            return []
        if not self.tiered:
            return self.tree.match(tokens)
        sources = self._match_sources

        def promote(node):
            src = node.tier
            if not self._promote(node):
                return False
            node.promoted_from = src  # consumed by on_match below
            return True

        def on_match(node):
            src = getattr(node, "promoted_from", None)
            if src is not None:
                node.promoted_from = None
            sources.append(src or TIER_HBM)

        return self.tree.match(tokens, promote=promote, on_match=on_match)

    def record_admission(self, n_shared_pages: int) -> None:
        """Count one STICKING admission that attempted prefix sharing —
        called by the engine after pages are secured, exactly once per
        admitted request, so hit_rate/tokens_saved match the Prometheus
        series no matter how many dry-pool retries preceded it. Savings
        attribute to each shared page's SOURCE tier at match time (the
        host/disk rows are the prefill recomputes tiering avoided)."""
        if n_shared_pages > 0:
            self.prefix_hits += 1
            self.tokens_saved += n_shared_pages * self.page_size
            sources = self._match_sources[:n_shared_pages]
            for i in range(n_shared_pages):
                src = sources[i] if i < len(sources) else TIER_HBM
                self.tokens_saved_by_tier[src] = (
                    self.tokens_saved_by_tier.get(src, 0) + self.page_size)
        else:
            self.prefix_misses += 1

    # -- tier transitions (ISSUE 12) ----------------------------------------

    def _note_tier(self, old: str | None, new: str | None) -> None:
        """Incremental tier-population ledger (the audit recounts it)."""
        if old is not None:
            self.tier_pages[old] -= 1
        if new is not None:
            self.tier_pages[new] += 1

    @staticmethod
    def _chain_ids(node: _Node) -> set:
        """id()s of ``node`` and every ancestor — the PROTECT set: while
        a node is mid-demotion or mid-promotion, neither it nor any
        ancestor may be dropped by a lower tier's pressure eviction (a
        dropped ancestor takes its whole subtree — including the node
        whose transition is in flight — with it)."""
        out = set()
        while node is not None:
            out.add(id(node))
            node = node.parent
        return out

    def demote_cold(self, n_pages: int, protect=frozenset()) -> int:
        """Write-behind demotion: move up to ``n_pages`` coldest tree-only
        HBM pages (pool refcount 1, not promotion-pending) down a tier —
        payload fetched from the device pool, stored host-first (host
        pressure spills host-LRU to disk first), HBM page released. A
        payload no lower tier can take DROPS (legacy eviction, with its
        now-unreachable subtree). Returns HBM pages freed."""
        if self._fetch is None:
            # no device reader bound (pure-host harnesses): fall back to
            # plain LRU eviction — the legacy drop path
            freed = self.tree.evict_lru(n_pages)
            self.evictions += freed
            return freed
        freed = 0
        while freed < n_pages:
            victims = [nd for nd in self.tree.nodes()
                       if nd.tier == TIER_HBM and not nd.pending
                       and self.pool.refcount(nd.page) == 1
                       and id(nd) not in protect]
            if not victims:
                break
            node = min(victims, key=lambda nd: nd.last_used)
            pid = node.page
            if self.corrupt_demote is not None and self.corrupt_demote():
                # seeded chaos mutation (drop_on_demote): the page leaves
                # HBM but its payload is never stored — the three-tier
                # audit must flag the host node with no live copy
                self._note_tier(TIER_HBM, TIER_HOST)
                node.tier, node.page, node.host_id = TIER_HOST, -1, -1
                self.pool.release(pid)
                freed += 1
                continue
            payload = self._fetch(pid)
            dest = self._store_down(node, payload,
                                    protect | self._chain_ids(node))
            if dest is None:
                self._drop_subtree(node)
            else:
                self._note_tier(TIER_HBM, dest)
                node.tier, node.page = dest, -1
                self.pool.release(pid)
                self.demotions[dest] += 1
            freed += 1
        return freed

    def _store_down(self, node: _Node, payload,
                    protect=frozenset()) -> str | None:
        """Place a demoted payload: host pool first (spilling the host
        LRU to disk under pressure), disk second. Returns the tier it
        landed in (node.host_id/disk_ref set), or None (nowhere — the
        caller drops the page). ``protect`` shields the in-flight node's
        ancestor chain from pressure drops."""
        if self.host is not None:
            hid = self.host.store(payload)
            if hid is None and self._spill_host(1, protect):
                hid = self.host.store(payload)
            if hid is not None:
                node.host_id = hid
                return TIER_HOST
        if self.disk is not None:
            ref = self._disk_store(payload, protect)
            if ref is not None:
                node.disk_ref = ref
                return TIER_DISK
        return None

    def _spill_host(self, n: int, protect=frozenset()) -> bool:
        """Host-budget pressure: move the LRU host-tier payloads to disk
        (write-behind, tier 2 → tier 3). Without a disk tier — or with a
        full one — the LRU host page DROPS (bottom-of-hierarchy eviction,
        subtree and all). True if any host slot was freed."""
        spilled = 0
        while spilled < n:
            cands = [nd for nd in self.tree.nodes()
                     if nd.tier == TIER_HOST and self.host.live(nd.host_id)
                     and id(nd) not in protect]
            if not cands:
                return spilled > 0
            node = min(cands, key=lambda nd: nd.last_used)
            payload = self.host.free(node.host_id)
            node.host_id = -1
            ref = self._disk_store(payload,
                                   protect | self._chain_ids(node))
            if ref is None:
                self._drop_subtree(node)
            else:
                self._note_tier(TIER_HOST, TIER_DISK)
                node.tier, node.disk_ref = TIER_DISK, ref
                self.demotions[TIER_DISK] += 1
            spilled += 1
        return True

    def _disk_store(self, payload, protect=frozenset()):
        """Append to the disk tier, evicting LRU disk pages when the
        live-byte budget is tight. None = no disk tier / nothing left to
        evict."""
        if self.disk is None:
            return None
        while True:
            ref = self.disk.store(payload)
            if ref is not None:
                return ref
            cands = [nd for nd in self.tree.nodes()
                     if nd.tier == TIER_DISK and id(nd) not in protect]
            if not cands:
                return None
            self._drop_subtree(min(cands, key=lambda nd: nd.last_used))

    def _drop_subtree(self, node: _Node) -> None:
        """Drop ``node`` and every descendant (children are unreachable
        without their prefix chain), releasing each one's tier storage.
        Post-order so parent dicts stay consistent."""
        for child in list(node.children.values()):
            self._drop_subtree(child)
        self.evictions += 1
        self.tree._drop(node)

    def _promote(self, node: _Node) -> bool:
        """Raise a spilled node back to HBM: allocate the target page
        (demoting colder pages if the pool is dry), load the payload
        (disk reads CRC-verify), and queue the async upload. False =
        could not promote (pool truly dry, or CRC-dead disk page — the
        node and its subtree are dropped and the caller's match stops at
        the spill boundary; prefill re-derives)."""
        src = node.tier
        pid = self.pool.alloc()
        if pid is None:
            # colder pages make room — with the promoting node's chain
            # protected, or the pressure path could drop it mid-flight
            self.demote_cold(1, protect=self._chain_ids(node))
            pid = self.pool.alloc()
        if pid is None:
            return False
        if src == TIER_HOST:
            if self.host is None or not self.host.live(node.host_id):
                self.pool.release(pid)
                raise RuntimeError(
                    f"kv tiering: host tier has no payload for node "
                    f"(host_id={node.host_id}) — a demotion dropped its "
                    f"bytes; the page ledger is corrupt")
            payload = self.host.free(node.host_id)
            node.host_id = -1
        else:
            payload = self.disk.load(node.disk_ref) if self.disk else None
            if payload is None:
                # CRC damage (or a lost store): this prefix chain is
                # gone — drop it and let prefill re-derive on the miss
                self.pool.release(pid)
                if self.disk is not None and self.disk.live(node.disk_ref):
                    self.disk.free(node.disk_ref)
                node.disk_ref = None
                self.crc_drops += 1
                self._drop_subtree(node)
                return False
            self.disk.free(node.disk_ref)
            node.disk_ref = None
        self._note_tier(src, TIER_HBM)
        node.tier, node.page, node.pending = TIER_HBM, pid, True
        self.promotions[src] += 1
        self._pending[pid] = node
        job = _PromotionJob(node=node, page=pid, payload=payload)
        self._jobs.append(job)
        if self._uploader is not None:
            self._uploader.submit(job)
        else:
            # synchronous-staging path: no uploader thread exists, so the
            # job never crosses a domain — staged is written before the
            # job is visible to anyone else
            job.staged = (self._stage(payload)  # threadcheck: allow[T001]
                          if self._stage is not None else payload)
        return True

    def _readopt(self, node: _Node, pid: int) -> None:
        """insert() found a spilled node whose content the inserting
        request just re-prefilled into fresh HBM pages: adopt the fresh
        page and free the spilled copy (promotion by recompute)."""
        self._note_tier(node.tier, TIER_HBM)
        if node.tier == TIER_HOST and self.host is not None \
                and self.host.live(node.host_id):
            self.host.free(node.host_id)
        elif node.tier == TIER_DISK and self.disk is not None \
                and self.disk.live(node.disk_ref):
            self.disk.free(node.disk_ref)
        node.tier, node.host_id, node.disk_ref = TIER_HBM, -1, None
        node.page = pid
        self.pool.retain(pid)
        self.promotions["reprefill"] += 1

    def adopt_remote_pages(self, tokens, payloads) -> list:
        """DCN handoff ingestion (ISSUE 14): adopt shipped page payloads
        under their full-page token-window keys as promotion-PENDING
        tree nodes — the decode pool's twin of a disk promotion, minus
        the disk. Each adopted window allocates its HBM target page now
        (evicting cold leaves under pressure), stages the payload
        (``bind_device_io``'s stage, or raw numpy for the apply jit to
        transfer), and queues the job for the engine's step-boundary
        apply (``take_staged_promotions``); a request matching the
        prefix meanwhile PAUSEs with the pages-starved semantics until
        the payload lands. ``payloads[i]`` covers window i of ``tokens``
        (wire-layout plane tuples, or None for a page that never arrived
        — the adoption stops at the gap and the suffix re-derives via
        prefill). Returns the adopted nodes (the handoff's cancel path
        drops them — mid-transfer cancel must free pages on this pool,
        not leave junk pending)."""
        adopted: list = []
        children, parent = self.tree._roots, None
        windows = self.tree._windows(tokens)
        for consumed, (key, payload) in enumerate(zip(windows, payloads)):
            if payload is None:
                break  # dropped/damaged in flight: prefill re-derives
            node = children.get(key)
            if node is None:
                pid = self.alloc_page()
                if pid is None:
                    # count only the pages actually left unplaced (windows
                    # already resident locally were consumed, not rejected)
                    self.remote_rejected += len(payloads) - consumed
                    break  # pool dry even after eviction: suffix re-derives
                node = _Node(key=key, page=pid, parent=parent,
                             last_used=self.tree._tick(), pending=True)
                children[key] = node
                self.tree._n_nodes += 1
                self._note_tier(None, TIER_HBM)
                self._pending[pid] = node
                job = _PromotionJob(node=node, page=pid, payload=payload)
                # adopted remote pages stage inline on the scheduler: the
                # job is constructed and staged here, before it is ever
                # published to the uploader's queue — no concurrent reader
                job.staged = (self._stage(payload)  # threadcheck: allow[T001]
                              if self._stage is not None else payload)
                self._jobs.append(job)
                self.remote_adopted += 1
                adopted.append(node)
            else:
                # window already stored locally (an earlier handoff or a
                # local prefill published it): the local copy wins — the
                # content is identical by the prefix key, and spilled
                # copies promote through the tier path on match
                node.last_used = self.tree._tick()
            children, parent = node.children, node
        return adopted

    def drop_adopted(self, nodes) -> int:
        """Cancel-path cleanup for ``adopt_remote_pages``: drop adopted
        nodes that are STILL promotion-pending (their payload never
        applied — nothing can be attending over them) so a cancelled
        mid-transfer handoff frees its pages on this pool immediately.
        Nodes whose payload already landed stay — they are ordinary
        tree-held prefix pages now, reusable by the next request."""
        dropped = 0
        for node in reversed(nodes):  # leaf-first: the chain unwinds
            if node.pending and self._pending.get(node.page) is node \
                    and not node.children:
                self.tree._drop(node)
                dropped += 1
        return dropped

    def release_node_storage(self, node: _Node) -> None:
        """Tree-drop hook (PrefixTree._drop): release whatever tier owns
        this node's payload. A promotion-pending node cancels its
        in-flight job (the engine discards dead jobs at the next drain)."""
        self._note_tier(node.tier, None)
        if node.tier == TIER_HBM:
            if node.pending:
                node.pending = False
                if self._pending.get(node.page) is node:
                    del self._pending[node.page]
            self.pool.release(node.page)
        elif node.tier == TIER_HOST:
            if self.host is not None and self.host.live(node.host_id):
                self.host.free(node.host_id)
        elif node.tier == TIER_DISK:
            if self.disk is not None and self.disk.live(node.disk_ref):
                self.disk.free(node.disk_ref)

    def take_staged_promotions(self) -> list[_PromotionJob]:
        """Promotion jobs whose payloads are device-ready — the engine
        applies them to the pool cache at a step boundary, then calls
        ``promotion_applied``. Jobs whose node was dropped (or whose
        target page was re-issued) in the meantime are dead and
        discarded; still-uploading jobs stay queued."""
        ready, rest = [], []
        for job in self._jobs:
            if (self._pending.get(job.page) is not job.node
                    or not job.node.pending):
                continue  # cancelled: node dropped / storage released
            if job.staged is None:
                rest.append(job)  # uploader still staging
                continue
            ready.append(job)
        self._jobs = rest
        return ready

    def promotion_applied(self, job: _PromotionJob) -> None:
        """The engine wrote ``job.staged`` into pool page ``job.page`` —
        the node's payload is live in HBM; waiting slots may dispatch."""
        job.node.pending = False
        if self._pending.get(job.page) is job.node:
            del self._pending[job.page]

    def is_pending(self, pid: int) -> bool:
        return pid in self._pending

    def slot_pending(self, pages) -> bool:
        """True while any of a slot's pages awaits its promotion upload —
        the engine PAUSEs the slot (pages-starved semantics) until the
        payload lands; dispatching earlier would attend over junk."""
        if not self._pending:
            return False
        return any(p in self._pending for p in pages)

    def tier_page_counts(self) -> dict:
        """Tree-node population per tier, recounted from the tree (the
        audit's ground truth; ``tier_pages`` is the incremental twin)."""
        counts = {TIER_HBM: 0, TIER_HOST: 0, TIER_DISK: 0}
        for nd in self.tree.nodes():
            counts[nd.tier] = counts.get(nd.tier, 0) + 1
        return counts

    def insert_prefix(self, tokens, pages) -> int:
        """Retire hook: publish a request's full prompt pages for reuse."""
        if not self.prefix_share:
            return 0
        n_full = min(len(tokens) // self.page_size, len(pages))
        return self.tree.insert(tokens[:n_full * self.page_size],
                                pages[:n_full])

    def release_pages(self, pages) -> None:
        for pid in pages:
            self.pool.release(pid)

    def audit(self, slot_page_lists) -> list[str]:
        """Full-accounting invariant check — the post-drill gate
        (runtime/chaos.py) and the ISSUE-8 "no leaked pages" oracle.

        ``slot_page_lists`` is every live slot's page list (the engine
        passes ``[s.pages for s in pool]``). For every physical page the
        pool thinks is allocated, its refcount must equal (# slot-list
        occurrences) + (1 if a tree node holds it) — a page with a
        refcount nothing explains is a LEAK, a mapped page with no
        refcount is a use-after-free in waiting. Also checks: the scrap
        page is never allocated or mapped, the free list has no
        duplicates and no allocated ids, and free + allocated covers the
        whole pool. Returns human-readable violations ([] = clean)."""
        problems: list[str] = []
        expected: dict[int, int] = {}
        for pages in slot_page_lists:
            for pid in pages:
                expected[pid] = expected.get(pid, 0) + 1
        # only HBM-tier nodes hold a pool ref; spilled nodes own a host/
        # disk copy instead (verified below)
        tree_pages = [n.page for n in self.tree.nodes()
                      if n.tier == TIER_HBM]
        for pid in tree_pages:
            expected[pid] = expected.get(pid, 0) + 1
        problems += self._audit_tiers()
        refs = self.pool.refcounts()
        for pid, want in sorted(expected.items()):
            if pid == SCRAP_PAGE:
                problems.append(f"scrap page {SCRAP_PAGE} is mapped by a "
                                f"slot or the tree")
                continue
            have = refs.get(pid, 0)
            if have != want:
                problems.append(
                    f"page {pid}: refcount {have} != {want} expected "
                    f"(slots+tree)")
        for pid, have in sorted(refs.items()):
            if pid not in expected:
                problems.append(f"page {pid}: leaked (refcount {have} but "
                                f"no slot or tree node maps it)")
        free = self.pool.free_ids()
        if len(set(free)) != len(free):
            problems.append("free list contains duplicate page ids")
        if SCRAP_PAGE in free or SCRAP_PAGE in refs:
            problems.append(f"scrap page {SCRAP_PAGE} entered the pool")
        for pid in free:
            if pid in refs:
                problems.append(f"page {pid} is both free and allocated")
        if len(free) + len(refs) != self.n_pages:
            problems.append(
                f"pool accounting: {len(free)} free + {len(refs)} "
                f"allocated != {self.n_pages} pages")
        return problems

    def _audit_tiers(self) -> list[str]:
        """The tier half of the invariant audit: every spilled node owns
        exactly one live host/disk copy, no copy is shared or orphaned,
        host-pool accounting closes, live disk records verify against
        their read-back CRC sidecars, promotion-pending nodes have
        in-flight jobs, and the incremental tier ledger matches a fresh
        recount — a page is owned by EXACTLY one tier."""
        if not self.tiered:
            return []
        problems: list[str] = []
        host_owner: dict[int, _Node] = {}
        disk_owner: dict[tuple, _Node] = {}
        for nd in self.tree.nodes():
            where = f"node {nd.key!r}"
            if nd.tier == TIER_HBM:
                if nd.host_id != -1 or nd.disk_ref is not None:
                    problems.append(f"tier audit: hbm {where} still "
                                    f"holds a host/disk copy (two-tier "
                                    f"ownership)")
                if nd.pending and nd.page not in self._pending:
                    problems.append(f"tier audit: {where} is promotion-"
                                    f"pending with no in-flight job")
            elif nd.tier == TIER_HOST:
                if self.host is None or not self.host.live(nd.host_id):
                    problems.append(f"tier audit: host {where} has no "
                                    f"live host-pool copy (payload "
                                    f"dropped on demote?)")
                elif nd.host_id in host_owner:
                    problems.append(f"tier audit: host page "
                                    f"{nd.host_id} owned by two nodes")
                else:
                    host_owner[nd.host_id] = nd
                if nd.page != -1 or nd.disk_ref is not None:
                    problems.append(f"tier audit: host {where} also "
                                    f"claims an hbm/disk copy")
            elif nd.tier == TIER_DISK:
                if self.disk is None or not self.disk.live(nd.disk_ref):
                    problems.append(f"tier audit: disk {where} has no "
                                    f"live disk record")
                elif (nd.disk_ref[0], nd.disk_ref[1]) in disk_owner:
                    problems.append(f"tier audit: disk record "
                                    f"{nd.disk_ref[:2]} owned by two "
                                    f"nodes")
                else:
                    disk_owner[(nd.disk_ref[0], nd.disk_ref[1])] = nd
                if nd.page != -1 or nd.host_id != -1:
                    problems.append(f"tier audit: disk {where} also "
                                    f"claims an hbm/host copy")
            else:
                problems.append(f"tier audit: {where} has unknown tier "
                                f"{nd.tier!r}")
        if self.host is not None:
            problems += self.host.audit()
            for hid in self.host.live_ids():
                if hid not in host_owner:
                    problems.append(f"tier audit: host page {hid} leaked "
                                    f"(live but no node owns it)")
        if self.disk is not None:
            problems += self.disk.audit()  # CRC read-back of live records
            for ref_key in self.disk.live_refs():
                if ref_key not in disk_owner:
                    problems.append(f"tier audit: disk record {ref_key} "
                                    f"leaked (live but no node owns it)")
        counts = self.tier_page_counts()
        if counts != self.tier_pages:
            problems.append(f"tier audit: incremental tier ledger "
                            f"{self.tier_pages} != recount {counts}")
        return problems

    def reset_counters(self) -> None:
        """Zero the admission counters WITHOUT touching pool/tree state —
        the bench's warm-up/timed-pass boundary: the timed pass then
        reports the warm-tree steady state alone, not a blend."""
        self.prefix_hits = self.prefix_misses = 0
        self.tokens_saved = self.evictions = 0
        self.demotions = {TIER_HOST: 0, TIER_DISK: 0}
        self.promotions = {TIER_HOST: 0, TIER_DISK: 0, "reprefill": 0}
        self.tokens_saved_by_tier = {TIER_HBM: 0, TIER_HOST: 0,
                                     TIER_DISK: 0}
        self.crc_drops = 0
        self.remote_adopted = self.remote_rejected = 0

    @property
    def hit_rate(self) -> float:
        n = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / n if n else 0.0
