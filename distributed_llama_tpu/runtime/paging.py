"""Paged KV allocation + radix prefix sharing — the host half of paged KV.

The continuous engine's contiguous layout charges every slot a full
``seq_len`` KV stripe (analysis/memory_model.kv_cache_device_bytes), so a
12-token chat request strands >99% of its stripe and the slot count — not
compute — caps concurrency. This module manages the replacement: a fixed
pool of fixed-size pages (vLLM's PagedAttention unit, Kwon et al. 2023)
plus a prefix tree over full pages (SGLang's RadixAttention, Zheng et al.
2023) so requests sharing a system prompt map the SAME physical prefill
pages instead of recomputing them.

Everything here is host-side bookkeeping over small Python ints — the
device never sees this module. The device-visible artifacts are the page
TABLE rows (int32 physical page ids per slot, staged by the engine into
one persistent numpy buffer — dlint D004) that models/llama.
forward_batch_paged walks, and the page-pool planes it indexes.

Invariants the unit tests pin (tests/test_paging.py):

* a page's refcount = (# slots mapping it) + (1 if the tree holds it);
  it returns to the free list exactly when that count reaches zero;
* page id 0 is RESERVED as the scrap page (parked/free slot rows write
  their dead k/v there); the pool never hands it out;
* the tree only shares FULL pages (``page_size`` tokens each): a
  partially-filled tail page is private to its request, so decode writes
  never land in a shared page;
* eviction frees least-recently-used tree LEAVES whose pages no live slot
  maps — interior nodes only become evictable once their children are
  gone (a child is unreachable without its prefix chain).
"""

from __future__ import annotations

import dataclasses

SCRAP_PAGE = 0  # physical page 0: dead-write target for parked slots


class PagePool:
    """Free-list + refcount accounting over physical page ids 1..n_pages.

    ``alloc`` hands out the lowest free id (deterministic schedules make
    the paged==contiguous parity tests reproducible); ``retain``/
    ``release`` move the per-page refcount, and a page re-enters the free
    list exactly at refcount zero.
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"page pool needs >= 1 page, got {n_pages}")
        self.n_pages = n_pages
        # lowest-id-first allocation order; ids 1..n_pages (0 = scrap)
        self._free = list(range(n_pages, 0, -1))
        self._ref: dict[int, int] = {}

    @property
    def n_free(self) -> int:
        return len(self._free)

    def alloc(self) -> int | None:
        """One page at refcount 1, or None when the pool is dry (the
        caller decides whether to evict or fail the request)."""
        if not self._free:
            return None
        pid = self._free.pop()
        self._ref[pid] = 1
        return pid

    def retain(self, pid: int) -> None:
        if pid not in self._ref:
            raise ValueError(f"retain of unallocated page {pid}")
        self._ref[pid] += 1

    def release(self, pid: int) -> None:
        n = self._ref.get(pid)
        if n is None:
            raise ValueError(f"release of unallocated page {pid}")
        if n == 1:
            del self._ref[pid]
            self._free.append(pid)
            # keep lowest-first order without re-sorting the whole list on
            # every release: append high, pop low via sort-on-alloc would be
            # O(n log n) per step — a lazy sort only when order broke
            if len(self._free) > 1 and self._free[-1] > self._free[-2]:
                self._free.sort(reverse=True)
        else:
            self._ref[pid] = n - 1

    def refcount(self, pid: int) -> int:
        return self._ref.get(pid, 0)

    def refcounts(self) -> dict[int, int]:
        """Copy of the live refcount table — the chaos-drill audit's view
        (runtime/chaos.py); mutating the copy touches nothing."""
        return dict(self._ref)

    def free_ids(self) -> list[int]:
        """Copy of the free list (drill introspection)."""
        return list(self._free)


@dataclasses.dataclass
class _Node:
    """One FULL page of the prefix tree: ``key`` is its page_size-token
    window, ``page`` the physical id the tree retains a ref on."""
    key: tuple
    page: int
    parent: "_Node | None"
    children: dict = dataclasses.field(default_factory=dict)
    last_used: int = 0


class PrefixTree:
    """Page-granular radix tree over token ids.

    Each node spans exactly one full page (``page_size`` token ids — the
    radix alphabet is page windows, so depth = pages, not tokens), holding
    one tree-owned reference on its physical page. ``match`` walks the
    longest stored page-aligned prefix and RETAINS every matched page for
    the caller; ``insert`` adopts a request's full prompt pages;
    ``evict_lru`` frees idle leaves when the pool runs dry.
    """

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        self._roots: dict[tuple, _Node] = {}
        self._clock = 0
        self._n_nodes = 0

    def __len__(self) -> int:
        return self._n_nodes

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _windows(self, tokens) -> list[tuple]:
        ps = self.page_size
        return [tuple(tokens[i:i + ps])
                for i in range(0, (len(tokens) // ps) * ps, ps)]

    def match(self, tokens) -> list[int]:
        """Physical page ids of the longest stored page-aligned prefix of
        ``tokens``; each returned page carries a NEW reference the caller
        must eventually release (slot retire)."""
        now = self._tick()
        pages: list[int] = []
        children = self._roots
        for key in self._windows(tokens):
            node = children.get(key)
            if node is None:
                break
            node.last_used = now
            self.pool.retain(node.page)
            pages.append(node.page)
            children = node.children
        return pages

    def insert(self, tokens, pages) -> int:
        """Adopt the full pages of ``tokens`` (prompt positions only —
        ``len(pages)`` pages covering ``len(pages) * page_size`` token
        ids). The tree retains one ref per NEWLY adopted page; windows
        already present just refresh recency (their pages stay whichever
        physical id got there first — content is identical by the prefix
        key). Returns the number of pages adopted."""
        now = self._tick()
        adopted = 0
        children, parent = self._roots, None
        for key, pid in zip(self._windows(tokens), pages):
            node = children.get(key)
            if node is None:
                node = _Node(key=key, page=pid, parent=parent,
                             last_used=now)
                children[key] = node
                self.pool.retain(pid)
                self._n_nodes += 1
                adopted += 1
            else:
                node.last_used = now
            children, parent = node.children, node
        return adopted

    def _leaves(self):
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            else:
                yield node

    def nodes(self):
        """Every node (drill introspection: each holds ONE tree ref on
        ``node.page``)."""
        stack = list(self._roots.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def evict_lru(self, n_pages: int) -> int:
        """Drop up to ``n_pages`` least-recently-used leaf pages that no
        live slot maps (pool refcount 1 = tree-only). Walks repeatedly so
        an interior chain unwinds leaf by leaf. Returns pages freed."""
        freed = 0
        while freed < n_pages:
            victims = [n for n in self._leaves()
                       if self.pool.refcount(n.page) == 1]
            if not victims:
                break
            node = min(victims, key=lambda n: n.last_used)
            self._drop(node)
            freed += 1
        return freed

    def _drop(self, node: _Node) -> None:
        siblings = (node.parent.children if node.parent is not None
                    else self._roots)
        del siblings[node.key]
        self._n_nodes -= 1
        self.pool.release(node.page)

    def clear(self) -> int:
        """Release every tree-held page (engine shutdown / fail_all)."""
        freed = 0
        while self._n_nodes:
            for node in list(self._leaves()):
                self._drop(node)
                freed += 1
        return freed


class PagedAllocator:
    """The engine-facing facade: pool + tree + the share/evict policy.

    ``alloc_page`` transparently evicts idle tree leaves when the free
    list runs dry; ``match_prefix``/``insert_prefix`` are the admission
    and retire hooks. Counters feed the engine's Prometheus series
    (dllama_kv_pages_free / dllama_prefix_hits_total) and the bench's
    prefix-hit columns.
    """

    def __init__(self, n_pages: int, page_size: int,
                 prefix_share: bool = True):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.n_pages = n_pages
        self.prefix_share = prefix_share
        self.pool = PagePool(n_pages)
        self.tree = PrefixTree(self.pool, page_size)
        self.prefix_hits = 0       # admissions that mapped >= 1 shared page
        self.prefix_misses = 0     # admissions that mapped none
        self.tokens_saved = 0      # prefill positions skipped via sharing
        self.evictions = 0

    @property
    def n_free(self) -> int:
        return self.pool.n_free

    def pages_for(self, n_positions: int) -> int:
        """Pages needed to cover ``n_positions`` sequence positions."""
        return -(-n_positions // self.page_size)

    def alloc_page(self) -> int | None:
        pid = self.pool.alloc()
        if pid is None and len(self.tree):
            self.evictions += self.tree.evict_lru(1)
            pid = self.pool.alloc()
        return pid

    def match_prefix(self, tokens) -> list[int]:
        """Admission hook: shared FULL pages for the longest stored prefix
        of ``tokens`` (refs retained for the caller). Counting is
        deferred to ``record_admission`` — an admission the pool cannot
        serve yet gets requeued and re-matches every retry, and counting
        here would inflate the hit/saved figures by the retry count."""
        if not self.prefix_share:
            return []
        return self.tree.match(tokens)

    def record_admission(self, n_shared_pages: int) -> None:
        """Count one STICKING admission that attempted prefix sharing —
        called by the engine after pages are secured, exactly once per
        admitted request, so hit_rate/tokens_saved match the Prometheus
        series no matter how many dry-pool retries preceded it."""
        if n_shared_pages > 0:
            self.prefix_hits += 1
            self.tokens_saved += n_shared_pages * self.page_size
        else:
            self.prefix_misses += 1

    def insert_prefix(self, tokens, pages) -> int:
        """Retire hook: publish a request's full prompt pages for reuse."""
        if not self.prefix_share:
            return 0
        n_full = min(len(tokens) // self.page_size, len(pages))
        return self.tree.insert(tokens[:n_full * self.page_size],
                                pages[:n_full])

    def release_pages(self, pages) -> None:
        for pid in pages:
            self.pool.release(pid)

    def audit(self, slot_page_lists) -> list[str]:
        """Full-accounting invariant check — the post-drill gate
        (runtime/chaos.py) and the ISSUE-8 "no leaked pages" oracle.

        ``slot_page_lists`` is every live slot's page list (the engine
        passes ``[s.pages for s in pool]``). For every physical page the
        pool thinks is allocated, its refcount must equal (# slot-list
        occurrences) + (1 if a tree node holds it) — a page with a
        refcount nothing explains is a LEAK, a mapped page with no
        refcount is a use-after-free in waiting. Also checks: the scrap
        page is never allocated or mapped, the free list has no
        duplicates and no allocated ids, and free + allocated covers the
        whole pool. Returns human-readable violations ([] = clean)."""
        problems: list[str] = []
        expected: dict[int, int] = {}
        for pages in slot_page_lists:
            for pid in pages:
                expected[pid] = expected.get(pid, 0) + 1
        tree_pages = [n.page for n in self.tree.nodes()]
        for pid in tree_pages:
            expected[pid] = expected.get(pid, 0) + 1
        refs = self.pool.refcounts()
        for pid, want in sorted(expected.items()):
            if pid == SCRAP_PAGE:
                problems.append(f"scrap page {SCRAP_PAGE} is mapped by a "
                                f"slot or the tree")
                continue
            have = refs.get(pid, 0)
            if have != want:
                problems.append(
                    f"page {pid}: refcount {have} != {want} expected "
                    f"(slots+tree)")
        for pid, have in sorted(refs.items()):
            if pid not in expected:
                problems.append(f"page {pid}: leaked (refcount {have} but "
                                f"no slot or tree node maps it)")
        free = self.pool.free_ids()
        if len(set(free)) != len(free):
            problems.append("free list contains duplicate page ids")
        if SCRAP_PAGE in free or SCRAP_PAGE in refs:
            problems.append(f"scrap page {SCRAP_PAGE} entered the pool")
        for pid in free:
            if pid in refs:
                problems.append(f"page {pid} is both free and allocated")
        if len(free) + len(refs) != self.n_pages:
            problems.append(
                f"pool accounting: {len(free)} free + {len(refs)} "
                f"allocated != {self.n_pages} pages")
        return problems

    def reset_counters(self) -> None:
        """Zero the admission counters WITHOUT touching pool/tree state —
        the bench's warm-up/timed-pass boundary: the timed pass then
        reports the warm-tree steady state alone, not a blend."""
        self.prefix_hits = self.prefix_misses = 0
        self.tokens_saved = self.evictions = 0

    @property
    def hit_rate(self) -> float:
        n = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / n if n else 0.0
