"""Prefill/decode disaggregation: the two-pool serving topology (ISSUE 14).

Prefill and decode fight for the same chips: one long prefill dispatch
stalls every in-flight decode behind it — the TTFT/TPOT interference the
disaggregation line of work (DistServe, OSDI'24; Splitwise, ISCA'24)
removes by giving each phase its own pool. This module is that topology
over two ``ContinuousEngine``s:

* the PREFILL pool admits requests (SLO-class priority order — the
  engine's ``slo_priority`` knob — with batch prefills preemptable at
  page-aligned chunk boundaries via ``prefill_hold``), fills their KV
  pages, and samples the FIRST token (the DistServe convention: TTFT is
  the prefill pool's responsibility);
* the request then hands off as its JOURNAL record — prompt ids, sampled
  tokens, coin cursor (runtime/journal.entry_to_wire): exactly the
  resumable state crash recovery replays, so the decode pool re-admits
  through the SAME path ``ContinuousEngine.recover`` uses and the
  continued stream is BITWISE the single-pool run's;
* its full prompt pages ship over the DCN page channel
  (runtime/page_channel.py) in the one page wire layout
  (runtime/pagewire.py — the disk tier's exact bytes, per-page CRC32,
  verified on arrival) and land in the decode pool's radix tree as
  promotion-PENDING nodes (``PagedAllocator.adopt_remote_pages``);
  admission PAUSEs the request with the pages-starved semantics until
  the payloads apply at a step boundary, then runs suffix-only prefill
  for the unshipped tail (the partial last page + the first sampled
  token) — a handoff costs one page upload per full prompt page, not a
  prefill recompute;
* later same-prefix requests hit the decode pool's tree directly (the
  radix publish happens on the DECODE pool after handoff).

Failure honesty: the hand-over is durable once the decode pool's journal
holds the admit record — the transfer and adoption can die at any point
after that and recovery re-derives the KV via prefill, bitwise
(``drill_kill_mid_handoff``). A decode-pool death in the window between
the prefill stub's retirement and the decode admit loses the
continuation; the client (or the fronting router) retries the request —
the same contract an un-journaled single pool offers for everything.

``DisaggPair`` drives both pools from ONE thread (the deterministic
CPU-simulation and drill harness); ``runtime/server.py`` wires the same
primitives across two processes (POST /prefill + the page channel).
"""

from __future__ import annotations

import dataclasses
import time

from ..obs import tracectx
from .continuous import ContinuousEngine, Request
from .journal import JournalEntry

HANDOFF_VERDICTS = ("shipped", "local", "failed")

# the cross-pool clock-skew anchor pair (ISSUE 15): the initiating pool
# records SPAN_HANDOFF_SEND around the transfer, the serving pool records
# SPAN_HANDOFF_RECV parented on it — tools/tracejoin.py aligns the two
# pools' clocks on exactly this pair and refuses when it is missing
SPAN_HANDOFF_SEND = "handoff"
SPAN_HANDOFF_RECV = "prefill_handoff"
HANDOFF_CAT = "handoff"


class DisaggMetrics:
    """The disaggregation observability surface (pre-registered at zero
    so a fresh scrape already shows the full matrix):

    * ``dllama_handoff_requests_total{verdict}`` — shipped (handed to
      the decode pool), local (completed on the prefill pool: the
      stream ended inside the prefill budget), failed (the handoff
      could not complete);
    * ``dllama_dcn_pages_shipped_total`` / ``dllama_dcn_bytes_total`` —
      page-channel volume (payload bytes, the DCN budget's unit);
    * ``dllama_handoff_seconds`` — prefill-retire -> decode-admission
      latency histogram;
    * ``dllama_handoff_queue_depth`` — handoffs published and not yet
      acked by the decode pool.
    """

    def __init__(self, registry):
        self.registry = registry
        self.handoffs = {
            v: registry.labeled_counter(
                "dllama_handoff_requests_total", {"verdict": v},
                "Prefill->decode handoffs by outcome (shipped/local/"
                "failed)")
            for v in HANDOFF_VERDICTS}
        self.pages_shipped = registry.counter(
            "dllama_dcn_pages_shipped_total",
            "KV pages shipped over the DCN page channel")
        self.bytes_shipped = registry.counter(
            "dllama_dcn_bytes_total",
            "KV page payload bytes shipped over the DCN page channel")
        self.handoff_latency = registry.histogram(
            "dllama_handoff_seconds",
            "Handoff latency: prefill retire to decode admission")
        self.queue_depth = registry.gauge(
            "dllama_handoff_queue_depth",
            "Handoffs in flight (published, not yet acked)")


def export_prefix_pages(engine: ContinuousEngine, tokens) -> list:
    """Wire payloads (host numpy plane tuples) of the full prompt pages
    the engine's radix tree holds for ``tokens`` — the prefill side of a
    handoff. Refs are retained for the duration of the read and released
    after; the tree keeps its own copy (and its recency bump) so the
    pages stay warm for same-prefix siblings."""
    from ..models.llama import fetch_page_planes

    alloc = engine.allocator
    if alloc is None:
        return []
    n_pre = len(tokens) - 1
    pages = alloc.tree.match(tokens[:n_pre])
    try:
        return [fetch_page_planes(engine.cache, pid) for pid in pages]
    finally:
        alloc.release_pages(pages)


def encode_handoff_pages(payloads, corrupt=None) -> list:
    """Frame each payload for the wire (pagewire.encode_record).
    ``corrupt`` is the chaos hook (ChaosMonkey.page_drop): when it fires
    for a page, the payload is replaced with ZEROS and re-framed with a
    VALID CRC — the seeded in-flight corruption that slips past framing,
    which only the bitwise stream gate can catch (the
    drop-page-in-flight mutation arm)."""
    import numpy as np

    from .pagewire import encode_record

    records = []
    for planes in payloads:
        if corrupt is not None and corrupt():
            # planes are host numpy (fetch_page_planes output) — zeroing
            # them is pure host work
            planes = tuple(np.zeros(p.shape, p.dtype) for p in planes)
        records.append(encode_record(planes))
    return records


def entry_for_stub(engine: ContinuousEngine, stub: Request) -> JournalEntry:
    """The handoff record of a retired prefill stub: the engine's journal
    entry when one exists (the production path — it carries the exact
    coin cursor), else derived from the stub directly — legal only for
    greedy streams, which draw no coins (the virtual-clock simulation's
    path)."""
    if engine._journal is not None:
        e = engine._journal.entry(stub.index)
        if e is not None:
            if stub.ledger is not None:
                # the stub's closed bill (carried merged in) rides the
                # handoff record — the decode pool's ledger seeds from it
                # so the request's cost stays whole across pools
                e.ledger = stub.ledger.snapshot()
            return e
    temp = (stub.temperature if stub.temperature is not None
            else engine.temperature)
    if temp != 0.0:
        raise ValueError(
            "handing off a sampled stream needs the prefill engine's "
            "journal (the coin cursor lives there); journal-less "
            "handoff is greedy-only")
    n_pre = len(stub.tokens) - 1
    return JournalEntry(
        rid=stub.index, tokens=list(stub.tokens), steps=stub.steps,
        temperature=temp,
        topp=stub.topp if stub.topp is not None else engine.topp,
        seed=(stub.seed if stub.seed is not None
              else engine.seed + stub.index),
        slo=stub.slo_class, cursor=0, sampled=list(stub.out[n_pre:]),
        trace=(stub.trace.to_header() if stub.trace is not None
               else None),
        ledger=(stub.ledger.snapshot() if stub.ledger is not None
                else None))


def decode_request(entry: JournalEntry, steps: int) -> Request:
    """The decode pool's re-admission request: the recovery replay shape
    (already-sampled tokens ride the forced window, the sampler
    fast-forwards by the coin cursor) with the ORIGINAL step budget —
    the stub's budget was the prefill cut, not the request's. The
    entry's traceparent (when the prefill pool propagated one) continues
    the SAME trace with a ``handoff`` link span (ISSUE 15)."""
    trace = None
    if entry.trace:
        try:
            trace = tracectx.from_header(entry.trace,
                                         link=tracectx.LINK_HANDOFF)
        except ValueError:
            trace = None  # a damaged header never blocks the handoff
    return Request(tokens=entry.replay_tokens, steps=steps,
                   temperature=entry.temperature, topp=entry.topp,
                   seed=entry.seed, slo_class=entry.slo,
                   coin_cursor=entry.cursor, trace=trace,
                   carried_cost=entry.ledger)


def make_priority_hold(engine: ContinuousEngine, policy):
    """The prefill pool's chunk-boundary preemption predicate: park a
    slot's prefill when a STRICTLY higher-ranked class is waiting in the
    queue (obs/slo.SLOPolicy.rank — 0 = highest). Wire it with
    ``engine.prefill_hold = make_priority_hold(engine, policy)``."""

    def hold(slot) -> bool:
        mine = policy.rank(slot.req.slo_class)
        with engine._lock:
            queued = list(engine._queue)
        return any(policy.rank(r.slo_class) < mine for r in queued)

    return hold


def prefill_stub(tokens, steps: int, temperature=None, topp=None,
                 seed=None, slo_class=None) -> tuple[Request, bool]:
    """The prefill pool's view of a request: budget cut to prompt
    positions + ONE sampled token (TTFT is the prefill pool's job; the
    decode pool owns the rest). Returns (request, may_hand_off) —
    False when the whole budget fits inside the prefill cut (short
    requests complete locally; no DCN bytes moved for nothing)."""
    n_pre = len(tokens) - 1
    pre_steps = min(steps, n_pre + 1)
    req = Request(tokens=list(tokens), steps=pre_steps,
                  temperature=temperature, topp=topp, seed=seed,
                  slo_class=slo_class)
    return req, pre_steps < steps


def stub_needs_handoff(stub: Request) -> bool:
    """True when a retired prefill stub's stream continues on the decode
    pool: it sampled its one token and that token was not the BOS stop
    (a BOS'd or errored stub IS the finished stream)."""
    if stub.error is not None or stub.cancelled:
        return False
    n_pre = len(stub.tokens) - 1
    return stub.n_sampled >= 1 and len(stub.out) == n_pre + 1


@dataclasses.dataclass
class Handoff:
    """One in-flight prefill->decode hand-over (DisaggPair bookkeeping)."""

    entry: JournalEntry
    req: Request              # the decode pool's re-admission request
    adopted: list             # decode-pool tree nodes holding shipped pages
    n_pages: int
    payload_bytes: int
    t_start: float


class DisaggPair:
    """Two engines, one scheduler thread: the deterministic two-pool
    harness (parity tests, chaos drills, the offline CLI path). The
    prefill engine needs a journal when any request samples at
    temperature > 0 (the coin cursor crosses pools in the journal
    record); the decode engine needs ``remote_pages=True``. With
    ``channel_host`` set, pages genuinely cross a TCP page channel
    (CRC-verified frames); without it they still round-trip the wire
    codec in memory — every handoff exercises the exact bytes the DCN
    would carry."""

    def __init__(self, prefill: ContinuousEngine, decode: ContinuousEngine,
                 channel_host: str | None = None, registry=None,
                 chaos=None):
        if prefill.page_size <= 0 or decode.page_size <= 0:
            raise ValueError("disaggregation ships KV PAGES: both pools "
                             "need page_size > 0")
        if prefill.page_size != decode.page_size:
            raise ValueError(
                f"page_size mismatch: prefill {prefill.page_size} != "
                f"decode {decode.page_size} — the wire unit must agree")
        if decode.allocator is None or not decode.allocator.remote:
            raise ValueError("the decode engine must be constructed with "
                             "remote_pages=True (handoff page ingestion)")
        self.prefill = prefill
        self.decode = decode
        self._chaos = chaos
        self.obs = DisaggMetrics(registry) if registry is not None else None
        self._server = None
        self._client = None
        if channel_host is not None:
            from .page_channel import PageChannelClient, PageChannelServer

            self._server = PageChannelServer(host=channel_host)
            self._client = PageChannelClient(
                f"{channel_host}:{self._server.port}")
        self.handoffs_shipped = 0
        self.handoffs_local = 0
        self.handoffs_failed = 0

    @property
    def channel_port(self) -> int | None:
        return self._server.port if self._server is not None else None

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
        self.prefill.close()
        self.decode.close()

    # ------------------------------------------------------------ handoff

    def _count(self, verdict: str) -> None:
        field = f"handoffs_{verdict}"
        setattr(self, field, getattr(self, field) + 1)
        if self.obs is not None:
            self.obs.handoffs[verdict].inc()
            if self._server is not None:
                self.obs.queue_depth.set(self._server.queue_depth)

    def handoff(self, stub: Request, steps: int,
                cut_after: int | None = None) -> Handoff | None:
        """Hand one retired prefill stub to the decode pool. Order is
        the durability contract: the decode ADMIT is journaled (submit)
        BEFORE any page moves, so a decode-pool death mid-transfer
        recovers the request from its journal — the shipped pages are an
        optimization, prefill re-derives them when they never land.
        ``cut_after`` (drills) aborts the page transfer after that many
        pages. Returns None when the stub needs no handoff (counted as
        a LOCAL completion)."""
        from .pagewire import decode_record, record_payload_bytes

        if not stub_needs_handoff(stub):
            self._count("local")
            return None
        t0 = time.monotonic()
        entry = entry_for_stub(self.prefill, stub)
        # trace propagation across the hand-over (ISSUE 15): the page
        # transfer rides its own RPC span — the send/recv anchor pair
        # tools/tracejoin.py aligns the two pools' clocks on. The
        # drop-traceparent mutation (ChaosMonkey.trace_drop) strips the
        # header at exactly this seam, so the decode pool's spans can no
        # longer join the prefill pool's — the orphan the join gate must
        # catch.
        parent = None
        if entry.trace:
            try:
                parent = tracectx.parse_header(entry.trace)
            except ValueError:
                parent = None
        dropped = self._chaos is not None and self._chaos.trace_drop()
        if dropped:
            entry.trace = None
        rpc = parent.child() if parent is not None else tracectx.mint()
        t_send0 = time.perf_counter()
        req = decode_request(entry, steps)
        self.decode.submit(req)  # journal admit lands FIRST (durability)
        t_recv0 = time.perf_counter()
        payloads = export_prefix_pages(self.prefill, stub.tokens)
        records = encode_handoff_pages(
            payloads, corrupt=(self._chaos.page_drop
                               if self._chaos is not None else None))
        nbytes = sum(record_payload_bytes(r) for r in records)
        if self.obs is not None and records:
            self.obs.pages_shipped.inc(len(records))
            self.obs.bytes_shipped.inc(nbytes)
        if self._server is not None:
            hid = f"h{stub.index}"
            self._server.publish(hid, records,
                                 trace=(None if dropped
                                        else rpc.to_header()))
            if self.obs is not None:
                self.obs.queue_depth.set(self._server.queue_depth)
            planes = self._client.fetch(hid, len(records),
                                        cut_after=cut_after)
        else:
            if cut_after is not None:
                records = records[:cut_after]
            planes = [decode_record(r) for r in records]
        if self.prefill._spans is not None:
            # the recv half of the anchor pair, on the prefill pool's
            # clock; a dropped header leaves it unparented — the
            # unjoined state the gate exists to surface
            recv = (tracectx.mint() if dropped or parent is None
                    else rpc.child())
            self.prefill._spans.add(
                SPAN_HANDOFF_RECV, HANDOFF_CAT, t_recv0,
                time.perf_counter() - t_recv0, pages=len(records),
                **tracectx.span_fields(recv))
        adopted = self.decode.allocator.adopt_remote_pages(
            stub.tokens[:len(stub.tokens) - 1], planes)
        if self.decode._spans is not None:
            self.decode._spans.add(
                SPAN_HANDOFF_SEND, HANDOFF_CAT, t_send0,
                time.perf_counter() - t_send0, pages=len(records),
                bytes=nbytes, **tracectx.span_fields(rpc))
        if req.ledger is not None:
            # the DCN bill + the seconds this request spent crossing
            # pools (seconds-only stall: no engine dispatch rode it)
            req.ledger.charge_dcn(len(records), nbytes)
            req.ledger.charge_stall_s("handoff_wait",
                                      time.monotonic() - t0)
        self._count("shipped")
        if self.obs is not None:
            self.obs.handoff_latency.observe(time.monotonic() - t0)
        return Handoff(entry=entry, req=req, adopted=adopted,
                       n_pages=len(records), payload_bytes=nbytes,
                       t_start=t0)

    def cancel(self, handoff: Handoff) -> None:
        """Mid-transfer/mid-decode cancel: the decode request retires at
        the next sweep (freeing its slot + pages) and the adopted-but-
        never-applied pending nodes drop NOW — a cancelled handoff must
        free pages on both pools, not strand pending junk."""
        self.decode.cancel(handoff.req)
        self.decode.allocator.drop_adopted(handoff.adopted)
        if self._server is not None:
            self._server.retire(f"h{handoff.entry.rid}")
            if self.obs is not None:
                self.obs.queue_depth.set(self._server.queue_depth)

    # ------------------------------------------------------------ offline

    def _drain(self, engine, max_iters: int = 100_000) -> None:
        it = 0
        while engine.step_many(engine.block_steps, quiet=True):
            it += 1
            if it >= max_iters:
                raise RuntimeError("disagg pool is not draining")

    def run(self, requests: list, steps: int) -> tuple[list, dict]:
        """Offline two-pool drive (ContinuousEngine.run's shape): decode
        every request to BOS or ``steps`` positions through prefill ->
        handoff -> decode; outputs in request order, bitwise the
        single-pool streams. Returns (outs, summary)."""
        stubs = []
        for i, tokens in enumerate(requests):
            if not tokens:
                raise ValueError(f"request {i} has no prompt tokens")
            stub, _ = prefill_stub(tokens, steps)
            self.prefill.submit(stub)
            stubs.append(stub)
        self._drain(self.prefill)
        finals: list = []
        for stub in stubs:
            h = self.handoff(stub, steps)
            finals.append(stub if h is None else h.req)
        self._drain(self.decode)
        outs = [r.out for r in finals]
        return outs, self.summary()

    def summary(self) -> dict:
        a = self.decode.allocator
        return {
            "shipped": self.handoffs_shipped,
            "local": self.handoffs_local,
            "failed": self.handoffs_failed,
            "pages_adopted": a.remote_adopted,
            "pages_rejected": a.remote_rejected,
            "prefill_steps": self.prefill.stats.steps,
            "prefill_chunks": self.prefill.stats.prefill_chunks,
            "decode_steps": self.decode.stats.steps,
            "decode_chunks": self.decode.stats.prefill_chunks,
            "channel_port": self.channel_port,
        }
