from .sampling import Sampler  # noqa: F401
from .generate import Engine, generate  # noqa: F401
