"""Generation loop + engine (reference generate(), tokenizer.cpp:321-394).

Engine wraps the jitted forward (single-chip or tensor-parallel) behind the
reference's `Inference::infer(token, pos) -> logits` shape
(transformer-tasks.cpp:535-547), and the loop reproduces the reference's
observable behavior: prompt tokens forced one at a time, sampling after the
prompt, stop on BOS, per-token stats line and final averages.

Stats: the reference splits per-token time into I (inference) and T (transfer)
via task-type timing (utils.cpp:104-106) and counts socket bytes. Under XLA
the collectives are fused into the step, so we report:
  I = device step time (jitted forward, block_until_ready)
  T = host-side time (logits transfer + sampling + loop overhead)
  S/R = analytic per-token collective bytes (parallel/comm_stats.py)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import numpy as np

from ..io.tokenizer import BOS, Tokenizer
from ..models.llama import forward, init_cache
from ..models.spec import TransformerSpec
from ..obs.log import log_event
from ..obs.metrics import summarize_values
from ..parallel.comm_stats import (CommStats, ici_all_gather_bytes,
                                   sp_lse_bytes, tp_scheme)
from .sampling import Sampler


class Engine:
    """Owns params + cache + the jitted step; exposes infer(token, pos)."""

    def __init__(self, spec: TransformerSpec, params: dict[str, Any],
                 mesh=None, cache_dtype=None, fast_prefill: bool = False):
        import functools

        import jax
        import jax.numpy as jnp

        self.spec = spec
        self.jnp = jnp
        self.mesh = mesh
        self.fast_prefill = fast_prefill
        # f32 = logit-parity default; bf16 halves cache memory + attention
        # HBM traffic (the reference's cache is f32, transformer.cpp:198-199)
        self.cache_dtype = cache_dtype or jnp.float32
        self.tp = mesh.shape["tp"] if mesh is not None else 1
        self.sp = mesh.shape.get("sp", 1) if mesh is not None else 1
        self.sharded = self.tp > 1 or self.sp > 1
        # resolved ONCE: the engine's program, its comm accounting, and the
        # stats line all describe the same collective schedule
        self.tp_scheme = tp_scheme()
        self._loops: dict = {}  # (temp, topp) -> compiled device loop
        if self.sharded:
            from ..parallel import (make_sharded_forward, shard_cache,
                                    shard_params, validate_sharding)

            validate_sharding(spec, mesh)  # clear error before any device_put
            self.params = shard_params(params, mesh, scheme=self.tp_scheme)
            self.cache = shard_cache(init_cache(spec, self.cache_dtype), mesh)
            self._fwd = make_sharded_forward(spec, mesh,
                                             scheme=self.tp_scheme)
            self._step_raw = self._fwd  # shard_map wrapper; traceable in scan
        else:
            from ..models.llama import params_to_device

            self.params = params_to_device(params, spec=spec)
            self.cache = init_cache(spec, self.cache_dtype)
            self._step_raw = functools.partial(forward, spec)
            self._fwd = jax.jit(self._step_raw, donate_argnums=1)
        if fast_prefill:
            # a SECOND compiled forward, traced under bf16 matmul precision
            # (ops/linear.bf16_prefill) — used only for T>8 prefill chunks;
            # decode and the T=1 prefill tail keep the parity program.
            # Documented tolerance: tests/test_prefill.py pins the
            # prefilled-cache drift bound.
            from ..ops.linear import bf16_prefill

            self._fwd_prefill = jax.jit(bf16_prefill(self._step_raw),
                                        donate_argnums=1)
        else:
            self._fwd_prefill = None

    def infer(self, token: int, pos: int) -> np.ndarray:
        """One decode step; returns f32 logits (vocab,). Blocks on device."""
        tok = self.jnp.asarray([token], dtype=self.jnp.int32)
        logits, self.cache = self._fwd(self.params, self.cache, tok,
                                       self.jnp.int32(pos))
        return np.asarray(logits[0])  # dlint: allow[D001] host sampler input

    def prefill(self, tokens: list[int], pos0: int = 0,
                chunk: int = 128) -> None:
        """Fill the KV cache for ``tokens`` at positions pos0.. in T=chunk
        forward passes — the prompt fast path (the reference replays its
        T=1 decode per prompt token, tokenizer.cpp:352-366; chunked T>1
        runs ~20x the tokens/s on TPU because the matmuls become MXU work).

        Chunks are FIXED-size (one XLA compilation): the tail pads with
        token 0 and simply writes junk at positions past the real prefix.
        That junk is invisible and short-lived — decode always writes cache
        slot p before attending 0..p, so every padded slot is overwritten
        before anything reads it. A padded window that would cross seq_len
        is NOT issued (dynamic_update_slice would clamp the start and shift
        the writes back over real positions); that tail runs as T=1 steps,
        reusing the decode compilation. Logits are discarded; callers
        continue with the next real token through the decode path.

        Two or more full windows run as ONE device program (a fori_loop
        over the chunk index with the cache donated through): the tunneled
        runtime charges a fixed ~100 ms dispatch per launched chain, so a
        7680-token prompt at chunk 1920 pays it once instead of 4x —
        measured prefill ladder, BASELINE.md r3. The traced chunk-count
        bound means one compilation per chunk size serves every prompt
        length.
        """
        jnp = self.jnp
        seq_len = self.spec.seq_len
        if pos0 + len(tokens) > seq_len:
            # fail loudly before any cache write: past here the fused path
            # would raise an opaque numpy broadcast error and the unfused
            # path would clamp cache writes — divergent, silent corruption
            raise ValueError(
                f"prefill overflow: pos0={pos0} + {len(tokens)} tokens "
                f"> seq_len={seq_len}")
        c = min(chunk, seq_len)
        n_full = len(tokens) // c
        rest, rest_pos = tokens, pos0
        if n_full >= 2 and c > 8:
            import numpy as _np

            max_chunks = seq_len // c
            mat = _np.zeros((max_chunks, c), _np.int32)
            # dlint: allow[D001] host prompt list -> numpy, no device value
            mat[:n_full] = _np.asarray(tokens[:n_full * c],
                                       _np.int32).reshape(n_full, c)
            self.cache = self._prefill_loop(c)(
                self.params, self.cache, jnp.asarray(mat),
                jnp.int32(pos0), jnp.int32(n_full))
            rest = tokens[n_full * c:]
            rest_pos = pos0 + n_full * c
        if not rest:
            return

        def fwd(part, start):
            # fast-prefill (bf16) applies to the T>8 MXU-bound chunks only;
            # the T=1 tail shares the decode parity program
            f = (self._fwd_prefill if self._fwd_prefill is not None
                 and len(part) > 8 else self._fwd)
            _, self.cache = f(self.params, self.cache,
                              jnp.asarray(part, jnp.int32),
                              jnp.int32(start))

        run_chunked_prefill(fwd, rest, rest_pos, chunk, seq_len)

    def _prefill_loop(self, chunk: int):
        """Compiled whole-prompt prefill (cached per chunk size): fori_loop
        over full T=chunk windows, cache donated, chunk count traced. Traces
        under the engine's prefill precision (bf16_prefill when
        fast_prefill is set, parity otherwise)."""
        import jax

        key = ("prefill", chunk)
        if key not in self._loops:
            jnp = self.jnp
            step = self._step_raw
            if self.fast_prefill:
                from ..ops.linear import bf16_prefill

                step = bf16_prefill(step)

            def run(params, cache, toks_mat, pos0, n_chunks):
                def body(i, cache):
                    part = jax.lax.dynamic_index_in_dim(
                        toks_mat, i, 0, keepdims=False)
                    _, cache = step(params, cache, part,
                                    pos0 + i * jnp.int32(chunk))
                    return cache
                return jax.lax.fori_loop(0, n_chunks, body, cache)

            self._loops[key] = jax.jit(run, donate_argnums=1)
        return self._loops[key]

    def decode_loop(self, temperature: float, topp: float):
        """Compiled on-device generation loop for this engine (cached).

        Keyed on the sampling config ONLY: the step budget rides through
        the loop as a traced bound (decode.make_decode_loop), so changing
        --steps costs nothing — one seq_len-shaped compilation serves
        every budget (VERDICT r1 #6: the old (steps, temp, topp) key
        recompiled the full chain per distinct --steps)."""
        from .decode import make_decode_loop

        key = (temperature, topp)
        if key not in self._loops:
            self._loops[key] = make_decode_loop(
                self._step_raw, self.spec.seq_len, temperature, topp)
        return self._loops[key]

    def reset(self):
        self.cache = init_cache(self.spec, self.cache_dtype)
        if self.sharded:
            from ..parallel import shard_cache

            self.cache = shard_cache(self.cache, self.mesh)

    def comm_stats(self) -> CommStats:
        tp_st = ici_all_gather_bytes(self.spec, self.tp, self.tp_scheme)
        sp_st = sp_lse_bytes(self.spec, self.sp, self.tp)
        return CommStats(tp_st.sent_bytes + sp_st.sent_bytes,
                         tp_st.recv_bytes + sp_st.recv_bytes)


def run_chunked_prefill(fwd, tokens: list[int], pos0: int, chunk: int,
                        seq_len: int) -> None:
    """The ONE fixed-chunk prefill schedule, shared by Engine.prefill and
    the continuous engine's admission prefill: full T=chunk windows, a
    zero-padded partial window when it stays inside seq_len, and a per-token
    tail when padding would cross seq_len (dynamic_update_slice would clamp
    the start and shift writes over real positions). ``fwd(part, start)``
    runs one forward pass and owns the cache state."""
    chunk = min(chunk, seq_len)
    for lo in range(0, len(tokens), chunk):
        part = tokens[lo:lo + chunk]
        start = pos0 + lo
        if len(part) == chunk:
            fwd(part, start)
        elif start + chunk <= seq_len:
            fwd(part + [0] * (chunk - len(part)), start)
        else:  # padded window would cross seq_len: per-token tail
            for i, t in enumerate(part):
                fwd([t], start + i)


@dataclasses.dataclass
class GenStats:
    tokens: int = 0
    total_ms: float = 0.0
    infer_ms: float = 0.0
    host_ms: float = 0.0
    final_pos: int = 0    # next step's pos — checkpoint/resume anchor
    final_token: int = 0  # next step's input token
    token_ms: list = dataclasses.field(default_factory=list)
    # ^ per-token wall ms (per-step loop only; the fused loop is one
    #   device program) — feeds the final-line latency histogram summary
    prompt_rest: list = dataclasses.field(default_factory=list)
    # ^ prompt tokens NOT yet consumed when the run ended (forced-token tail
    #   for a resumed continuation; empty once the prompt is exhausted)

    @property
    def avg(self) -> tuple[float, float, float]:
        n = max(self.tokens, 1)
        return self.total_ms / n, self.infer_ms / n, self.host_ms / n


def _prefill_prefix(engine: Engine, prompt_tokens: list[int], steps: int,
                    chunk: int, out_tokens: list[int],
                    emit: Callable[[str], None] | None,
                    tokenizer) -> int | None:
    """Shared prefill gate for both loops: fill the cache for the prompt
    prefix in T=chunk passes and echo the forced tokens into ``out_tokens``
    (the loops append forced prompt tokens to the output — reference
    behavior — so the prefilled region must appear there too).

    Returns the start position for the decode loop (= len(prompt) - 1), or
    None when prefill doesn't apply (short prompt, or prompt doesn't fit in
    ``steps`` — then the per-token path keeps the reference's forced-token
    output semantics exactly).
    """
    from ..io.tokenizer import BOS as _BOS

    n_pre = len(prompt_tokens) - 1
    if chunk <= 1 or n_pre < 2 or n_pre >= steps:
        return None
    if _BOS in prompt_tokens[1:]:
        # a mid-stream BOS stops the per-token loop (tokenizer.cpp:376);
        # only that path reproduces the truncated output
        return None
    engine.prefill(prompt_tokens[:n_pre], 0, chunk)
    prev = prompt_tokens[0]
    for t in prompt_tokens[1:n_pre + 1]:
        out_tokens.append(t)
        if emit is not None:
            piece = tokenizer.decode_piece(prev, t)
            emit(piece.decode("utf-8", errors="replace"))
        prev = t
    return n_pre


def generate(engine: Engine, tokenizer: Tokenizer, sampler: Sampler,
             prompt: str, steps: int,
             emit: Callable[[str], None] | None = None,
             quiet: bool = False,
             resume: tuple[int, int] | None = None,
             resume_prompt: list[int] | None = None,
             prefill_chunk: int = 0) -> tuple[list[int], GenStats]:
    """Reference generation loop (tokenizer.cpp:321-394).

    Encodes the prompt with BOS (no EOS), forces prompt tokens, samples after,
    stops early on BOS, prints the per-token stats line and final averages.

    ``resume=(pos, token)`` continues an interrupted generation instead of
    starting one: the engine's cache and the sampler's RNG must have been
    restored first (runtime/checkpoint.py), the prompt argument is ignored
    (``resume_prompt`` carries any prompt tail the interrupted run had not
    yet consumed — GenStats.prompt_rest), and up to ``steps`` more positions
    run.

    ``prefill_chunk > 1`` fills the cache for the prompt prefix in chunked
    T>1 passes (Engine.prefill) instead of forcing tokens through the T=1
    decode path — the same output token stream, minus the per-prompt-token
    stats lines (those positions never run the loop; stats cover the decode
    phase).
    """
    spec = engine.spec
    out_tokens: list[int] = []
    if resume is not None:
        start_pos, token = resume
        # re-anchor the unconsumed prompt tail at absolute positions: the
        # loop forces prompt_tokens[pos + 1], so pad the consumed prefix
        prompt_tokens = ([-1] * (start_pos + 1)) + list(resume_prompt or [])
        steps = min(start_pos + steps, spec.seq_len)
    else:
        start_pos, steps = 0, min(steps, spec.seq_len)
        prompt_tokens = tokenizer.encode(prompt or "", bos=True, eos=False)
        if not prompt_tokens:
            raise ValueError(
                "something is wrong, expected at least 1 prompt token")
        token = prompt_tokens[0]
        pre = _prefill_prefix(engine, prompt_tokens, steps, prefill_chunk,
                              out_tokens, emit, tokenizer)
        if pre is not None:
            start_pos, token = pre, prompt_tokens[pre]

    comm = engine.comm_stats()
    stats = GenStats(final_pos=start_pos, final_token=token)
    pos = start_pos
    while pos < steps:
        t0 = time.perf_counter()
        logits = engine.infer(token, pos)
        t1 = time.perf_counter()

        if pos + 1 < len(prompt_tokens):
            next_token = prompt_tokens[pos + 1]
        else:
            next_token = sampler.sample(logits)
        t2 = time.perf_counter()

        gen_ms = (t2 - t0) * 1000
        stats.tokens += 1
        stats.total_ms += gen_ms
        stats.infer_ms += (t1 - t0) * 1000
        stats.host_ms += (t2 - t1) * 1000
        stats.token_ms.append(gen_ms)

        pos += 1
        stats.final_pos, stats.final_token = pos, int(next_token)
        stats.prompt_rest = [t for t in prompt_tokens[pos + 1:] if t >= 0]
        if next_token == BOS:
            break  # reference stops on BOS before decoding it (tokenizer.cpp:376)
        out_tokens.append(next_token)
        piece = tokenizer.decode_piece(token, next_token)
        if emit is not None:
            emit(piece.decode("utf-8", errors="replace"))
        if not quiet:
            # the 🔶 reference stats line, or one NDJSON object per token
            # with the same fields under DLLAMA_LOG_JSON=1 (obs/log.py)
            log_event(
                "decode.token",
                f"🔶 G {gen_ms:7.2f} ms I {(t1 - t0) * 1000:7.2f} ms "
                f"T {(t2 - t1) * 1000:7.2f} ms "
                f"S {comm.sent_bytes / 1024:7.0f} kB "
                f"R {comm.recv_bytes / 1024:7.0f} kB "
                f"{piece.decode('utf-8', errors='replace')!r}",
                pos=pos, token=int(next_token),
                gen_ms=round(gen_ms, 3),
                infer_ms=round((t1 - t0) * 1000, 3),
                host_ms=round((t2 - t1) * 1000, 3),
                sent_bytes=comm.sent_bytes, recv_bytes=comm.recv_bytes,
                piece=piece.decode("utf-8", errors="replace"))
        token = next_token

    if stats.tokens:
        # the SAME summary shape the serving metrics expose (/health,
        # bench.py rows): p50/p95/p99 over the per-token wall times plus
        # the analytic per-token collective bytes
        lat = summarize_values(stats.token_ms)
        if not quiet:
            g, i, t = stats.avg
            print(f"Generated tokens:    {stats.tokens}")
            print(f"Avg generation time: {g:.2f} ms")
            print(f"Avg inference time:  {i:.2f} ms")
            print(f"Avg transfer time:   {t:.2f} ms")
            print(f"Latency ms/token:    p50 {lat['p50']:.2f}  "
                  f"p95 {lat['p95']:.2f}  p99 {lat['p99']:.2f} | "
                  f"ICI S {comm.sent_bytes / 1024:.0f} kB "
                  f"R {comm.recv_bytes / 1024:.0f} kB /token")
        log_event("run.summary", None, tokens=stats.tokens,
                  avg_ms=round(stats.total_ms / stats.tokens, 3),
                  latency_ms={k: round(v, 3) for k, v in lat.items()},
                  sent_bytes_per_token=comm.sent_bytes,
                  recv_bytes_per_token=comm.recv_bytes)
    return out_tokens, stats


def generate_batch(spec: TransformerSpec, params: dict[str, Any],
                   tokenizer: Tokenizer, prompts: list[str], steps: int,
                   temperature: float, topp: float, seed: int,
                   cache_dtype=None, mesh=None,
                   quiet: bool = False) -> tuple[list[list[int]], GenStats]:
    """Generate for B prompts in one fused lockstep batch.

    A capability extension (the reference is strictly batch=1): all rows
    decode in lockstep via models/llama.forward_batch; ragged prompts
    right-pad and start sampling when their own prompt runs out. Each row
    samples from its own xorshift stream seeded ``seed + row`` (batch has
    no single-stream reference semantics to preserve). Rows stop at BOS on
    the host, like generate().

    With a ``mesh`` (tp > 1) the step runs tensor-parallel: weights in
    MatmulSlice bands, batched cache kv-head-sharded, same per-layer
    collectives as the B=1 sharded path (parallel/tp.py).
    """
    import jax
    import jax.numpy as jnp

    from ..models.llama import init_cache_batch, params_to_device
    from ..utils.rng import Xorshift64
    from .decode import make_batch_decode_loop

    B = len(prompts)
    steps = min(steps, spec.seq_len)
    dtype = cache_dtype or jnp.float32
    toks_per_row = [tokenizer.encode(p or "", bos=True, eos=False)
                    for p in prompts]
    padded = np.full((B, steps + 1), -1, dtype=np.int32)
    coins = np.zeros((B, steps), dtype=np.float32)
    for b, pt in enumerate(toks_per_row):
        pt = pt[:steps + 1]
        padded[b, :len(pt)] = pt
        n_sampled = steps - (len(pt) - 1)
        if n_sampled > 0 and temperature != 0.0:
            coins[b, len(pt) - 1:] = Xorshift64(seed + b).f32_array(n_sampled)

    if mesh is not None and (mesh.shape["tp"] > 1
                             or mesh.shape.get("sp", 1) > 1):
        from ..parallel import (make_sharded_forward_batch, shard_cache_batch,
                                shard_params, validate_sharding)

        scheme = tp_scheme()  # one resolution for program + params
        validate_sharding(spec, mesh)
        dev_params = shard_params(params, mesh, scheme=scheme)
        cache0 = shard_cache_batch(init_cache_batch(spec, B, dtype), mesh)
        step_fn = make_sharded_forward_batch(spec, mesh, scheme=scheme)
        run = make_batch_decode_loop(spec, steps, temperature, topp,
                                     step_fn=step_fn)
    else:
        dev_params = params_to_device(params)  # batch: T>1 paths, no mega prep
        cache0 = init_cache_batch(spec, B, dtype)
        run = make_batch_decode_loop(spec, steps, temperature, topp)
    t0 = time.perf_counter()
    toks, _ = run(dev_params, cache0,
                  jnp.asarray(padded),
                  jnp.asarray([p[0] for p in toks_per_row], jnp.int32),
                  jnp.asarray(coins))
    toks = np.asarray(toks)  # dlint: allow[D001] whole-chain result drain
    total_ms = (time.perf_counter() - t0) * 1000

    outs: list[list[int]] = []
    for b in range(B):
        row: list[int] = []
        for t in map(int, toks[b]):
            if t == BOS:
                break
            row.append(t)
        outs.append(row)
        if not quiet:
            prev = toks_per_row[b][0]
            text = b""
            for t in row:
                text += tokenizer.decode_piece(prev, t)
                prev = t
            print(f"[{b}] {text.decode('utf-8', errors='replace')!r}")
    n_tokens = sum(len(r) for r in outs)
    stats = GenStats(tokens=n_tokens, total_ms=total_ms, infer_ms=total_ms)
    if not quiet:
        print(f"Generated tokens:    {n_tokens} across {B} rows")
        print(f"Avg generation time: {total_ms / max(1, B * steps):.2f} "
              f"ms/token ({B} rows x {steps} lockstep steps)")
    return outs, stats


def generate_fast(engine: Engine, tokenizer: Tokenizer, sampler: Sampler,
                  prompt: str, steps: int,
                  quiet: bool = False,
                  resume: tuple[int, int] | None = None,
                  resume_prompt: list[int] | None = None,
                  prefill_chunk: int = 0) -> tuple[list[int], GenStats]:
    """The fused-loop generation path: one device program for the whole chain.

    Same observable token stream as generate() (forced prompt, reference
    sampler semantics via runtime/decode.py, stop on BOS) but per-token
    timing collapses into one on-device scan — the TPU-idiomatic hot path.
    Pieces and the averaged stats line print after the device loop returns.

    ``resume=(pos, token)`` continues an interrupted generation (same
    contract as generate(): cache + sampler RNG restored first via
    runtime/checkpoint.py, ``resume_prompt`` is the unconsumed prompt tail,
    up to ``steps`` more positions run) — the scan simply starts its
    position clock at ``pos``.

    ``prefill_chunk > 1``: the prompt prefix fills the cache in chunked
    T>1 passes (Engine.prefill) and the fused chain starts at the last
    prompt token — same output stream, far less time on long prompts.
    """
    spec = engine.spec
    pre_out: list[int] = []
    if resume is not None:
        start_pos, first = resume
        # the loop's forced stream is relative to the chain: [first] + tail
        prompt_tokens = [first] + list(resume_prompt or [])
        steps = min(steps, spec.seq_len - start_pos)
    else:
        start_pos = 0
        steps = min(steps, spec.seq_len)
        prompt_tokens = tokenizer.encode(prompt or "", bos=True, eos=False)
        if not prompt_tokens:
            raise ValueError(
                "something is wrong, expected at least 1 prompt token")
        emit_fn = None if quiet else (
            lambda s: print(s, end="", flush=True))
        pre = _prefill_prefix(engine, prompt_tokens, steps, prefill_chunk,
                              pre_out, emit_fn, tokenizer)
        if pre is not None:
            # chain takes over at the last prompt token; its forced stream
            # is empty (relative prompt = [prompt[-1]]), clock starts at pre
            start_pos = pre
            prompt_tokens = prompt_tokens[pre:]
            steps = steps - pre
    prompt_tail = prompt_tokens[steps + 1:]  # beyond this chain: resume tail
    if len(prompt_tokens) > steps + 1:
        prompt_tokens = prompt_tokens[:steps + 1]

    run = engine.decode_loop(sampler.temperature, sampler.topp)

    jnp = engine.jnp
    # buffers are seq_len-shaped (the loop's ONE compiled shape); the actual
    # budget rides in as the traced num_steps bound
    max_steps = spec.seq_len
    padded = np.full((max_steps + 1,), -1, dtype=np.int32)
    padded[:len(prompt_tokens)] = prompt_tokens
    # pre-draw the xorshift coins for every potentially-sampled step, in the
    # order the device consumes them (positions >= len(prompt)-1); drawn on a
    # THROWAWAY copy of the rng so the sampler's stream can be rewound to
    # exactly what the per-step loop would have consumed (BOS early stop
    # means later coins were never "really" drawn)
    coins = np.zeros((max_steps,), dtype=np.float32)
    n_sampled = steps - (len(prompt_tokens) - 1)
    if n_sampled > 0 and sampler.temperature != 0.0:
        coins[len(prompt_tokens) - 1:steps] = sampler.rng.clone().f32_array(
            n_sampled)

    t0 = time.perf_counter()
    toks, engine.cache = run(engine.params, engine.cache,
                             jnp.asarray(padded),
                             jnp.int32(prompt_tokens[0]), jnp.asarray(coins),
                             jnp.int32(start_pos), jnp.int32(steps))
    toks = np.asarray(toks)  # dlint: allow[D001] whole-chain result drain
    total_ms = (time.perf_counter() - t0) * 1000

    out_tokens: list[int] = list(pre_out)  # prefilled prompt echo, if any
    prev = prompt_tokens[0]
    for t in map(int, toks):
        if t == BOS:
            break
        out_tokens.append(t)
        if not quiet:
            piece = tokenizer.decode_piece(prev, t)
            print(piece.decode("utf-8", errors="replace"), end="", flush=True)
        prev = t
    # all chain accounting is in CHAIN terms: out_tokens also carries the
    # prefill-echoed prompt tokens, which the chain never produced
    chain_generated = len(out_tokens) - len(pre_out)
    # advance the sampler's real stream by only the coins the per-step loop
    # would have consumed: one per SAMPLED iteration, including the one that
    # produced a terminating BOS (the loop breaks after drawing it)
    if n_sampled > 0 and sampler.temperature != 0.0:
        early_bos = chain_generated < steps
        last_iter = chain_generated if early_bos else steps - 1
        consumed = max(0, last_iter - (len(prompt_tokens) - 1) + 1)
        if consumed:
            sampler.rng.f32_array(min(consumed, n_sampled))
    # stats cover the timed fused chain (like generate()'s loop iterations;
    # the prefill phase is separate work and would deflate ms/token)
    n = max(1, chain_generated)
    stats = GenStats(tokens=chain_generated, total_ms=total_ms,
                     infer_ms=total_ms, host_ms=0.0)
    early_bos = chain_generated < steps
    if steps > 0 and not early_bos:  # no early BOS: resumable
        # the buffer is seq_len long; the chain's last written slot is
        # steps-1 (slots past it are BOS padding)
        stats.final_pos = start_pos + steps
        stats.final_token = int(toks[steps - 1])
        stats.prompt_rest = prompt_tail
    # the while_loop stops on a produced BOS: executed = generated
    # tokens + the terminating step, not the whole budget
    executed = chain_generated + 1 if early_bos else steps
    if not quiet:
        print(f"\nGenerated tokens:    {stats.tokens}")
        print(f"Avg generation time: {total_ms / n:.2f} ms "
              f"(fused loop, {executed} device steps)")
    log_event("run.summary", None, tokens=stats.tokens,
              avg_ms=round(total_ms / n, 3), fused=True,
              device_steps=executed)
    return out_tokens, stats
