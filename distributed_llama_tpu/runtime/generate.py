"""Generation loop + engine (reference generate(), tokenizer.cpp:321-394).

Engine wraps the jitted forward (single-chip or tensor-parallel) behind the
reference's `Inference::infer(token, pos) -> logits` shape
(transformer-tasks.cpp:535-547), and the loop reproduces the reference's
observable behavior: prompt tokens forced one at a time, sampling after the
prompt, stop on BOS, per-token stats line and final averages.

Stats: the reference splits per-token time into I (inference) and T (transfer)
via task-type timing (utils.cpp:104-106) and counts socket bytes. Under XLA
the collectives are fused into the step, so we report:
  I = device step time (jitted forward, block_until_ready)
  T = host-side time (logits transfer + sampling + loop overhead)
  S/R = analytic per-token collective bytes (parallel/comm_stats.py)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import numpy as np

from ..io.tokenizer import BOS, Tokenizer
from ..models.llama import KVCache, forward, init_cache
from ..models.spec import TransformerSpec
from ..parallel.comm_stats import (CommStats, ici_all_gather_bytes,
                                   sp_lse_bytes)
from .sampling import Sampler


class Engine:
    """Owns params + cache + the jitted step; exposes infer(token, pos)."""

    def __init__(self, spec: TransformerSpec, params: dict[str, Any],
                 mesh=None):
        import functools

        import jax
        import jax.numpy as jnp

        self.spec = spec
        self.jnp = jnp
        self.mesh = mesh
        self.tp = mesh.shape["tp"] if mesh is not None else 1
        self.sp = mesh.shape.get("sp", 1) if mesh is not None else 1
        self.sharded = self.tp > 1 or self.sp > 1
        if self.sharded:
            from ..parallel import (make_sharded_forward, shard_cache,
                                    shard_params)

            self.params = shard_params(params, mesh)
            self.cache = shard_cache(init_cache(spec), mesh)
            self._fwd = make_sharded_forward(spec, mesh)
        else:
            from ..models.llama import params_to_device

            self.params = params_to_device(params)
            self.cache = init_cache(spec)
            self._fwd = jax.jit(
                functools.partial(forward, spec), donate_argnums=1)

    def infer(self, token: int, pos: int) -> np.ndarray:
        """One decode step; returns f32 logits (vocab,). Blocks on device."""
        tok = self.jnp.asarray([token], dtype=self.jnp.int32)
        logits, self.cache = self._fwd(self.params, self.cache, tok,
                                       self.jnp.int32(pos))
        return np.asarray(logits[0])

    def reset(self):
        self.cache = init_cache(self.spec)
        if self.sharded:
            from ..parallel import shard_cache

            self.cache = shard_cache(self.cache, self.mesh)

    def comm_stats(self) -> CommStats:
        tp_st = ici_all_gather_bytes(self.spec, self.tp)
        sp_st = sp_lse_bytes(self.spec, self.sp, self.tp)
        return CommStats(tp_st.sent_bytes + sp_st.sent_bytes,
                         tp_st.recv_bytes + sp_st.recv_bytes)


@dataclasses.dataclass
class GenStats:
    tokens: int = 0
    total_ms: float = 0.0
    infer_ms: float = 0.0
    host_ms: float = 0.0

    @property
    def avg(self) -> tuple[float, float, float]:
        n = max(self.tokens, 1)
        return self.total_ms / n, self.infer_ms / n, self.host_ms / n


def generate(engine: Engine, tokenizer: Tokenizer, sampler: Sampler,
             prompt: str, steps: int,
             emit: Callable[[str], None] | None = None,
             quiet: bool = False) -> tuple[list[int], GenStats]:
    """Reference generation loop (tokenizer.cpp:321-394).

    Encodes the prompt with BOS (no EOS), forces prompt tokens, samples after,
    stops early on BOS, prints the per-token stats line and final averages.
    """
    spec = engine.spec
    steps = min(steps, spec.seq_len)
    prompt_tokens = tokenizer.encode(prompt or "", bos=True, eos=False)
    if not prompt_tokens:
        raise ValueError("something is wrong, expected at least 1 prompt token")

    comm = engine.comm_stats()
    stats = GenStats()
    out_tokens: list[int] = []
    token = prompt_tokens[0]
    pos = 0
    while pos < steps:
        t0 = time.perf_counter()
        logits = engine.infer(token, pos)
        t1 = time.perf_counter()

        if pos + 1 < len(prompt_tokens):
            next_token = prompt_tokens[pos + 1]
        else:
            next_token = sampler.sample(logits)
        t2 = time.perf_counter()

        gen_ms = (t2 - t0) * 1000
        stats.tokens += 1
        stats.total_ms += gen_ms
        stats.infer_ms += (t1 - t0) * 1000
        stats.host_ms += (t2 - t1) * 1000

        pos += 1
        if next_token == BOS:
            break  # reference stops on BOS before decoding it (tokenizer.cpp:376)
        out_tokens.append(next_token)
        piece = tokenizer.decode_piece(token, next_token)
        if emit is not None:
            emit(piece.decode("utf-8", errors="replace"))
        if not quiet:
            print(f"🔶 G {gen_ms:7.2f} ms I {(t1 - t0) * 1000:7.2f} ms "
                  f"T {(t2 - t1) * 1000:7.2f} ms "
                  f"S {comm.sent_bytes / 1024:7.0f} kB "
                  f"R {comm.recv_bytes / 1024:7.0f} kB "
                  f"{piece.decode('utf-8', errors='replace')!r}")
        token = next_token

    if not quiet and stats.tokens:
        g, i, t = stats.avg
        print(f"Generated tokens:    {stats.tokens}")
        print(f"Avg generation time: {g:.2f} ms")
        print(f"Avg inference time:  {i:.2f} ms")
        print(f"Avg transfer time:   {t:.2f} ms")
    return out_tokens, stats
