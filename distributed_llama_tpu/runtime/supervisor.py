"""Serving supervision: step watchdog, health state machine, crash-loop
backoff (ISSUE 9).

Three independent pieces the crash-safe server composes:

* ``StepWatchdog`` — a deadline on each device dispatch. The scheduler
  thread arms it just before launching a step and disarms it once the
  host outputs land; a monitor thread fires ``on_hang`` when a dispatch
  overruns its deadline (a wedged device runtime, a hung collective, a
  dead tunnel). Detection only: the watchdog cannot cancel device work —
  it marks the server DEGRADED and logs, and the ``--supervise`` wrapper
  (or the operator) decides whether to restart. A dispatch that
  eventually completes after tripping disarms normally and the health
  machine recovers to SERVING.
* ``HealthMonitor`` — the starting/serving/degraded/draining/stopped
  state machine, surfaced in ``/health`` as ``"state"`` and as the
  ``dllama_health_state`` gauge (numeric code; see ``HEALTH_CODES``).
  Transitions are validated: a server cannot leave ``stopped``, and
  ``draining`` only moves to ``stopped`` — anything else is a
  programming error and raises.
* ``CrashLoopBackoff`` + ``supervise()`` — the ``serve --supervise``
  wrapper: respawn the serve child when it dies non-zero, doubling the
  delay for RAPID crash loops (a child that served healthily for
  ``healthy_s`` resets the backoff), forwarding SIGTERM to the child so
  graceful drain (runtime/server.py) runs exactly once, and exiting
  with the child's code once it exits 0 or the restart budget is spent.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import threading
import time

from ..obs.log import log_event

HEALTH_STATES = ("starting", "serving", "degraded", "draining", "stopped")
HEALTH_CODES = {s: i for i, s in enumerate(HEALTH_STATES)}
_TRANSITIONS = {
    "starting": {"serving", "draining", "stopped"},
    "serving": {"degraded", "draining", "stopped"},
    "degraded": {"serving", "draining", "stopped"},
    "draining": {"stopped"},
    "stopped": set(),
}


class HealthMonitor:
    """The serving health state machine (module docstring). Thread-safe:
    the scheduler, watchdog monitor, and signal paths all transition."""

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._state = "starting"
        self._gauge = None
        if registry is not None:
            self._gauge = registry.gauge(
                "dllama_health_state",
                "Serving health state machine: 0=starting 1=serving "
                "2=degraded 3=draining 4=stopped")
            self._gauge.set(HEALTH_CODES[self._state])

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def to(self, state: str) -> bool:
        """Transition; returns True if the state changed. Same-state is a
        no-op, an ILLEGAL transition raises — with two fault-path
        carve-outs (bookkeeping must never crash a fault handler):
        ``stopped`` is enterable from any live state, and ``degraded``
        from any state still ADMITTING (starting/serving). ``draining``
        stays one-way: a watchdog trip mid-drain must NOT reopen
        admission by bouncing through degraded -> serving."""
        if state not in HEALTH_CODES:
            raise ValueError(f"unknown health state {state!r}")
        with self._lock:
            if state == self._state:
                return False
            if (state not in _TRANSITIONS[self._state]
                    and not (state == "stopped"
                             and self._state != "stopped")
                    and not (state == "degraded"
                             and self._state in ("starting", "serving"))):
                raise ValueError(
                    f"illegal health transition {self._state} -> {state}")
            prev, self._state = self._state, state
            if self._gauge is not None:
                self._gauge.set(HEALTH_CODES[state])
        # stderr: health transitions fire from library threads inside
        # tools whose stdout is a machine-readable artifact (loadcheck
        # --json) — diagnostics must not pollute it
        log_event("health.state", f"🌐 health: {prev} -> {state}",
                  file=sys.stderr, prev=prev, state=state)
        return True


class StepWatchdog:
    """Per-dispatch deadline (module docstring).

    ``arm()`` before the device call, ``disarm()`` after the host
    outputs sync; the monitor thread fires ``on_hang(elapsed_s)`` ONCE
    per armed dispatch that overruns ``timeout_s``. ``trips`` counts
    firings. Use as a context manager around the dispatch::

        with watchdog:            # arm ... disarm, exception-safe
            out = step(...)
    """

    def __init__(self, timeout_s: float, on_hang=None):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, "
                             f"got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.on_hang = on_hang
        self.trips = 0
        self._cond = threading.Condition()
        self._deadline: float | None = None
        self._armed_at = 0.0
        self._fired = False
        self._closed = False
        self._thread = threading.Thread(target=self._monitor, daemon=True,
                                        name="dllama-step-watchdog")
        self._thread.start()

    def arm(self) -> None:
        with self._cond:
            self._armed_at = time.monotonic()
            self._deadline = self._armed_at + self.timeout_s
            self._fired = False
            self._cond.notify()

    def disarm(self) -> None:
        with self._cond:
            self._deadline = None
            self._cond.notify()

    def __enter__(self):
        self.arm()
        return self

    def __exit__(self, *exc):
        self.disarm()
        return False

    @property
    def overdue(self) -> bool:
        """True while an armed dispatch has already overrun (the health
        recovery check: do not flip back to serving under a live hang)."""
        with self._cond:
            return (self._deadline is not None
                    and time.monotonic() >= self._deadline)

    def _monitor(self) -> None:
        while True:
            with self._cond:
                if self._closed:
                    return
                if self._deadline is None or self._fired:
                    self._cond.wait()
                    continue
                now = time.monotonic()
                if now < self._deadline:
                    self._cond.wait(self._deadline - now)
                    continue
                # overrun: fire once for this arm
                self._fired = True
                self.trips += 1
                elapsed = now - self._armed_at
            log_event("watchdog.trip",
                      f"🔶 watchdog: dispatch exceeded "
                      f"{self.timeout_s * 1e3:.0f} ms "
                      f"({elapsed * 1e3:.0f} ms and counting)",
                      file=sys.stderr, timeout_s=self.timeout_s,
                      elapsed_s=round(elapsed, 6))
            if self.on_hang is not None:
                try:
                    self.on_hang(elapsed)
                except Exception:  # noqa: BLE001 - a broken callback must
                    pass           # never kill the monitor thread

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout=5)


class CrashLoopBackoff:
    """Exponential restart delay for rapidly-crashing children.

    ``next_delay(uptime_s)`` is called after each non-zero child exit
    with how long that child lived: a child that survived at least
    ``healthy_s`` resets the delay to ``initial_s`` (the crash was news,
    not a loop); shorter lives double it up to ``max_s``."""

    def __init__(self, initial_s: float = 1.0, max_s: float = 60.0,
                 healthy_s: float = 30.0):
        self.initial_s = initial_s
        self.max_s = max_s
        self.healthy_s = healthy_s
        self._delay = 0.0

    def next_delay(self, uptime_s: float) -> float:
        if uptime_s >= self.healthy_s:
            self._delay = self.initial_s
        elif self._delay <= 0.0:
            self._delay = self.initial_s
        else:
            self._delay = min(self._delay * 2.0, self.max_s)
        return self._delay


def supervise(child_cmd: list[str], max_restarts: int | None = None,
              backoff: CrashLoopBackoff | None = None,
              sleep=time.sleep, popen=subprocess.Popen,
              install_signals: bool = True,
              flightrec_dir: str | None = None) -> int:
    """Run ``child_cmd`` under crash-loop supervision (``serve
    --supervise``). Restarts on non-zero exits with ``backoff`` delays;
    exits with the child's code on a clean 0 or once ``max_restarts``
    respawns are spent (None = unbounded). SIGTERM/SIGINT forward to the
    child — its graceful drain runs, it exits 0, and the supervisor
    exits 0 without respawning. With ``flightrec_dir`` set, every
    crash-loop respawn drops a flight-recorder bundle (ISSUE 15) from
    the SUPERVISOR's vantage — exit code, uptime, restart count, the
    spawn history ring — next to whatever bundles the child's own
    recorder managed to write before dying."""
    backoff = backoff or CrashLoopBackoff()
    terminating = {"flag": False}
    child_box: dict = {"proc": None}
    recorder = None
    if flightrec_dir is not None:
        from ..obs.flightrec import FlightRecorder

        recorder = FlightRecorder()

    def _forward(signum, frame):
        terminating["flag"] = True
        proc = child_box["proc"]
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)

    if install_signals:
        signal.signal(signal.SIGTERM, _forward)
        signal.signal(signal.SIGINT, _forward)

    restarts = 0
    while True:
        t0 = time.monotonic()
        proc = popen(child_cmd)
        child_box["proc"] = proc
        log_event("supervisor.spawn",
                  f"🌐 supervisor: child pid {proc.pid} started",
                  file=sys.stderr, pid=proc.pid, restarts=restarts)
        if recorder is not None:
            recorder.note("supervisor.spawn", pid=proc.pid,
                          restarts=restarts)
        rc = proc.wait()
        uptime = time.monotonic() - t0
        if recorder is not None and rc != 0 and not terminating["flag"]:
            # the crash-loop postmortem bundle: written BEFORE the
            # respawn, so an operator paging in mid-loop finds the
            # history even while the loop is still spinning
            recorder.note("supervisor.crash", rc=rc,
                          uptime_s=round(uptime, 3), restarts=restarts)
            try:
                recorder.dump(flightrec_dir, "crash_loop")
            except OSError:
                pass  # a failed dump must never block the respawn
        if rc == 0 or terminating["flag"]:
            log_event("supervisor.exit",
                      f"🌐 supervisor: child exited {rc} "
                      f"({'terminated' if terminating['flag'] else 'clean'})",
                      file=sys.stderr, rc=rc,
                      uptime_s=round(uptime, 3))
            return rc
        if max_restarts is not None and restarts >= max_restarts:
            log_event("supervisor.give_up",
                      f"🔶 supervisor: child crashed (exit {rc}) and the "
                      f"restart budget ({max_restarts}) is spent",
                      file=sys.stderr, rc=rc, restarts=restarts)
            return rc
        delay = backoff.next_delay(uptime)
        restarts += 1
        log_event("supervisor.restart",
                  f"🔶 supervisor: child crashed (exit {rc}) after "
                  f"{uptime:.1f}s; restart {restarts} in {delay:.1f}s",
                  file=sys.stderr, rc=rc, uptime_s=round(uptime, 3),
                  delay_s=delay, restarts=restarts)
        sleep(delay)


def serve_child_cmd(serve_argv: list[str]) -> list[str]:
    """The re-exec command for ``serve --supervise``: this interpreter,
    this package, the same serve argv minus the supervision flags (the
    child must SERVE, not recurse into another supervisor)."""
    stripped: list[str] = []
    skip = False
    for arg in serve_argv:
        if skip:
            skip = False
            continue
        if arg == "--supervise":
            continue
        if arg in ("--max-restarts",):
            skip = True
            continue
        if arg.startswith("--max-restarts="):
            continue
        stripped.append(arg)
    return [sys.executable, "-m", "distributed_llama_tpu", "serve",
            *stripped]
