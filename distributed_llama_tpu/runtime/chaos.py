"""Deterministic fault injection + chaos drills for the continuous engine.

The telemetry stack (obs/) can SHOW a leak or a wedged pool; nothing before
this module ever CAUSED one on purpose. Each drill here drives a fresh
engine through one failure mode the serving layer must absorb — pool
exhaustion, transient page starvation, oversized prompts, mid-stream client
disconnects, injected step-latency spikes, a profiler capture under load —
and then asserts the post-drill invariants that define "absorbed":

* no leaked pages or slots: every allocated page's refcount is explained
  by a live slot mapping or a radix-tree node (paging.PagedAllocator.audit
  — the introspection hooks exist for exactly this), the pool drains to
  free + tree-held == capacity, and every slot is free;
* metrics still scrapeable: the registry's Prometheus exposition parses;
* the engine still admits: a probe request runs to completion afterwards.

Injection is DETERMINISTIC — counters, not coin flips: "delay every Nth
dispatch", "deny the first N page allocations". A drill that fails
reproduces identically under the same config, which is the property that
makes tools/loadcheck.py a CI gate rather than a flake source. The
``ChaosMonkey`` hooks are consulted by the engine at three points
(pre-dispatch, page allocation, cancelled-retire release) and by
``serve --chaos`` for operator-driven drills against a live server.

``leak_on_cancel`` is the gate's MUTATION arm (ISSUE 8 satellite): it
makes the engine deliberately drop one page on every cancelled-request
release, which the disconnect drill's audit must flag — proving the red
path fires (tools/ci.sh asserts loadcheck exits 1 under it).
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class ChaosMonkey:
    """Deterministic fault-injection state, registered on an engine (the
    ``chaos=`` constructor knob) and/or a server. All knobs default OFF;
    counters record what actually fired so drills can assert injection
    happened.

    * ``step_delay_every``/``step_delay_s`` — sleep before every Nth
      device dispatch (a step-latency spike: a preempted host, a slow
      interconnect);
    * ``deny_pages`` — fail the first N page allocations (transient pool
      pressure without filling the pool);
    * ``leak_on_cancel`` — drop one page from every cancelled request's
      release (the seeded fault the invariant audit must catch).
    """

    step_delay_every: int = 0
    step_delay_s: float = 0.0
    deny_pages: int = 0
    leak_on_cancel: bool = False
    # injection counters (read by drills / surfaced in loadcheck rows)
    injected_delays: int = 0
    denied_allocs: int = 0
    leaked_pages: list = dataclasses.field(default_factory=list)
    _dispatches: int = 0

    def on_dispatch(self) -> None:
        """Engine hook: called once per device dispatch, before launch."""
        self._dispatches += 1
        if (self.step_delay_every > 0 and self.step_delay_s > 0
                and self._dispatches % self.step_delay_every == 0):
            self.injected_delays += 1
            time.sleep(self.step_delay_s)

    def deny_page(self) -> bool:
        """Engine hook: True = this page allocation must fail (the engine
        then takes its real dry-pool path: pause, requeue, breaker)."""
        if self.denied_allocs < self.deny_pages:
            self.denied_allocs += 1
            return True
        return False

    def filter_release(self, pages: list) -> list:
        """Engine hook on a cancelled request's page release: with
        ``leak_on_cancel`` armed, steal one page so it is never released —
        the deliberate leak the audit must then report."""
        if self.leak_on_cancel and pages:
            self.leaked_pages.append(pages.pop())
        return pages

    def injection_summary(self) -> dict:
        return {"dispatches": self._dispatches,
                "injected_delays": self.injected_delays,
                "denied_allocs": self.denied_allocs,
                "leaked_pages": len(self.leaked_pages)}

    @classmethod
    def parse(cls, text: str) -> "ChaosMonkey":
        """``key=value[,key=value...]`` (the --chaos CLI format): keys
        step_delay_every, step_delay_ms, deny_pages, leak_on_cancel."""
        kw: dict = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad chaos knob {part!r}: want key=value")
            key, val = part.split("=", 1)
            key = key.strip()
            if key == "step_delay_ms":
                kw["step_delay_s"] = float(val) / 1e3
            elif key in ("step_delay_every", "deny_pages"):
                kw[key] = int(val)
            elif key == "leak_on_cancel":
                kw[key] = val.strip().lower() not in ("0", "false", "")
            else:
                raise ValueError(
                    f"unknown chaos knob {key!r} (have step_delay_every, "
                    f"step_delay_ms, deny_pages, leak_on_cancel)")
        return cls(**kw)


@dataclasses.dataclass
class DrillResult:
    """One drill's verdict: ``passed`` is the gate bit; ``violations``
    lists every failed invariant (empty when passed); ``details`` carries
    the drill's observed counters for the loadcheck JSON row."""

    name: str
    passed: bool
    violations: list
    details: dict

    def to_json(self) -> dict:
        return {"name": self.name, "passed": self.passed,
                "violations": list(self.violations),
                "details": dict(self.details)}


def scrape_problems(registry) -> list[str]:
    """Parse the registry's Prometheus exposition; any unparseable sample
    line is a violation (a drill must not leave /metrics broken)."""
    if registry is None:
        return []
    try:
        text = registry.expose()
    except Exception as e:  # noqa: BLE001 - a raising scrape IS the finding
        return [f"/metrics exposition raised {type(e).__name__}: {e}"]
    problems = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            float(value)
        except ValueError:
            problems.append(f"unparseable exposition line: {line!r}")
        if not name:
            problems.append(f"sample line without a name: {line!r}")
    return problems


def check_invariants(eng, expect_drained: bool = True) -> list[str]:
    """The shared post-drill gate (module docstring): drained pool, page
    accounting clean, metrics scrapeable, engine still admitting."""
    problems: list[str] = []
    active = sum(not s.free for s in eng._pool)
    with eng._lock:
        queued = len(eng._queue)
    if expect_drained and (active or queued):
        problems.append(f"engine not drained: {active} active slots, "
                        f"{queued} queued requests")
    problems += [f"page audit: {p}" for p in eng.audit_pages()]
    if eng.allocator is not None:
        alloc = eng.allocator
        tree_held = sum(1 for _ in alloc.tree.nodes())
        slot_held = sum(len(s.pages) for s in eng._pool)
        # only decisive once slots drained: a shared-prefix page is held
        # by a slot AND the tree at once (the audit covers the live case)
        if (slot_held == 0
                and alloc.n_free + tree_held != alloc.n_pages):
            problems.append(
                f"page leak: {alloc.n_free} free + {tree_held} tree-held "
                f"!= {alloc.n_pages} pool pages with all slots drained")
    registry = eng._obs.registry if eng._obs is not None else None
    problems += scrape_problems(registry)
    # the engine must still admit and finish new work after the drill
    probe = [1, 7, 9]
    try:
        outs, _ = eng.run([probe], steps=3, quiet=True)
        if not outs[0]:
            problems.append("post-drill probe request produced no tokens")
    except Exception as e:  # noqa: BLE001 - a raising engine IS the finding
        problems.append(f"post-drill probe raised {type(e).__name__}: {e}")
    return problems


def _drain(eng, max_iters: int = 10_000) -> int:
    """Step until idle; returns iterations. Bounded — a scheduler that
    never drains is itself a drill failure (the caller sees active>0)."""
    it = 0
    while eng.step_many(eng.block_steps, quiet=True) and it < max_iters:
        it += 1
    return it


def _result(name: str, eng, chaos, extra_violations=(), **details):
    violations = list(extra_violations) + check_invariants(eng)
    if chaos is not None:
        details.update(chaos.injection_summary())
    return DrillResult(name=name, passed=not violations,
                       violations=violations, details=details)


def drill_pool_exhaustion(make_engine) -> DrillResult:
    """Oversubscribe the page pool: more concurrent demand than pages, so
    slots PAUSE for pages and admissions requeue — the engine must serve
    everything (or fail loudly via the deadlock breaker), then account
    for every page."""
    eng = make_engine()
    ps, pool = eng.page_size, eng.allocator.n_pages
    seq = eng.spec.seq_len
    # each request wants ~seq positions; enough requests that total demand
    # is several times the pool
    n_req = max(4, (3 * pool * ps) // seq)
    reqs = [[1] + [5 + (i * 3 + j) % 90 for j in range(3)]
            for i in range(n_req)]
    outs, stats = eng.run(reqs, steps=seq, quiet=True)
    empty = sum(1 for o in outs if not o)
    return _result("pool_exhaustion", eng, None,
                   extra_violations=(
                       [f"{empty} requests produced no output"]
                       if empty else []),
                   requests=n_req, pauses=stats.pauses,
                   tokens=stats.tokens)


def drill_transient_starvation(make_engine) -> DrillResult:
    """Deny the first N page allocations (ChaosMonkey.deny_pages): the
    engine's dry-pool paths (pause / head-of-queue requeue) must retry and
    complete every request once the denials run out."""
    chaos = ChaosMonkey(deny_pages=6)
    eng = make_engine(chaos=chaos)
    reqs = [[1] + [5 + (i * 7 + j) % 90 for j in range(4)]
            for i in range(4)]
    outs, stats = eng.run(reqs, steps=8, quiet=True)
    violations = []
    if chaos.denied_allocs != 6:
        violations.append(f"expected 6 denied allocations, got "
                          f"{chaos.denied_allocs}")
    if any(not o for o in outs):
        violations.append("a request starved permanently under transient "
                          "denial")
    return _result("transient_starvation", eng, chaos,
                   extra_violations=violations, pauses=stats.pauses)


def drill_oversized_prompt(make_engine) -> DrillResult:
    """Prompts longer than the position budget (and than seq_len): the
    engine must clamp to its budget, retire cleanly, and reject empty
    prompts with a clean error — never wedge or leak."""
    eng = make_engine()
    seq = eng.spec.seq_len
    huge = [1] + [5 + (j % 90) for j in range(2 * seq)]
    outs, _ = eng.run([huge, [1, 9, 9]], steps=seq, quiet=True)
    violations = []
    if len(outs[0]) > seq:
        violations.append(f"oversized prompt emitted {len(outs[0])} "
                          f"tokens past the {seq}-position budget")
    try:
        eng.run([[]], steps=4, quiet=True)
        violations.append("empty prompt was accepted")
    except ValueError:
        pass
    return _result("oversized_prompt", eng, None,
                   extra_violations=violations, echoed=len(outs[0]))


def drill_disconnect(make_engine) -> DrillResult:
    """Mid-flight client disconnects: cancel requests while they hold KV
    pages; every page must return to the pool (cancelled requests publish
    nothing to the radix tree), and kv_pages_free must round-trip."""
    from .continuous import Request

    eng = make_engine()
    free_before = eng.allocator.n_free
    seq = eng.spec.seq_len
    reqs = [Request(tokens=[1] + [5 + (i * 11 + j) % 90 for j in range(3)],
                    steps=seq) for i in range(3)]
    for r in reqs:
        eng.submit(r)
    for _ in range(3):  # get them decoding (pages held)
        eng.step_many(eng.block_steps, quiet=True)
    held = sum(len(s.pages) for s in eng._pool)
    for r in reqs:
        eng.cancel(r)
    iters = _drain(eng)
    violations = []
    if held == 0:
        violations.append("drill never put pages at risk (no slot held "
                          "pages at cancel time)")
    if not all(r.done.is_set() for r in reqs):
        violations.append("a cancelled request never completed")
    free_after = eng.allocator.n_free
    if free_after != free_before:
        violations.append(
            f"kv_pages_free did not round-trip: {free_before} before, "
            f"{free_after} after cancel+drain")
    return _result("disconnect", eng, getattr(eng, "_chaos", None),
                   extra_violations=violations, pages_at_risk=held,
                   drain_iters=iters)


def drill_latency_spike(make_engine) -> DrillResult:
    """Inject step-latency spikes (sleep before every 2nd dispatch): the
    engine must finish the workload, and the step-duration histogram must
    have recorded through the spikes."""
    chaos = ChaosMonkey(step_delay_every=2, step_delay_s=0.002)
    eng = make_engine(chaos=chaos)
    reqs = [[1] + [5 + (i * 5 + j) % 90 for j in range(3)]
            for i in range(3)]
    outs, _ = eng.run(reqs, steps=6, quiet=True)
    violations = []
    if chaos.injected_delays == 0:
        violations.append("no latency spikes were injected")
    if any(not o for o in outs):
        violations.append("a request produced no output under spikes")
    if eng._obs is not None and eng._obs.step_duration.count == 0:
        violations.append("step-duration histogram recorded nothing")
    return _result("latency_spike", eng, chaos, extra_violations=violations)


def drill_profiler_under_load(make_engine) -> DrillResult:
    """Start a jax.profiler capture WHILE the engine serves: serving must
    not stall, and the capture must start and stop cleanly (the
    POST /profile contract, exercised under load instead of idle)."""
    import tempfile

    from ..obs import profiler

    eng = make_engine()
    violations = []
    trace_dir = tempfile.mkdtemp(prefix="dllama-chaos-profile-")
    reqs = [[1] + [5 + (i * 7 + j) % 90 for j in range(3)]
            for i in range(3)]
    try:
        profiler.start_capture(trace_dir, seconds=0.2)
    except RuntimeError as e:
        violations.append(f"capture would not start: {e}")
    outs, _ = eng.run(reqs, steps=6, quiet=True)
    if any(not o for o in outs):
        violations.append("a request produced no output under capture")
    if not profiler.wait_capture(timeout=30.0):
        violations.append("profiler capture never stopped")
    return _result("profiler_under_load", eng, None,
                   extra_violations=violations, trace_dir=trace_dir)


DRILLS = (
    ("pool_exhaustion", drill_pool_exhaustion),
    ("transient_starvation", drill_transient_starvation),
    ("oversized_prompt", drill_oversized_prompt),
    ("disconnect", drill_disconnect),
    ("latency_spike", drill_latency_spike),
    ("profiler_under_load", drill_profiler_under_load),
)


def run_drills(make_engine, which=None) -> list[DrillResult]:
    """Run the drill suite against fresh engines from ``make_engine``
    (a callable accepting ``chaos=`` plus engine-constructor overrides;
    every drill gets its own engine — faults must not bleed). ``which``
    filters by drill name. A drill that RAISES is converted into a failed
    result — the gate must report, not crash."""
    results = []
    for name, fn in DRILLS:
        if which is not None and name not in which:
            continue
        try:
            results.append(fn(make_engine))
        except Exception as e:  # noqa: BLE001 - report, never crash the gate
            results.append(DrillResult(
                name=name, passed=False,
                violations=[f"drill raised {type(e).__name__}: {e}"],
                details={}))
    return results


def render_drill_table(results) -> str:
    """The human verdict table (tracecheck-style)."""
    lines = [f"{'drill':<24} {'verdict':<8} detail"]
    for r in results:
        detail = ("; ".join(r.violations) if r.violations
                  else ", ".join(f"{k}={v}" for k, v in
                                 sorted(r.details.items())
                                 if not isinstance(v, str)))
        lines.append(f"{r.name:<24} {'OK' if r.passed else 'FAIL':<8} "
                     f"{detail}")
    return "\n".join(lines)
