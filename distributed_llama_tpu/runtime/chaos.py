"""Deterministic fault injection + chaos drills for the continuous engine.

The telemetry stack (obs/) can SHOW a leak or a wedged pool; nothing before
this module ever CAUSED one on purpose. Each drill here drives a fresh
engine through one failure mode the serving layer must absorb — pool
exhaustion, transient page starvation, oversized prompts, mid-stream client
disconnects, injected step-latency spikes, a profiler capture under load —
and then asserts the post-drill invariants that define "absorbed":

* no leaked pages or slots: every allocated page's refcount is explained
  by a live slot mapping or a radix-tree node (paging.PagedAllocator.audit
  — the introspection hooks exist for exactly this), the pool drains to
  free + tree-held == capacity, and every slot is free;
* metrics still scrapeable: the registry's Prometheus exposition parses;
* the engine still admits: a probe request runs to completion afterwards.

Injection is DETERMINISTIC — counters, not coin flips: "delay every Nth
dispatch", "deny the first N page allocations". A drill that fails
reproduces identically under the same config, which is the property that
makes tools/loadcheck.py a CI gate rather than a flake source. The
``ChaosMonkey`` hooks are consulted by the engine at three points
(pre-dispatch, page allocation, cancelled-retire release) and by
``serve --chaos`` for operator-driven drills against a live server.

``leak_on_cancel`` is the gate's MUTATION arm (ISSUE 8 satellite): it
makes the engine deliberately drop one page on every cancelled-request
release, which the disconnect drill's audit must flag — proving the red
path fires (tools/ci.sh asserts loadcheck exits 1 under it).
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class ChaosMonkey:
    """Deterministic fault-injection state, registered on an engine (the
    ``chaos=`` constructor knob) and/or a server. All knobs default OFF;
    counters record what actually fired so drills can assert injection
    happened.

    * ``step_delay_every``/``step_delay_s`` — sleep before every Nth
      device dispatch (a step-latency spike: a preempted host, a slow
      interconnect);
    * ``deny_pages`` — fail the first N page allocations (transient pool
      pressure without filling the pool);
    * ``leak_on_cancel`` — drop one page from every cancelled request's
      release (the seeded fault the invariant audit must catch);
    * ``drop_on_demote`` — KV tiering (ISSUE 12): every write-behind
      demotion discards its payload instead of storing it, so the tree
      records a host-tier page whose bytes exist nowhere — the seeded
      fault the three-tier audit (or a promotion of the lost page) must
      catch.
    """

    step_delay_every: int = 0
    step_delay_s: float = 0.0
    deny_pages: int = 0
    leak_on_cancel: bool = False
    drop_on_demote: bool = False
    # disaggregation (ISSUE 14): every handed-off page's payload is
    # replaced with zeros RE-FRAMED UNDER A VALID CRC — in-flight
    # corruption that slips past the channel's framing checks, which
    # only the bitwise stream gate can catch (the kill_mid_handoff
    # drill's mutation arm)
    drop_page_in_flight: bool = False
    # distributed tracing (ISSUE 15): strip the traceparent header at
    # the handoff seam — the decode pool's spans then cannot join the
    # prefill pool's, which tools/tracejoin.py must report as orphan
    # spans (the trace-propagation gate's mutation arm)
    drop_traceparent: bool = False
    # cost ledger (ISSUE 16): charge every decode/spec dispatch TWICE
    # into the per-request ledgers while the census counts it once —
    # breaks the Σ-ledger == engine-totals conservation equalities,
    # which tools/costcheck.py must catch (the accounting gate's
    # mutation arm)
    double_count_dispatch: bool = False
    # cost ledger (ISSUE 16): retire requests WITHOUT closing their
    # ledger — the zero-open-ledgers-after-drain check must flag the
    # orphans
    leak_ledger: bool = False
    # token-budget scheduler (ISSUE 18): the mixed dispatch's prefill
    # slice ignores the remaining budget and takes the whole staging
    # width — sum(span) then exceeds the budget, the virtual clock
    # charges the overrun as extra step time, and loadcheck's budget
    # gate must exit 1 (the budget sweep's mutation arm)
    overrun_budget: bool = False
    # injection counters (read by drills / surfaced in loadcheck rows)
    injected_delays: int = 0
    denied_allocs: int = 0
    leaked_pages: list = dataclasses.field(default_factory=list)
    dropped_demotions: int = 0
    dropped_pages: int = 0
    dropped_traceparents: int = 0
    double_counted: int = 0
    leaked_ledgers: int = 0
    overran_budgets: int = 0
    _dispatches: int = 0

    def on_dispatch(self) -> None:
        """Engine hook: called once per device dispatch, before launch."""
        self._dispatches += 1
        if (self.step_delay_every > 0 and self.step_delay_s > 0
                and self._dispatches % self.step_delay_every == 0):
            self.injected_delays += 1
            time.sleep(self.step_delay_s)

    def deny_page(self) -> bool:
        """Engine hook: True = this page allocation must fail (the engine
        then takes its real dry-pool path: pause, requeue, breaker)."""
        if self.denied_allocs < self.deny_pages:
            self.denied_allocs += 1
            return True
        return False

    def filter_release(self, pages: list) -> list:
        """Engine hook on a cancelled request's page release: with
        ``leak_on_cancel`` armed, steal one page so it is never released —
        the deliberate leak the audit must then report."""
        if self.leak_on_cancel and pages:
            self.leaked_pages.append(pages.pop())
        return pages

    def demote_drop(self) -> bool:
        """Allocator hook per write-behind demotion (KV tiering): True =
        discard this demotion's payload — the page leaves HBM but its
        bytes land in NO tier, the exactly-one-tier violation the
        three-tier audit must flag."""
        if self.drop_on_demote:
            self.dropped_demotions += 1
            return True
        return False

    def page_drop(self) -> bool:
        """Handoff-pack hook (runtime/disagg.encode_handoff_pages): True
        = zero this page's payload before framing — the seeded in-flight
        corruption the bitwise handoff gate must catch."""
        if self.drop_page_in_flight:
            self.dropped_pages += 1
            return True
        return False

    def trace_drop(self) -> bool:
        """Handoff-seam hook (runtime/disagg.DisaggPair.handoff, the
        server's POST /prefill): True = this hand-over loses its
        traceparent header — the trace-continuity break the tracejoin
        orphan gate (ISSUE 15) must catch."""
        if self.drop_traceparent:
            self.dropped_traceparents += 1
            return True
        return False

    def dispatch_double(self) -> bool:
        """Ledger hook per decode/spec dispatch charge pass: True =
        multiply this dispatch's LEDGER charges by two while the census
        counts it once — the conservation break costcheck must catch."""
        if self.double_count_dispatch:
            self.double_counted += 1
            return True
        return False

    def ledger_leak(self) -> bool:
        """Retire hook: True = skip closing this request's ledger — the
        orphan the zero-open-after-drain check must flag."""
        if self.leak_ledger:
            self.leaked_ledgers += 1
            return True
        return False

    def budget_overrun(self) -> bool:
        """Mixed-dispatch hook per prefill-slice cut (ISSUE 18): True =
        the slice ignores the remaining token budget and takes the whole
        staging width — the seeded overrun the loadcheck budget gate's
        virtual clock must catch as inflated decode latency."""
        if self.overrun_budget:
            self.overran_budgets += 1
            return True
        return False

    def injection_summary(self) -> dict:
        return {"dispatches": self._dispatches,
                "injected_delays": self.injected_delays,
                "denied_allocs": self.denied_allocs,
                "leaked_pages": len(self.leaked_pages),
                "dropped_demotions": self.dropped_demotions,
                "dropped_pages": self.dropped_pages,
                "dropped_traceparents": self.dropped_traceparents,
                "double_counted": self.double_counted,
                "leaked_ledgers": self.leaked_ledgers,
                "overran_budgets": self.overran_budgets}

    @classmethod
    def parse(cls, text: str) -> "ChaosMonkey":
        """``key=value[,key=value...]`` (the --chaos CLI format): keys
        step_delay_every, step_delay_ms, deny_pages, leak_on_cancel."""
        kw: dict = {}
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"bad chaos knob {part!r}: want key=value")
            key, val = part.split("=", 1)
            key = key.strip()
            if key == "step_delay_ms":
                kw["step_delay_s"] = float(val) / 1e3
            elif key in ("step_delay_every", "deny_pages"):
                kw[key] = int(val)
            elif key in ("leak_on_cancel", "drop_on_demote",
                         "drop_page_in_flight", "drop_traceparent",
                         "double_count_dispatch", "leak_ledger",
                         "overrun_budget"):
                kw[key] = val.strip().lower() not in ("0", "false", "")
            else:
                raise ValueError(
                    f"unknown chaos knob {key!r} (have step_delay_every, "
                    f"step_delay_ms, deny_pages, leak_on_cancel, "
                    f"drop_on_demote, drop_page_in_flight, "
                    f"drop_traceparent, double_count_dispatch, "
                    f"leak_ledger, overrun_budget)")
        return cls(**kw)


@dataclasses.dataclass
class DrillResult:
    """One drill's verdict: ``passed`` is the gate bit; ``violations``
    lists every failed invariant (empty when passed); ``details`` carries
    the drill's observed counters for the loadcheck JSON row."""

    name: str
    passed: bool
    violations: list
    details: dict

    def to_json(self) -> dict:
        return {"name": self.name, "passed": self.passed,
                "violations": list(self.violations),
                "details": dict(self.details)}


def scrape_problems(registry) -> list[str]:
    """Parse the registry's Prometheus exposition; any unparseable sample
    line is a violation (a drill must not leave /metrics broken)."""
    if registry is None:
        return []
    try:
        text = registry.expose()
    except Exception as e:  # noqa: BLE001 - a raising scrape IS the finding
        return [f"/metrics exposition raised {type(e).__name__}: {e}"]
    problems = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            float(value)
        except ValueError:
            problems.append(f"unparseable exposition line: {line!r}")
        if not name:
            problems.append(f"sample line without a name: {line!r}")
    return problems


def check_invariants(eng, expect_drained: bool = True) -> list[str]:
    """The shared post-drill gate (module docstring): drained pool, page
    accounting clean, metrics scrapeable, engine still admitting."""
    problems: list[str] = []
    active = sum(not s.free for s in eng._pool)
    with eng._lock:
        queued = len(eng._queue)
    if expect_drained and (active or queued):
        problems.append(f"engine not drained: {active} active slots, "
                        f"{queued} queued requests")
    problems += [f"page audit: {p}" for p in eng.audit_pages()]
    if eng.allocator is not None:
        from .paging import TIER_HBM

        alloc = eng.allocator
        # spilled (host/disk) nodes hold no pool page — only HBM-tier
        # nodes count against the device pool (the tier audit inside
        # audit_pages covers the spilled copies)
        tree_held = sum(1 for n in alloc.tree.nodes()
                        if n.tier == TIER_HBM)
        slot_held = sum(len(s.pages) for s in eng._pool)
        # only decisive once slots drained: a shared-prefix page is held
        # by a slot AND the tree at once (the audit covers the live case)
        if (slot_held == 0
                and alloc.n_free + tree_held != alloc.n_pages):
            problems.append(
                f"page leak: {alloc.n_free} free + {tree_held} tree-held "
                f"!= {alloc.n_pages} pool pages with all slots drained")
    registry = eng._obs.registry if eng._obs is not None else None
    problems += scrape_problems(registry)
    # the engine must still admit and finish new work after the drill
    probe = [1, 7, 9]
    try:
        outs, _ = eng.run([probe], steps=3, quiet=True)
        if not outs[0]:
            problems.append("post-drill probe request produced no tokens")
    except Exception as e:  # noqa: BLE001 - a raising engine IS the finding
        problems.append(f"post-drill probe raised {type(e).__name__}: {e}")
    return problems


def _drain(eng, max_iters: int = 10_000) -> int:
    """Step until idle; returns iterations. Bounded — a scheduler that
    never drains is itself a drill failure (the caller sees active>0)."""
    it = 0
    while eng.step_many(eng.block_steps, quiet=True) and it < max_iters:
        it += 1
    return it


def _result(name: str, eng, chaos, extra_violations=(), **details):
    violations = list(extra_violations) + check_invariants(eng)
    if chaos is not None:
        details.update(chaos.injection_summary())
    return DrillResult(name=name, passed=not violations,
                       violations=violations, details=details)


def drill_pool_exhaustion(make_engine) -> DrillResult:
    """Oversubscribe the page pool: more concurrent demand than pages, so
    slots PAUSE for pages and admissions requeue — the engine must serve
    everything (or fail loudly via the deadlock breaker), then account
    for every page."""
    eng = make_engine()
    ps, pool = eng.page_size, eng.allocator.n_pages
    seq = eng.spec.seq_len
    # each request wants ~seq positions; enough requests that total demand
    # is several times the pool
    n_req = max(4, (3 * pool * ps) // seq)
    reqs = [[1] + [5 + (i * 3 + j) % 90 for j in range(3)]
            for i in range(n_req)]
    outs, stats = eng.run(reqs, steps=seq, quiet=True)
    empty = sum(1 for o in outs if not o)
    return _result("pool_exhaustion", eng, None,
                   extra_violations=(
                       [f"{empty} requests produced no output"]
                       if empty else []),
                   requests=n_req, pauses=stats.pauses,
                   tokens=stats.tokens)


def drill_transient_starvation(make_engine) -> DrillResult:
    """Deny the first N page allocations (ChaosMonkey.deny_pages): the
    engine's dry-pool paths (pause / head-of-queue requeue) must retry and
    complete every request once the denials run out."""
    chaos = ChaosMonkey(deny_pages=6)
    eng = make_engine(chaos=chaos)
    reqs = [[1] + [5 + (i * 7 + j) % 90 for j in range(4)]
            for i in range(4)]
    outs, stats = eng.run(reqs, steps=8, quiet=True)
    violations = []
    if chaos.denied_allocs != 6:
        violations.append(f"expected 6 denied allocations, got "
                          f"{chaos.denied_allocs}")
    if any(not o for o in outs):
        violations.append("a request starved permanently under transient "
                          "denial")
    return _result("transient_starvation", eng, chaos,
                   extra_violations=violations, pauses=stats.pauses)


def drill_oversized_prompt(make_engine) -> DrillResult:
    """Prompts longer than the position budget (and than seq_len): the
    engine must clamp to its budget, retire cleanly, and reject empty
    prompts with a clean error — never wedge or leak."""
    eng = make_engine()
    seq = eng.spec.seq_len
    huge = [1] + [5 + (j % 90) for j in range(2 * seq)]
    outs, _ = eng.run([huge, [1, 9, 9]], steps=seq, quiet=True)
    violations = []
    if len(outs[0]) > seq:
        violations.append(f"oversized prompt emitted {len(outs[0])} "
                          f"tokens past the {seq}-position budget")
    try:
        eng.run([[]], steps=4, quiet=True)
        violations.append("empty prompt was accepted")
    except ValueError:
        pass
    return _result("oversized_prompt", eng, None,
                   extra_violations=violations, echoed=len(outs[0]))


def drill_disconnect(make_engine) -> DrillResult:
    """Mid-flight client disconnects: cancel requests while they hold KV
    pages; every page must return to the pool (cancelled requests publish
    nothing to the radix tree), and kv_pages_free must round-trip."""
    from .continuous import Request

    eng = make_engine()
    free_before = eng.allocator.n_free
    seq = eng.spec.seq_len
    reqs = [Request(tokens=[1] + [5 + (i * 11 + j) % 90 for j in range(3)],
                    steps=seq) for i in range(3)]
    for r in reqs:
        eng.submit(r)
    for _ in range(3):  # get them decoding (pages held)
        eng.step_many(eng.block_steps, quiet=True)
    held = sum(len(s.pages) for s in eng._pool)
    for r in reqs:
        eng.cancel(r)
    iters = _drain(eng)
    violations = []
    if held == 0:
        violations.append("drill never put pages at risk (no slot held "
                          "pages at cancel time)")
    if not all(r.done.is_set() for r in reqs):
        violations.append("a cancelled request never completed")
    free_after = eng.allocator.n_free
    if free_after != free_before:
        violations.append(
            f"kv_pages_free did not round-trip: {free_before} before, "
            f"{free_after} after cancel+drain")
    return _result("disconnect", eng, getattr(eng, "_chaos", None),
                   extra_violations=violations, pages_at_risk=held,
                   drain_iters=iters)


def drill_latency_spike(make_engine) -> DrillResult:
    """Inject step-latency spikes (sleep before every 2nd dispatch): the
    engine must finish the workload, and the step-duration histogram must
    have recorded through the spikes."""
    chaos = ChaosMonkey(step_delay_every=2, step_delay_s=0.002)
    eng = make_engine(chaos=chaos)
    reqs = [[1] + [5 + (i * 5 + j) % 90 for j in range(3)]
            for i in range(3)]
    outs, _ = eng.run(reqs, steps=6, quiet=True)
    violations = []
    if chaos.injected_delays == 0:
        violations.append("no latency spikes were injected")
    if any(not o for o in outs):
        violations.append("a request produced no output under spikes")
    if eng._obs is not None and eng._obs.step_duration.count == 0:
        violations.append("step-duration histogram recorded nothing")
    return _result("latency_spike", eng, chaos, extra_violations=violations)


def drill_tier_spill_storm(make_engine) -> DrillResult:
    """KV-tiering churn drill (ISSUE 12): a working set several times the
    HBM page pool cycles through twice under injected page-allocation
    denials, forcing deterministic demote (HBM→host→disk, write-behind)
    and promote (radix hit on a spilled prefix → async upload + PAUSE)
    churn — then the three-tier ``PagedAllocator.audit`` must close the
    ledger (every payload owned by exactly one tier, disk records
    CRC-verified by read-back, promotion/demotion counters consistent),
    the metrics exposition must still parse, and the engine must still
    admit. Pass 2 must also actually SAVE prefill tokens from spilled
    tiers — a hierarchy that spills but never promotes is not a cache."""
    import tempfile

    chaos = ChaosMonkey(deny_pages=4)
    disk_dir = tempfile.mkdtemp(prefix="dllama-chaos-tier-")
    eng = make_engine(chaos=chaos, kv_pages=8, kv_host_pages=6,
                      kv_disk_dir=disk_dir, slots=2)
    ps = eng.page_size
    n_prefix = 8  # 2 full pages each = 16 prefix pages vs the 8-page pool
    waves = []
    for tail in (3, 9):
        waves.append([[1] + [(7 * i + j) % 90 + 5 for j in range(2 * ps)]
                      + [tail + i] for i in range(n_prefix)])
    for wave in waves:
        eng.run(wave, steps=4 * ps, quiet=True)
    a = eng.allocator
    violations = []
    if sum(a.demotions.values()) == 0:
        violations.append("no demotions under a working set several "
                          "times the HBM pool")
    if sum(a.promotions.values()) == 0:
        violations.append("no promotions: spilled prefixes were never "
                          "raised back on re-match")
    spilled_saved = (a.tokens_saved_by_tier.get("host", 0)
                     + a.tokens_saved_by_tier.get("disk", 0))
    if spilled_saved == 0:
        violations.append("no prefill tokens saved from spilled tiers — "
                          "tiering rescued nothing from recompute")
    if chaos.denied_allocs == 0:
        violations.append("deny_pages pressure never fired")
    return _result("tier_spill_storm", eng, chaos,
                   extra_violations=violations,
                   demotions=dict(a.demotions),
                   promotions=dict(a.promotions),
                   tier_pages=a.tier_page_counts(),
                   prefill_saved_spilled=spilled_saved,
                   crc_drops=a.crc_drops)


def drill_profiler_under_load(make_engine) -> DrillResult:
    """Start a jax.profiler capture WHILE the engine serves: serving must
    not stall, and the capture must start and stop cleanly (the
    POST /profile contract, exercised under load instead of idle)."""
    import tempfile

    from ..obs import profiler

    eng = make_engine()
    violations = []
    trace_dir = tempfile.mkdtemp(prefix="dllama-chaos-profile-")
    reqs = [[1] + [5 + (i * 7 + j) % 90 for j in range(3)]
            for i in range(3)]
    try:
        profiler.start_capture(trace_dir, seconds=0.2)
    except RuntimeError as e:
        violations.append(f"capture would not start: {e}")
    outs, _ = eng.run(reqs, steps=6, quiet=True)
    if any(not o for o in outs):
        violations.append("a request produced no output under capture")
    if not profiler.wait_capture(timeout=30.0):
        violations.append("profiler capture never stopped")
    return _result("profiler_under_load", eng, None,
                   extra_violations=violations, trace_dir=trace_dir)


# ------------------------------------------------------------- recovery
# Crash-safety drills (ISSUE 9). The kill-mid-decode drill spawns a REAL
# subprocess child, SIGKILLs it mid-decode, and proves the recovered
# continuation is bitwise the uninterrupted run — so the parent and the
# child must construct the SAME engine and requests from these fixed
# constants (a factory closure cannot cross the process boundary).

_RECOVERY_SPEC_KW = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                         n_kv_heads=2, vocab_size=128, seq_len=32)
# (tokens, steps, temperature, topp, seed): one greedy, one seeded-sampled
# — recovery must replay BOTH bitwise. Seeds chosen so neither stream hits
# BOS before its budget (the drill needs requests that are genuinely
# mid-decode at kill time).
_RECOVERY_REQS = (
    ([1, 9, 17, 25], 24, 0.0, 0.9, 501),
    ([1, 9, 17, 42], 24, 0.9, 0.9, 502),
)

# kill_mid_handoff's workload (ISSUE 14): prompts spanning >= 2 FULL
# pages (page_size 4) so the handoff genuinely ships pages the cut can
# interrupt; one greedy, one seeded-sampled — the handed-off stream must
# replay bitwise through the decode journal's coin cursor in both modes.
_HANDOFF_REQS = (
    ([1, 9, 17, 25, 31, 7, 3, 44, 11], 24, 0.0, 0.9, 501),
    ([1, 9, 17, 25, 31, 7, 3, 44, 5], 24, 0.9, 0.9, 502),
)


def _recovery_engine(journal=None, chaos=None, watchdog=None):
    from ..models.spec import TransformerSpec
    from ..models.synth import synth_params
    from ..obs.metrics import Registry
    from .continuous import ContinuousEngine

    spec = TransformerSpec(**_RECOVERY_SPEC_KW)
    params = synth_params(spec, q40=False, seed=4, scale=0.3)
    return ContinuousEngine(spec, params, slots=2, temperature=0.8,
                            topp=0.9, seed=11, metrics=Registry(),
                            prefill_chunk=4, page_size=4, kv_pages=24,
                            chaos=chaos, journal=journal, watchdog=watchdog)


def _submit_recovery_requests(eng) -> list:
    from .continuous import Request

    reqs = []
    for tokens, steps, temp, topp, seed in _RECOVERY_REQS:
        r = Request(tokens=list(tokens), steps=steps, temperature=temp,
                    topp=topp, seed=seed)
        eng.submit(r)
        reqs.append(r)
    return reqs


def recovery_child(journal_path: str) -> None:
    """Subprocess body for the kill-mid-decode drill: serve the fixed
    recovery workload against a write-ahead journal (fsync=always: every
    record durable before the next dispatch) with an injected per-dispatch
    stall widening the kill window — then spin until the parent SIGKILLs
    us. Deliberately NEVER exits: finishing early would leave nothing to
    recover, which the parent reports as a drill failure."""
    from .journal import RequestJournal

    journal = RequestJournal(journal_path, fsync="always")
    eng = _recovery_engine(
        journal=journal, chaos=ChaosMonkey(step_delay_every=1,
                                           step_delay_s=0.05))
    _submit_recovery_requests(eng)
    while True:
        eng.step_many(eng.block_steps, quiet=True)
        time.sleep(0.01)


def drill_kill_mid_decode(make_engine, inject=frozenset()) -> DrillResult:
    """THE crash-safety acceptance drill: SIGKILL a journaling child
    process mid-decode, recover its journal into a fresh engine, and
    require the continued streams to be BITWISE identical to an
    uninterrupted reference run — greedy trivially, seeded-sampled via
    coin-cursor replay — with a clean page audit afterwards.

    ``inject={"corrupt-journal"}`` is the gate's mutation arm: a byte
    smashed MID-file (not the torn tail, which is legal damage) before
    recovery — loading must raise JournalCorruption, turning the drill
    red (tools/ci.sh asserts loadcheck exits 1 under it)."""
    import os
    import signal
    import subprocess
    import sys
    import tempfile

    from .journal import RequestJournal, load_journal

    violations: list = []
    tmp = tempfile.mkdtemp(prefix="dllama-chaos-recovery-")
    jpath = os.path.join(tmp, "requests.journal")
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    child = subprocess.Popen(
        [sys.executable, "-c",
         "from distributed_llama_tpu.runtime.chaos import recovery_child; "
         f"recovery_child({jpath!r})"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    # wait until the journal PROVES both requests are mid-decode (>= 2
    # durable sampled tokens each, neither retired), then kill -9
    deadline = time.time() + 240.0
    ready = False
    while time.time() < deadline and child.poll() is None:
        try:
            entries = [e for e in load_journal(jpath) if e.status is None]
        except Exception:  # noqa: BLE001 - not created yet / torn reads
            entries = []
        if (len(entries) == len(_RECOVERY_REQS)
                and all(len(e.sampled) >= 2 for e in entries)):
            ready = True
            break
        time.sleep(0.005)
    if child.poll() is not None:
        err = (child.stderr.read() or b"").decode("utf-8", "replace")
        violations.append(f"child exited rc={child.returncode} before the "
                          f"kill: {err[-300:]}")
    else:
        if not ready:
            violations.append("journal never showed both requests "
                              "mid-decode within the window")
        child.send_signal(signal.SIGKILL)
    child.wait()
    if child.stderr is not None:
        child.stderr.close()

    if "corrupt-journal" in inject:
        # seeded mutation: damage a byte INSIDE the second record — deep
        # enough that torn-tail repair cannot explain it away
        with open(jpath, "rb") as fh:
            data = fh.read()
        pos = data.index(b"\n") + 2
        with open(jpath, "r+b") as fh:
            fh.seek(pos)
            fh.write(b"\xff")

    # uninterrupted reference: same engine recipe, same requests, no crash
    ref_eng = _recovery_engine()
    ref_reqs = _submit_recovery_requests(ref_eng)
    _drain(ref_eng)
    ref_outs = [r.out for r in ref_reqs]

    # recovery: reopen the journal (torn-tail repair happens here; any
    # deeper corruption raises and the gate goes red), re-admit, drain
    journal = RequestJournal(jpath)
    pre_entries = journal.incomplete()
    replayed = sum(len(e.sampled) for e in pre_entries)
    eng = _recovery_engine(journal=journal)
    n_recovered = eng.recover()
    with eng._lock:
        recovered = list(eng._queue)
    _drain(eng)
    if n_recovered != len(_RECOVERY_REQS):
        violations.append(f"expected {len(_RECOVERY_REQS)} journaled "
                          f"requests to recover, got {n_recovered}")
    for i, req in enumerate(recovered):
        if req.out != ref_outs[i]:
            violations.append(
                f"recovered stream {i} diverged from the uninterrupted "
                f"reference (first {min(len(req.out), len(ref_outs[i]))} "
                f"positions compared)")
    # trace continuity across the SIGKILL seam (ISSUE 15): the continued
    # life must keep the trace_id the killed process journaled, in a new
    # span linked 'recovers' — the cross-process join depends on it
    violations += _trace_continuity_violations(recovered, pre_entries,
                                               "recovers")
    if eng._spans is not None and recovered:
        links = [s for s in eng._spans.snapshot() if s.cat == "link"
                 and s.name == "recovers"]
        if len(links) != len(recovered):
            violations.append(
                f"expected {len(recovered)} 'recovers' link spans, "
                f"got {len(links)}")
    res = _result("kill_mid_decode", eng, None,
                  extra_violations=violations,
                  recovered=n_recovered, replayed_tokens=replayed)
    journal.close()
    return res


def _trace_continuity_violations(recovered, entries, link: str) -> list:
    """Shared seam check (ISSUE 15): each recovered/handed-off request
    must continue its journaled trace_id in a new span carrying the
    expected continuation link."""
    from ..obs import tracectx

    violations = []
    by_trace = {}
    for e in entries:
        if e.trace is None:
            violations.append(f"journaled request {e.rid} carries no "
                              f"trace header")
            continue
        try:
            by_trace[tracectx.parse_header(e.trace).trace_id] = e.rid
        except ValueError as exc:
            # recover() tolerates a damaged header (it never blocks
            # recovery); the drill must report it red, not crash
            violations.append(f"journaled request {e.rid} carries a "
                              f"malformed trace header: {exc}")
    for req in recovered:
        if req.trace is None:
            violations.append("recovered request carries no trace context")
        elif req.trace.trace_id not in by_trace:
            violations.append(
                f"recovered request's trace {req.trace.trace_id} matches "
                f"no journaled trace — the continuation re-minted instead "
                f"of continuing")
        elif req.trace.link != link:
            violations.append(
                f"recovered request's trace link is {req.trace.link!r}, "
                f"expected {link!r}")
    return violations


def drill_journal_wal(make_engine) -> DrillResult:
    """The write-ahead journal's durability contract under an engine:
    retired requests leave no live entries, compaction drops them from the
    file, a TORN TAIL (crash mid-append) repairs by truncation, and
    mid-file damage fails LOUDLY (JournalCorruption) instead of recovering
    untrusted state."""
    import os
    import tempfile

    from .journal import JournalCorruption, RequestJournal

    tmp = tempfile.mkdtemp(prefix="dllama-chaos-journal-")
    path = os.path.join(tmp, "requests.journal")
    journal = RequestJournal(path, fsync="batch", compact_every=2)
    eng = make_engine(journal=journal)
    reqs = [[1] + [5 + (i * 7 + j) % 90 for j in range(3)]
            for i in range(3)]
    outs, _ = eng.run(reqs, steps=6, quiet=True)
    journal.sync(force=True)
    violations = []
    if any(not o for o in outs):
        violations.append("a journaled request produced no output")
    if journal.incomplete():
        violations.append("retired requests still live in the journal")
    size_before = os.path.getsize(path)
    # torn tail: a crash mid-append leaves a partial line — reopening must
    # physically truncate it back to the last valid record
    with open(path, "ab") as fh:
        fh.write(b'{"t":"tok","id"')
    reopened = RequestJournal(path)
    reopened.close()
    if os.path.getsize(path) != size_before:
        violations.append(
            f"torn tail not repaired: {os.path.getsize(path)} bytes after "
            f"reopen, expected {size_before}")
    # mid-file damage: smash a byte of the FIRST record with more records
    # after it — this history cannot be trusted and must raise
    corrupt = os.path.join(tmp, "corrupt.journal")
    with open(corrupt, "wb") as fh:
        fh.write(b'{"t":"journal","v":1}\n'
                 b'{"t":"admit","id":0,"tokens":[1,5],"steps":4,'
                 b'"temperature":0.0,"topp":0.9,"seed":7,"slo":null,'
                 b'"cursor":0}\n'
                 b'{"t":"tok","id":0,"tok":9,"cursor":0}\n')
    with open(corrupt, "r+b") as fh:
        fh.seek(30)
        fh.write(b"\xff")
    try:
        RequestJournal(corrupt)
        violations.append("mid-file journal corruption was silently "
                          "accepted")
    except JournalCorruption:
        pass
    res = _result("journal_wal", eng, None, extra_violations=violations,
                  records=journal.records_total)
    journal.close()
    return res


def drill_hung_dispatch(make_engine) -> DrillResult:
    """A wedged device dispatch (injected stall far past the watchdog
    deadline): the StepWatchdog must TRIP and degrade health while the
    dispatch hangs, and — because this stall eventually resolves — the
    workload must still complete and health recover to serving."""
    from .supervisor import HealthMonitor, StepWatchdog

    health = HealthMonitor()
    health.to("serving")
    chaos = ChaosMonkey(step_delay_every=2, step_delay_s=0.25)
    watchdog = StepWatchdog(0.05, on_hang=lambda el: health.to("degraded"))
    eng = make_engine(chaos=chaos, watchdog=watchdog)
    try:
        reqs = [[1] + [5 + (i * 5 + j) % 90 for j in range(3)]
                for i in range(3)]
        outs, _ = eng.run(reqs, steps=6, quiet=True)
    finally:
        watchdog.close()
    violations = []
    if watchdog.trips == 0:
        violations.append("watchdog never tripped under an injected stall")
    if any(not o for o in outs):
        violations.append("a request produced no output under the stall")
    if health.state != "degraded":
        violations.append(f"the hang did not degrade health "
                          f"(state {health.state!r})")
    elif not health.to("serving"):
        violations.append("health would not recover to serving")
    return _result("hung_dispatch", eng, chaos,
                   extra_violations=violations, trips=watchdog.trips)


class _FlakyProxy:
    """Deterministic mid-transfer disconnect injector for the
    weight-stream drill: a TCP proxy relaying to an upstream WeightServer
    that hard-closes the client connection after relaying ``cut_after``
    upstream bytes — for the first ``cuts`` connections; later ones relay
    cleanly, so a resuming fetch always finishes. ``drops`` counts cuts
    actually injected."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 cut_after: int, cuts: int = 2):
        import socket
        import threading

        self._socket, self._threading = socket, threading
        self.upstream = (upstream_host, upstream_port)
        self.cut_after = cut_after
        self.cuts = cuts
        self.drops = 0
        self._conns = 0
        self._lock = threading.Lock()
        self._listen = socket.socket()
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(("127.0.0.1", 0))
        self._listen.listen(8)
        self.port = self._listen.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while True:
            try:
                client, _ = self._listen.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                flaky = self._conns < self.cuts
                self._conns += 1
            self._threading.Thread(target=self._relay,
                                   args=(client, flaky),
                                   daemon=True).start()

    def _relay(self, client, flaky: bool):
        socket = self._socket
        try:
            up = socket.create_connection(self.upstream, timeout=30)
        except OSError:
            client.close()
            return

        def pump_requests():
            try:
                while True:
                    d = client.recv(65536)
                    if not d:
                        break
                    up.sendall(d)
            except OSError:
                pass

        self._threading.Thread(target=pump_requests, daemon=True).start()
        relayed = 0
        try:
            while True:
                d = up.recv(65536)
                if not d:
                    break
                if flaky and relayed + len(d) >= self.cut_after:
                    client.sendall(d[:self.cut_after - relayed])
                    with self._lock:
                        self.drops += 1
                    break  # the mid-transfer cut
                client.sendall(d)
                relayed += len(d)
        except OSError:
            pass
        finally:
            for sk in (client, up):
                # shutdown BEFORE close: the pump thread's in-flight recv
                # holds a kernel reference to the socket, so a bare close
                # would not emit the FIN until that recv returns — the
                # fetch client would stall on its own timeout instead of
                # seeing the disconnect immediately
                try:
                    sk.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sk.close()
                except OSError:
                    pass

    def close(self):
        try:
            self._listen.close()
        except OSError:
            pass


def drill_weight_stream_disconnect(make_engine) -> DrillResult:
    """Mid-transfer disconnects + cache corruption on the weight stream:
    the slice fetch must RESUME through the range machinery (reconnect,
    re-fetch only the missing chunks) and end byte-identical to an
    uninterrupted reference fetch; then a corrupted resident byte must
    fail its sidecar CRC on the next fetch and be repaired."""
    import os
    import tempfile

    import numpy as np

    from ..io.loader import write_model
    from ..io.stream import WeightServer, fetch_model_slices
    from ..models.spec import TransformerSpec
    from ..ops.quants import FloatType

    tmp = tempfile.mkdtemp(prefix="dllama-chaos-stream-")
    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=300, seq_len=32,
                           weights_float_type=FloatType.Q40)
    rng = np.random.default_rng(5)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    tensors = {"tok_embedding": t(spec.vocab_size, spec.dim),
               "rms_att": 1 + t(spec.n_layers, spec.dim),
               "rms_ffn": 1 + t(spec.n_layers, spec.dim),
               "rms_final": 1 + t(spec.dim),
               "wcls": t(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        tensors[name] = t(spec.n_layers, *shape)
    src = os.path.join(tmp, "model.bin")
    write_model(src, spec, tensors)
    violations: list = []
    details: dict = {}
    server = WeightServer(src, host="127.0.0.1")
    proxy = _FlakyProxy("127.0.0.1", server.port, cut_after=64 << 10,
                        cuts=2)
    try:
        flaky_dst = os.path.join(tmp, "flaky", "model.bin")
        fetch_model_slices(f"127.0.0.1:{proxy.port}", flaky_dst,
                           FloatType.Q40, 1, {0}, quiet=True,
                           connect_window=20, max_resumes=8,
                           chunk_bytes=16 << 10)
        ref_dst = os.path.join(tmp, "ref", "model.bin")
        fetch_model_slices(f"127.0.0.1:{server.port}", ref_dst,
                           FloatType.Q40, 1, {0}, quiet=True)
        details["drops"] = proxy.drops
        if proxy.drops == 0:
            violations.append("the proxy never cut a connection — the "
                              "drill injected nothing")
        with open(flaky_dst, "rb") as a, open(ref_dst, "rb") as b:
            if a.read() != b.read():
                violations.append("resumed fetch is not byte-identical to "
                                  "the uninterrupted reference fetch")
        # corruption arm: flip one resident byte; the sidecar CRC must
        # catch it on the next fetch and re-fetch exactly that range
        size = os.path.getsize(src)
        with open(flaky_dst, "r+b") as fh:
            fh.seek(size // 2)
            byte = fh.read(1)
            fh.seek(size // 2)
            fh.write(bytes([byte[0] ^ 0xFF]))
        fetch_model_slices(f"127.0.0.1:{server.port}", flaky_dst,
                           FloatType.Q40, 1, {0}, quiet=True)
        with open(flaky_dst, "rb") as a, open(ref_dst, "rb") as b:
            if a.read() != b.read():
                violations.append("CRC verification did not repair the "
                                  "corrupted cache range")
    finally:
        proxy.close()
        server.close()
    return DrillResult(name="weight_stream_disconnect",
                       passed=not violations, violations=violations,
                       details=details)


def drill_kill_mid_handoff(make_engine, inject=frozenset()) -> DrillResult:
    """THE disaggregation acceptance drill (ISSUE 14): kill the decode
    pool MID-PAGE-TRANSFER — after its journal durably holds the handoff
    admit (the durability point of the hand-over protocol), while page
    records are still crossing the TCP page channel — then restart it on
    the same journal. Recovery must re-admit the handed-off requests,
    the re-fetched pages must adopt, and the continued streams must be
    BITWISE the uninterrupted single-pool run (greedy AND seeded-sampled
    via the journal's coin cursor), with BOTH pools ending in a clean
    ``PagedAllocator.audit``.

    ``inject={"drop-page-in-flight"}`` is the gate's mutation arm: every
    shipped page's payload is zeroed and RE-FRAMED UNDER A VALID CRC —
    corruption the channel's framing cannot see — so the decode pool
    attends over junk and the bitwise gate must go red (tools/ci.sh
    asserts loadcheck exits 1 under it)."""
    import os
    import tempfile

    from .disagg import DisaggPair, prefill_stub, stub_needs_handoff
    from .journal import RequestJournal

    from .continuous import Request

    violations: list = []
    chaos = ChaosMonkey(
        drop_page_in_flight="drop-page-in-flight" in inject)
    tmp = tempfile.mkdtemp(prefix="dllama-chaos-handoff-")
    jp_path = os.path.join(tmp, "prefill.journal")
    jd_path = os.path.join(tmp, "decode.journal")

    # uninterrupted single-pool reference: same recipe, same requests
    ref_eng = _recovery_engine()
    ref_reqs = []
    for tokens, steps, temp, topp, seed in _HANDOFF_REQS:
        r = Request(tokens=list(tokens), steps=steps, temperature=temp,
                    topp=topp, seed=seed)
        ref_eng.submit(r)
        ref_reqs.append(r)
    _drain(ref_eng)
    ref_outs = [r.out for r in ref_reqs]

    prefill = _recovery_engine(journal=RequestJournal(jp_path))
    journal_a = RequestJournal(jd_path)
    decode_a = _disagg_decode_engine(journal_a)
    pair = DisaggPair(prefill, decode_a, channel_host="127.0.0.1",
                      chaos=chaos)
    stubs = []
    for tokens, steps, temp, topp, seed in _HANDOFF_REQS:
        stub, _ = prefill_stub(tokens, steps, temperature=temp,
                               topp=topp, seed=seed)
        prefill.submit(stub)
        stubs.append((stub, steps))
    _drain(prefill)
    cut = 0
    for stub, steps in stubs:
        if not stub_needs_handoff(stub):
            violations.append(f"stub {stub.index} retired without a "
                              f"continuation — nothing to hand off")
            continue
        try:
            # the decode admit lands in its journal, then the transfer is
            # CUT after one page — the kill window
            pair.handoff(stub, steps, cut_after=1)
            violations.append("page transfer was never cut mid-flight")
        except OSError:
            cut += 1
    # "kill" the decode pool: discard engine A entirely (its journal — the
    # durable admits — survives, exactly what a SIGKILL leaves behind;
    # the file handle closes so the restart reads a settled file)
    journal_a.sync(force=True)
    decode_a.close()
    journal_a._fh.close()
    del decode_a

    # restart: fresh decode pool on the same journal; recovery re-admits,
    # the channel still holds the unacked page records — re-fetch + adopt
    journal_b = RequestJournal(jd_path)
    pre_entries = journal_b.incomplete()
    decode_b = _disagg_decode_engine(journal_b)
    n_rec = decode_b.recover()
    with decode_b._lock:
        recovered = list(decode_b._queue)
    from ..obs import tracectx as _tracectx

    for stub, steps in stubs:
        # the channel serves the handoff's trace identity NEXT TO its
        # pages (the TRACE command) — the restarted pool cross-checks it
        # against the trace the prefill stub opened before adopting
        # (fetch first: a completed fetch ACKs and retires the record)
        hdr = pair._client.trace(f"h{stub.index}")
        if hdr is None:
            violations.append(f"page channel lost the trace header for "
                              f"handoff h{stub.index}")
        elif _tracectx.parse_header(hdr).trace_id \
                != stub.trace.trace_id:
            violations.append(
                f"page channel trace for h{stub.index} does not match "
                f"the prefill stub's trace — the shipped pages would "
                f"join the wrong trace")
        records = pair._client.fetch(f"h{stub.index}")
        if records:
            decode_b.allocator.adopt_remote_pages(
                stub.tokens[:len(stub.tokens) - 1], records)
    _drain(decode_b)
    if n_rec != cut:
        violations.append(f"expected {cut} journaled handoffs to recover, "
                          f"got {n_rec}")
    for req in recovered:
        # recovered ids restart from the decode journal's next_id; map to
        # the reference by prompt (the original prompt is the replay
        # prefix)
        want = None
        for i, (tokens, *_rest) in enumerate(_HANDOFF_REQS):
            if list(req.tokens[:len(tokens)]) == list(tokens):
                want = ref_outs[i]
                break
        if want is None:
            violations.append("recovered request matches no reference "
                              "prompt")
        elif req.out != want:
            violations.append(
                "recovered handoff stream diverged from the uninterrupted "
                "single-pool reference (first "
                f"{min(len(req.out), len(want))} positions compared)")
    if decode_b.allocator.remote_adopted == 0 and not violations:
        violations.append("no pages were adopted on the restarted decode "
                          "pool — the re-fetch path never ran")
    # trace continuity across kill-mid-handoff (ISSUE 15): the decode
    # journal's admits carried the trace the PREFILL pool opened (same
    # trace_id, handoff-linked); the restarted pool's recovery must
    # continue it again (now 'recovers'-linked — the second seam)
    violations += _trace_continuity_violations(recovered, pre_entries,
                                               "recovers")
    for name, eng in (("prefill", prefill), ("decode", decode_b)):
        for p in eng.audit_pages():
            violations.append(f"{name} pool audit: {p}")
    details = {"handoffs_cut": cut, "recovered": n_rec,
               "pages_adopted": decode_b.allocator.remote_adopted,
               **chaos.injection_summary()}
    pair._server.close()
    prefill.close()
    decode_b.close()
    journal_b.close()
    return DrillResult(name="kill_mid_handoff", passed=not violations,
                       violations=violations, details=details)


def _disagg_decode_engine(journal=None):
    """The kill-mid-handoff drill's decode pool: the recovery-drill
    engine recipe with the DCN ingestion knob on."""
    from ..models.spec import TransformerSpec
    from ..models.synth import synth_params
    from ..obs.metrics import Registry
    from .continuous import ContinuousEngine

    spec = TransformerSpec(**_RECOVERY_SPEC_KW)
    params = synth_params(spec, q40=False, seed=4, scale=0.3)
    return ContinuousEngine(spec, params, slots=2, temperature=0.8,
                            topp=0.9, seed=11, metrics=Registry(),
                            prefill_chunk=4, page_size=4, kv_pages=24,
                            journal=journal, remote_pages=True)


# drill names that make up the ISSUE 9 recovery gate (loadcheck surfaces
# their verdicts as dedicated columns in its JSON row)
RECOVERY_DRILLS = ("journal_wal", "kill_mid_decode", "hung_dispatch",
                   "weight_stream_disconnect")

# drill names that make up the ISSUE 12 KV-tiering gate (same loadcheck
# coverage contract as RECOVERY_DRILLS: the baseline band file names them,
# and a full run that silently skips one fails the gate)
TIERING_DRILLS = ("tier_spill_storm",)

# ... and the ISSUE 14 disaggregation gate (kill the decode pool mid-page-
# transfer; recovery via its journal must be bitwise, both pools' audits
# clean) — same coverage contract, under "disagg_drills" in the baseline
DISAGG_DRILLS = ("kill_mid_handoff",)

DRILLS = (
    ("pool_exhaustion", drill_pool_exhaustion),
    ("transient_starvation", drill_transient_starvation),
    ("oversized_prompt", drill_oversized_prompt),
    ("disconnect", drill_disconnect),
    ("latency_spike", drill_latency_spike),
    ("profiler_under_load", drill_profiler_under_load),
    ("tier_spill_storm", drill_tier_spill_storm),
    ("journal_wal", drill_journal_wal),
    ("kill_mid_handoff", drill_kill_mid_handoff),
    ("kill_mid_decode", drill_kill_mid_decode),
    ("hung_dispatch", drill_hung_dispatch),
    ("weight_stream_disconnect", drill_weight_stream_disconnect),
)


def run_drills(make_engine, which=None, inject=None) -> list[DrillResult]:
    """Run the drill suite against fresh engines from ``make_engine``
    (a callable accepting ``chaos=`` plus engine-constructor overrides;
    every drill gets its own engine — faults must not bleed). ``which``
    filters by drill name; ``inject`` names seeded mutations forwarded to
    drills that accept them (the gate's self-test arms). A drill that
    RAISES is converted into a failed result — the gate must report, not
    crash."""
    import inspect

    inject = frozenset(inject or ())
    results = []
    for name, fn in DRILLS:
        if which is not None and name not in which:
            continue
        kwargs = ({"inject": inject}
                  if "inject" in inspect.signature(fn).parameters else {})
        try:
            results.append(fn(make_engine, **kwargs))
        except Exception as e:  # noqa: BLE001 - report, never crash the gate
            results.append(DrillResult(
                name=name, passed=False,
                violations=[f"drill raised {type(e).__name__}: {e}"],
                details={}))
    return results


def render_drill_table(results) -> str:
    """The human verdict table (tracecheck-style)."""
    lines = [f"{'drill':<24} {'verdict':<8} detail"]
    for r in results:
        detail = ("; ".join(r.violations) if r.violations
                  else ", ".join(f"{k}={v}" for k, v in
                                 sorted(r.details.items())
                                 if not isinstance(v, str)))
        lines.append(f"{r.name:<24} {'OK' if r.passed else 'FAIL':<8} "
                     f"{detail}")
    return "\n".join(lines)
