"""Generation checkpoint/resume.

The reference has NO checkpoint subsystem (SURVEY.md §5: inference-only; its
nearest analog is the startup weight-scatter protocol, transformer.cpp:
250-273). Resumable generation is a capability extension: the complete decode
state is (KV-cache prefix, next token, position, sampler RNG state), and all
of it is exact — the xorshift64* stream is a single uint64, the cache is
plain f32 — so a resumed run continues BIT-IDENTICALLY to the run that was
interrupted (test_checkpoint.py proves split == unsplit token streams).

Format: one .npz with a version field and the 28-byte spec header for
compatibility checking; cache arrays are gathered to host (works for sharded
engines — np.asarray on a sharded array is an all-gather) and re-sharded on
load by the restoring engine's own mesh, so a checkpoint written by a tp=4
run restores into a tp=8 run and vice versa.
"""

from __future__ import annotations

import numpy as np

from ..models.llama import KVCache
from .generate import Engine
from .sampling import Sampler

FORMAT_VERSION = 1


def save_generation_state(path: str, engine: Engine, sampler: Sampler,
                          pos: int, token: int,
                          tokens_out: list[int],
                          prompt_rest: list[int] | None = None) -> None:
    """Snapshot a generation: resume later with load + generate(resume=...).

    ``pos``/``token``: the next inference step's inputs (GenStats.final_pos /
    final_token from the interrupted run). ``tokens_out``: tokens emitted so
    far (stored so the caller can reconstruct the full stream).
    ``prompt_rest``: prompt tokens the interrupted run had NOT yet consumed
    (GenStats.prompt_rest) — without them a resumed run would sample where
    the unsplit run forces, silently diverging.
    """
    # write through a file object: np.savez(str_path) would silently append
    # '.npz', landing the file somewhere other than the path we report
    with open(path, "wb") as f:
        _savez(f, engine, sampler, pos, token, tokens_out,
               prompt_rest or [])


def _savez(f, engine, sampler, pos, token, tokens_out, prompt_rest):
    np.savez(
        f,
        version=np.int32(FORMAT_VERSION),
        header=np.frombuffer(engine.spec.header(), dtype=np.uint8),
        # stored f32 regardless of engine cache dtype (np.savez can't hold
        # bf16; f32 is lossless for both); gathers if sharded. Only the live
        # prefix [0, pos) is stored — the suffix is dead (masked by every
        # attention path) and would make each 7B/2048 checkpoint ~2.1GB
        # regardless of progress
        # dlint: allow[D001] checkpointing gathers the cache by design
        k=np.asarray(engine.cache.k[:, :pos]).astype(np.float32),
        # dlint: allow[D001] (module docstring: np.asarray == all-gather)
        v=np.asarray(engine.cache.v[:, :pos]).astype(np.float32),
        cache_dtype=np.array(np.dtype(engine.cache_dtype).name),
        pos=np.int32(pos),
        token=np.int32(token),
        rng_state=np.uint64(sampler.rng.state),
        tokens_out=np.asarray(tokens_out, dtype=np.int32),  # dlint: allow[D001] host list
        prompt_rest=np.asarray(prompt_rest, dtype=np.int32),  # dlint: allow[D001] host list
    )


def load_generation_state(
        path: str, engine: Engine,
        sampler: Sampler) -> tuple[int, int, list[int], list[int]]:
    """Restore a snapshot into ``engine``/``sampler``.

    Returns (pos, token, tokens_out, prompt_rest) — pass (pos, token) to
    generate(resume=...) and prompt_rest to its ``resume_prompt``. Raises
    ValueError on format/spec mismatch.
    """
    import jax.numpy as jnp

    z = np.load(path)
    version = int(z["version"])
    if version != FORMAT_VERSION:
        raise ValueError(f"checkpoint version {version}, expected "
                         f"{FORMAT_VERSION}")
    if z["header"].tobytes() != engine.spec.header():
        raise ValueError("checkpoint spec header does not match the loaded "
                         "model")
    saved_dtype = str(z["cache_dtype"]) if "cache_dtype" in z else "float32"
    if saved_dtype != np.dtype(engine.cache_dtype).name:
        # restoring into a different cache precision would silently break
        # the bit-identical-resume contract (module docstring)
        raise ValueError(
            f"checkpoint cache dtype {saved_dtype!r} does not match the "
            f"engine's {np.dtype(engine.cache_dtype).name!r} — resume with "
            f"the same --kv-cache-dtype")
    def _restore(a):  # zero-pad the dead suffix back to seq_len
        full = np.zeros((a.shape[0], engine.spec.seq_len, *a.shape[2:]),
                        np.float32)
        full[:, :a.shape[1]] = a
        return jnp.asarray(full, dtype=engine.cache_dtype)

    cache = KVCache(_restore(z["k"]), _restore(z["v"]))
    if engine.sharded:
        from ..parallel import shard_cache

        cache = shard_cache(cache, engine.mesh)
    engine.cache = cache
    sampler.rng.state = int(z["rng_state"])
    rest = (z["prompt_rest"].astype(int).tolist()
            if "prompt_rest" in z else [])
    return (int(z["pos"]), int(z["token"]),
            z["tokens_out"].astype(int).tolist(), rest)
