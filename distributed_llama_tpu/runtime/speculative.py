"""Self-speculative decoding: n-gram drafter + lossless acceptance rules.

BENCH_r05 pinned a hard per-token collective-latency floor the fused tp
scheme cannot remove (13b-tp8: 1.13 ms/token of all-gather hop latency
across 161 collectives — 15% of the projection, dominant on worse
interconnects). Speculative decoding (Leviathan et al. 2023) amortizes it:
draft K-1 cheap guesses, score current-token + drafts in ONE K-query
dispatch (models/llama.forward_batch_spec_paged), keep the longest prefix
the real model agrees with — each dispatch pays the per-layer collective
schedule once for up to K emitted tokens.

The drafter is prompt-lookup / n-gram self-drafting (Saxena 2023): the
proposal for "what comes next" is whatever followed the most recent earlier
occurrence of the stream's final n-gram. No second model, no extra
weights — exactly right for a reproduction that ships one checkpoint, and
strong on the repetitive structure real decodes (and the reference's
greedy loops) exhibit.

Losslessness contract (the tier-1 gate of tests/test_speculative.py):

* greedy rows (temperature 0): a draft is accepted iff it equals the
  verified argmax — the emitted stream is BITWISE the spec-off stream by
  construction (the verify forward reproduces decode logits bitwise);
* sampled rows (temperature > 0): Leviathan-style rejection sampling
  against the sampler's EFFECTIVE distribution (temperature softmax +
  the reference's nucleus filter, ``effective_probs``). The n-gram
  drafter is a point mass q = one-hot(draft), so accept with probability
  p(draft); on rejection resample from the residual norm(max(0, p - q)) =
  p with the draft zeroed, renormalized. Combined law: P(x = draft) =
  p(draft), P(x = y) = (1 - p(draft)) * p(y)/(1 - p(draft)) = p(y) — the
  output DISTRIBUTION is provably the baseline sampler's (the coin
  stream realization necessarily differs; temperature-0 keeps bitwise
  stream parity).

Everything here is host-side numpy over one row's logits — the device half
is the K-query verify forward; the engine half (draft window assembly,
replay, page-table rollback) lives in runtime/continuous.step_spec.

Interaction with token-budget scheduling (ISSUE 18): ``--spec-k`` and
``--dispatch-tokens`` are MUTUALLY EXCLUSIVE, rejected at argparse time
(exit 2) and again in ContinuousEngine.__init__. Both features spend the
same resource — the per-row span of the fused dispatch window. Speculative
decoding fills each row's extra columns with draft guesses to verify;
mixed batching gives every decode row span 1 and spends the remainder on
one prefill slice. A combined mode would have to arbitrate the window
between drafts and the slice per dispatch; until someone builds that,
pick one: --spec-k when decode latency dominates (collective-floor
amortization), --dispatch-tokens when prefill/decode interference
dominates (attainment under mixed load, tools/loadcheck.py --budget).
"""

from __future__ import annotations

import numpy as np

from .sampling import Sampler, sample_mult, softmax_f32


def draft_tokens(history, k: int, max_n: int = 3, min_n: int = 1) -> list:
    """Prompt-lookup proposal: up to ``k`` tokens copied from after an
    earlier occurrence of the stream's final n-gram.

    Tries the longest n-gram first (``max_n`` down to ``min_n``) — longer
    context matches give higher-precision continuations; the n=1 fallback
    keeps the drafter productive on short histories. Among matches of one
    n-gram length, the NEAREST one whose continuation fills the whole
    window wins (recency = relevance), falling back to the longest
    continuation available — matches near the stream's end truncate, so
    a short-period repetition (the greedy-loop shape) would otherwise
    never fill the window. Returns [] when no earlier occurrence exists
    (the verify dispatch then scores only real positions).
    O(len(history) * n) per candidate length via a backwards scan —
    histories are bounded by seq_len, and this runs once per dispatch,
    not per token.
    """
    if k <= 0:
        return []
    h = list(history)
    for n in range(max_n, min_n - 1, -1):
        if len(h) <= n:
            continue
        tail = h[-n:]
        best: list = []
        # windows equal to the tail, ending BEFORE the stream's end —
        # j + n <= len(h) - 1, so a match's continuation is never empty
        for j in range(len(h) - n - 1, -1, -1):
            if h[j:j + n] == tail:
                cont = h[j + n:j + n + k]
                if len(cont) == k:
                    return cont
                if len(cont) > len(best):
                    best = cont
        if best:
            return best
    return []


def effective_probs(logits: np.ndarray, temperature: float,
                    topp: float) -> np.ndarray:
    """The baseline sampler's EFFECTIVE distribution over the vocab — the
    per-step law Sampler.sample realizes with one uniform coin, as an
    explicit (V,) f32 vector the rejection test can evaluate.

    Mirrors runtime/sampling.py exactly: softmax(logits/temperature) in
    f32; topp outside (0,1) keeps the full multinomial; otherwise the
    reference nucleus filter — (1-p)/(n-1) cutoff pre-filter, stable
    descending sort, cut at cumulative > topp — restricted and
    renormalized by the kept prefix's f32-accumulated mass (the same
    running sum sample_topp scales its coin by). The degenerate nucleus
    (cutoff keeps nothing) collapses to the argmax point mass, matching
    the host sampler's fallback.
    """
    # dlint: allow[D001] host acceptance math — logits are host by contract
    probs = softmax_f32(np.asarray(logits, np.float32)
                        / np.float32(temperature))
    n = len(probs)
    if topp <= 0 or topp >= 1 or n == 1:
        return probs
    cutoff = np.float32(1.0 - topp) / np.float32(n - 1)
    idx = np.nonzero(probs >= cutoff)[0]
    out = np.zeros_like(probs)
    if len(idx) == 0:
        out[int(np.argmax(probs))] = 1.0
        return out
    order = idx[np.argsort(-probs[idx], kind="stable")]
    p_sorted = probs[order].astype(np.float32)
    cum = np.float32(0.0)
    last = len(order) - 1
    for i, p in enumerate(p_sorted):
        cum += p
        if cum > topp:
            last = i
            break
    kept = order[:last + 1]
    out[kept] = probs[kept] / cum
    return out


def accept_or_resample(logits: np.ndarray, draft: int,
                       sampler: Sampler) -> tuple[int, bool]:
    """One Leviathan rejection-sampling step for a point-mass drafter.

    Returns (next_token, accepted). Accept the draft with probability
    p_eff(draft) (one coin from the row's xorshift stream); on rejection
    draw ONE more coin and CDF-walk the residual distribution — p_eff with
    the draft zeroed, renormalized — so the emitted token's law is exactly
    p_eff (module docstring). Draft positions never reached by the replay
    consume no coin at all: the stream advances only for decisions
    actually made, keeping reruns of a seeded engine deterministic.
    """
    # dlint: allow[D001] host acceptance math — logits are host by contract
    p = effective_probs(np.asarray(logits, np.float32)[:sampler.vocab_size],
                        sampler.temperature, sampler.topp)
    coin = sampler.rng.f32()
    if coin < p[draft]:
        return int(draft), True
    residual = p.copy()
    residual[draft] = 0.0
    total = np.float32(residual.sum(dtype=np.float32))
    if total <= 0.0:
        # p_eff was a point mass on the draft yet the coin landed outside
        # [0, 1) float mass — unreachable for xorshift f32 coins, but a
        # deterministic fallback beats a crash
        return int(np.argmax(p)), False
    return int(sample_mult(residual / total, sampler.rng.f32())), False
