"""On-device generation loop: the whole token loop as ONE jitted program.

The reference's generation loop (tokenizer.cpp:321-394) calls infer() once per
token from the host. On TPU that per-token host round-trip costs more than the
7B forward pass itself (dispatch + transfer latency, especially over a remote
runtime), so the TPU-native hot path moves the loop on device: a ``lax.scan``
over decode steps where each step runs the forward pass AND picks the next
token, with no host involvement until the whole chain is done.

Sampling runs on device with the reference's semantics (tokenizer.cpp:206-319):
argmax at temperature 0, otherwise softmax(logits/temp) + nucleus top-p with
the (1-p)/(n-1) cutoff pre-filter, or a plain multinomial CDF walk when topp
is outside (0,1). The per-step random coins are the ONE thing precomputed on
the host: the reference draws them from a stateful xorshift64* stream
(utils.cpp:27-38), and the stream is data-independent, so the host pre-draws
``coins[i]`` for every post-prompt step and the device consumes them in order
— bit-identical coin sequence, no uint64 emulation on device.

Early stop: the reference breaks on BOS before decoding it. The single-
sequence loop is a ``lax.while_loop`` that terminates on a produced BOS, so
an early stop costs only the steps actually run; unwritten tail slots of the
token buffer read as BOS, and the host truncates at the first BOS as always.
The batch loop is a fixed-length scan (lockstep rows share the position
clock), with finished rows frozen to emit the same BOS-filled tail — the two
paths share one post-BOS output contract.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

# step_fn(params, cache, tokens (1,), pos) -> (logits (1, V), cache)
StepFn = Callable[..., tuple[jax.Array, Any]]


def _mult_walk(probs: jax.Array, coin: jax.Array) -> jax.Array:
    """Multinomial CDF walk (tokenizer.cpp:226-238)."""
    v = probs.shape[-1]
    cdf = jnp.cumsum(probs)
    return jnp.minimum(jnp.searchsorted(cdf, coin, side="right"),
                       v - 1).astype(jnp.int32)


def _nucleus_walk(probs: jax.Array, coin: jax.Array,
                  topp: jax.Array | float) -> jax.Array:
    """Nucleus pick (tokenizer.cpp:240-281): cutoff pre-filter, stable
    descending sort, cut at cum > topp, CDF walk over the kept prefix
    scaled by coin*cum. Works with static or traced ``topp`` — the ONE
    copy of the math shared by sample_device and sample_device_dynamic.
    When the cutoff keeps nothing (possible for topp < 1/v) falls back to
    the argmax, like the host Sampler."""
    v = probs.shape[-1]
    cutoff = (1.0 - topp) / (v - 1)
    kept = jnp.where(probs >= cutoff, probs, 0.0)
    order = jnp.argsort(-kept)  # stable: ties keep index order
    p_sorted = kept[order]
    cum = jnp.cumsum(p_sorted)
    # first index where cumulative prob exceeds topp (== last kept index)
    last = jnp.argmax(cum > topp)
    last = jnp.where(cum[-1] > topp, last, v - 1)
    r = coin * cum[last]
    idx = jnp.minimum(jnp.searchsorted(cum, r, side="right"), last)
    nuc = order[idx].astype(jnp.int32)
    return jnp.where(cum[-1] > 0.0, nuc,
                     jnp.argmax(probs).astype(jnp.int32))


def sample_device(logits: jax.Array, coin: jax.Array, temperature: float,
                  topp: float) -> jax.Array:
    """Reference Sampler::sample on device. logits (V,) f32; coin scalar f32.

    temperature/topp are static (fixed per generation run), so the strategy
    branch resolves at trace time.
    """
    if temperature == 0.0:
        return jnp.argmax(logits).astype(jnp.int32)
    probs = jax.nn.softmax(logits.astype(jnp.float32) / temperature)
    if topp <= 0 or topp >= 1:
        return _mult_walk(probs, coin)
    return _nucleus_walk(probs, coin, topp)


def sample_device_dynamic(logits: jax.Array, coin: jax.Array,
                          temperature: jax.Array,
                          topp: jax.Array) -> jax.Array:
    """Reference sampler with TRACED temperature/topp — the per-row variant
    for the fused continuous chain (runtime/continuous.step_many), where
    each slot carries its own request's sampling params. Computes the
    greedy/multinomial/nucleus candidates and selects (the strategy branch
    cannot resolve at trace time); semantics mirror sample_device and the
    host Sampler, including the degenerate-nucleus argmax fallback.
    """
    greedy = jnp.argmax(logits).astype(jnp.int32)
    safe_t = jnp.where(temperature == 0.0, 1.0, temperature)
    probs = jax.nn.softmax(logits.astype(jnp.float32) / safe_t)
    in01 = (topp > 0.0) & (topp < 1.0)
    return jnp.where(temperature == 0.0, greedy,
                     jnp.where(in01, _nucleus_walk(probs, coin, topp),
                               _mult_walk(probs, coin)))


def greedy_verify_tokens(logits: jax.Array) -> jax.Array:
    """Device-side argmax over a (B, K, V) speculative-verify logit block
    (runtime/continuous.step_spec): when EVERY active row is greedy the
    host replay needs only the argmax ids, so the chain ships a (B, K)
    int32 block instead of the full f32 logit cube — the same transfer cut
    the fused chain's greedy_only branch makes. Ties break lowest-index,
    matching np.argmax in the host sampler (sample_argmax), so the greedy
    bitwise-parity contract is unchanged."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _make_decode_run(step_fn: StepFn, max_steps: int, temperature: float,
                     topp: float):
    """Build run(params, cache, prompt_padded, first_token, coins,
    start_pos, num_steps) -> (tokens (max_steps,), cache): the fused
    generation loop (raw traceable fn; make_decode_loop jits it).

    ``max_steps`` (typically seq_len) fixes the BUFFER shapes only; the
    actual step budget ``num_steps`` is a traced scalar bound of the
    while_loop, so every --steps value reuses ONE compilation (a distinct
    --steps used to recompile the whole chain — the round-1 cold-start
    trap). The int32 token buffer is max_steps long: seq_len=2048 costs
    8 kB, nothing, against a ~minute XLA compile per distinct shape.

    prompt_padded: (max_steps+1,) int32, prompt tokens then -1 padding.
    Step ``i`` (absolute position start_pos + i) forces prompt_padded[i+1]
    when >= 0, else samples — exactly the forced-prompt-then-sample
    schedule of the reference loop (tokenizer.cpp:360-366). coins:
    (max_steps,) f32, consumed at sampled steps. start_pos: 0 for a fresh
    generation, the checkpointed position for a resumed one.
    """

    from ..io.tokenizer import BOS

    def run(params, cache, prompt_padded, first_token, coins, start_pos,
            num_steps):
        """start_pos: absolute position of the first step — 0 for a fresh
        generation, the checkpointed position for a resumed one (the cache
        must already hold positions 0..start_pos-1; runtime/checkpoint.py).

        The loop is a lax.while_loop, not a scan: a sampled BOS ends the
        chain EARLY on device (the reference's stop condition), so a
        2048-step budget that terminates at step 50 costs 50 forwards, not
        2048 — and a num_steps budget below max_steps likewise stops at
        num_steps. The token buffer is BOS-initialized — untouched slots
        read as the terminator, so the host-side truncation is unchanged.
        """
        if isinstance(params, dict):
            # packed-i4 carriers always unpack here (a bitcast, not a
            # compute pass); u8 leaves convert iff DLLAMA_Q40_I4=on.
            # In-program because int4 cannot cross this runtime's jit
            # boundary.
            from ..ops.pallas_q40 import chain_weight_prep

            params = chain_weight_prep(params)
        toks0 = jnp.full((max_steps,), BOS, dtype=jnp.int32)

        def cond(carry):
            i, done, token, cache, toks = carry
            return (i < num_steps) & ~done

        def body(carry):
            i, done, token, cache, toks = carry
            logits, cache = step_fn(params, cache, token[None],
                                    start_pos + i)
            sampled = sample_device(logits[0], coins[i], temperature, topp)
            nxt = jnp.where(prompt_padded[i + 1] >= 0, prompt_padded[i + 1],
                            sampled)
            # stop on a PRODUCED BOS (the input token at i=0 is legitimately
            # BOS — every prompt starts with it)
            return (i + 1, nxt == BOS, nxt, cache, toks.at[i].set(nxt))

        _, _, _, cache, toks = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.bool_(False), first_token, cache,
                         toks0))
        return toks, cache

    run.__name__ = "decode_chain"
    return run


def make_decode_loop(step_fn: StepFn, max_steps: int, temperature: float,
                     topp: float):
    """The fused generation loop, jitted (see _make_decode_run)."""
    return jax.jit(_make_decode_run(step_fn, max_steps, temperature, topp),
                   donate_argnums=1)


def make_decode_loop_aot(step_fn: StepFn, max_steps: int,
                         temperature: float, topp: float,
                         exe_cache_dir: str | None = None):
    """make_decode_loop variant that AOT-compiles with the parameter layouts
    PINNED to what the placed arrays actually have, instead of letting the
    (tunnel-side) AOT compiler choose compact input layouts and convert
    them inside the program.

    Why: with unconstrained inputs the compiler may pick a parameter layout
    different from what the Pallas kernels pin (row-major), materializing
    layout-conversion copies of every multi-GB weight stack INSIDE the
    chain — at 13B those tile-padded temps alone are ~10 GB, an OOM on a
    16 GB chip. Pinning in_shardings to a layout we choose does not work
    either: device_put over the tunnel runtime silently keeps its own
    transfer layout, and Layout.AUTO can publish formats the final
    executable then rejects. So the one self-consistent order is place
    FIRST, read each leaf's actual Format, and compile with exactly those —
    the executable accepts the arrays by construction, and any residual
    conversion is the compiler's explicit, visible choice.

    ``exe_cache_dir`` (VERDICT r2 #7, sub-minute warm start): persist the
    fully-compiled executable via jax.experimental.serialize_executable,
    keyed by the sha256 of the LOWERED HLO (any code/shape/kernel change
    re-keys cleanly) + jax version + platform. Unlike the persistent HLO
    compile cache, the serialized executable also carries the compiled
    custom-call artifacts, so a warm process skips the per-kernel
    compile-service round-trips the first execution otherwise pays.

    Returns compile_and_place(params_host, cache, prompt, first, coins,
    start, n) -> (compiled, params_on_device).
    """
    import numpy as np

    run = _make_decode_run(step_fn, max_steps, temperature, topp)

    def compile_and_place(params_host, *rest):
        def sds(a):
            # dlint: allow[D001] host-tree leaves only — shape/dtype probe
            a = np.asarray(a) if not hasattr(a, "dtype") else a
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        placed = jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a)), params_host)
        touchers = _touch_async(placed)
        try:
            # Array.format is newer-jax; older images pin layouts via the
            # sharding only (same in_shardings slot either way)
            param_formats = jax.tree_util.tree_map(
                lambda a: getattr(a, "format", None) or a.sharding, placed)
            jitted = jax.jit(run, donate_argnums=1,
                             in_shardings=(param_formats,) + (None,) * 6)
            abstract = (jax.tree_util.tree_map(sds, placed),
                        *(jax.tree_util.tree_map(sds, r) for r in rest))
            lowered = jitted.lower(*abstract)
            compiled = _load_or_compile(lowered, exe_cache_dir)
        except BaseException:
            if touchers is not None:
                # failure path: drop queued touches so they don't contend
                # with the caller's retry attempt
                touchers.shutdown(wait=False, cancel_futures=True)
            raise
        if touchers is not None:
            # success (incl. warm exe-cache hits, where compile returns in
            # seconds): queued touches must KEEP draining so the upload
            # still overlaps the first chain instead of stalling it
            touchers.shutdown(wait=False)
        return compiled, placed

    return compile_and_place


def _touch_async(placed):
    """Start materializing every placed leaf from a thread pool, so the
    host->device upload streams WHILE the caller lowers + compiles
    (VERDICT r3 #5: on the tunneled runtime device_put is lazy and the
    ~4 GB 7B upload otherwise runs serially AFTER compile, stalling the
    first chain). Reading one element forces the whole buffer resident.
    DLLAMA_UPLOAD_OVERLAP=0 disables (the measurement ladder's off arm).
    Returns the executor (caller may shutdown(wait=False)) or None."""
    import concurrent.futures as cf
    import os

    import numpy as np

    if os.environ.get("DLLAMA_UPLOAD_OVERLAP", "1") == "0":
        return None
    leaves = [a for a in jax.tree_util.tree_leaves(placed)
              if hasattr(a, "addressable_shards")]
    if not leaves:
        return None
    ex = cf.ThreadPoolExecutor(max_workers=8,
                               thread_name_prefix="dllama-upload")

    def touch(a):
        try:
            # read ONE element (tiny slice program) — a.reshape(-1) would
            # materialize a full-size device copy of every leaf. 0-d
            # leaves have no axis to slice ((0,)*-1 == () then [:1] fails
            # on a scalar) and nothing worth overlapping — read directly.
            if a.ndim == 0:
                np.asarray(a)  # dlint: allow[D001] the sync IS the point
            else:
                # dlint: allow[D001] upload touch — blocking is the point
                np.asarray(a[(0,) * (a.ndim - 1)][:1])
        except Exception as e:  # noqa: BLE001 - overlap is best-effort
            import sys

            print(f"upload touch failed ({type(e).__name__}: {e}); leaf "
                  f"uploads lazily at first use", file=sys.stderr)

    for a in sorted(leaves, key=lambda a: -a.nbytes):
        ex.submit(touch, a)
    return ex


def _load_or_compile(lowered, exe_cache_dir: str | None):
    """Deserialize a cached executable for this exact lowering, else
    compile and serialize it. Any failure in the serialization layer
    degrades to a plain compile (never blocks the run)."""
    if not exe_cache_dir:
        return lowered.compile()
    import hashlib
    import os
    import pickle
    import sys

    path = None
    try:
        # key on everything that could invalidate a compiled binary: jax +
        # runtime lib versions, the CHIP KIND (default_backend() is just
        # 'tpu' for every TPU generation), and the lowered HLO itself (which
        # embeds source line numbers in op metadata — so ANY edit to files
        # on the traced path re-keys; conservative by design)
        dev = jax.devices()[0]
        salt = (jax.__version__ + getattr(jax.lib, "__version__", "")
                + jax.default_backend() + getattr(dev, "device_kind", ""))
        key = hashlib.sha256(
            (salt + lowered.as_text()).encode()).hexdigest()[:32]
        path = os.path.join(exe_cache_dir, f"exe_{key}.pkl")
        from jax.experimental.serialize_executable import deserialize_and_load

        if os.path.exists(path):
            try:
                with open(path, "rb") as fh:
                    payload, in_tree, out_tree = pickle.load(fh)
                compiled = deserialize_and_load(payload, in_tree, out_tree)
                print(f"⏩ loaded serialized executable ({path})",
                      file=sys.stderr)
                return compiled
            except Exception as e:
                # corrupt/stale entry: drop it and fall through to a fresh
                # compile + re-serialize below (returning early here would
                # leave the cache empty for the NEXT process too)
                print(f"💡 dropping unreadable executable cache entry "
                      f"({type(e).__name__}: {e})", file=sys.stderr)
                os.unlink(path)
    except Exception as e:  # noqa: BLE001 - cache must never kill the run
        print(f"💡 executable cache unavailable "
              f"({type(e).__name__}: {e}); compiling", file=sys.stderr)
        path = None
    compiled = lowered.compile()
    if path is not None:
        try:  # serialize/write failures must not recompile or kill the run
            from jax.experimental.serialize_executable import serialize

            os.makedirs(exe_cache_dir, exist_ok=True)
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump(serialize(compiled), fh)
            os.replace(tmp, path)
        except Exception as e:  # noqa: BLE001
            print(f"💡 executable serialization unavailable "
                  f"({type(e).__name__}: {e}); continuing uncached",
                  file=sys.stderr)
    return compiled


def make_batch_decode_loop(spec, steps: int, temperature: float, topp: float,
                           step_fn: StepFn | None = None):
    """Fused decode loop over B sequences in lockstep (models/llama.
    forward_batch) — the throughput path the reference lacks (batch=1 only).

    run(params, cache, prompts (B, steps+1), first_tokens (B,),
        coins (B, steps)) -> (tokens (B, steps), cache).

    All rows share the position clock (the shared-pos contract that keeps
    the cache update an in-place dynamic_update_slice — see forward_batch).
    Ragged prompts right-pad with -1: at position p a row forces
    prompts[b, p+1] when >= 0, else samples with its own coin (vmapped
    reference sampler semantics).

    ``step_fn`` overrides the single-chip forward_batch with another
    (params, cache, tokens (B,), pos) -> (logits (B, V), cache) step — the
    tensor-parallel composition passes parallel/tp.make_sharded_forward_batch.
    """
    import functools

    from ..models.llama import forward_batch

    if steps > spec.seq_len:
        raise ValueError(f"steps={steps} exceeds seq_len={spec.seq_len}")
    if step_fn is None:
        step_fn = functools.partial(forward_batch, spec)

    from ..io.tokenizer import BOS

    def run(params, cache, prompts, first_tokens, coins):
        def body(carry, xs):
            tokens, active, cache = carry
            pos, coin_row = xs
            logits, cache = step_fn(params, cache, tokens, pos)
            sampled = jax.vmap(
                lambda lg, c: sample_device(lg, c, temperature, topp)
            )(logits, coin_row)
            forced = prompts[:, pos + 1]
            nxt = jnp.where(forced >= 0, forced, sampled)
            # a finished row (produced BOS earlier) freezes its input token
            # and emits BOS — the same post-BOS tail the single-sequence
            # while_loop's untouched buffer yields
            rec = jnp.where(active, nxt, BOS)
            active = active & (nxt != BOS)
            tokens = jnp.where(active, nxt, tokens)
            return (tokens, active, cache), rec

        B = first_tokens.shape[0]
        xs = (jnp.arange(steps, dtype=jnp.int32), coins.T)
        (_, _, cache), toks = jax.lax.scan(
            body, (first_tokens, jnp.ones((B,), bool), cache), xs)
        return toks.T, cache  # (B, steps)

    return jax.jit(run, donate_argnums=1)


