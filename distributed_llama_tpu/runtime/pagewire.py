"""The ONE page wire codec: pack/unpack + CRC framing for KV page payloads.

A KV page leaves the device pool in exactly one byte layout — the plane
tuple ``models/llama.fetch_page_planes`` reads back: ``(k, v)`` f32/bf16
planes or ``(kq, kd, vq, vd)`` Q8 codes+deltas, serialized contiguously
in tuple order. PR 12's disk tier stores that blob; ISSUE 14's DCN page
channel ships it between pools. Before this module each consumer carried
its own copy of the pack/unpack pair (the disk tier's private
``_pack_planes``), which is exactly how two "identical" layouts drift —
so the codec lives HERE and both tiers import it (the byte-identity of
the refactor is pinned by tests/test_disagg.py against a raw disk
record).

Two granularities:

* ``pack_planes``/``unpack_planes`` — the bare payload blob + the
  shape/dtype metadata needed to rebuild it. The disk tier stores the
  blob and carries the metadata in its record ref; the CRC travels in
  the segment's ``.slices`` sidecar (io/stream.append_record_verified).
* ``encode_record``/``decode_record`` — a SELF-DESCRIBING framed record
  (metadata + CRC32 + blob in one byte string) for transports with no
  sidecar: the DCN page channel ships these, and ``decode_record``
  returns None on ANY damage — short frame, garbled metadata, CRC
  mismatch — so a dropped or corrupted in-flight page degrades to a
  re-fetch (or a prefill re-derive), never to wrong attention bytes.

Frame layout (little-endian):

    u32 meta_len | meta json (shapes + dtype strs) | u32 crc32(blob)
    | u64 blob_len | blob

The blob bytes inside a frame are ``pack_planes``' output VERBATIM — the
disk tier's on-disk record and the channel's in-flight payload are the
same bytes for the same page, which is what lets PARITY.md price both
with one number.
"""

from __future__ import annotations

import json
import struct
import zlib

_HEAD = struct.Struct("<I")   # meta_len / crc32
_LEN = struct.Struct("<Q")    # blob_len


def pack_planes(planes) -> tuple[bytes, tuple]:
    """Serialize a page payload (tuple of numpy plane arrays in the page
    wire layout — (k, v) f32 planes or (kq, kd, vq, vd) Q8 codes+deltas)
    into one blob + the shape/dtype metadata needed to rebuild it."""
    import numpy as np

    metas = tuple((tuple(a.shape), a.dtype.str) for a in planes)
    blob = b"".join(np.ascontiguousarray(a).tobytes() for a in planes)
    return blob, metas


def unpack_planes(blob: bytes, metas) -> tuple:
    """pack_planes' inverse. Returns read-only views over ``blob`` — the
    consumers (device_put / .at[].set) copy anyway."""
    import numpy as np

    out, off = [], 0
    for shape, dt in metas:
        dtype = np.dtype(dt)
        n = 1
        for d in shape:
            n *= int(d)
        out.append(np.frombuffer(blob, dtype, count=n,
                                 offset=off).reshape(shape))
        off += n * dtype.itemsize
    return tuple(out)


def encode_record(planes) -> bytes:
    """One self-describing framed page record (module docstring layout):
    the DCN channel's wire unit. The payload blob is byte-identical to
    the disk tier's record for the same planes."""
    blob, metas = pack_planes(planes)
    meta = json.dumps([[list(s), d] for s, d in metas],
                      separators=(",", ":")).encode()
    return (_HEAD.pack(len(meta)) + meta
            + _HEAD.pack(zlib.crc32(blob)) + _LEN.pack(len(blob)) + blob)


def decode_record(data: bytes):
    """Planes of one framed record, CRC-verified — None on ANY damage
    (truncation, garbled metadata, checksum mismatch). The caller treats
    None as "this page never arrived": re-fetch it, or let prefill
    re-derive the positions it covered."""
    try:
        if len(data) < _HEAD.size:
            return None
        (meta_len,) = _HEAD.unpack_from(data, 0)
        off = _HEAD.size
        meta_raw = data[off:off + meta_len]
        if len(meta_raw) != meta_len:
            return None
        off += meta_len
        (crc,) = _HEAD.unpack_from(data, off)
        off += _HEAD.size
        (blob_len,) = _LEN.unpack_from(data, off)
        off += _LEN.size
        blob = data[off:off + blob_len]
        if len(blob) != blob_len or off + blob_len != len(data):
            return None
        if zlib.crc32(blob) != crc:
            return None
        metas = tuple((tuple(int(d) for d in s), dt)
                      for s, dt in json.loads(meta_raw))
        return unpack_planes(blob, metas)
    except (ValueError, KeyError, TypeError, struct.error):
        return None


def record_payload_bytes(planes_or_record) -> int:
    """Payload (blob) bytes of a page — the number the DCN budget term
    (parallel/comm_stats.dcn_handoff_budget) prices; framing overhead is
    the small constant on top."""
    if isinstance(planes_or_record, (bytes, bytearray)):
        (meta_len,) = _HEAD.unpack_from(planes_or_record, 0)
        off = _HEAD.size + meta_len + _HEAD.size
        (blob_len,) = _LEN.unpack_from(planes_or_record, off)
        return int(blob_len)
    return len(pack_planes(planes_or_record)[0])
