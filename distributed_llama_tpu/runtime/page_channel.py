"""DCN page channel: CRC-verified, resumable KV-page shipping (ISSUE 14).

The prefill pool of a disaggregated topology fills KV pages and the
decode pool attends over them — the bytes have to cross the data-center
network in between. This module is that wire: a line-framed TCP protocol
in the weight stream's mold (io/stream.py — the SPEC/GET/DONE shape,
``connect_with_retry``'s transient-only backoff, ``recv_exact``'s
short-read discipline), shipping pages in the ONE wire layout everything
else already uses: ``runtime/pagewire.encode_record`` frames — the exact
plane bytes the disk tier stores, plus self-describing metadata and a
per-page CRC32 verified on arrival.

Pull model, like the weight stream: the PREFILL side publishes a
handoff's page records under its handoff id and serves them; the DECODE
side fetches page-by-page, which makes mid-transfer resume trivial — a
dropped connection reconnects and continues from the first page it does
not hold (``max_resumes`` bounds the patience), and a page whose frame
fails its CRC re-fetches once before being given up as None (the
ingestion side then stops adoption at the gap and prefill re-derives the
suffix — damage degrades to recompute, never to wrong attention bytes).

Protocol (line-framed requests, binary responses):

* ``SPEC`` -> magic + ``<q`` protocol check (wrong server fails loudly);
* ``COUNT <hid>`` -> ``<q`` page count (-1 = unknown handoff);
* ``PAGE <hid> <idx>`` -> ``<q`` record length + the framed record
  bytes (-1 = unknown handoff/index);
* ``TRACE <hid>`` -> ``<q`` length + the handoff's traceparent header
  bytes (ISSUE 15: the distributed-trace identity rides the channel
  next to the pages it describes, for consumers that fetch pages
  WITHOUT the journal wire record — operator tooling, and the
  kill-mid-handoff restart path, which cross-checks it against the
  journaled identity before adopting; the normal decode path gets the
  same header from ``entry_from_wire``. -1 = unknown handoff or no
  trace published — a trace-less fetch still works, it just doesn't
  join);
* ``ACK <hid>`` -> ``<q`` 0; the server drops the handoff's records
  (the decode pool holds them now — the publish buffer is a relay, not
  a cache);
* ``DONE`` -> close.

Trust model: unauthenticated byte service on a trusted cluster network,
same as the weight stream (io/stream.WeightServer docstring).
"""

from __future__ import annotations

import socketserver
import struct
import threading

from ..io.stream import connect_with_retry, is_transient, recv_exact

_MAGIC = b"DLPCH1"  # page-channel protocol tag; bump on framing changes
_I64 = struct.Struct("<q")


class PageChannelServer:
    """Prefill-side record service: ``publish`` a handoff's framed page
    records, serve them until the decode pool ``ACK``s (or ``retire`` is
    called — a cancelled handoff must not strand its bytes). ``port=0``
    picks a free port (exposed as ``.port``). The store is a RELAY with
    a retention cap, not a cache: beyond ``retain_max`` unacked handoffs
    the oldest is dropped (its decode pool re-derives via prefill) — a
    flaky peer that never acks must not grow host memory without bound."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 retain_max: int = 256):
        self._lock = threading.Lock()
        self._store: dict[str, list[bytes]] = {}  # insertion-ordered
        self._traces: dict[str, str] = {}  # hid -> traceparent header
        self.retain_max = max(1, retain_max)
        self.published_pages = 0
        self.served_pages = 0
        self.evicted_handoffs = 0
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                f = self.request.makefile("rb")
                while True:
                    line = f.readline()
                    if not line or line.strip() == b"DONE":
                        return
                    parts = line.split()
                    if not parts:
                        return
                    if parts[0] == b"SPEC":
                        self.request.sendall(_MAGIC + _I64.pack(1))
                    elif parts[0] == b"COUNT" and len(parts) == 2:
                        hid = parts[1].decode("ascii", "replace")
                        with outer._lock:
                            recs = outer._store.get(hid)
                        n = -1 if recs is None else len(recs)
                        self.request.sendall(_I64.pack(n))
                    elif parts[0] == b"PAGE" and len(parts) == 3:
                        hid = parts[1].decode("ascii", "replace")
                        idx = int(parts[2])
                        with outer._lock:
                            recs = outer._store.get(hid)
                            rec = (recs[idx] if recs is not None
                                   and 0 <= idx < len(recs) else None)
                        if rec is None:
                            self.request.sendall(_I64.pack(-1))
                        else:
                            self.request.sendall(_I64.pack(len(rec)) + rec)
                            with outer._lock:
                                outer.served_pages += 1
                    elif parts[0] == b"TRACE" and len(parts) == 2:
                        hid = parts[1].decode("ascii", "replace")
                        with outer._lock:
                            hdr = outer._traces.get(hid)
                        if hdr is None:
                            self.request.sendall(_I64.pack(-1))
                        else:
                            raw = hdr.encode("ascii", "replace")
                            self.request.sendall(_I64.pack(len(raw)) + raw)
                    elif parts[0] == b"ACK" and len(parts) == 2:
                        outer.retire(parts[1].decode("ascii", "replace"))
                        self.request.sendall(_I64.pack(0))
                    else:
                        return  # malformed: drop the connection

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def publish(self, hid: str, records: list[bytes],
                trace: str | None = None) -> None:
        """Stage a handoff's framed records (+ optionally its traceparent
        header, ISSUE 15 — served by the TRACE command so the fetching
        pool joins the shipped pages to the sending pool's trace)."""
        with self._lock:
            self._store[hid] = list(records)
            if trace is not None:
                self._traces[hid] = str(trace)
            self.published_pages += len(records)
            while len(self._store) > self.retain_max:
                # dicts iterate in insertion order: drop the OLDEST
                # unacked handoff (its fetch, if it ever comes, returns
                # nothing and the decode pool prefills instead)
                gone = next(iter(self._store))
                self._store.pop(gone)
                self._traces.pop(gone, None)
                self.evicted_handoffs += 1

    def retire(self, hid: str) -> None:
        with self._lock:
            self._store.pop(hid, None)
            self._traces.pop(hid, None)

    @property
    def queue_depth(self) -> int:
        """Handoffs published and not yet acked — the /health "disagg"
        block's backlog figure."""
        with self._lock:
            return len(self._store)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class PageChannelClient:
    """Decode-side fetcher. One ``fetch`` per handoff: page-by-page pull
    with mid-transfer resume (reconnect + continue from the first
    missing page) and per-page CRC verification through
    ``pagewire.decode_record``."""

    def __init__(self, addr: str, timeout: float = 60.0,
                 connect_window: float = 20.0, max_resumes: int = 4):
        host, port_s = addr.rsplit(":", 1)
        self.host, self.port = host, int(port_s)
        self.timeout = timeout
        self.connect_window = connect_window
        self.max_resumes = max_resumes
        self.resumes = 0
        self.crc_refetches = 0

    def _connect(self):
        s = connect_with_retry(self.host, self.port, self.timeout,
                               self.connect_window)
        try:
            s.sendall(b"SPEC\n")
            head = recv_exact(s, len(_MAGIC) + _I64.size)
        except BaseException:
            s.close()
            raise
        if head[:len(_MAGIC)] != _MAGIC:
            s.close()
            raise ValueError(f"page channel protocol mismatch "
                             f"(got {head[:len(_MAGIC)]!r})")
        return s

    @staticmethod
    def _req_page(s, hid: str, idx: int) -> bytes | None:
        s.sendall(f"PAGE {hid} {idx}\n".encode())
        (n,) = _I64.unpack(recv_exact(s, _I64.size))
        if n < 0:
            return None
        return recv_exact(s, n)

    def trace(self, hid: str) -> str | None:
        """The traceparent header published with handoff ``hid`` (ISSUE
        15), or None when the server holds none — a trace-less handoff
        still fetches; its spans just don't join."""
        s = self._connect()
        try:
            s.sendall(f"TRACE {hid}\n".encode())
            (n,) = _I64.unpack(recv_exact(s, _I64.size))
            hdr = (recv_exact(s, n).decode("ascii", "replace")
                   if n >= 0 else None)
            s.sendall(b"DONE\n")
            return hdr
        finally:
            try:
                s.close()
            except OSError:
                pass

    def ack(self, hid: str) -> None:
        """Explicitly retire a handoff server-side (the decode pool's
        give-up path: nothing will fetch these pages now — don't leave
        them to the retention cap)."""
        s = self._connect()
        try:
            s.sendall(f"ACK {hid}\n".encode())
            recv_exact(s, _I64.size)
            s.sendall(b"DONE\n")
        finally:
            try:
                s.close()
            except OSError:
                pass

    def fetch(self, hid: str, n_pages: int | None = None,
              ack: bool = True, cut_after: int | None = None) -> list:
        """Every page payload of handoff ``hid`` as decoded plane tuples
        (wire layout, CRC-verified). A page that cannot be produced —
        unknown on the server, or CRC-dead after one re-fetch — comes
        back as None in its slot; the adoption side stops at the first
        gap and prefill re-derives the rest. ``cut_after`` (drills)
        hard-aborts the transfer after that many pages — the
        kill-mid-handoff injection point. ``ack=True`` retires the
        handoff server-side once every page decoded."""
        from .pagewire import decode_record

        s = self._connect()
        planes: list = []
        try:
            if n_pages is None:
                s.sendall(f"COUNT {hid}\n".encode())
                (n_pages,) = _I64.unpack(recv_exact(s, _I64.size))
                if n_pages < 0:
                    return []
            resumes = 0
            idx = 0
            retried: set = set()  # pages already given their CRC retry
            while idx < n_pages:
                if cut_after is not None and idx >= cut_after:
                    raise ConnectionError(
                        "page channel cut mid-transfer (injected)")
                try:
                    rec = self._req_page(s, hid, idx)
                except OSError as e:
                    if not is_transient(e) or resumes >= self.max_resumes:
                        raise
                    resumes += 1
                    self.resumes += 1
                    try:
                        s.close()
                    except OSError:
                        pass
                    # resume: reconnect and continue from the first page
                    # we do not hold — pages already decoded stay
                    s = self._connect()
                    continue
                got = decode_record(rec) if rec is not None else None
                if got is None and rec is not None and idx not in retried:
                    # in-flight damage: ONE re-fetch, routed back through
                    # this same loop so a transient disconnect during the
                    # retry rides the resume machinery like any other
                    retried.add(idx)
                    self.crc_refetches += 1
                    continue
                planes.append(got)  # None = page given up: re-derive
                idx += 1
            if ack and all(p is not None for p in planes):
                s.sendall(f"ACK {hid}\n".encode())
                recv_exact(s, _I64.size)
            s.sendall(b"DONE\n")
        finally:
            try:
                s.close()
            except OSError:
                pass
        return planes
