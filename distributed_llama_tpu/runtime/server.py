"""HTTP inference server on the continuous-batching engine.

A minimal stdlib (http.server) API surface over runtime/continuous.py — the
serving layer the reference lacks entirely (its only interface is the argv
one-shot, main.cpp:38-63). Requests from concurrent clients stream through
the slot pool: admission happens mid-flight between device steps, so a short
request never waits for a long one to finish.

Endpoints:
  POST /generate  {"prompt": str, "steps"?: int, "temperature"?: float,
                   "topp"?: float, "seed"?: int, "stream"?: bool,
                   "class"?: str  (SLO priority class, --slo policy)}
               -> {"text": str, "tokens": [int], "steps": int}
               or, with "stream": true, chunked newline-delimited JSON:
               one {"token": int, "piece": str} line per token as it
               decodes, then a final {"done": true, "text": ..., "steps": N}
  GET  /health -> {"active": int, "queued": int, "slots": int,
                   "steps": int, "generated_tokens": int, "uptime_s",
                   "occupancy", (--spec-k on) a "speculative" block with
                   proposed/accepted/accept_rate, and (metrics on)
                   "ttft_s"/"token_latency_s"/"queue_wait_s" p50/p95/p99
                   summaries}
  GET  /metrics -> Prometheus text exposition of the obs registry (request
               lifecycle histograms, engine step/occupancy, counters, and
               the per-scheme collective schedule series)
  GET  /debug/timeline -> Chrome-trace/Perfetto JSON of the engine's recent
               spans (request → prefill/decode windows, obs/spans.py);
               ``?format=ndjson`` emits one span object per line instead
  GET  /debug/incidents -> the watchtower plane (obs/watch.py, ISSUE 20):
               detector states + incident log with evidence rows + the
               signal-ring tail; ``?kind=`` filters, ``?n=`` bounds the
               tails, ``?format=ndjson`` streams one incident per line;
               /health carries the compact "watch" heartbeat block and an
               incident dumps a reason="incident" flight-recorder bundle
  POST /profile  {"seconds"?: float, "dir"?: str} -> starts a jax.profiler
               capture into dir for N seconds WHILE SERVING (409 if one is
               already running) — profile under real load
  POST /prefill  (--disagg-role prefill only, ISSUE 14) the decode pool's
               internal handoff RPC: {"tokens": [ids], "steps": N, ...}
               -> the request's journal-record state + page-channel
               coordinates (or {"final": true} when the stream ended
               inside the prefill cut); /health gains a "disagg" block
               (role, peer, page channel, handoff queue depth) on both
               roles

Threading model: http.server's ThreadingHTTPServer handles each connection
on its own thread; handlers only encode, submit (thread-safe), and wait on
the request's done event. ONE scheduler thread owns the device loop
(ContinuousEngine.step_once), sleeping briefly when idle — the JAX step and
all slot state stay single-threaded.

Crash safety (ISSUE 9): with a write-ahead journal (``journal=``,
runtime/journal.py) the server recovers journaled in-flight requests at
construction, a step watchdog (``watchdog_s``, runtime/supervisor.py)
detects hung dispatches and degrades health, SIGTERM triggers a graceful
drain — stop admission (503), finish in-flight work within ``drain_s``,
journal the remainder, exit 0 — and the health state machine
(starting/serving/degraded/draining/stopped) is surfaced in ``/health``
and the ``dllama_health_state`` gauge.
"""

from __future__ import annotations

import json
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from ..io.tokenizer import Tokenizer
from ..models.spec import TransformerSpec
from ..obs import tracectx
from ..obs.log import log_event
from .continuous import ContinuousEngine, Request
from .supervisor import HealthMonitor, StepWatchdog

_IDLE_SLEEP_S = 0.002

# /health schema version, emitted as the payload's "schema" key so a
# fleet rollup can see version skew across replicas (absent on pre-
# schema replicas — obs/fleet treats that as 0). Keep equal to
# analysis/wiremodel.HEALTH_SCHEMA_VERSION (the registry cannot import
# the runtime; tests/test_wirecheck_repo.py pins the two equal) and
# bump BOTH when the payload gains or renames a key.
HEALTH_SCHEMA = 3


class OversizedRequest(ValueError):
    """A request the model literally cannot serve (prompt or steps beyond
    seq_len) — its own 400 + ``admission_rejected{reason="oversized"}``
    series, distinct from malformed-payload bad_request."""


class InferenceServer:
    """Owns the engine, the HTTP listener, and the scheduler thread."""

    def __init__(self, spec: TransformerSpec, params: dict[str, Any],
                 tokenizer: Tokenizer, host: str, port: int, slots: int,
                 steps: int, temperature: float, topp: float, seed: int,
                 cache_dtype=None, mesh=None, prefill_chunk: int = 0,
                 block_steps: int = 1, quiet: bool = False,
                 fast_prefill: bool = False, metrics: bool = True,
                 registry=None, page_size: int = 0, kv_pages: int = 0,
                 spec_k: int = 0, spec_ngram: int = 3,
                 dispatch_tokens: int = 0, slo=None,
                 chaos=None, journal=None, watchdog_s: float = 0.0,
                 drain_s: float = 10.0, kv_quant: str = "f32",
                 kv_host_pages: int = 0, kv_disk_dir: str | None = None,
                 kv_disk_bytes: int = 0, disagg_role: str | None = None,
                 disagg_peer: str | None = None,
                 page_channel_port: int = 0, handoff_min_pages: int = 2,
                 flightrec_dir: str | None = None,
                 watch_interval_s: float = 0.0):
        self.spec = spec
        self.tokenizer = tokenizer
        self.default_steps = steps
        self.quiet = quiet
        self.drain_s = drain_s
        # prefill/decode disaggregation (ISSUE 14): "prefill" serves
        # POST /prefill + the page channel; "decode" fronts clients and
        # forwards long prompts to ``disagg_peer`` (host:port of the
        # prefill server), ingesting the returned journal record + the
        # shipped pages. None = plain single-pool serving.
        if disagg_role not in (None, "prefill", "decode"):
            raise ValueError(f"disagg_role {disagg_role!r}: expected "
                             f"prefill|decode|None")
        if disagg_role is not None and page_size <= 0:
            raise ValueError("disaggregation ships KV PAGES: pass "
                             "page_size > 0 (--kv-page-size)")
        if disagg_role == "decode" and not disagg_peer:
            raise ValueError("--disagg-role decode needs --disagg-peer "
                             "HOST:PORT (the prefill server)")
        self.disagg_role = disagg_role
        self.disagg_peer = disagg_peer
        self.handoff_min_pages = max(1, handoff_min_pages)
        self._page_channel = None
        self._disagg_obs = None
        self._handoff_seq = 0
        # SLO policy (obs/slo.SLOPolicy) — verdicts per priority class in
        # /health + /metrics; ``chaos`` (runtime/chaos.ChaosMonkey) arms
        # deterministic fault injection for operator drills (--chaos)
        self.slo_policy = slo
        # metrics default ON for the server (it IS the observability
        # surface); --no-metrics turns collection off, and /metrics then
        # 404s. Each server gets its OWN registry unless one is injected —
        # two servers in one process must not sum their counters.
        if metrics:
            from ..obs.metrics import Registry

            self.registry = registry if registry is not None else Registry()
        else:
            self.registry = None
        self._t_start = time.monotonic()
        # crash-safety surface (ISSUE 9): the health state machine is
        # always on (a journal-less server still reports starting/serving/
        # draining/stopped); the watchdog and journal are opt-in knobs
        self.health = HealthMonitor(self.registry)
        self.journal = journal
        # crash-forensics flight recorder (ISSUE 15): the ring is ALWAYS
        # recording (cheap); bundle files land in flightrec_dir when the
        # watchdog fires or the SIGTERM drain runs (None = ring only)
        from ..obs.flightrec import FlightRecorder

        self.flightrec_dir = flightrec_dir
        self.flightrec = FlightRecorder(
            registry=self.registry,
            journal_path=journal.path if journal is not None else None,
            config=(dict(journal.config)
                    if journal is not None and journal.config else {}))
        self._watchdog = (StepWatchdog(watchdog_s, on_hang=self._on_hang)
                          if watchdog_s > 0 else None)
        self._drain_hist = (self.registry.histogram(
            "dllama_drain_seconds",
            "Graceful-drain duration: SIGTERM to in-flight work finished "
            "or journaled") if self.registry is not None else None)
        self.engine = ContinuousEngine(spec, params, slots, temperature,
                                       topp, seed, cache_dtype=cache_dtype,
                                       mesh=mesh,
                                       prefill_chunk=prefill_chunk,
                                       block_steps=block_steps,
                                       fast_prefill=fast_prefill,
                                       metrics=self.registry,
                                       page_size=page_size,
                                       kv_pages=kv_pages, spec_k=spec_k,
                                       spec_ngram=spec_ngram,
                                       dispatch_tokens=dispatch_tokens,
                                       slo=slo,
                                       chaos=chaos, journal=journal,
                                       watchdog=self._watchdog,
                                       kv_quant=kv_quant,
                                       kv_host_pages=kv_host_pages,
                                       kv_disk_dir=kv_disk_dir,
                                       kv_disk_bytes=kv_disk_bytes,
                                       remote_pages=(
                                           disagg_role == "decode"),
                                       slo_priority=(
                                           disagg_role == "prefill"
                                           and slo is not None))
        if disagg_role == "prefill":
            from .disagg import make_priority_hold
            from .page_channel import PageChannelServer

            # bind the channel on the same interface as the HTTP listener:
            # a 0.0.0.0 serve host means remote decode pools connect, and
            # the page channel must be reachable from exactly as far
            self._page_channel = PageChannelServer(
                host=host if host else "0.0.0.0",
                port=page_channel_port)
            if slo is not None:
                # SLO-aware admission: interactive prefills jump the
                # queue AND preempt batch prefills at page-aligned
                # chunk boundaries
                self.engine.prefill_hold = make_priority_hold(
                    self.engine, slo)
        if disagg_role is not None and self.registry is not None:
            from .disagg import DisaggMetrics

            self._disagg_obs = DisaggMetrics(self.registry)
        # the engine's span tracer feeds the flight recorder's bundle
        # (None when metrics are off — the ring of notes still records);
        # the census ring + ledger book (always on) ride along so a
        # crash bundle shows WHAT the scheduler was dispatching and
        # WHOSE requests were mid-flight (ISSUE 16)
        self.flightrec.bind(spans=self.engine._spans,
                            census=self.engine.sched_census,
                            ledgers=self.engine.ledger_book)
        self.flightrec.note("server.start", role=disagg_role or "single",
                            slots=slots, page_size=page_size)
        # incident-detection plane (ISSUE 20): always constructed — the
        # detectors run on every watch_tick() whether the periodic
        # supervisor loop is on (watch_interval_s > 0) or a test/sim
        # drives ticks by hand. A firing detector dumps a flight-
        # recorder bundle with reason="incident" + the detector kind.
        from ..obs.watch import Watchtower

        self.watch_interval_s = watch_interval_s
        self._watch = Watchtower(registry=self.registry,
                                 spans=self.engine._spans,
                                 on_incident=self._on_incident)
        self._watch_stop = threading.Event()
        # replay the previous life's unfinished requests BEFORE the
        # listener opens: recovered work re-queues first, so a restarted
        # server continues exactly where the crash cut it off
        self.recovered = (self.engine.recover(quiet=quiet)
                          if journal is not None else 0)
        if self.recovered:
            self.flightrec.note("server.recovered", n=self.recovered)
        self._shutdown = threading.Event()
        self._stopped = threading.Event()  # stop() ran to completion
        # live streaming-handler threads (the _stream loop): stop() joins
        # these AFTER waking their requests — a blocked q.get/done.wait
        # must not outlive the server (the thread-leak satellite)
        self._streams: set = set()
        self._streams_lock = threading.Lock()
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 is required for Transfer-Encoding: chunked — on a
            # /1.0 status line RFC-compliant clients (curl) do not de-chunk
            # and would see raw chunk framing; the non-streaming path is
            # fine either way (it always sends Content-Length)
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet the per-request noise
                if not server.quiet:
                    log_event("http.request",
                              f"🌐 {self.address_string()} {fmt % args}",
                              client=self.address_string(),
                              line=fmt % args)

            def _json(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.split("?")[0] == "/debug/timeline":
                    return self._timeline()
                if self.path.split("?")[0] == "/debug/sched":
                    return self._sched()
                if self.path.split("?")[0] == "/debug/incidents":
                    return self._incidents()
                if self.path == "/metrics":
                    if server.registry is None:
                        return self._json(404, {"error": "metrics disabled "
                                                "(--no-metrics)"})
                    body = server.registry.expose().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path != "/health":
                    return self._json(404, {"error": "unknown path"})
                self._json(200, server._health_payload())

            def _timeline(self):
                """GET /debug/timeline: the engine's recent span timeline
                (request → prefill/decode windows, obs/spans.py).
                Default: Chrome-trace JSON — save it and load it straight
                into Perfetto / chrome://tracing; ?format=ndjson streams
                one span object per line for log shippers;
                ?trace=<trace_id> filters to ONE distributed trace's
                spans (the cross-pool join view, ISSUE 15)."""
                from urllib.parse import parse_qs, urlparse

                spans = server.engine._spans
                if spans is None:
                    return self._json(404, {"error": "timeline disabled "
                                            "(--no-metrics)"})
                q = parse_qs(urlparse(self.path).query)
                trace_id = (q.get("trace") or [None])[0]
                if (q.get("format") or [None])[0] == "ndjson":
                    body = spans.export_ndjson(trace_id).encode()
                    ctype = "application/x-ndjson"
                else:
                    body = json.dumps(spans.export_chrome(trace_id)).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _sched(self):
                """GET /debug/sched: the per-dispatch scheduler census
                ring + the cost-ledger state (ISSUE 16). Default: one
                JSON document (census totals + ring tail, open-ledger
                snapshots, closed tail, grand/per-class cost columns);
                ?format=ndjson streams one census record per line for
                log shippers; ?n=<k> bounds both tails (default 64)."""
                from urllib.parse import parse_qs, urlparse

                eng = server.engine
                q = parse_qs(urlparse(self.path).query)
                try:
                    n = int((q.get("n") or ["64"])[0])
                except ValueError:
                    return self._json(400, {"error": "n must be an "
                                            "integer"})
                census, book = eng.sched_census, eng.ledger_book
                if (q.get("format") or [None])[0] == "ndjson":
                    body = "".join(
                        json.dumps(r, sort_keys=True) + "\n"
                        for r in census.tail(n)).encode()
                    ctype = "application/x-ndjson"
                else:
                    doc = census.to_json(tail=n)
                    doc["open_ledgers"] = book.open_snapshots()
                    doc["closed_tail"] = book.closed_tail(n)
                    doc["cost_totals"] = book.grand_totals()
                    doc["cost_by_class"] = book.class_rollup()
                    body = json.dumps(doc).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _incidents(self):
                """GET /debug/incidents: the watchtower's incident log
                + detector states + the signal-ring tail (ISSUE 20).
                Default: one JSON document (Watchtower.to_json);
                ``?format=ndjson`` streams one incident per line for
                log shippers; ``?n=<k>`` bounds the incident tail and
                the ring tail (default 64); ``?kind=<detector>``
                filters the ndjson stream to one detector kind."""
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                try:
                    n = int((q.get("n") or ["64"])[0])
                except ValueError:
                    return self._json(400, {"error": "n must be an "
                                            "integer"})
                kind = (q.get("kind") or [None])[0]
                watch = server._watch
                if (q.get("format") or [None])[0] == "ndjson":
                    body = "".join(
                        json.dumps(inc.to_json(), sort_keys=True) + "\n"
                        for inc in watch.incidents(n, kind)).encode()
                    ctype = "application/x-ndjson"
                else:
                    doc = watch.to_json(tail=n)
                    doc["incident_log"] = [
                        inc.to_json() for inc in watch.incidents(n, kind)]
                    body = json.dumps(doc).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path == "/profile":
                    return self._profile()
                if self.path == "/prefill":
                    return self._prefill_handoff()
                if self.path != "/generate":
                    return self._json(404, {"error": "unknown path"})
                if server.health.state in ("draining", "stopped"):
                    # drain contract: admission stops FIRST; clients get a
                    # clean retryable refusal, never a dropped request
                    server.count_reject("draining")
                    return self._json(503, {"error": "server is draining; "
                                            "retry after restart"})
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError("body must be a JSON object")
                    stream = bool(payload.get("stream", False))
                    req = server.make_request(payload)
                except OversizedRequest as e:
                    server.count_reject("oversized")
                    return self._json(400, {"error": str(e)})
                except (ValueError, KeyError, TypeError) as e:
                    server.count_reject("bad_request")
                    return self._json(400, {"error": str(e)})
                if server.disagg_role == "decode":
                    req, submit = server.remote_prefill(req)
                else:
                    submit = lambda r=req: server.engine.submit(r)  # noqa: E731
                if stream:
                    return self._stream(req, submit)
                if submit is not None:
                    submit()
                req.done.wait()
                if req.error is not None:
                    return self._json(500, {"error": req.error})
                text = server.decode(req)
                self._json(200, {"text": text, "tokens": req.out,
                                 "steps": len(req.out)})

            def _prefill_handoff(self):
                """POST /prefill (prefill role, ISSUE 14): the decode
                pool's internal RPC. Body: {"tokens": [ids], "steps":
                N, "temperature"?, "topp"?, "seed"?, "class"?}. Runs
                prompt prefill + samples the FIRST token, publishes the
                full prompt pages on the page channel, and returns the
                request's journal-record state for the decode pool to
                re-admit — or {"final": true, ...} when the stream ended
                inside the prefill cut."""
                from .disagg import (encode_handoff_pages, entry_for_stub,
                                     prefill_stub, stub_needs_handoff)
                from .journal import entry_to_wire

                if server.disagg_role != "prefill":
                    return self._json(404, {"error": "not a prefill pool"})
                if server.health.state in ("draining", "stopped"):
                    server.count_reject("draining")
                    return self._json(503, {"error": "draining"})
                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    tokens = [int(t) for t in payload["tokens"]]
                    steps = int(payload["steps"])
                    if not tokens or steps < 1 \
                            or len(tokens) > server.spec.seq_len:
                        raise ValueError(
                            f"bad handoff prompt/steps ({len(tokens)} "
                            f"tokens, {steps} steps)")
                    temp = payload.get("temperature")
                    topp = payload.get("topp")
                    seed = payload.get("seed")
                    slo_class = payload.get("class")
                except (ValueError, KeyError, TypeError) as e:
                    server.count_reject("bad_request")
                    return self._json(400, {"error": str(e)})
                # trace propagation (ISSUE 15): continue the decode
                # pool's trace from the RPC's traceparent — the recv
                # half of the clock-skew anchor pair tracejoin aligns
                # on. The drop-traceparent mutation severs it HERE.
                trace_hdr = payload.get("trace")
                chaos = server.engine._chaos
                if trace_hdr is not None and chaos is not None \
                        and chaos.trace_drop():
                    trace_hdr = None
                recv_parent = None
                if trace_hdr:
                    try:
                        recv_parent = tracectx.parse_header(str(trace_hdr))
                    except ValueError:
                        recv_parent = None
                recv = (recv_parent.child() if recv_parent is not None
                        else tracectx.mint())
                t_recv0 = time.perf_counter()
                stub, _ = prefill_stub(
                    tokens, steps,
                    temperature=None if temp is None else float(temp),
                    topp=None if topp is None else float(topp),
                    seed=None if seed is None else int(seed),
                    slo_class=slo_class)
                stub.trace = recv.child()
                server.engine.submit(stub)
                stub.done.wait()

                def recv_span(pages: int) -> None:
                    if server.engine._spans is not None:
                        from .disagg import HANDOFF_CAT, SPAN_HANDOFF_RECV

                        server.engine._spans.add(
                            SPAN_HANDOFF_RECV, HANDOFF_CAT, t_recv0,
                            time.perf_counter() - t_recv0, pages=pages,
                            **tracectx.span_fields(recv))

                if stub.error is not None:
                    return self._json(500, {"error": stub.error})
                if not stub_needs_handoff(stub):
                    if server._disagg_obs is not None:
                        # wirecheck: allow[W002] metric verdict label, not a wire key
                        server._disagg_obs.handoffs["local"].inc()
                    recv_span(0)
                    return self._json(200, {"final": True,
                                            "out": stub.out})
                try:
                    entry = entry_for_stub(server.engine, stub)
                except ValueError as e:  # sampled stream, no journal
                    return self._json(500, {"error": str(e)})
                payloads = server.engine.export_prefix_sync(tokens)
                records = encode_handoff_pages(payloads)
                hid = f"h{stub.index}"
                server._page_channel.publish(hid, records,
                                             trace=entry.trace)
                recv_span(len(records))
                if server._disagg_obs is not None:
                    from .pagewire import record_payload_bytes

                    obs = server._disagg_obs
                    # wirecheck: allow[W002] metric verdict label, not a wire key
                    obs.handoffs["shipped"].inc()
                    if records:
                        # PAYLOAD bytes (the DCN budget's unit — frame
                        # overhead excluded), the same accounting as
                        # DisaggPair: the series stays reconcilable
                        # against dcn_handoff_budget
                        obs.pages_shipped.inc(len(records))
                        obs.bytes_shipped.inc(sum(
                            record_payload_bytes(r) for r in records))
                    obs.queue_depth.set(server._page_channel.queue_depth)
                self._json(200, {
                    "record": entry_to_wire(entry),
                    "hid": hid, "n_pages": len(records),
                    "channel_port": server._page_channel.port})

            def _profile(self):
                """POST /profile: capture a jax.profiler trace for N
                seconds while the server keeps serving. One capture per
                process (jax.profiler is a singleton) -> 409 on overlap."""
                import tempfile

                from ..obs import profiler

                try:
                    n = int(self.headers.get("Content-Length", "0"))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError("body must be a JSON object")
                    seconds = float(payload.get("seconds", 5.0))
                    trace_dir = payload.get("dir") \
                        or profiler.env_profile_dir() \
                        or tempfile.mkdtemp(prefix="dllama-profile-")
                    profiler.start_capture(trace_dir, seconds)
                except RuntimeError as e:  # capture already in flight
                    return self._json(409, {"error": str(e)})
                except OSError as e:
                    # unwritable/uncreatable trace dir (bad
                    # DLLAMA_PROFILE_DIR): a server-side env problem, and
                    # the capture never started — the next request may
                    # name a good dir
                    return self._json(500, {"error": f"trace dir: {e}"})
                except (ValueError, KeyError, TypeError) as e:
                    return self._json(400, {"error": str(e)})
                self._json(200, {"dir": trace_dir, "seconds": seconds})

            def _stream(self, req, submit=None):
                """Chunked newline-delimited JSON, one line per token.

                The scheduler thread only enqueues (on_token must never
                block the decode loop on a slow client socket); THIS
                handler thread drains the queue and does the blocking
                writes. ``submit`` hands the request to the engine AFTER
                the hook is registered (the disagg decode path passes an
                ingest closure; None with ``done`` already set means the
                request completed remotely — replay its tokens).
                """
                import queue

                q: queue.Queue = queue.Queue()
                req.on_token = q.put
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(obj):
                    body = (json.dumps(obj) + "\n").encode()
                    self.wfile.write(f"{len(body):x}\r\n".encode() + body
                                     + b"\r\n")
                    self.wfile.flush()

                if submit is None and req.done.is_set():
                    # completed inside the peer's prefill cut: replay the
                    # finished stream as one burst
                    try:
                        prev = req.tokens[0]
                        for tok in req.out:
                            piece = server.tokenizer.decode_piece(prev,
                                                                  tok)
                            prev = tok
                            chunk({"token": tok,
                                   "piece": piece.decode(
                                       "utf-8", errors="replace")})
                        if req.error is not None:
                            chunk({"done": True, "error": req.error})
                        else:
                            chunk({"done": True,
                                   "text": server.decode(req),
                                   "steps": len(req.out)})
                        self.wfile.write(b"0\r\n\r\n")
                        self.wfile.flush()
                    except OSError:
                        pass
                    return

                # register with the server so stop() can join this thread
                # once the request is woken — without the registry a
                # handler blocked in q.get outlives the server silently
                with server._streams_lock:
                    server._streams.add(threading.current_thread())
                if submit is not None:
                    submit()
                else:
                    server.engine.submit(req)
                prev = req.tokens[0]
                sent = 0
                try:
                    while True:
                        try:
                            tok = q.get(timeout=0.1)
                        except queue.Empty:
                            if req.done.is_set() and sent == len(req.out):
                                break
                            continue
                        piece = server.tokenizer.decode_piece(prev, tok)
                        prev = tok
                        sent += 1
                        chunk({"token": tok,
                               "piece": piece.decode("utf-8",
                                                     errors="replace")})
                    if req.error is not None:
                        chunk({"done": True, "error": req.error})
                    else:
                        chunk({"done": True, "text": server.decode(req),
                               "steps": len(req.out)})
                    self.wfile.write(b"0\r\n\r\n")
                    self.wfile.flush()
                except OSError:
                    # client went away mid-stream: cancel in the ENGINE —
                    # a queued request completes now, an in-flight one is
                    # swept before the next dispatch, freeing its slot and
                    # KV pages immediately instead of decoding the rest of
                    # the budget (or another whole fused chain) for nobody
                    server.engine.cancel(req)
                finally:
                    with server._streams_lock:
                        server._streams.discard(
                            threading.current_thread())

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self._threads: list[threading.Thread] = []

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def count_reject(self, reason: str) -> None:
        """Feed the admission_rejected{reason} series (no-op dark)."""
        if self.engine._obs is not None:
            self.engine._obs.reject(reason)

    def make_request(self, payload: dict) -> Request:
        if not isinstance(payload, dict):
            raise ValueError("body must be a JSON object")
        prompt = payload.get("prompt", "")
        if not isinstance(prompt, str):
            raise ValueError("prompt must be a string")
        steps = int(payload.get("steps", self.default_steps))
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        if steps > self.spec.seq_len:
            raise OversizedRequest(
                f"steps must be in 1..{self.spec.seq_len}, got {steps}")
        temp = payload.get("temperature")
        topp = payload.get("topp")
        seed = payload.get("seed")
        slo_class = payload.get("class")
        if slo_class is not None:
            if self.slo_policy is None:
                raise ValueError(
                    "request names an SLO class but the server has no "
                    "--slo policy")
            self.slo_policy.resolve(str(slo_class))  # unknown -> 400
            slo_class = str(slo_class)
        tokens = self.tokenizer.encode(prompt, bos=True, eos=False)
        if len(tokens) > self.spec.seq_len:
            # the model literally cannot hold this prompt; truncating
            # silently would return an answer to a question never asked
            raise OversizedRequest(
                f"prompt encodes to {len(tokens)} positions, over the "
                f"model's seq_len {self.spec.seq_len}")
        return Request(tokens=tokens, steps=steps,
                       temperature=None if temp is None else float(temp),
                       topp=None if topp is None else float(topp),
                       seed=None if seed is None else int(seed),
                       slo_class=slo_class,
                       # trace minted at INGRESS (ISSUE 15): the id every
                       # span, journal record, and handoff hop of this
                       # request's life carries from here on
                       trace=tracectx.mint())

    def decode(self, req: Request) -> str:
        from .continuous import decode_stream

        return decode_stream(self.tokenizer, req.tokens[0], req.out)

    def remote_prefill(self, req: Request):
        """Decode-role routing (ISSUE 14): prompts spanning >=
        ``handoff_min_pages`` full pages forward to the prefill peer
        (POST /prefill), whose reply is either the finished stream (it
        ended inside the prefill cut) or a journal record + page-channel
        coordinates; shipped pages are fetched, CRC-verified, and handed
        to the scheduler with the re-admission request. Shorter prompts
        — and ANY peer failure — run locally: disaggregation degrades to
        single-pool serving, never to a dropped request.

        Returns ``(request, submit_fn)``: the request to track (the
        original, or the peer-built re-admission) and a thunk that hands
        it to the engine — None when it is already complete. Callers
        register streaming hooks BEFORE invoking the thunk."""
        import urllib.request

        from .disagg import HANDOFF_CAT, SPAN_HANDOFF_SEND, decode_request
        from .journal import entry_from_wire
        from .page_channel import PageChannelClient

        local = (req, lambda: self.engine.submit(req))
        n_full = (len(req.tokens) - 1) // max(self.engine.page_size, 1)
        if n_full < self.handoff_min_pages:
            if self._disagg_obs is not None:
                # wirecheck: allow[W002] metric verdict label, not a wire key
                self._disagg_obs.handoffs["local"].inc()
            return local
        t0 = time.monotonic()
        # the RPC span (ISSUE 15): the send half of the clock-skew
        # anchor pair — its traceparent rides the POST body, so the
        # prefill pool's spans become this span's descendants
        rpc = (req.trace.child() if req.trace is not None
               else tracectx.mint())
        t_send0 = time.perf_counter()

        def send_span(pages: int) -> None:
            if self.engine._spans is not None:
                self.engine._spans.add(
                    SPAN_HANDOFF_SEND, HANDOFF_CAT, t_send0,
                    time.perf_counter() - t_send0, pages=pages,
                    **tracectx.span_fields(rpc))

        dreq = None
        resp = None
        try:
            body = json.dumps({
                "tokens": req.tokens, "steps": req.steps,
                "temperature": req.temperature, "topp": req.topp,
                "seed": req.seed, "class": req.slo_class,
                "trace": rpc.to_header()}).encode()
            rq = urllib.request.Request(
                f"http://{self.disagg_peer}/prefill", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(rq, timeout=120) as r:
                resp = json.loads(r.read())
            if resp.get("final"):
                req.out.extend(int(t) for t in resp["out"])
                req.done.set()
                send_span(0)
                return req, None
            entry = entry_from_wire(resp["record"])
            dreq = decode_request(entry, req.steps)
            if self.engine._journal is not None:
                # the durability point: the admit record lands BEFORE
                # any page moves, so a crash mid-transfer recovers the
                # request from this journal (the kill_mid_handoff
                # contract, honored on the HTTP path too)
                self.engine.prejournal(dreq)
            host = self.disagg_peer.rsplit(":", 1)[0]
            client = PageChannelClient(
                f"{host}:{resp['channel_port']}")
            planes = client.fetch(resp["hid"], int(resp["n_pages"]))
            prompt = list(req.tokens)
            if self._disagg_obs is not None:
                obs = self._disagg_obs
                # wirecheck: allow[W002] metric verdict label, not a wire key
                obs.handoffs["shipped"].inc()
                obs.handoff_latency.observe(time.monotonic() - t0)
            send_span(int(resp["n_pages"]))
            log_event("disagg.handoff_shipped", None, trace=rpc,
                      peer=self.disagg_peer, pages=int(resp["n_pages"]))
            return dreq, (lambda: self.engine.ingest_remote(
                prompt, planes, dreq))
        except (OSError, ValueError, KeyError, TypeError) as e:
            log_event("disagg.handoff_failed",
                      f"🔶 handoff to {self.disagg_peer} failed "
                      f"({type(e).__name__}: {e}); serving locally",
                      file=sys.stderr, trace=rpc,
                      error=f"{type(e).__name__}: {e}")
            if dreq is not None:
                # the fallback serves the ORIGINAL request — retire the
                # prejournaled life, or the next recovery would replay
                # it on top of the fallback's stream
                self.engine.abandon_prejournaled(dreq)
            if resp is not None and resp.get("hid"):
                # best-effort: tell the prefill pool to drop the
                # published pages (nothing will fetch them now)
                try:
                    host = self.disagg_peer.rsplit(":", 1)[0]
                    PageChannelClient(
                        f"{host}:{resp['channel_port']}",
                        connect_window=2.0).ack(resp["hid"])
                except (OSError, ValueError, KeyError):
                    pass  # the channel's retention cap bounds the leak
            if self._disagg_obs is not None:
                # wirecheck: allow[W002] metric verdict label, not a wire key
                self._disagg_obs.handoffs["failed"].inc()
            return local

    def _health_payload(self) -> dict:
        """Assemble the GET /health JSON (the fleet plane's primary
        scrape surface — the registered producer of wiremodel's
        "health" format). Shared by the HTTP handler and the watch
        plane's self-scrape (watch_tick), so the detectors see exactly
        the payload a remote scraper would."""
        eng = self.engine
        with eng._lock:
            queued = len(eng._queue)
        active = sum(not s.free for s in eng._pool)
        payload = {
            "schema": HEALTH_SCHEMA,
            "state": self.health.state,
            "active": active,
            "queued": queued,
            "queue_depth": queued,
            "slots": eng.slots,
            "steps": eng.stats.steps,
            "generated_tokens": eng.stats.tokens,
            "uptime_s": round(time.monotonic() - self._t_start, 3),
            "occupancy": round(active / eng.slots, 4),
            # admission-pressure counters (ISSUE 8): page-starved
            # slot pauses and dry-pool head-of-queue requeues
            "pauses": eng.stats.pauses,
            "requeues": eng.stats.requeues,
        }
        if eng.allocator is not None:
            # paged-KV capacity surface (ISSUE 11): pool shape,
            # occupancy, the KV quantization in play, and the
            # pool planes' GLOBAL logical bytes (whole pool
            # across tp shards; per-device is /tp) — the
            # /metrics dllama_kv_quant_info / page-pool gauges'
            # JSON twin
            a = eng.allocator
            payload["paged_kv"] = {
                "page_size": a.page_size,
                "pages": a.n_pages,
                "pages_free": a.n_free,
                "kv_quant": eng.kv_quant,
                "pool_bytes": sum(int(x.nbytes)
                                  for x in eng.cache),
                "prefix_hit_rate": round(a.hit_rate, 4),
                # raw hit/miss COUNTS (ISSUE 15): the fleet
                # plane recomputes aggregate hit rates from
                # summed counts, never from averaged ratios
                "prefix_hits": a.prefix_hits,
                "prefix_misses": a.prefix_misses,
                "prefill_tokens_saved": a.tokens_saved,
                "evictions": a.evictions,
            }
            if a.tiered:
                # KV-tier hierarchy surface (ISSUE 12): per-tier
                # page population + promotion/demotion flow +
                # the prefill tokens the spilled tiers rescued —
                # the dllama_kv_tier_pages/... series' JSON twin
                counts = a.tier_page_counts()
                payload["kv_tiers"] = {
                    "pages": counts,
                    "host_capacity": (a.host.n_pages
                                      if a.host else 0),
                    "disk_live_bytes": (a.disk.live_bytes
                                        if a.disk else 0),
                    "disk_budget_bytes": (a.disk.budget_bytes
                                          if a.disk else 0),
                    "demotions": dict(a.demotions),
                    "promotions": dict(a.promotions),
                    "prefill_tokens_saved_by_tier":
                        dict(a.tokens_saved_by_tier),
                    "crc_drops": a.crc_drops,
                }
        if self.disagg_role is not None:
            # disaggregated-topology surface (ISSUE 14): this
            # pool's role, its peer, and the handoff backlog —
            # the dllama_handoff_*/dllama_dcn_* series' JSON twin
            payload["disagg"] = {
                "role": self.disagg_role,
                "peer": self.disagg_peer,
                "page_channel_port": (
                    self._page_channel.port
                    if self._page_channel is not None else None),
                "handoff_queue_depth": (
                    self._page_channel.queue_depth
                    if self._page_channel is not None else 0),
            }
            if eng.allocator is not None:
                payload["disagg"]["pages_adopted"] = \
                    eng.allocator.remote_adopted
        if self.journal is not None:
            # recovery bookkeeping: requests replayed from the
            # journal at startup + append volume since
            payload["journal"] = {
                "path": self.journal.path,
                "fsync": self.journal.fsync,
                "recovered": self.recovered,
                "records": self.journal.records_total,
            }
        if self._watchdog is not None:
            payload["watchdog"] = {
                "timeout_s": self._watchdog.timeout_s,
                "trips": self._watchdog.trips,
            }
        if eng.slo_tracker is not None:
            # per-class attempted/met/violated/failed + attainment
            # + goodput (obs/slo.SLOTracker.snapshot)
            payload["slo"] = eng.slo_tracker.snapshot()
        if eng._obs is not None:
            payload["admission_rejected"] = \
                eng._obs.rejected_total()
        # cost-accounting surface (ISSUE 16): census dispatch
        # totals + ledger book counts and per-class cost columns
        # — GET /debug/sched's summary twin, the block the fleet
        # plane (obs/fleet.signals_from_health) sums across
        # replicas
        book = eng.ledger_book
        payload["sched"] = {
            "census": eng.sched_census.totals(),
            "ledgers": {"opened": book.opened_n,
                        "closed": book.closed_n,
                        "open": book.n_open},
            "cost_totals": book.grand_totals(),
            "cost_by_class": book.class_rollup(),
        }
        if eng.spec_k:
            # speculative decoding health (ISSUE 7): proposal
            # volume + accept rate of the n-gram self-drafter
            payload["speculative"] = {
                "k": eng.spec_k,
                "proposed": eng.stats.spec_proposed,
                "accepted": eng.stats.spec_accepted,
                "accept_rate": round(eng.stats.spec_accept_rate, 4),
            }
        # incident-detection heartbeat (ISSUE 20): detection-plane
        # tick count + per-kind incident totals and hysteresis states
        # (evidence stays on /debug/incidents — health is a heartbeat,
        # not a forensics dump)
        payload["watch"] = self._watch.snapshot()
        if self.registry is not None:
            for key, name in (
                    ("ttft_s", "dllama_request_ttft_seconds"),
                    ("token_latency_s",
                     "dllama_request_decode_token_seconds"),
                    ("queue_wait_s",
                     "dllama_request_queue_wait_seconds")):
                h = self.registry.get(name)
                s = h.summary()
                payload[key] = {k: round(v, 6) if k != "count"
                                else v for k, v in s.items()}
        return payload

    def watch_tick(self) -> list:
        """One detection-plane scrape of THIS process: assemble the
        /health payload, fold it (plus the parsed /metrics exposition)
        into a fleet row, and feed the watchtower — exactly what a
        remote scraper's tick would see. Returns the NEW incidents
        (transitions into firing). Called by the ``_watch_loop``
        supervisor thread when ``watch_interval_s > 0``; tests and sim
        drivers call it directly on their own clock."""
        from ..obs.fleet import parse_metrics, signals_from_health
        from ..obs.watch import sample_from_signals

        row = signals_from_health("self", self._health_payload())
        samples = (parse_metrics(self.registry.expose())
                   if self.registry is not None else None)
        return self._watch.observe("self", sample_from_signals(row,
                                                               samples))

    def _watch_loop(self):
        """Supervisor thread (threadmodel ENTRYPOINTS): periodic
        watch_tick every ``watch_interval_s`` seconds until stop() sets
        the event. Detector exceptions are logged, never fatal — a
        broken detector must not take the watch plane down."""
        while not self._watch_stop.wait(self.watch_interval_s):
            try:
                self.watch_tick()
            except Exception as e:  # noqa: BLE001 - keep the loop alive
                log_event("watch.error",
                          f"🔶 watch tick failed: {e!r}",
                          file=sys.stderr,
                          error=f"{type(e).__name__}: {e}")

    def _on_incident(self, inc) -> None:
        """Watchtower firing hook (obs/watch.Incident): auto-forensics.
        Note the incident into the flight-recorder ring and dump a
        bundle with reason="incident" + the detector kind — the
        postmortem snapshot taken AT detection time, not at the
        operator's later convenience."""
        from ..obs.flightrec import REASON_INCIDENT

        log_event("watch.incident",
                  f"🔶 incident #{inc.seq} {inc.kind} on {inc.replica} "
                  f"tick {inc.tick}: {inc.note}",
                  file=sys.stderr, kind=inc.kind, replica=inc.replica,
                  tick=inc.tick, note=inc.note)
        self._flightrec_dump(REASON_INCIDENT, incident_kind=inc.kind)

    def _flightrec_dump(self, reason: str,
                        incident_kind: str | None = None) -> None:
        """One postmortem bundle (obs/flightrec): note the trigger into
        the ring, then write a bundle file when a directory is
        configured. Never raises — this runs on fault paths."""
        self.flightrec.note(reason, state=self.health.state,
                            outstanding=self._outstanding(),
                            **({"incident_kind": incident_kind}
                               if incident_kind else {}))
        if not self.flightrec_dir:
            return
        try:
            path = self.flightrec.dump(self.flightrec_dir, reason,
                                       incident_kind=incident_kind)
            log_event("flightrec.dump",
                      f"🔶 flight recorder: {reason} bundle -> {path}",
                      file=sys.stderr, path=path, reason=reason)
        except OSError as e:
            log_event("flightrec.failed",
                      f"🔶 flight recorder dump failed: {e}",
                      file=sys.stderr, error=f"{type(e).__name__}: {e}")

    def _on_hang(self, elapsed_s: float):
        """Watchdog trip (monitor thread): a dispatch overran its deadline.
        Detection only — mark the server degraded (and drop a flight-
        recorder bundle: the hung state IS the postmortem moment); the
        scheduler flips it back to serving once dispatches complete on
        time again."""
        try:
            self.health.to("degraded")
        except ValueError:
            pass  # already draining/stopped: the drain verdict wins
        self._flightrec_dump("watchdog")

    def _scheduler(self):
        while not self._shutdown.is_set():
            try:
                active = self.engine.step_many(self.engine.block_steps,
                                               quiet=self.quiet)
            except Exception as e:
                # a dead scheduler must not leave clients blocked forever:
                # fail everything queued/in flight (handlers answer 500) and
                # keep the loop alive — a persistent device fault just fails
                # each subsequent request the same way
                import traceback

                traceback.print_exc()
                log_event("scheduler.error",
                          f"🌐 scheduler step failed: {e!r}; failing "
                          f"pending requests",
                          error=f"{type(e).__name__}: {e}")
                self.engine.fail_all(f"{type(e).__name__}: {e}")
                time.sleep(0.1)
                continue
            if (self.health.state == "degraded"
                    and self._watchdog is not None
                    and not self._watchdog.overdue):
                # the hang resolved: dispatches are landing again (never
                # flip back while an armed dispatch is still overrunning)
                try:
                    self.health.to("serving")
                except ValueError:
                    pass  # drain/stop raced us: their state wins
            if active == 0:
                time.sleep(_IDLE_SLEEP_S)

    def _outstanding(self) -> int:
        with self.engine._lock:
            queued = len(self.engine._queue)
        return queued + sum(not s.free for s in self.engine._pool)

    def _scheduler_stopped(self, timeout: float) -> bool:
        """Join the scheduler thread (started first); True once it is no
        longer running. suspend()/fail_all() walk the slot pool, so the
        shutdown paths must never run them concurrently with a live
        scheduler step — when this times out (a wedged dispatch, the
        watchdog's scenario) the caller SKIPS them: journaled work stays
        live for the next process, which is the safe outcome."""
        for t in self._threads[:1]:
            t.join(timeout=timeout)
            if t.is_alive():
                log_event("server.scheduler_wedged",
                          f"🔶 scheduler did not stop within {timeout:.0f}s "
                          f"(wedged dispatch?) — leaving in-flight work "
                          f"journaled instead of racing a live step",
                          file=sys.stderr, timeout_s=timeout)
                return False
        return True

    def start(self):
        """Start the scheduler + HTTP threads and return (non-blocking).
        With ``watch_interval_s > 0`` the watch-plane supervisor thread
        rides along (incident detection over the process's own signal
        plane, ISSUE 20)."""
        for target in (self._scheduler, self.httpd.serve_forever,
                       self._watch_loop):
            if target == self._watch_loop and self.watch_interval_s <= 0:
                continue  # detectors still run on manual watch_tick()
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        self.health.to("serving")

    def serve_forever(self):
        """Blocking entry (cmd_serve): serve until SIGTERM or Ctrl-C, then
        drain gracefully — stop admission, finish in-flight work within the
        drain budget, journal whatever remains — and return (exit 0)."""
        self.start()
        stop_requested = threading.Event()
        prev_handler = None
        try:
            prev_handler = signal.signal(
                signal.SIGTERM, lambda signum, frame: stop_requested.set())
        except ValueError:
            pass  # not the main thread (tests): rely on stop()/drain()
        try:
            while not stop_requested.is_set():
                time.sleep(0.2)
        except KeyboardInterrupt:
            pass
        finally:
            if prev_handler is not None:
                signal.signal(signal.SIGTERM, prev_handler)
            self.drain()

    def drain(self, budget_s: float | None = None) -> int:
        """Graceful shutdown: stop admission (handlers 503), let the
        scheduler finish in-flight work for up to ``budget_s`` seconds,
        then journal whatever is still outstanding (suspend) — or fail it
        loudly when there is no journal — and stop. Returns the number of
        requests left journaled for the next process."""
        budget = self.drain_s if budget_s is None else budget_s
        t0 = time.monotonic()
        try:
            self.health.to("draining")
        except ValueError:
            return 0  # already stopped
        # the SIGTERM postmortem bundle: state AS THE DRAIN BEGINS —
        # in-flight work, queue depth, journal tail, recent spans
        self._flightrec_dump("sigterm_drain")
        log_event("server.drain",
                  f"🌐 draining: admission stopped, "
                  f"{self._outstanding()} requests in flight, "
                  f"budget {budget:.1f}s",
                  outstanding=self._outstanding(), budget_s=budget)
        deadline = t0 + budget
        while self._outstanding() and time.monotonic() < deadline:
            time.sleep(0.01)
        # scheduler off BEFORE suspending: a step racing a retire-less
        # suspend could double-process a request's slot
        self._shutdown.set()
        sched_ok = self._scheduler_stopped(30)
        remainder = self._outstanding()
        if remainder and sched_ok:
            if self.journal is not None:
                self.engine.suspend()
            else:
                self.engine.fail_all("server draining: request dropped "
                                     "(no --journal to recover from)")
        drain_s = time.monotonic() - t0
        if self._drain_hist is not None:
            self._drain_hist.observe(drain_s)
        journaled = remainder if self.journal is not None else 0
        if not remainder:
            msg = (f"🌐 drained in {drain_s:.2f}s: all in-flight work "
                   f"completed")
        elif self.journal is not None:
            msg = (f"🌐 drained in {drain_s:.2f}s: {remainder} requests "
                   f"journaled for recovery")
        else:
            msg = (f"🔶 drained in {drain_s:.2f}s: {remainder} requests "
                   f"DROPPED (no --journal to carry them over)")
        log_event("server.drained", msg, seconds=round(drain_s, 3),
                  journaled=journaled, dropped=remainder - journaled)
        self.stop()
        return remainder

    def stop(self):
        """Tear down every thread the server owns. Idempotent; safe from
        any thread. Requests still outstanding are failed (use drain() for
        the graceful path) so no handler stays blocked on done.wait or the
        stream queue — then the streaming handler threads are JOINED, not
        abandoned."""
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._shutdown.set()
        # park the watch loop FIRST: a watch tick mid-teardown would
        # scrape a half-closed engine (the event also bounds the
        # _watch_loop thread's lifetime — threadmodel's joined_by)
        self._watch_stop.set()
        self.httpd.shutdown()
        sched_ok = self._scheduler_stopped(30)
        for t in self._threads[1:]:
            t.join(timeout=5)
        if self._outstanding() and sched_ok:
            # stop() without drain(): wake every waiter NOW — handlers
            # answer 500/stream-error and their threads exit. With a
            # journal the interrupted work is suspended (recoverable),
            # without one it is failed loudly. Skipped when the
            # scheduler would not stop (_scheduler_stopped): walking the
            # pool under a live step risks double-frees — the journal
            # carries the work instead.
            if self.journal is not None:
                self.engine.suspend()
            else:
                self.engine.fail_all("server stopped")
        # join streaming handlers until the registry drains. A single
        # snapshot has a TOCTOU hole: a handler that registers AFTER the
        # snapshot (its request raced the shutdown) would never be
        # joined. Re-snapshot under the lock each pass — joins happen
        # OUTSIDE the lock so a handler's deregister (finally block)
        # can't deadlock against us.
        deadline = time.monotonic() + 5.0
        while True:
            with self._streams_lock:
                pending = [t for t in self._streams if t.is_alive()]
            if not pending:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            for t in pending:
                t.join(timeout=max(0.05, remaining))
        self.httpd.server_close()
        if self._page_channel is not None:
            self._page_channel.close()
        self.engine.close()  # KV-tier uploader thread (no-op untiered)
        if self._watchdog is not None:
            self._watchdog.close()
        if self.journal is not None:
            self.journal.close()
        try:
            self.health.to("stopped")
        except ValueError:
            pass
