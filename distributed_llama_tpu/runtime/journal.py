"""Write-ahead request journal: crash-safe serving state (ISSUE 9).

The reference is a single-shot process — a crash loses everything. The
continuous engine already has the two properties that make real
crash-safety CHEAP here: seeded coin-replay determinism (a request's
token stream is a pure function of its prompt, sampler config, and coin
cursor — rejected speculative positions and forced steps consume no
coins), and radix prefix sharing (re-prefilling a recovered request
mostly hits the tree once its siblings re-admit). This module adds the
missing piece: a durable, append-only record of every request's inputs
and progress, from which ``ContinuousEngine.recover`` re-derives the
exact in-flight state.

Format: NDJSON, one record per line, four record types —

* ``{"t": "journal", "v": 1[, "config": {...}]}`` — the header, always
  line 1. ``config`` (PR 10) is the model-config FINGERPRINT of the
  process that created the journal (``config_fingerprint``: model dims,
  quant types, tp scheme, the sampler seed policy, a weight-file digest
  prefix). Replay determinism is only as good as the config it replays
  under — the same coin cursor against different weights, a different
  buffer float type, or a different pinned seed produces confidently
  WRONG bytes — so ``ContinuousEngine.recover`` refuses (raising
  ``JournalConfigMismatch``) when the serving config's fingerprint
  differs from the journaled one AND the journal holds live work; with
  nothing incomplete the journal adopts the new config instead
  (``adopt_config`` — a config upgrade over a fully-retired journal has
  nothing to corrupt). Legacy headers (pre-fingerprint journals) carry
  no config and recover without the check — the operator kept them on
  purpose;
* ``{"t": "admit", "id", "tokens", "steps", "temperature", "topp",
  "seed", "slo", "cursor"[, "recovers"]}`` — written at ``submit()``
  time (write-AHEAD of the scheduler ever seeing the request). ``seed``
  is the RESOLVED per-request seed (the engine's ``seed + index``
  default is process-local and would not survive a restart) and
  ``cursor`` the coin draws already consumed (non-zero only for
  re-journaled recovered requests). ``recovers`` names the previous
  life's id on a recovery re-admission: the ONE record opens the new
  life and retires the old atomically;
* ``{"t": "tok", "id", "tok", "cursor"}`` — one per SAMPLED token, with
  the cumulative coin cursor AFTER sampling it (forced prompt echoes are
  derivable from the admit record and are not journaled);
* ``{"t": "retire", "id", "status"}`` — ``done`` / ``cancelled`` /
  ``failed`` / ``recovered``; a request with a retire record (or whose
  id a later admit ``recovers``) needs no recovery.

Durability policy (``fsync=``): ``always`` fsyncs every record (survives
power loss, slowest), ``batch`` fsyncs once per scheduler step — the
engine calls ``sync()`` at each step boundary, so at most one dispatch's
tokens are at risk (the default), ``off`` leaves flushing to the OS
(process-crash-safe only). Every append is a single ``write()`` of one
complete line either way, so a torn record can only be the file's tail.

Corruption contract: a torn TAIL record (a crash mid-append) is expected
damage — loading truncates the file at the last valid line and reports
it. Anything else — garbage mid-file, an unknown record type, a record
referencing an unadmitted id, a missing header — raises
``JournalCorruption``: silently "recovering" from a journal whose
history cannot be trusted would serve wrong bytes with a straight face.

Compaction: retired requests' records are dead weight. ``compact()``
atomically rewrites the journal as one MERGED admit record per live
request (prompt + sampled-so-far as the token list, cursor carried
forward — exactly the reconstruction ``recover`` performs), dropping
everything retired. ``maybe_compact()`` applies the rotation policy
(``compact_every`` retirements); the engine calls it at step boundaries.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading

_HEADER = {"t": "journal", "v": 1}
FSYNC_POLICIES = ("always", "batch", "off")


class JournalCorruption(RuntimeError):
    """The journal's history cannot be trusted (non-tail damage) — fail
    loudly instead of recovering wrong state."""


class JournalConfigMismatch(RuntimeError):
    """The journal was written under a different serving config (model
    dims / quant types / tp scheme / seed policy / weight file) — a
    bitwise replay against it would be silently wrong, so recovery
    refuses. Move the journal aside to drop the in-flight work, or
    restart with the original config to recover it."""


def config_fingerprint(spec, scheme: str, seed_policy: str,
                       weights_digest: str | None = None,
                       kv_quant: str = "f32",
                       kv_cache_dtype: str = "f32",
                       kv_host_pages: int = 0,
                       kv_disk: bool = False) -> dict:
    """The serving-config fingerprint the WAL header records: everything a
    bitwise replay depends on — model dims, weight/buffer quant types,
    the tp collective scheme (schemes are bitwise-distinct only across
    the ref boundary, but the scheme also gates which program replays),
    the sampler SEED POLICY, and a weight-file digest prefix
    (``weight_file_digest``). Plain JSON-able dict so == is the whole
    comparison.

    ``seed_policy`` is ``"explicit:<seed>"`` when the operator pinned
    --seed (a restart under a different pinned seed changes every NEW
    request's stream — refuse) or ``"time"`` for the time-derived
    default (restarts under the default always pass: REPLAY never reads
    the base seed — admit records carry each request's RESOLVED seed —
    and new-request streams were already restart-variant by
    construction).

    ``kv_quant`` / ``kv_cache_dtype`` (ISSUE 11): Q8 KV pages — and a
    bf16 cache dtype — change every logit past the first position
    (quantized/narrowed K/V feed attention), so a replay across either
    KV-dtype change would be deterministic-but-wrong — the fingerprint
    refuses it. Both keys are recorded only when != 'f32' so pre-PR-11
    journals (no key) keep recovering under f32 serving, while any
    f32↔q8 or f32↔bf16 flip mismatches in BOTH directions."""
    fp = {
        "dim": spec.dim, "hidden_dim": spec.hidden_dim,
        "n_layers": spec.n_layers, "n_heads": spec.n_heads,
        "n_kv_heads": spec.n_kv_heads, "vocab_size": spec.vocab_size,
        "seq_len": spec.seq_len,
        "weights_ftype": int(spec.weights_float_type),
        "buffer_ftype": int(spec.buffer_float_type),
        "tp_scheme": scheme, "seed_policy": str(seed_policy),
        "weights_digest": weights_digest,
    }
    if kv_quant != "f32":
        fp["kv_quant"] = kv_quant
    if kv_cache_dtype != "f32":
        fp["kv_cache_dtype"] = kv_cache_dtype
    # KV tiering (ISSUE 12): tiering never changes a stream (demote→
    # promote round-trips are byte-exact), but the spill budgets shape
    # which pauses/requeues a replayed schedule hits, and a restart that
    # silently drops the disk tier orphans its segments — record the
    # knobs so drift is explicit. Omitted when OFF, so every pre-tiering
    # journal keeps recovering under untiered serving.
    if kv_host_pages:
        fp["kv_host_pages"] = int(kv_host_pages)
    if kv_disk:
        fp["kv_disk"] = True
    return fp


def weight_file_digest(path: str, head_bytes: int = 1 << 20) -> str:
    """A cheap weight-file identity: sha256 over (file size || first MiB),
    16 hex chars. Full-file hashing of a multi-GB model would stall every
    serve start; the header + first tensors + the size catch every
    practical swap (different model, different quantization, truncation).
    """
    import hashlib

    h = hashlib.sha256()
    size = os.path.getsize(path)
    h.update(str(size).encode())
    with open(path, "rb") as fh:
        h.update(fh.read(head_bytes))
    return h.hexdigest()[:16]


@dataclasses.dataclass
class JournalEntry:
    """One request's journaled state: the admit record plus every sampled
    token appended since. ``replay_tokens`` is what recovery re-admits:
    the prompt with the already-sampled suffix riding the forced-token
    window, and ``cursor`` the coin draws the recovered sampler must
    fast-forward past."""

    rid: int
    tokens: list
    steps: int
    temperature: float
    topp: float
    seed: int
    slo: str | None = None
    cursor: int = 0
    sampled: list = dataclasses.field(default_factory=list)
    status: str | None = None  # None = incomplete (needs recovery)
    # distributed-trace identity (ISSUE 15): the request's traceparent
    # header (obs/tracectx) at admit time. Recovery and the disagg
    # handoff continue the SAME trace from it — the continuation opens a
    # new span parented on this one with a recovers/handoff link, so a
    # request's whole multi-process life joins on one trace_id. None on
    # legacy records (pre-trace journals recover fine, just unjoined).
    trace: str | None = None
    # carried cost-ledger snapshot (ISSUE 16): the resource bill this
    # request accumulated in a PREVIOUS life (prefill pool / pre-crash
    # process), attached at admit so a migrated request's ledger is
    # whole. None on legacy records and on first lives.
    ledger: dict | None = None

    @property
    def replay_tokens(self) -> list:
        return list(self.tokens) + list(self.sampled)


class RequestJournal:
    """Append-side handle over one journal file (engine-owned).

    Opening an existing journal loads its state (so compaction knows the
    live set), REPAIRS a torn tail by physically truncating it, and
    raises ``JournalCorruption`` on any deeper damage. Appends are
    thread-safe (submit runs on handler threads, tokens on the
    scheduler thread).
    """

    def __init__(self, path: str, fsync: str = "batch",
                 compact_every: int = 256, config: dict | None = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in "
                             f"{FSYNC_POLICIES}")
        self.path = path
        self.fsync = fsync
        self.compact_every = compact_every
        # the SERVING config's fingerprint (config_fingerprint); written
        # into fresh headers and compared against header_config (the
        # journaled one) by check_config / ContinuousEngine.recover
        self.config = config
        self.header_config: dict | None = None
        # RLock: admit/token/retire mutate ``_entries`` AND append under
        # one critical section (submit runs on handler threads while
        # compact() rebuilds the dict on the scheduler thread — an
        # unlocked dict-set could vanish into the pre-compaction dict and
        # leave a journaled request the in-memory state no longer knows)
        self._lock = threading.RLock()
        self._metric = None  # obs counter (.inc) — bind_metrics
        self.records_total = 0  # appended by THIS handle
        self._dirty = False     # unsynced appends (batch policy)
        self._entries: dict[int, JournalEntry] = {}
        self._n_retired = 0
        existing = os.path.exists(path) and os.path.getsize(path) > 0
        self._fresh = not existing
        if existing:
            state, valid_bytes, header_cfg = _load_file(path)
            if valid_bytes < os.path.getsize(path):
                # torn tail: a crash mid-append left a partial last line —
                # truncate to the last valid record before appending, or
                # the next load would see garbage MID-file and refuse
                with open(path, "r+b") as fh:
                    fh.truncate(valid_bytes)
            existing = valid_bytes > 0  # fully-torn file: start fresh
            self._fresh = not existing
            self._entries = state
            self.header_config = header_cfg
            self._n_retired = sum(1 for e in state.values()
                                  if e.status is not None)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "ab")
        if not existing:
            self._append(self._header_record())
            self.header_config = config
            self.sync(force=True)

    def _header_record(self) -> dict:
        rec = dict(_HEADER)
        if self.config is not None:
            rec["config"] = self.config
        return rec

    def set_config(self, config: dict) -> None:
        """Attach the serving-config fingerprint AFTER construction — the
        CLI opens the journal before the model load (corruption must
        fail fast, in milliseconds, not after minutes of weight
        streaming) and only then knows the spec the fingerprint needs. A
        freshly created journal rewrites its header to carry the config
        (the header was written config-less at open); existing journals
        keep their recorded header for check_config to compare."""
        with self._lock:
            self.config = config
            if self._fresh and self.header_config is None:
                # the just-written header lacks the config: rewrite in
                # place (compact() emits self.config into the header and
                # preserves any entries admitted in between)
                self.compact()
                self.header_config = config

    def adopt_config(self) -> None:
        """Re-stamp the journal with the CURRENT serving config — only
        legal when nothing is live (ContinuousEngine.recover calls this
        when ``incomplete()`` is empty): with no in-flight work there is
        nothing a config change could replay wrongly, and refusing would
        strand every journaling deployment on a scheme/config upgrade.
        The compaction rewrite drops retired records and writes the new
        fingerprint, so the NEXT crash compares against the config its
        requests actually ran under."""
        with self._lock:
            if self.config is None or self.header_config == self.config:
                return
            assert not any(e.status is None for e in
                           self._entries.values()), \
                "adopt_config with live entries — recover() gates this"
            self.compact()
            self.header_config = self.config

    def check_config(self) -> None:
        """Refuse a journal whose recorded config fingerprint differs from
        the serving one (JournalConfigMismatch, listing the drifted keys).
        Legacy journals (no recorded config) and config-less handles pass
        — there is nothing trustworthy to compare."""
        old, new = self.header_config, self.config
        if old is None or new is None or old == new:
            return
        drifted = sorted(k for k in set(old) | set(new)
                         if old.get(k) != new.get(k))
        detail = ", ".join(
            f"{k}: journaled {old.get(k)!r} != serving {new.get(k)!r}"
            for k in drifted)
        raise JournalConfigMismatch(
            f"journal {self.path} was written under a different serving "
            f"config ({detail}) — a bitwise replay against it would be "
            f"silently wrong. Move the journal aside to drop its "
            f"in-flight work, or restart with the original config.")

    # ------------------------------------------------------------ state

    def bind_metrics(self, counter) -> None:
        """Attach an obs counter (``dllama_journal_records_total``)."""
        self._metric = counter

    def incomplete(self) -> list[JournalEntry]:
        """Entries with no retire record, in admission (rid) order — the
        recovery set."""
        with self._lock:
            return sorted((e for e in self._entries.values()
                           if e.status is None), key=lambda e: e.rid)

    def entry(self, rid: int) -> JournalEntry | None:
        """One request's journaled state by id, retired or live — the
        DCN handoff reads the retired prefill stub's entry here (prompt
        ids, sampled tokens, coin cursor: the exact resumable state the
        decode pool re-admits; runtime/disagg.py). A deep copy, so the
        caller can rewrite ``steps`` for the handoff without touching
        the journal's in-memory state."""
        with self._lock:
            e = self._entries.get(rid)
            if e is None:
                return None
            return JournalEntry(rid=e.rid, tokens=list(e.tokens),
                                steps=e.steps, temperature=e.temperature,
                                topp=e.topp, seed=e.seed, slo=e.slo,
                                cursor=e.cursor, sampled=list(e.sampled),
                                status=e.status, trace=e.trace,
                                ledger=dict(e.ledger)
                                if e.ledger is not None else None)

    @property
    def next_id(self) -> int:
        """One past the highest journaled request id — a fresh engine
        appending to this journal must start numbering here, or new
        records would alias old requests."""
        with self._lock:
            return max(self._entries, default=-1) + 1

    # ----------------------------------------------------------- append

    def _append(self, obj: dict) -> None:
        line = (json.dumps(obj, separators=(",", ":")) + "\n").encode()
        with self._lock:
            self._fh.write(line)
            self.records_total += 1
            if self.fsync == "always":
                self._fh.flush()
                # WAL durability point: the fsync must serialize with
                # appends or a concurrent write could land after the
                # sync yet claim its durability
                os.fsync(self._fh.fileno())  # threadcheck: allow[T003]
            else:
                self._dirty = True
        if self._metric is not None:
            self._metric.inc()

    def admit(self, rid: int, tokens, steps: int, temperature: float,
              topp: float, seed: int, slo: str | None = None,
              cursor: int = 0, recovers: int | None = None,
              trace: str | None = None,
              ledger: dict | None = None) -> None:
        """Journal a request's admission. ``recovers`` names the PREVIOUS
        life's rid when this admit is a recovery re-admission: the one
        appended record atomically opens the new life AND retires the old
        (status ``recovered``) — a crash on either side of a two-record
        handoff would otherwise leave zero or two live entries for the
        same request. ``trace`` is the request's traceparent header
        (ISSUE 15) — the id a later life continues the trace from.
        ``ledger`` is the carried cost-ledger snapshot (ISSUE 16): the
        bill a handed-off/recovered request brought from its previous
        life."""
        entry = JournalEntry(rid=rid, tokens=list(tokens), steps=steps,
                             temperature=temperature, topp=topp, seed=seed,
                             slo=slo, cursor=cursor, trace=trace,
                             ledger=dict(ledger)
                             if ledger is not None else None)
        rec = {"t": "admit", "id": rid, "tokens": entry.tokens,
               "steps": steps, "temperature": temperature,
               "topp": topp, "seed": seed, "slo": slo, "cursor": cursor}
        if trace is not None:
            rec["trace"] = str(trace)
        if entry.ledger is not None:
            rec["ledger"] = entry.ledger
        if recovers is not None:
            rec["recovers"] = int(recovers)
        with self._lock:
            self._entries[rid] = entry
            if recovers is not None:
                old = self._entries.get(recovers)
                if old is not None and old.status is None:
                    old.status = "recovered"
                    self._n_retired += 1
            self._append(rec)

    def token(self, rid: int, tok: int, cursor: int) -> None:
        with self._lock:
            e = self._entries[rid]
            e.sampled.append(int(tok))
            e.cursor = int(cursor)
            self._append({"t": "tok", "id": rid, "tok": int(tok),
                          "cursor": int(cursor)})

    def retire(self, rid: int, status: str = "done") -> None:
        with self._lock:
            e = self._entries.get(rid)
            if e is None or e.status is not None:
                return  # already retired (or never journaled): idempotent
            e.status = status
            self._n_retired += 1
            self._append({"t": "retire", "id": rid, "status": status})

    def sync(self, force: bool = False) -> None:
        """Step-boundary durability point (batch policy): one flush+fsync
        covering every record since the last sync. No-op when nothing is
        dirty or the policy already synced per record."""
        with self._lock:
            if not (self._dirty or force):
                return
            self._fh.flush()
            if self.fsync != "off" or force:
                # batch durability point: same WAL contract as _append —
                # the fsync covers exactly the records under this lock
                os.fsync(self._fh.fileno())  # threadcheck: allow[T003]
            self._dirty = False

    def close(self) -> None:
        self.sync(force=True)
        with self._lock:
            self._fh.close()

    # ------------------------------------------------------- compaction

    def compact(self) -> int:
        """Atomically rewrite the journal as merged admit records of the
        LIVE requests only (module docstring), dropping retired ones.
        Crash-safe: the new content lands in a sibling temp file, is
        fsynced, and replaces the journal in one ``os.replace`` — at any
        kill point exactly one complete journal exists. Returns the
        number of retired requests dropped."""
        with self._lock:
            live = sorted((e for e in self._entries.values()
                           if e.status is None), key=lambda e: e.rid)
            dropped = self._n_retired
            tmp = self.path + ".compact"
            # preserve the journal's recorded config across rotation (a
            # handle opened without one must not strip the fingerprint)
            head = dict(_HEADER)
            cfg = self.config if self.config is not None \
                else self.header_config
            if cfg is not None:
                head["config"] = cfg
            with open(tmp, "wb") as fh:
                fh.write((json.dumps(head, separators=(",", ":"))
                          + "\n").encode())
                for e in live:
                    rec = {"t": "admit", "id": e.rid,
                           "tokens": e.replay_tokens, "steps": e.steps,
                           "temperature": e.temperature, "topp": e.topp,
                           "seed": e.seed, "slo": e.slo,
                           "cursor": e.cursor}
                    if e.trace is not None:
                        rec["trace"] = e.trace
                    if e.ledger is not None:
                        rec["ledger"] = e.ledger
                    fh.write((json.dumps(rec, separators=(",", ":"))
                              + "\n").encode())
                fh.flush()
                # compaction writes the replacement file atomically;
                # appends must stall until the rename lands or they'd
                # hit the about-to-be-replaced fd
                os.fsync(fh.fileno())  # threadcheck: allow[T003]
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "ab")
            self._entries = {
                e.rid: JournalEntry(
                    rid=e.rid, tokens=e.replay_tokens, steps=e.steps,
                    temperature=e.temperature, topp=e.topp, seed=e.seed,
                    slo=e.slo, cursor=e.cursor, trace=e.trace,
                    ledger=e.ledger)
                for e in live}
            self._n_retired = 0
            self._dirty = False
        return dropped

    def maybe_compact(self) -> int:
        """The rotation policy: compact once ``compact_every`` retired
        requests have accumulated. Called at step boundaries."""
        if self._n_retired >= self.compact_every:
            return self.compact()
        return 0


def _parse_record(obj, entries: dict[int, JournalEntry],
                  lineno: int) -> None:
    """Apply one parsed record to the state; JournalCorruption on any
    schema violation."""
    if not isinstance(obj, dict) or not isinstance(obj.get("t"), str):
        raise JournalCorruption(f"line {lineno}: not a journal record")
    t = obj["t"]
    try:
        if t == "admit":
            rid = int(obj["id"])
            if rid in entries:
                raise JournalCorruption(
                    f"line {lineno}: duplicate admit for request {rid}")
            tokens = obj["tokens"]
            if not isinstance(tokens, list) or not tokens:
                raise JournalCorruption(
                    f"line {lineno}: admit {rid} has no prompt tokens")
            trace = obj.get("trace")
            if trace is not None and not isinstance(trace, str):
                raise JournalCorruption(
                    f"line {lineno}: admit {rid} trace is not a string")
            ledger = obj.get("ledger")
            if ledger is not None and not isinstance(ledger, dict):
                raise JournalCorruption(
                    f"line {lineno}: admit {rid} ledger is not an object")
            entries[rid] = JournalEntry(
                rid=rid, tokens=[int(x) for x in tokens],
                steps=int(obj["steps"]),
                temperature=float(obj["temperature"]),
                topp=float(obj["topp"]), seed=int(obj["seed"]),
                slo=obj.get("slo"), cursor=int(obj.get("cursor", 0)),
                trace=trace, ledger=ledger)
            recovers = obj.get("recovers")
            if recovers is not None:
                # recovery re-admission: this one record also closes the
                # previous life (see RequestJournal.admit)
                old = entries.get(int(recovers))
                if old is not None and old.status is None:
                    old.status = "recovered"
        elif t == "tok":
            rid = int(obj["id"])
            e = entries.get(rid)
            if e is None:
                raise JournalCorruption(
                    f"line {lineno}: token for unadmitted request {rid}")
            if e.status is not None:
                raise JournalCorruption(
                    f"line {lineno}: token for retired request {rid}")
            e.sampled.append(int(obj["tok"]))
            e.cursor = int(obj["cursor"])
        elif t == "retire":
            rid = int(obj["id"])
            e = entries.get(rid)
            if e is None:
                raise JournalCorruption(
                    f"line {lineno}: retire for unadmitted request {rid}")
            status = obj.get("status")
            if status not in ("done", "cancelled", "failed", "recovered"):
                raise JournalCorruption(
                    f"line {lineno}: retire status {status!r}")
            e.status = status
        else:
            raise JournalCorruption(
                f"line {lineno}: unknown record type {t!r}")
    except (KeyError, TypeError, ValueError) as exc:
        raise JournalCorruption(
            f"line {lineno}: malformed {t!r} record: {exc}") from exc


def _load_file(path: str) -> tuple[dict[int, JournalEntry], int,
                                   dict | None]:
    """Parse a journal file. Returns (entries, valid_bytes, header_config)
    where valid_bytes is the offset just past the last VALID record —
    shorter than the file only for a torn tail — and header_config the
    config fingerprint the header recorded (None on legacy headers).
    Raises JournalCorruption for any non-tail damage (module docstring)."""
    entries: dict[int, JournalEntry] = {}
    header_cfg: dict | None = None
    with open(path, "rb") as fh:
        data = fh.read()
    lines = data.split(b"\n")
    # data ending in \n splits to a trailing b"" — complete final record;
    # anything else in the last slot is a torn (unterminated) tail
    torn = lines.pop() if lines else b""
    offset = 0
    for i, raw in enumerate(lines):
        try:
            obj = json.loads(raw)
        except ValueError as exc:
            if i == len(lines) - 1 and not torn:
                # newline-terminated but unparsable LAST line: a torn
                # record whose tail bytes happened to include the \n —
                # same truncate-and-report treatment
                return entries, offset, header_cfg
            raise JournalCorruption(
                f"line {i + 1}: unparseable record "
                f"{raw[:64]!r}") from exc
        if i == 0:
            if (not isinstance(obj, dict) or obj.get("t") != "journal"
                    or obj.get("v") != 1):
                raise JournalCorruption(
                    "missing or wrong journal header (line 1)")
            cfg = obj.get("config")
            if cfg is not None and not isinstance(cfg, dict):
                raise JournalCorruption(
                    "header config fingerprint is not an object")
            header_cfg = cfg
        else:
            try:
                _parse_record(obj, entries, i + 1)
            except JournalCorruption:
                if i == len(lines) - 1 and not torn:
                    # schema-torn tail (e.g. a short but valid-JSON
                    # fragment): truncate like any other torn tail
                    return entries, offset, header_cfg
                raise
        offset += len(raw) + 1
    # no complete line at all (killed mid-header-write): fully torn —
    # truncate to zero and start fresh rather than refusing a journal
    # that never recorded anything
    return entries, offset, header_cfg


def entry_to_wire(entry: JournalEntry) -> dict:
    """The handoff wire form of a journal entry (ISSUE 14): the plain
    JSON-able dict a prefill pool ships to a decode pool — exactly the
    fields ``ContinuousEngine.recover`` replays from, so a handed-off
    request and a crash-recovered one re-admit through ONE code path.
    ``sampled`` stays separate from ``tokens`` (the receiver composes
    ``replay_tokens`` itself) so the record is honest about what was
    prompt and what was generated. ``trace`` carries the traceparent
    header (ISSUE 15): the decode pool continues the SAME trace the
    prefill pool opened."""
    rec = {"id": entry.rid, "tokens": list(entry.tokens),
           "sampled": list(entry.sampled), "cursor": entry.cursor,
           "steps": entry.steps, "temperature": entry.temperature,
           "topp": entry.topp, "seed": entry.seed, "slo": entry.slo,
           "trace": entry.trace}
    if entry.ledger is not None:
        rec["ledger"] = dict(entry.ledger)
    return rec


def entry_from_wire(rec: dict) -> JournalEntry:
    """entry_to_wire's inverse, with the same strictness as journal
    loading: a malformed handoff record raises ValueError (the decode
    pool refuses it — admitting a half-parsed request would serve wrong
    bytes with a straight face)."""
    try:
        tokens = [int(t) for t in rec["tokens"]]
        if not tokens:
            raise ValueError("handoff record has no prompt tokens")
        trace = rec.get("trace")
        if trace is not None and not isinstance(trace, str):
            raise ValueError("handoff record trace is not a string")
        ledger = rec.get("ledger")
        if ledger is not None and not isinstance(ledger, dict):
            raise ValueError("handoff record ledger is not an object")
        return JournalEntry(
            rid=int(rec["id"]), tokens=tokens,
            steps=int(rec["steps"]),
            temperature=float(rec["temperature"]),
            topp=float(rec["topp"]), seed=int(rec["seed"]),
            slo=rec.get("slo"), cursor=int(rec.get("cursor", 0)),
            sampled=[int(t) for t in rec.get("sampled", ())],
            trace=trace, ledger=ledger)
    except (KeyError, TypeError, ValueError) as exc:
        raise ValueError(f"malformed handoff record: {exc}") from exc


def load_journal(path: str) -> list[JournalEntry]:
    """Read-only load: every entry (retired included), rid-sorted. The
    torn-tail rule applies; the file is not modified."""
    entries, _, _ = _load_file(path)
    return sorted(entries.values(), key=lambda e: e.rid)
