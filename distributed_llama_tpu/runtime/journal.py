"""Write-ahead request journal: crash-safe serving state (ISSUE 9).

The reference is a single-shot process — a crash loses everything. The
continuous engine already has the two properties that make real
crash-safety CHEAP here: seeded coin-replay determinism (a request's
token stream is a pure function of its prompt, sampler config, and coin
cursor — rejected speculative positions and forced steps consume no
coins), and radix prefix sharing (re-prefilling a recovered request
mostly hits the tree once its siblings re-admit). This module adds the
missing piece: a durable, append-only record of every request's inputs
and progress, from which ``ContinuousEngine.recover`` re-derives the
exact in-flight state.

Format: NDJSON, one record per line, four record types —

* ``{"t": "journal", "v": 1}`` — the header, always line 1;
* ``{"t": "admit", "id", "tokens", "steps", "temperature", "topp",
  "seed", "slo", "cursor"[, "recovers"]}`` — written at ``submit()``
  time (write-AHEAD of the scheduler ever seeing the request). ``seed``
  is the RESOLVED per-request seed (the engine's ``seed + index``
  default is process-local and would not survive a restart) and
  ``cursor`` the coin draws already consumed (non-zero only for
  re-journaled recovered requests). ``recovers`` names the previous
  life's id on a recovery re-admission: the ONE record opens the new
  life and retires the old atomically;
* ``{"t": "tok", "id", "tok", "cursor"}`` — one per SAMPLED token, with
  the cumulative coin cursor AFTER sampling it (forced prompt echoes are
  derivable from the admit record and are not journaled);
* ``{"t": "retire", "id", "status"}`` — ``done`` / ``cancelled`` /
  ``failed`` / ``recovered``; a request with a retire record (or whose
  id a later admit ``recovers``) needs no recovery.

Durability policy (``fsync=``): ``always`` fsyncs every record (survives
power loss, slowest), ``batch`` fsyncs once per scheduler step — the
engine calls ``sync()`` at each step boundary, so at most one dispatch's
tokens are at risk (the default), ``off`` leaves flushing to the OS
(process-crash-safe only). Every append is a single ``write()`` of one
complete line either way, so a torn record can only be the file's tail.

Corruption contract: a torn TAIL record (a crash mid-append) is expected
damage — loading truncates the file at the last valid line and reports
it. Anything else — garbage mid-file, an unknown record type, a record
referencing an unadmitted id, a missing header — raises
``JournalCorruption``: silently "recovering" from a journal whose
history cannot be trusted would serve wrong bytes with a straight face.

Compaction: retired requests' records are dead weight. ``compact()``
atomically rewrites the journal as one MERGED admit record per live
request (prompt + sampled-so-far as the token list, cursor carried
forward — exactly the reconstruction ``recover`` performs), dropping
everything retired. ``maybe_compact()`` applies the rotation policy
(``compact_every`` retirements); the engine calls it at step boundaries.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading

_HEADER = {"t": "journal", "v": 1}
FSYNC_POLICIES = ("always", "batch", "off")


class JournalCorruption(RuntimeError):
    """The journal's history cannot be trusted (non-tail damage) — fail
    loudly instead of recovering wrong state."""


@dataclasses.dataclass
class JournalEntry:
    """One request's journaled state: the admit record plus every sampled
    token appended since. ``replay_tokens`` is what recovery re-admits:
    the prompt with the already-sampled suffix riding the forced-token
    window, and ``cursor`` the coin draws the recovered sampler must
    fast-forward past."""

    rid: int
    tokens: list
    steps: int
    temperature: float
    topp: float
    seed: int
    slo: str | None = None
    cursor: int = 0
    sampled: list = dataclasses.field(default_factory=list)
    status: str | None = None  # None = incomplete (needs recovery)

    @property
    def replay_tokens(self) -> list:
        return list(self.tokens) + list(self.sampled)


class RequestJournal:
    """Append-side handle over one journal file (engine-owned).

    Opening an existing journal loads its state (so compaction knows the
    live set), REPAIRS a torn tail by physically truncating it, and
    raises ``JournalCorruption`` on any deeper damage. Appends are
    thread-safe (submit runs on handler threads, tokens on the
    scheduler thread).
    """

    def __init__(self, path: str, fsync: str = "batch",
                 compact_every: int = 256):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in "
                             f"{FSYNC_POLICIES}")
        self.path = path
        self.fsync = fsync
        self.compact_every = compact_every
        # RLock: admit/token/retire mutate ``_entries`` AND append under
        # one critical section (submit runs on handler threads while
        # compact() rebuilds the dict on the scheduler thread — an
        # unlocked dict-set could vanish into the pre-compaction dict and
        # leave a journaled request the in-memory state no longer knows)
        self._lock = threading.RLock()
        self._metric = None  # obs counter (.inc) — bind_metrics
        self.records_total = 0  # appended by THIS handle
        self._dirty = False     # unsynced appends (batch policy)
        self._entries: dict[int, JournalEntry] = {}
        self._n_retired = 0
        existing = os.path.exists(path) and os.path.getsize(path) > 0
        if existing:
            state, valid_bytes = _load_file(path)
            if valid_bytes < os.path.getsize(path):
                # torn tail: a crash mid-append left a partial last line —
                # truncate to the last valid record before appending, or
                # the next load would see garbage MID-file and refuse
                with open(path, "r+b") as fh:
                    fh.truncate(valid_bytes)
            existing = valid_bytes > 0  # fully-torn file: start fresh
            self._entries = state
            self._n_retired = sum(1 for e in state.values()
                                  if e.status is not None)
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "ab")
        if not existing:
            self._append(_HEADER)
            self.sync(force=True)

    # ------------------------------------------------------------ state

    def bind_metrics(self, counter) -> None:
        """Attach an obs counter (``dllama_journal_records_total``)."""
        self._metric = counter

    def incomplete(self) -> list[JournalEntry]:
        """Entries with no retire record, in admission (rid) order — the
        recovery set."""
        with self._lock:
            return sorted((e for e in self._entries.values()
                           if e.status is None), key=lambda e: e.rid)

    @property
    def next_id(self) -> int:
        """One past the highest journaled request id — a fresh engine
        appending to this journal must start numbering here, or new
        records would alias old requests."""
        with self._lock:
            return max(self._entries, default=-1) + 1

    # ----------------------------------------------------------- append

    def _append(self, obj: dict) -> None:
        line = (json.dumps(obj, separators=(",", ":")) + "\n").encode()
        with self._lock:
            self._fh.write(line)
            self.records_total += 1
            if self.fsync == "always":
                self._fh.flush()
                os.fsync(self._fh.fileno())
            else:
                self._dirty = True
        if self._metric is not None:
            self._metric.inc()

    def admit(self, rid: int, tokens, steps: int, temperature: float,
              topp: float, seed: int, slo: str | None = None,
              cursor: int = 0, recovers: int | None = None) -> None:
        """Journal a request's admission. ``recovers`` names the PREVIOUS
        life's rid when this admit is a recovery re-admission: the one
        appended record atomically opens the new life AND retires the old
        (status ``recovered``) — a crash on either side of a two-record
        handoff would otherwise leave zero or two live entries for the
        same request."""
        entry = JournalEntry(rid=rid, tokens=list(tokens), steps=steps,
                             temperature=temperature, topp=topp, seed=seed,
                             slo=slo, cursor=cursor)
        rec = {"t": "admit", "id": rid, "tokens": entry.tokens,
               "steps": steps, "temperature": temperature,
               "topp": topp, "seed": seed, "slo": slo, "cursor": cursor}
        if recovers is not None:
            rec["recovers"] = int(recovers)
        with self._lock:
            self._entries[rid] = entry
            if recovers is not None:
                old = self._entries.get(recovers)
                if old is not None and old.status is None:
                    old.status = "recovered"
                    self._n_retired += 1
            self._append(rec)

    def token(self, rid: int, tok: int, cursor: int) -> None:
        with self._lock:
            e = self._entries[rid]
            e.sampled.append(int(tok))
            e.cursor = int(cursor)
            self._append({"t": "tok", "id": rid, "tok": int(tok),
                          "cursor": int(cursor)})

    def retire(self, rid: int, status: str = "done") -> None:
        with self._lock:
            e = self._entries.get(rid)
            if e is None or e.status is not None:
                return  # already retired (or never journaled): idempotent
            e.status = status
            self._n_retired += 1
            self._append({"t": "retire", "id": rid, "status": status})

    def sync(self, force: bool = False) -> None:
        """Step-boundary durability point (batch policy): one flush+fsync
        covering every record since the last sync. No-op when nothing is
        dirty or the policy already synced per record."""
        with self._lock:
            if not (self._dirty or force):
                return
            self._fh.flush()
            if self.fsync != "off" or force:
                os.fsync(self._fh.fileno())
            self._dirty = False

    def close(self) -> None:
        self.sync(force=True)
        with self._lock:
            self._fh.close()

    # ------------------------------------------------------- compaction

    def compact(self) -> int:
        """Atomically rewrite the journal as merged admit records of the
        LIVE requests only (module docstring), dropping retired ones.
        Crash-safe: the new content lands in a sibling temp file, is
        fsynced, and replaces the journal in one ``os.replace`` — at any
        kill point exactly one complete journal exists. Returns the
        number of retired requests dropped."""
        with self._lock:
            live = sorted((e for e in self._entries.values()
                           if e.status is None), key=lambda e: e.rid)
            dropped = self._n_retired
            tmp = self.path + ".compact"
            with open(tmp, "wb") as fh:
                fh.write((json.dumps(_HEADER, separators=(",", ":"))
                          + "\n").encode())
                for e in live:
                    fh.write((json.dumps(
                        {"t": "admit", "id": e.rid,
                         "tokens": e.replay_tokens, "steps": e.steps,
                         "temperature": e.temperature, "topp": e.topp,
                         "seed": e.seed, "slo": e.slo,
                         "cursor": e.cursor},
                        separators=(",", ":")) + "\n").encode())
                fh.flush()
                os.fsync(fh.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "ab")
            self._entries = {
                e.rid: JournalEntry(
                    rid=e.rid, tokens=e.replay_tokens, steps=e.steps,
                    temperature=e.temperature, topp=e.topp, seed=e.seed,
                    slo=e.slo, cursor=e.cursor)
                for e in live}
            self._n_retired = 0
            self._dirty = False
        return dropped

    def maybe_compact(self) -> int:
        """The rotation policy: compact once ``compact_every`` retired
        requests have accumulated. Called at step boundaries."""
        if self._n_retired >= self.compact_every:
            return self.compact()
        return 0


def _parse_record(obj, entries: dict[int, JournalEntry],
                  lineno: int) -> None:
    """Apply one parsed record to the state; JournalCorruption on any
    schema violation."""
    if not isinstance(obj, dict) or not isinstance(obj.get("t"), str):
        raise JournalCorruption(f"line {lineno}: not a journal record")
    t = obj["t"]
    try:
        if t == "admit":
            rid = int(obj["id"])
            if rid in entries:
                raise JournalCorruption(
                    f"line {lineno}: duplicate admit for request {rid}")
            tokens = obj["tokens"]
            if not isinstance(tokens, list) or not tokens:
                raise JournalCorruption(
                    f"line {lineno}: admit {rid} has no prompt tokens")
            entries[rid] = JournalEntry(
                rid=rid, tokens=[int(x) for x in tokens],
                steps=int(obj["steps"]),
                temperature=float(obj["temperature"]),
                topp=float(obj["topp"]), seed=int(obj["seed"]),
                slo=obj.get("slo"), cursor=int(obj.get("cursor", 0)))
            if obj.get("recovers") is not None:
                # recovery re-admission: this one record also closes the
                # previous life (see RequestJournal.admit)
                old = entries.get(int(obj["recovers"]))
                if old is not None and old.status is None:
                    old.status = "recovered"
        elif t == "tok":
            rid = int(obj["id"])
            e = entries.get(rid)
            if e is None:
                raise JournalCorruption(
                    f"line {lineno}: token for unadmitted request {rid}")
            if e.status is not None:
                raise JournalCorruption(
                    f"line {lineno}: token for retired request {rid}")
            e.sampled.append(int(obj["tok"]))
            e.cursor = int(obj["cursor"])
        elif t == "retire":
            rid = int(obj["id"])
            e = entries.get(rid)
            if e is None:
                raise JournalCorruption(
                    f"line {lineno}: retire for unadmitted request {rid}")
            status = obj.get("status")
            if status not in ("done", "cancelled", "failed", "recovered"):
                raise JournalCorruption(
                    f"line {lineno}: retire status {status!r}")
            e.status = status
        else:
            raise JournalCorruption(
                f"line {lineno}: unknown record type {t!r}")
    except (KeyError, TypeError, ValueError) as exc:
        raise JournalCorruption(
            f"line {lineno}: malformed {t!r} record: {exc}") from exc


def _load_file(path: str) -> tuple[dict[int, JournalEntry], int]:
    """Parse a journal file. Returns (entries, valid_bytes) where
    valid_bytes is the offset just past the last VALID record — shorter
    than the file only for a torn tail. Raises JournalCorruption for any
    non-tail damage (module docstring)."""
    entries: dict[int, JournalEntry] = {}
    with open(path, "rb") as fh:
        data = fh.read()
    lines = data.split(b"\n")
    # data ending in \n splits to a trailing b"" — complete final record;
    # anything else in the last slot is a torn (unterminated) tail
    torn = lines.pop() if lines else b""
    offset = 0
    saw_header = False
    for i, raw in enumerate(lines):
        try:
            obj = json.loads(raw)
        except ValueError as exc:
            if i == len(lines) - 1 and not torn:
                # newline-terminated but unparsable LAST line: a torn
                # record whose tail bytes happened to include the \n —
                # same truncate-and-report treatment
                return entries, offset
            raise JournalCorruption(
                f"line {i + 1}: unparseable record "
                f"{raw[:64]!r}") from exc
        if i == 0:
            if (not isinstance(obj, dict) or obj.get("t") != "journal"
                    or obj.get("v") != 1):
                raise JournalCorruption(
                    "missing or wrong journal header (line 1)")
            saw_header = True
        else:
            try:
                _parse_record(obj, entries, i + 1)
            except JournalCorruption:
                if i == len(lines) - 1 and not torn:
                    # schema-torn tail (e.g. a short but valid-JSON
                    # fragment): truncate like any other torn tail
                    return entries, offset
                raise
        offset += len(raw) + 1
    # no complete line at all (killed mid-header-write): fully torn —
    # truncate to zero and start fresh rather than refusing a journal
    # that never recorded anything
    del saw_header
    return entries, offset


def load_journal(path: str) -> list[JournalEntry]:
    """Read-only load: every entry (retired included), rid-sorted. The
    torn-tail rule applies; the file is not modified."""
    entries, _ = _load_file(path)
    return sorted(entries.values(), key=lambda e: e.rid)
