"""threadcheck: the thread-ownership lint over runtime/ + obs/ (ISSUE 17).

The fourth analysis head (beside dlint's AST hazards, the jaxpr
contracts, and shardcheck): a pure-AST pass that enforces the declared
thread model in ``analysis/threadmodel.py`` — never importing the
runtime, exactly like dlint, so it runs anywhere in milliseconds.

Rules (each has firing + non-firing fixtures in
tests/test_threadcheck_rules.py):

* **T001 unlocked cross-domain write** — a write to a registered
  attribute family outside its declared lock, reachable from a thread
  domain the family does not own. Families with ``lock=None`` are
  domain-private: any foreign-domain write fires regardless.
* **T002 lock-order inversion** — the lock acquisition graph (built
  from ``with lock:`` nesting plus resolved calls made while holding a
  lock) contains a cycle: two threads taking the same pair in opposite
  orders is the classic ABBA deadlock.
* **T003 blocking call under a lock** — fsync/sleep/socket I/O/thread
  join/``wait`` on a FOREIGN primitive while holding a lock turns every
  other thread wanting that lock into a hostage of the slow operation.
  (``cond.wait()`` under ``with cond:`` is the sanctioned idiom — the
  wait releases the condition's own lock — and is exempt.)
* **T004 unregistered thread** — a ``threading.Thread(target=...)``
  whose target is not in the entrypoint registry: every thread must
  declare its domain and its join/stop path.
* **T005 mutable state escape** — returning a registered mutable
  attribute RAW from a method callable cross-domain; the caller's
  domain would then mutate or iterate it unlocked. Return a copy
  (``list(...)``/``dict(...)``) — the snapshot crossing point.

The analysis is deliberately scoped at what a reviewer can trust:
domains propagate through ``self.``-calls within a class and through
the declared INSTANCE_HINTS across classes, from the registered
entrypoints and METHOD_DOMAINS seeds; lock identity resolves through
the same hints. What it cannot resolve it does not guess — unresolved
targets are skipped (T002/T003) or flagged for registration (T004).

Suppression reuses dlint's machinery verbatim: ``# threadcheck:
allow[T003] reason`` pragmas at the site, and the line-number-
independent baseline in tools/threadcheck_baseline.txt for
grandfathered findings (burn-down notes in its header).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .lint import Finding, ModuleContext, iter_module_contexts
from . import threadmodel as tm

# rule catalogue (rendered by --threadcheck and the README table)
THREAD_RULES: dict[str, tuple[str, str]] = {
    "T000": ("unreadable input",
             "fix the path or the parse error"),
    "T001": ("unlocked cross-domain write",
             "hold the family's declared lock, or marshal through the "
             "owner domain (inbox/Event box)"),
    "T002": ("lock-order inversion",
             "acquire locks in one global order; release before calling "
             "into another locked object"),
    "T003": ("blocking call while holding a lock",
             "move the blocking operation outside the critical section; "
             "snapshot under the lock, block after"),
    "T004": ("thread started outside the entrypoint registry",
             "register the target in threadmodel.ENTRYPOINTS with its "
             "domain and join/stop path"),
    "T005": ("mutable state escapes its domain",
             "return a copy (list()/dict()) — the snapshot crossing "
             "point — not the guarded object itself"),
}

_SCOPES = ("runtime/", "obs/")

# mutating container methods: a call through one of these writes the
# receiver just as surely as an assignment does
_MUTATORS = frozenset({
    "append", "appendleft", "add", "extend", "insert", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
})

# copy-constructor call names that turn a return into a snapshot
_SNAPSHOTS = frozenset({"list", "dict", "tuple", "set", "frozenset",
                        "sorted", "copy", "deepcopy"})

_LOCKISH = re.compile(r"(^|_)(lock|cond|mutex|sem)\b|_lock$|_cond$")

# dotted names that block (module-qualified)
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "os.fsync", "os.fdatasync", "select.select",
    "socket.create_connection", "subprocess.run", "subprocess.check_call",
    "jax.block_until_ready",
})
# attribute calls that block regardless of receiver
_BLOCKING_ATTRS = frozenset({
    "recv", "recvfrom", "accept", "sendall", "serve_forever",
    "block_until_ready", "fsync", "fdatasync",
})


def _is_lock_attr(name: str) -> bool:
    return name in tm.LOCK_ATTRS or bool(_LOCKISH.search(name))


# -- program index ---------------------------------------------------------


class _Method:
    """One method (plus everything nested in it) of an indexed class."""

    def __init__(self, cls: str, name: str, node: ast.AST,
                 ctx: ModuleContext):
        self.cls = cls
        self.name = name
        self.qual = f"{cls}.{name}"
        self.node = node
        self.ctx = ctx
        self.self_calls: set[str] = set()          # self.m(...)
        self.hint_calls: set[tuple[str, str]] = set()  # (class, method)
        self.lock_keys: set[str] = set()           # locks acquired here


class _Index:
    """Cross-module program index: classes, methods, domains, locks."""

    def __init__(self, contexts: list[ModuleContext]):
        self.contexts = contexts
        self.methods: dict[str, _Method] = {}      # qual -> method
        self.by_class: dict[str, dict[str, _Method]] = {}
        self.method_of_node: dict[ast.AST, _Method] = {}
        for ctx in contexts:
            self._index_module(ctx)
        self._resolve_calls()
        self.domains = self._propagate_domains()
        self.acquires = self._transitive_acquires()

    # -- construction ------------------------------------------------------

    def _index_module(self, ctx: ModuleContext) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cls = node.name
            table = self.by_class.setdefault(cls, {})
            for child in node.body:
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                    continue
                m = _Method(cls, child.name, child, ctx)
                # first definition wins (server.py defines Handler once;
                # fixtures may shadow — per-run indexes are fresh)
                self.methods.setdefault(m.qual, m)
                table.setdefault(child.name, m)
                for sub in ast.walk(child):
                    self.method_of_node[sub] = m

    def method_for(self, node: ast.AST) -> "_Method | None":
        return self.method_of_node.get(node)

    def _resolve_calls(self) -> None:
        for m in self.methods.values():
            for node in ast.walk(m.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        key = self.lock_key(m, item.context_expr)
                        if key:
                            m.lock_keys.add(key)
                if not isinstance(node, ast.Call):
                    continue
                dotted = m.ctx.dotted(node.func)
                if not dotted:
                    continue
                parts = dotted.split(".")
                if len(parts) == 2 and parts[0] == "self":
                    m.self_calls.add(parts[1])
                elif len(parts) >= 2:
                    hint = tm.INSTANCE_HINTS.get(parts[-2])
                    if hint:
                        m.hint_calls.add((hint, parts[-1]))

    # -- lock identity -----------------------------------------------------

    def lock_key(self, m: "_Method | None",
                 expr: ast.AST) -> str | None:
        """Graph-node identity of a ``with <expr>:`` lock acquisition,
        resolved through the declared instance hints so the same lock
        reached by different attribute paths keys one node. None when
        the expression is not lock-shaped."""
        ctx = m.ctx if m is not None else None
        dotted = ctx.dotted(expr) if ctx is not None else None
        if dotted is None:
            return None
        parts = dotted.split(".")
        attr = parts[-1]
        if not _is_lock_attr(attr):
            return None
        if parts[0] == "self" and len(parts) == 2 and m is not None:
            return f"{m.cls}.{attr}"
        hint = tm.INSTANCE_HINTS.get(parts[-2]) if len(parts) >= 2 \
            else None
        if hint:
            return f"{hint}.{attr}"
        return f"?.{attr}"

    # -- domain propagation ------------------------------------------------

    def _propagate_domains(self) -> dict[str, frozenset]:
        """Seed method domains from the registry, then flow them through
        self-calls and hinted cross-class calls to a fixpoint. A method
        no declared or inferred domain reaches runs only in its class's
        owner domain."""
        dom: dict[str, set] = {q: set() for q in self.methods}
        for qual, m in self.methods.items():
            if m.name in tm.CONSTRUCTION_METHODS:
                continue
            if qual in tm.METHOD_DOMAINS:
                dom[qual] |= tm.METHOD_DOMAINS[qual]
            ep = tm.ENTRYPOINTS.get(qual) or tm.ENTRYPOINTS.get(m.name)
            if ep is not None and ep.key in (qual, m.name):
                dom[qual].add(ep.domain)
        changed = True
        while changed:
            changed = False
            for qual, m in self.methods.items():
                src = dom[qual]
                if not src:
                    continue
                targets = [f"{m.cls}.{n}" for n in m.self_calls]
                targets += [f"{c}.{n}" for c, n in m.hint_calls]
                for t in targets:
                    tmedia = self.methods.get(t)
                    if tmedia is None \
                            or tmedia.name in tm.CONSTRUCTION_METHODS:
                        continue
                    # declared methods hold their declared set: the
                    # registry row IS the crossing-point contract, and
                    # widening it silently would hide missing rows
                    if t in tm.METHOD_DOMAINS:
                        continue
                    if not src <= dom[t]:
                        dom[t] |= src
                        changed = True
        out: dict[str, frozenset] = {}
        for qual, m in self.methods.items():
            if dom[qual]:
                out[qual] = frozenset(dom[qual])
            else:
                out[qual] = frozenset(
                    {tm.CLASS_OWNER.get(m.cls, tm.MAIN)})
        return out

    def method_domains(self, m: "_Method") -> frozenset:
        return self.domains.get(m.qual,
                                frozenset({tm.CLASS_OWNER.get(m.cls,
                                                              tm.MAIN)}))

    # -- transitive lock acquisition ---------------------------------------

    def _transitive_acquires(self) -> dict[str, frozenset]:
        memo: dict[str, frozenset] = {}

        def visit(qual: str, stack: frozenset) -> frozenset:
            if qual in memo:
                return memo[qual]
            if qual in stack:
                return frozenset()
            m = self.methods.get(qual)
            if m is None:
                return frozenset()
            acc = set(m.lock_keys)
            nxt = stack | {qual}
            for n in m.self_calls:
                acc |= visit(f"{m.cls}.{n}", nxt)
            for c, n in m.hint_calls:
                acc |= visit(f"{c}.{n}", nxt)
            memo[qual] = frozenset(acc)
            return memo[qual]

        for qual in list(self.methods):
            visit(qual, frozenset())
        return memo


# -- shared AST helpers ----------------------------------------------------


def _write_targets(node: ast.AST):
    """Yield (base_expr, attr_name) for every attribute-family write in
    a statement: plain/aug assigns, subscript stores, del of a keyed
    entry, and mutator-method calls."""
    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            while isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute):
                yield t.value, t.attr
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            while isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute):
                yield t.value, t.attr
    elif isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS \
                and isinstance(f.value, ast.Attribute):
            yield f.value.value, f.value.attr


def _held_locks(index: _Index, m: _Method, node: ast.AST) -> set[str]:
    """Lock keys of every ``with`` lexically enclosing ``node`` within
    its own function."""
    held: set[str] = set()
    ctx = m.ctx
    cur = ctx.parent(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Module)):
        if isinstance(cur, ast.With):
            for item in cur.items:
                key = index.lock_key(m, item.context_expr)
                if key:
                    held.add(key)
        cur = ctx.parent(cur)
    return held


def _held_lock_exprs(index: _Index, m: _Method,
                     node: ast.AST) -> set[str]:
    """Dotted spellings of the enclosing with-locks (the T003 condition-
    idiom exemption compares the wait receiver against these)."""
    held: set[str] = set()
    ctx = m.ctx
    cur = ctx.parent(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                  ast.Module)):
        if isinstance(cur, ast.With):
            for item in cur.items:
                if index.lock_key(m, item.context_expr):
                    d = ctx.dotted(item.context_expr)
                    if d:
                        held.add(d)
        cur = ctx.parent(cur)
    return held


def _finding(ctx: ModuleContext, node: ast.AST, rule: str,
             message: str) -> Finding:
    line = getattr(node, "lineno", 0)
    snippet = (ctx.lines[line - 1].strip()
               if 0 < line <= len(ctx.lines) else "")
    return Finding(rule=rule, path=ctx.relpath, line=line,
                   message=message, hint=THREAD_RULES[rule][1],
                   context=ctx.qualname(node), snippet=snippet)


def _cross_domains(domains: frozenset, owner: str) -> frozenset:
    """Domains that make an access cross-domain: everything beyond the
    family owner and the exempt (quiesced/setup) domains."""
    return frozenset(domains) - {owner} - tm.EXEMPT_DOMAINS


# -- rules -----------------------------------------------------------------


def _base_class(ctx: ModuleContext, m, base) -> str | None:
    """Best-effort class of a write's base expression: ``self`` is the
    enclosing class; anything else resolves its leaf name through
    INSTANCE_HINTS (``self.engine`` -> ContinuousEngine)."""
    if isinstance(base, ast.Name) and base.id == "self":
        return m.cls
    dotted = ctx.dotted(base)
    if dotted:
        return tm.INSTANCE_HINTS.get(dotted.split(".")[-1])
    return None


def _rule_t001(index: _Index, ctx: ModuleContext):
    """Unlocked writes to registered attribute families."""
    for node in ast.walk(ctx.tree):
        for base, attr in _write_targets(node):
            m = index.method_for(node)
            if m is None or m.name in tm.CONSTRUCTION_METHODS:
                continue
            fam = tm.family_for(_base_class(ctx, m, base), attr)
            if fam is None:
                continue
            domains = index.method_domains(m)
            if domains <= tm.EXEMPT_DOMAINS:
                continue
            if fam.lock is not None:
                held = _held_locks(index, m, node)
                if any(k.endswith(f".{fam.lock}") for k in held):
                    continue
                yield _finding(
                    ctx, node, "T001",
                    f"write to {fam.owner_class}.{attr} (owned by "
                    f"{fam.domain!r}) without holding "
                    f"{fam.owner_class}.{fam.lock} — reachable from "
                    f"{{{', '.join(sorted(domains))}}}")
            else:
                cross = _cross_domains(domains, fam.domain)
                if not cross:
                    continue
                yield _finding(
                    ctx, node, "T001",
                    f"write to {fam.owner_class}.{attr} from "
                    f"{{{', '.join(sorted(cross))}}} but the family is "
                    f"{fam.domain!r}-private (no lock declared)")


def _rule_t002(index: _Index, contexts: list[ModuleContext]):
    """Lock-order inversion over the global acquisition graph."""
    # edges: (outer key, inner key) -> first acquisition site
    edges: dict[tuple[str, str], tuple[ModuleContext, ast.AST]] = {}

    def note(outer: str, inner: str, ctx: ModuleContext,
             site: ast.AST) -> None:
        if outer != inner:
            edges.setdefault((outer, inner), (ctx, site))

    for ctx in contexts:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            m = index.method_for(node)
            if m is None:
                continue
            inner_keys = [index.lock_key(m, it.context_expr)
                          for it in node.items]
            inner_keys = [k for k in inner_keys if k]
            if not inner_keys:
                continue
            outer_held = _held_locks(index, m, node)
            for outer in outer_held:
                for inner in inner_keys:
                    note(outer, inner, ctx, node)
            # multiple locks in ONE with statement acquire in item order
            for i, outer in enumerate(inner_keys):
                for inner in inner_keys[i + 1:]:
                    note(outer, inner, ctx, node)
            # calls made while holding these locks acquire the callee's
            # transitive lock set
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = m.ctx.dotted(sub.func)
                if not dotted:
                    continue
                parts = dotted.split(".")
                target = None
                if len(parts) == 2 and parts[0] == "self":
                    target = f"{m.cls}.{parts[1]}"
                elif len(parts) >= 2:
                    hint = tm.INSTANCE_HINTS.get(parts[-2])
                    if hint:
                        target = f"{hint}.{parts[-1]}"
                if target is None:
                    continue
                for inner in index.acquires.get(target, frozenset()):
                    for outer in inner_keys:
                        note(outer, inner, ctx, sub)

    # cycle detection (iterative DFS, deterministic order)
    graph: dict[str, list[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    for a in graph:
        graph[a].sort()
    seen_cycles: set[tuple] = set()
    state: dict[str, int] = {}  # 0 visiting / 1 done

    def dfs(start: str, path: list[str]):
        node = path[-1]
        for nxt in graph.get(node, ()):
            if nxt in path:
                cyc = tuple(path[path.index(nxt):])
                canon = min(tuple(cyc[i:] + cyc[:i])
                            for i in range(len(cyc)))
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    a, b = node, nxt
                    ctx, site = edges[(a, b)]
                    yield ctx, site, cyc + (nxt,)
            elif state.get(nxt) != 1:
                yield from dfs(start, path + [nxt])
        state[node] = 1

    for start in sorted(graph):
        if state.get(start) != 1:
            yield from (
                _finding(ctx, site, "T002",
                         f"lock-order inversion: "
                         f"{' -> '.join(cycle)} forms a cycle")
                for ctx, site, cycle in dfs(start, [start]))


def _rule_t003(index: _Index, ctx: ModuleContext):
    """Blocking calls lexically inside a with-lock body."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        m = index.method_for(node)
        if m is None:
            continue
        held = _held_locks(index, m, node)
        if not held:
            continue
        dotted = m.ctx.dotted(node.func) or ""
        attr = (node.func.attr
                if isinstance(node.func, ast.Attribute) else "")
        blocking = None
        if dotted in _BLOCKING_DOTTED \
                or any(dotted.endswith("." + d.split(".")[-1])
                       and dotted.split(".")[-2:] == d.split(".")[-2:]
                       for d in _BLOCKING_DOTTED):
            blocking = dotted
        elif attr in _BLOCKING_ATTRS:
            blocking = attr
        elif attr == "join":
            base = node.func.value
            base_dotted = m.ctx.dotted(base) or ""
            if isinstance(base, ast.Constant):
                pass  # ", ".join(...) — string join
            elif base_dotted.endswith("path") or ".path." in base_dotted:
                pass  # os.path.join
            else:
                blocking = f"{base_dotted or '<expr>'}.join"
        elif attr == "wait":
            base_dotted = m.ctx.dotted(node.func.value) or ""
            if base_dotted and base_dotted in _held_lock_exprs(
                    index, m, node):
                pass  # cond.wait() under `with cond:` — sanctioned
            else:
                blocking = f"{base_dotted or '<expr>'}.wait"
        if blocking is None:
            continue
        yield _finding(
            ctx, node, "T003",
            f"blocking call {blocking}() while holding "
            f"{{{', '.join(sorted(held))}}}")


def _thread_target_keys(ctx: ModuleContext, m: "_Method | None",
                        expr: ast.AST) -> list[str] | None:
    """Registry keys a Thread target expression can resolve to; None
    when unresolvable. A Name bound by an enclosing ``for x in (a, b)``
    resolves to every element (the server.start idiom)."""
    dotted = ctx.dotted(expr)
    if dotted is not None:
        parts = dotted.split(".")
        if parts[0] == "self" and len(parts) == 2 and m is not None:
            return [f"{m.cls}.{parts[1]}", parts[1]]
        return [dotted, parts[-1]]
    return None


def _rule_t004(index: _Index, ctx: ModuleContext):
    """Thread construction outside the entrypoint registry."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = ctx.dotted(node.func) or ""
        if not (dotted == "threading.Thread"
                or dotted.endswith(".Thread")):
            continue
        m = index.method_for(node)
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and node.args:
            # Thread(group, target, ...) positional form
            target = node.args[1] if len(node.args) > 1 else None
        if target is None:
            yield _finding(ctx, node, "T004",
                           "Thread() without a resolvable target")
            continue
        candidates: list[list[str]] = []
        if isinstance(target, ast.Name):
            # loop-bound target: `for t in (self._a, self._b):` — every
            # element must be registered; a Name with no such binding
            # is a local function spawned by name (pump_requests)
            cur = ctx.parent(node)
            while cur is not None and not isinstance(cur,
                                                     ast.FunctionDef):
                if isinstance(cur, ast.For) \
                        and isinstance(cur.target, ast.Name) \
                        and cur.target.id == target.id \
                        and isinstance(cur.iter, (ast.Tuple, ast.List)):
                    for el in cur.iter.elts:
                        k = _thread_target_keys(ctx, m, el)
                        if k is not None:
                            candidates.append(k)
                    break
                cur = ctx.parent(cur)
            if not candidates:
                candidates.append([target.id])
        else:
            keys = _thread_target_keys(ctx, m, target)
            if keys is not None:
                candidates.append(keys)
        if not candidates:
            yield _finding(ctx, node, "T004",
                           "Thread() target not statically resolvable "
                           "— register it or name it directly")
            continue
        for keys in candidates:
            if not any(k in tm.ENTRYPOINTS for k in keys):
                yield _finding(
                    ctx, node, "T004",
                    f"thread target {keys[0]!r} is not in the "
                    f"entrypoint registry (threadmodel.ENTRYPOINTS)")


def _rule_t005(index: _Index, ctx: ModuleContext):
    """Raw mutable family attrs returned across a domain boundary."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        val = node.value
        if not isinstance(val, ast.Attribute):
            continue
        attr = val.attr
        m = index.method_for(node)
        if m is None or m.name in tm.CONSTRUCTION_METHODS:
            continue
        fam = tm.family_for(_base_class(ctx, m, val.value), attr)
        if fam is None:
            continue
        domains = index.method_domains(m)
        cross = _cross_domains(domains, fam.domain)
        if not cross:
            continue
        yield _finding(
            ctx, node, "T005",
            f"returns {fam.owner_class}.{attr} raw to "
            f"{{{', '.join(sorted(cross))}}} — the {fam.domain!r}-owned "
            f"object escapes its domain")


# -- driver ----------------------------------------------------------------


def thread_scope(relpath: str) -> bool:
    """The checked surface: the host runtime and the observability
    plane (where every thread domain meets)."""
    return any(s in relpath for s in _SCOPES)


def run_threadcheck(files: list[Path], rel_to: Path) -> list[Finding]:
    """Parse, index, and run every T-rule; returns pragma-filtered
    findings sorted by (path, line, rule). Same contract as
    lint.lint_paths, same Finding/baseline machinery."""
    contexts: list[ModuleContext] = []
    findings: list[Finding] = []
    for ctx in iter_module_contexts(files, rel_to):
        if isinstance(ctx, tuple):  # (relpath, read/parse error)
            relpath, err = ctx
            if thread_scope(relpath):
                findings.append(Finding(
                    rule="T000", path=relpath,
                    line=getattr(err, "lineno", None) or 0,
                    message=f"unreadable or unparseable: "
                            f"{type(err).__name__}: {err}",
                    hint=THREAD_RULES["T000"][1],
                    snippet=getattr(err, "text", None) or ""))
            continue
        if thread_scope(ctx.relpath):
            contexts.append(ctx)
    index = _Index(contexts)
    raw: list[Finding] = list(_rule_t002(index, contexts))
    for ctx in contexts:
        raw.extend(_rule_t001(index, ctx))
        raw.extend(_rule_t003(index, ctx))
        raw.extend(_rule_t004(index, ctx))
        raw.extend(_rule_t005(index, ctx))
    ctx_by_path = {c.relpath: c for c in contexts}
    for f in raw:
        ctx = ctx_by_path.get(f.path)
        if ctx is not None:
            allowed = (ctx.pragmas.get(f.line, set())
                       | ctx.pragmas_below.get(f.line, set()))
            if f.rule in allowed:
                continue
        findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
