"""dlint CLI: ``python -m distributed_llama_tpu.analysis`` (tools/dlint.py).

    --lint            AST hazard rules over the package source (default)
    --contracts       jaxpr program-structure contracts (traces on CPU)
    --shardcheck      sharding & HBM-footprint verifier over the support
                      matrix (J004/J005/J006 + budget; tools/shardcheck.py
                      emits the same run as JSON)
    --shardcheck-matrix PATH  JSON support-matrix override for --shardcheck
    --threadcheck     thread-ownership lint over runtime/ + obs/ (T-rules
                      against the analysis/threadmodel.py registry;
                      tools/threadcheck.py is the alias)
    --wirecheck       wire/persistence schema drift lint over runtime/ +
                      obs/ + tools/ (W-rules against the
                      analysis/wiremodel.py registry; tools/wirecheck.py
                      is the dynamic twin — the golden-corpus skew matrix)
    --all             all five heads
    --baseline PATH   grandfathered-findings file
                      (default tools/dlint_baseline.txt)
    --write-baseline  rewrite the baseline from current findings and exit 0
    --threadcheck-baseline PATH  threadcheck's grandfathered findings
                      (default tools/threadcheck_baseline.txt)
    --write-threadcheck-baseline rewrite it from current findings, exit 0
    --wirecheck-baseline PATH  wirecheck's grandfathered findings
                      (default tools/wirecheck_baseline.txt)
    --write-wirecheck-baseline rewrite it from current findings, exit 0
    --no-baseline     report every finding, baselines ignored

Exit status: 0 = no new findings and all contracts/configs hold; 1 =
findings; 2 = usage error. The contract and shardcheck heads force
JAX_PLATFORMS=cpu and an 8-way virtual host mesh BEFORE jax initializes,
so they are safe (and fast) on a box with a TPU attached; the lint,
threadcheck, and wirecheck heads never import the checked code at all.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]
PACKAGE_DIR = Path(__file__).resolve().parents[1]
DEFAULT_BASELINE = REPO_ROOT / "tools" / "dlint_baseline.txt"
DEFAULT_THREAD_BASELINE = REPO_ROOT / "tools" / "threadcheck_baseline.txt"
DEFAULT_WIRE_BASELINE = REPO_ROOT / "tools" / "wirecheck_baseline.txt"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dlint", description="JAX/TPU static analysis: AST hazard "
        "lint + jaxpr contract verifier")
    ap.add_argument("--lint", action="store_true",
                    help="run the AST hazard rules (default)")
    ap.add_argument("--contracts", action="store_true",
                    help="run the jaxpr contracts (imports jax, CPU-only)")
    ap.add_argument("--shardcheck", action="store_true",
                    help="verify sharding + HBM budgets over the support "
                         "matrix (imports jax, CPU-only)")
    ap.add_argument("--shardcheck-matrix", type=Path, default=None,
                    help="JSON support-matrix override for --shardcheck")
    ap.add_argument("--threadcheck", action="store_true",
                    help="run the thread-ownership lint over runtime/ + "
                         "obs/ (pure AST, imports nothing)")
    ap.add_argument("--wirecheck", action="store_true",
                    help="run the wire-schema drift lint over runtime/ + "
                         "obs/ + tools/ (pure AST, imports nothing)")
    ap.add_argument("--all", action="store_true", help="all five heads")
    ap.add_argument("--baseline", type=Path, default=DEFAULT_BASELINE,
                    help=f"baseline file (default {DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current lint findings")
    ap.add_argument("--threadcheck-baseline", type=Path,
                    default=DEFAULT_THREAD_BASELINE,
                    help=f"threadcheck baseline file "
                         f"(default {DEFAULT_THREAD_BASELINE})")
    ap.add_argument("--write-threadcheck-baseline", action="store_true",
                    help="rewrite the threadcheck baseline from current "
                         "findings")
    ap.add_argument("--wirecheck-baseline", type=Path,
                    default=DEFAULT_WIRE_BASELINE,
                    help=f"wirecheck baseline file "
                         f"(default {DEFAULT_WIRE_BASELINE})")
    ap.add_argument("--write-wirecheck-baseline", action="store_true",
                    help="rewrite the wirecheck baseline from current "
                         "findings")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baselines (report everything)")
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files to lint (default: the whole package)")
    args = ap.parse_args(argv)

    # --write-baseline is a lint-head operation: it implies --lint, so
    # `--contracts --write-baseline` can't silently skip the rewrite
    do_lint = (args.lint or args.all or args.write_baseline
               or not (args.contracts or args.shardcheck
                       or args.shardcheck_matrix is not None
                       or args.threadcheck
                       or args.write_threadcheck_baseline
                       or args.wirecheck
                       or args.write_wirecheck_baseline))
    do_contracts = args.contracts or args.all
    # a matrix override implies the head that consumes it (same rule as
    # --write-baseline implying --lint): a forgotten --shardcheck must not
    # silently skip the drift gate the matrix encodes
    do_shardcheck = (args.shardcheck or args.all
                     or args.shardcheck_matrix is not None)
    # same implication rule: rewriting threadcheck's baseline IS running
    # the threadcheck head
    do_threadcheck = (args.threadcheck or args.all
                      or args.write_threadcheck_baseline)
    do_wirecheck = (args.wirecheck or args.all
                    or args.write_wirecheck_baseline)
    if args.write_baseline and args.paths:
        # the baseline is global: rewriting it from a partial scan would
        # silently drop every grandfathered entry for unscanned files
        print("dlint: --write-baseline requires a full-package scan "
              "(no explicit paths)", file=sys.stderr)
        return 2
    status = 0

    if do_lint:
        from .lint import (apply_baseline, lint_paths, load_baseline,
                           package_files, write_baseline)

        if args.paths:
            missing = [p for p in args.paths if not p.exists()]
            if missing:
                print(f"dlint: no such file: {missing[0]}",
                      file=sys.stderr)
                return 2
            # a directory argument means "everything under it"
            files = [f for p in args.paths
                     for f in (package_files(p) if p.is_dir() else [p])]
        else:
            files = package_files(PACKAGE_DIR)
        findings = lint_paths(files, REPO_ROOT)
        if args.write_baseline:
            write_baseline(args.baseline, findings)
            print(f"dlint: baseline rewritten with {len(findings)} "
                  f"finding(s) -> {args.baseline}")
            return 0
        baseline = (load_baseline(args.baseline) if not args.no_baseline
                    else None)
        if baseline is not None:
            new, suppressed, stale = apply_baseline(findings, baseline)
            if args.paths:
                # partial scan: a baseline entry for an unscanned file is
                # not stale, it just wasn't looked at this run
                stale = []
        else:
            new, suppressed, stale = findings, 0, []
        for f in new:
            print(f.render())
        for key in stale:
            print(f"dlint: stale baseline entry (finding fixed — prune "
                  f"with --write-baseline): {key}", file=sys.stderr)
        print(f"dlint: {len(new)} new finding(s), {suppressed} "
              f"baseline-suppressed, {len(files)} file(s)")
        if new:
            status = 1

    if do_threadcheck:
        from .lint import (apply_baseline, load_baseline, package_files,
                           write_baseline)
        from .threadcheck import run_threadcheck, thread_scope

        if args.paths:
            missing = [p for p in args.paths if not p.exists()]
            if missing:
                print(f"threadcheck: no such file: {missing[0]}",
                      file=sys.stderr)
                return 2
            tfiles = [f for p in args.paths
                      for f in (package_files(p) if p.is_dir() else [p])]
        else:
            tfiles = package_files(PACKAGE_DIR)
        if args.write_threadcheck_baseline and args.paths:
            print("threadcheck: --write-threadcheck-baseline requires a "
                  "full-package scan (no explicit paths)",
                  file=sys.stderr)
            return 2
        tfindings = run_threadcheck(tfiles, REPO_ROOT)
        if args.write_threadcheck_baseline:
            write_baseline(args.threadcheck_baseline, tfindings)
            print(f"threadcheck: baseline rewritten with "
                  f"{len(tfindings)} finding(s) -> "
                  f"{args.threadcheck_baseline}")
            return 0
        tbaseline = (load_baseline(args.threadcheck_baseline)
                     if not args.no_baseline else None)
        if tbaseline is not None:
            tnew, tsupp, tstale = apply_baseline(tfindings, tbaseline)
            if args.paths:
                tstale = []  # partial scan: unscanned files aren't stale
        else:
            tnew, tsupp, tstale = tfindings, 0, []
        for f in tnew:
            print(f.render())
        for key in tstale:
            print(f"threadcheck: stale baseline entry (finding fixed — "
                  f"prune with --write-threadcheck-baseline): {key}",
                  file=sys.stderr)
        n_scoped = sum(1 for f in tfiles
                       if thread_scope(f.as_posix()))
        print(f"threadcheck: {len(tnew)} new finding(s), {tsupp} "
              f"baseline-suppressed, {n_scoped} file(s) in scope")
        if tnew:
            status = 1

    if do_wirecheck:
        from .lint import (apply_baseline, load_baseline, package_files,
                           write_baseline)
        from .wirecheck import run_wirecheck, wire_files, wire_scope

        if args.paths:
            missing = [p for p in args.paths if not p.exists()]
            if missing:
                print(f"wirecheck: no such file: {missing[0]}",
                      file=sys.stderr)
                return 2
            wfiles = [f for p in args.paths
                      for f in (package_files(p) if p.is_dir() else [p])]
        else:
            # unlike the other heads, the scan set includes tools/*.py:
            # the fleet scraper and the corpus CLIs consume these
            # formats from outside the package
            wfiles = wire_files(PACKAGE_DIR, REPO_ROOT)
        if args.write_wirecheck_baseline and args.paths:
            print("wirecheck: --write-wirecheck-baseline requires a "
                  "full-package scan (no explicit paths)",
                  file=sys.stderr)
            return 2
        # registry-consistency and site-resolution checks only make
        # sense against the whole tree — a partial scan would report
        # every unscanned site as unresolved
        wfindings = run_wirecheck(wfiles, REPO_ROOT,
                                  full_scan=not args.paths)
        if args.write_wirecheck_baseline:
            write_baseline(args.wirecheck_baseline, wfindings)
            print(f"wirecheck: baseline rewritten with "
                  f"{len(wfindings)} finding(s) -> "
                  f"{args.wirecheck_baseline}")
            return 0
        wbaseline = (load_baseline(args.wirecheck_baseline)
                     if not args.no_baseline else None)
        if wbaseline is not None:
            wnew, wsupp, wstale = apply_baseline(wfindings, wbaseline)
            if args.paths:
                wstale = []  # partial scan: unscanned files aren't stale
        else:
            wnew, wsupp, wstale = wfindings, 0, []
        for f in wnew:
            print(f.render())
        for key in wstale:
            print(f"wirecheck: stale baseline entry (finding fixed — "
                  f"prune with --write-wirecheck-baseline): {key}",
                  file=sys.stderr)
        n_wscoped = sum(1 for f in wfiles
                        if wire_scope(f.as_posix()))
        print(f"wirecheck: {len(wnew)} new finding(s), {wsupp} "
              f"baseline-suppressed, {n_wscoped} file(s) in scope")
        if wnew:
            status = 1

    if do_contracts or do_shardcheck:
        # the traced heads run on a virtual CPU mesh regardless of what
        # hardware is attached. The env vars must land before jax's
        # backend initializes — and an axon sitecustomize sets
        # jax_platforms='axon,cpu' as EXPLICIT config at interpreter
        # start, which overrides the env var (tests/conftest.py fights
        # the same battle), so re-update the config value too.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    if do_contracts:
        from .jaxpr_contracts import run_contracts

        results = run_contracts()
        for r in results:
            mark = "ok " if r.ok else "FAIL"
            print(f"dlint: contract {r.contract} {mark} {r.name}: "
                  f"{r.detail}")
            if not r.ok:
                status = 1

    if do_shardcheck:
        from .memory_model import GIB
        from .shardcheck import load_matrix, run_shardcheck

        matrix = (load_matrix(args.shardcheck_matrix)
                  if args.shardcheck_matrix else None)
        results = run_shardcheck(matrix)
        n_bad = 0
        for r in results:
            if r.ok:
                rep = r.report
                print(f"shardcheck: {r.config} ok "
                      f"{'fits' if rep.fits else 'no-fit (as declared)'}, "
                      f"{rep.total_bytes / GIB:.2f} GiB/chip, headroom "
                      f"{rep.headroom_bytes / GIB:+.2f} GiB")
            else:
                n_bad += 1
                for f in r.findings:
                    print(f.render())
        print(f"shardcheck: {len(results)} config(s), {n_bad} violating")
        if n_bad:
            status = 1

    return status


if __name__ == "__main__":
    sys.exit(main())
