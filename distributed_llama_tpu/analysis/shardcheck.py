"""shardcheck: static sharding & HBM-footprint verifier for the tp grid.

Third analysis head, beside the AST lint (rules.py) and the jaxpr
contracts (jaxpr_contracts.py). For every config in the declared support
matrix — model in {7B, 13B, 70B} x tp in {1,2,4,8} x scheme in
{ref, fused, overlap} x weights in {Q40, F16}, 72 configs — it proves,
statically, on CPU, with zero weight bytes materialized:

  HBM     the per-device footprint (analysis/memory_model.py: weight
          shards, replicated tensors, KV cache at max sequence, traced
          activation peak, collective staging) fits the device budget with
          headroom, and the verdict AGREES with the declared matrix — a
          config that stops fitting fails loudly, and a config that starts
          fitting flags the matrix as stale. Megatron budgets memory this
          way before a job starts; vLLM rejects un-servable configs before
          serving — this is the same gate for our grid, where an OOM or a
          silent full replication on an 8-chip 70B run is the most
          expensive bug class we can hit.
  J004    the traced program's per-operand sharding (shard_map in_names)
          equals parallel/tp.py's declared contract
          (tp.expected_shard_names), and no matmul-weight operand rides
          replicated on a tp>1 mesh (an accidental everywhere-copy /
          all-gather of weight bytes).
  J005    no weight-scale int->f32 materialization outside the registered
          dequant sites (ops/dequant_sites.py) — a rogue dequant is an 8x
          HBM transient the memory model does not account for.
  J006    shapes shard uniformly: ragged head/vocab/block bands would give
          every rank a different program (one compile per rank) — reported
          as findings instead of a mid-load traceback.

Traces ride ``jax.make_jaxpr`` over abstract trees (ShapeDtypeStruct
leaves), so even the 70B grid verifies in seconds. Run under
JAX_PLATFORMS=cpu with an 8-device virtual mesh (the CLI forces it, like
the contract head); ``tools/shardcheck.py`` emits the machine-readable
JSON report that PARITY.md's footprint table is generated from.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path

from ..parallel.comm_stats import SCHEMES
from .memory_model import (GIB, MemoryReport, device_footprint,
                           live_interval_peak, sub_jaxprs)

# avals at or above this many bytes count as "weight-shaped" for the J004
# replication hazard and the J005 rogue-dequant detector (activation
# vectors at decode shapes sit orders of magnitude below it)
WEIGHT_BYTES_THRESHOLD = 1 << 18

MODELS = ("7b", "13b", "70b")
WEIGHT_TYPES = ("q40", "f16")

# The declared support matrix: per (model, weights) x tp, does the config
# fit a v5e chip (16 GiB, 10% headroom reserve)? Derived from the closed-
# form footprint and pinned here so MODEL DRIFT IS LOUD: if the memory
# model (or a spec dim) changes a verdict, shardcheck fails until this
# table is consciously updated. The scheme does not move a verdict (both
# schemes shard every matmul 1/tp; only the ~KB staging term differs).
_EXPECT_FITS = {
    ("7b", "q40"): {1: True, 2: True, 4: True, 8: True},
    ("7b", "f16"): {1: False, 2: True, 4: True, 8: True},
    ("13b", "q40"): {1: True, 2: True, 4: True, 8: True},
    ("13b", "f16"): {1: False, 2: True, 4: True, 8: True},
    ("70b", "q40"): {1: False, 2: False, 4: True, 8: True},
    ("70b", "f16"): {1: False, 2: False, 4: False, 8: False},
}


@dataclasses.dataclass(frozen=True)
class MatrixEntry:
    model: str
    tp: int
    scheme: str
    wtype: str
    expect_fits: bool
    # KV page quantization column (ISSUE 11): 'f32' prices the contiguous
    # max-seq KV stripe (the historical verdicts); 'q8' prices the paged
    # pool at the engine's default page count in the Q80 codes+deltas
    # layout (memory_model.kv_position_bytes) — a SMALLER KV term, so a
    # config can only gain headroom, never lose it, and the declared
    # verdict must still agree (an undeclared/stale q8 verdict fails
    # exactly like the PR 4 stale-matrix case).
    kv_quant: str = "f32"

    @property
    def label(self) -> str:
        base = f"{self.model}-tp{self.tp}-{self.scheme}-{self.wtype}"
        return base if self.kv_quant == "f32" else f"{base}-{self.kv_quant}"


SUPPORT_MATRIX = tuple(
    MatrixEntry(m, tp, s, w, _EXPECT_FITS[(m, w)][tp])
    for m in MODELS for tp in (1, 2, 4, 8)
    for s in SCHEMES for w in WEIGHT_TYPES) + tuple(
    # the q8 KV-quant column: the serving codec (q40 weights) across the
    # tp grid under the fused scheme (KV pricing is scheme-invariant;
    # one scheme keeps the matrix's trace cost flat). q8 KV only SHRINKS
    # the footprint, and none of the q40 verdicts sits within one KV
    # stripe of its budget edge, so the verdict column matches f32 —
    # pinned here so a memory-model edit that flips one fails loudly.
    MatrixEntry(m, tp, "fused", "q40", _EXPECT_FITS[(m, "q40")][tp],
                kv_quant="q8")
    for m in MODELS for tp in (1, 2, 4, 8))


@dataclasses.dataclass(frozen=True)
class ShardFinding:
    rule: str     # J004 | J005 | J006 | HBM-BUDGET | KV-PAGED | TRACE
    config: str
    detail: str

    def render(self) -> str:
        return f"shardcheck: {self.config} FAIL {self.rule}: {self.detail}"


@dataclasses.dataclass(frozen=True)
class ConfigResult:
    config: str
    expect_fits: bool | None
    report: MemoryReport | None
    findings: tuple
    kv_quant: str = "f32"  # the matrix entry's KV-quant column, verbatim

    @property
    def ok(self) -> bool:
        return not self.findings


def model_spec(model: str, wtype: str):
    from ..models import synth
    from ..ops.quants import FloatType

    factory = {"7b": synth.llama2_7b_spec, "13b": synth.llama2_13b_spec,
               "70b": synth.llama2_70b_spec}[model]
    ft = {"q40": FloatType.Q40, "f16": FloatType.F16,
          "f32": FloatType.F32}[wtype]
    return factory(weights_float_type=ft)


def abstract_model_params(spec):
    """The param tree as avals for the spec's weights_float_type — Q40
    leaves as codec-layout (qs, d16) pairs, dense leaves as f16/f32. Built
    under eval_shape, so nothing is materialized at any scale."""
    import jax
    import jax.numpy as jnp

    from ..io.loader import Q40Weight
    from ..models.synth import _build_tree
    from ..ops.quants import QK, FloatType

    ft = spec.weights_float_type

    def t(*shape):
        return jnp.zeros(shape, jnp.float32)

    def mm(*shape):
        if ft == FloatType.Q40:
            *lead, d, n = shape
            return Q40Weight(jnp.zeros((*lead, d, n // QK, 16), jnp.uint8),
                             jnp.zeros((*lead, d, n // QK), jnp.float16))
        dt = jnp.float16 if ft == FloatType.F16 else jnp.float32
        return jnp.zeros(shape, dt)

    return jax.eval_shape(lambda: _build_tree(spec, t, mm))


# -- tracing ----------------------------------------------------------------


def _find_shard_map(jaxpr):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "shard_map":
            return eqn
        for sub in sub_jaxprs(eqn):  # incl. tuple-valued cond branches
            found = _find_shard_map(sub)
            if found is not None:
                return found
    return None


def trace_tp_forward(spec, tp: int, scheme: str, forward_builder=None):
    """make_jaxpr the real tp entry point (or a test-supplied builder of
    the same signature) over abstract params/cache/token avals. Returns
    (closed_jaxpr, abstract_params_tree)."""
    import jax
    import jax.numpy as jnp

    from ..models.llama import init_cache
    from ..parallel import make_mesh, make_sharded_forward

    if len(jax.devices()) < tp:
        raise RuntimeError(
            f"needs {tp} devices, have {len(jax.devices())} — set "
            f"--xla_force_host_platform_device_count (the CLI does)")
    mesh = make_mesh(tp=tp, devices=jax.devices()[:tp])
    builder = forward_builder or make_sharded_forward
    fwd = builder(spec, mesh, scheme)
    params = abstract_model_params(spec)
    cache = jax.eval_shape(lambda: init_cache(spec, jnp.float32))
    tokens = jax.ShapeDtypeStruct((1,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    closed = jax.make_jaxpr(fwd)(params, cache, tokens, pos)
    return closed, params


def mutant_replicated_forward(replicate=("wcls",)):
    """A forward builder that OVERRIDES the named weights' partition spec
    to fully replicated — the seeded J004 fixture (guards the checker
    against rot; tests/test_shardcheck_repo.py). Only weights whose
    replication is shape-silent downstream (e.g. wcls: the widened logits
    gather has no later consumer) stay traceable."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel import tp as tp_mod
    from ..utils.compat import shard_map as _shard_map

    def build(spec, mesh, scheme):
        n_slices = mesh.shape["tp"]
        local_step = tp_mod.make_local_step(spec, n_slices, 1, scheme=scheme)

        def wrap(params, cache, tokens, pos):
            specs = tp_mod.param_specs(params, scheme)
            for name in replicate:
                specs[name] = P()  # fully replicated: the seeded hazard
            in_specs = (specs, tp_mod.CACHE_SPEC, P(), P())
            fn = _shard_map(local_step, mesh=mesh, in_specs=in_specs,
                            out_specs=(P(), tp_mod.CACHE_SPEC))
            return fn(params, cache, tokens, pos)

        return jax.jit(wrap, donate_argnums=1)

    build.replicated = tuple(replicate)
    return build


# -- the contract checks ----------------------------------------------------


def _user_frames(eqn):
    from jax._src import source_info_util

    return list(source_info_util.user_frames(eqn.source_info))


def _dequant_site_filter():
    from ..ops.dequant_sites import frames_allowed

    def allowed(eqn) -> bool:
        try:
            return frames_allowed(_user_frames(eqn))
        except Exception:  # noqa: BLE001 - source info is best-effort
            return False

    return allowed


def check_traced_sharding(closed_jaxpr, params, scheme: str, tp: int,
                          config: str, expected=None) -> list[ShardFinding]:
    """J004: shard_map's recorded in_names vs tp.expected_shard_names, plus
    the replication hazard — a matmul-weight operand with no 'tp' axis on a
    tp>1 mesh is an everywhere-copy the memory model never budgeted.
    ``expected`` overrides the declared rows (mutation self-tests)."""
    from ..parallel import tp as tp_mod

    sm = _find_shard_map(closed_jaxpr.jaxpr)
    if sm is None:
        return [ShardFinding("J004", config,
                             "no shard_map eqn in the traced forward — "
                             "jaxpr structure changed?")]
    rows = expected if expected is not None else \
        tp_mod.expected_shard_names(params, scheme)
    in_names = sm.params["in_names"]
    if len(in_names) < len(rows):
        return [ShardFinding("J004", config,
                             f"{len(in_names)} traced operands < "
                             f"{len(rows)} declared leaves")]
    tail_names = in_names[-len(rows):]
    tail_vars = sm.invars[-len(rows):]
    matmul_keys = tp_mod.LAYER_KEYS[2:] + ("wcls",)  # wq..w3 + classifier
    findings = []
    # operands BEFORE the declared leaves are consts jax hoisted out of the
    # body (closed-over values). They carry no declared spec and ride
    # replicated — fine for iota/rope tables, but a weight-sized hoisted
    # const is the silent-full-replication hazard J004 exists to catch
    n_consts = len(in_names) - len(rows)
    for var, names in zip(sm.invars[:n_consts], in_names[:n_consts]):
        aval = getattr(var, "aval", None)
        if aval is None or any("tp" in ax for ax in dict(names).values()):
            continue
        if tp > 1 and aval.size * aval.dtype.itemsize \
                >= WEIGHT_BYTES_THRESHOLD:
            findings.append(ShardFinding(
                "J004", config,
                f"const hoisted into shard_map: weight-shaped closed-over "
                f"value ({tuple(aval.shape)} {aval.dtype}) is REPLICATED "
                f"on a tp={tp} mesh — pass it through the params tree with "
                f"a partition spec"))
    for (name, want), got, var in zip(rows, tail_names, tail_vars):
        got = {int(k): tuple(v) for k, v in dict(got).items()}
        want = {int(k): tuple(v) for k, v in want.items()}
        if got != want:
            findings.append(ShardFinding(
                "J004", config,
                f"{name}: traced sharding {got} != declared {want} "
                f"(tp.py param_specs drifted from the program)"))
            continue
        is_matmul = any(f"'{k}'" in name for k in matmul_keys)
        aval = getattr(var, "aval", None)
        big = aval is not None and aval.size * aval.dtype.itemsize \
            >= WEIGHT_BYTES_THRESHOLD
        sharded_over_tp = any("tp" in axes for axes in got.values())
        if tp > 1 and is_matmul and big and not sharded_over_tp:
            findings.append(ShardFinding(
                "J004", config,
                f"{name}: weight-shaped operand "
                f"({tuple(aval.shape)} {aval.dtype}) is REPLICATED on a "
                f"tp={tp} mesh — every chip pays full bytes (accidental "
                f"all-gather)"))
    return findings


def check_dequant_sites(closed_jaxpr, config: str,
                        threshold: int = WEIGHT_BYTES_THRESHOLD
                        ) -> list[ShardFinding]:
    """J005: every weight-scale int->float materialization must descend
    from a registered dequant site (ops/dequant_sites.py)."""
    from ..ops.dequant_sites import frames_allowed
    from .jaxpr_contracts import walk_eqns

    int_names = {"uint8", "int8", "int4", "uint4"}
    findings = []
    for eqn in walk_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        iv, ov = eqn.invars[0].aval, eqn.outvars[0].aval
        if ov.dtype.name not in ("float32", "bfloat16"):
            continue
        if iv.dtype.name not in int_names:
            continue
        if ov.size * ov.dtype.itemsize < threshold:
            continue
        try:
            frames = _user_frames(eqn)
        except Exception:  # noqa: BLE001 - no source info, cannot attribute
            frames = []
        if frames_allowed(frames):
            continue
        where = (f"{frames[0].file_name.rsplit('/', 1)[-1]}:"
                 f"{frames[0].function_name}" if frames else "<unknown>")
        findings.append(ShardFinding(
            "J005", config,
            f"{tuple(iv.shape)} {iv.dtype} -> {ov.dtype} materialization "
            f"at {where}, outside the registered dequant sites "
            f"(ops/dequant_sites.py)"))
    return findings


def check_uniform_shards(spec, tp: int, scheme: str,
                         config: str) -> list[ShardFinding]:
    """J006: ragged shards force per-rank shapes, hence one compile per
    rank — the same constraints parallel/tp.validate_sharding raises on,
    reported as findings plus the Q40-block granularity of the fused
    scheme's input-sharded wo/w2."""
    from ..ops.quants import QK, FloatType

    findings = []

    def ragged(value, what):
        findings.append(ShardFinding(
            "J006", config,
            f"{what}={value} does not divide over tp={tp}: ranks get "
            f"ragged shards (distinct shapes => one compile per rank)"))

    for value, what in ((spec.n_heads, "n_heads"),
                        (spec.n_kv_heads, "n_kv_heads"),
                        (spec.hidden_dim, "hidden_dim"),
                        (spec.vocab_size, "vocab_size")):
        if value % tp:
            ragged(value, what)
    if scheme in ("fused", "overlap") \
            and spec.weights_float_type == FloatType.Q40:
        for value, what in ((spec.dim, "dim"),
                            (spec.hidden_dim, "hidden_dim")):
            if tp > 1 and value % tp == 0 and (value // tp) % QK:
                findings.append(ShardFinding(
                    "J006", config,
                    f"{scheme} scheme shards {what}={value} along the Q40 "
                    f"input-block axis: {value}/{tp} must be a "
                    f"{QK}-multiple"))
    if scheme == "overlap" and tp > 1 and spec.dim % tp:
        findings.append(ShardFinding(
            "J006", config,
            f"overlap scheme ring-chunks the residual width: "
            f"dim={spec.dim} does not divide over tp={tp}"))
    if spec.buffer_float_type == FloatType.Q80:
        for value, what in ((spec.dim, "dim"), (spec.hidden_dim,
                                                "hidden_dim")):
            if value % tp == 0 and (value // tp) % QK:
                findings.append(ShardFinding(
                    "J006", config,
                    f"Q80 buffers need {what}/tp to be a {QK}-multiple, "
                    f"got {value}/{tp}"))
    return findings


def check_kv_quant_pricing(spec, tp: int, config: str) -> list[ShardFinding]:
    """KV-QUANT: the q8 page-byte formula must price the Q80 wire layout
    EXACTLY — per position, kv_dim int8 codes + one f16 delta per 32-value
    block of the flattened shard-local row (34 bytes per 32 values) — and
    the equal-HBM page multiplier vs f32 must clear 2x (it is 32*4/34 ≈
    3.76x at f32 pages; the acceptance floor is the ~2x capacity claim).
    Recomputed here from first principles so a memory_model edit cannot
    silently drift the capacity math the engine and bench rely on."""
    from ..ops.quants import QK
    from .memory_model import (DEFAULT_PAGE_SIZE, default_kv_pages,
                               equal_hbm_kv_pages, kv_position_bytes)

    findings = []
    kv_loc = (spec.n_kv_heads // tp) * spec.head_size
    if kv_loc % QK:
        findings.append(ShardFinding(
            "KV-QUANT", config,
            f"shard-local kv width {kv_loc} does not divide into "
            f"{QK}-value Q80 blocks — q8 KV pages cannot run this config"))
        return findings
    want = 2 * spec.n_layers * (kv_loc + 2 * (kv_loc // QK))
    got = kv_position_bytes(spec, tp, kv_quant="q8")
    if got != want:
        findings.append(ShardFinding(
            "KV-QUANT", config,
            f"q8 position bytes {got} != {want} (Q80 codes+deltas) — the "
            f"memory_model q8 formula drifted from the wire layout"))
    pages = default_kv_pages(spec, 1, DEFAULT_PAGE_SIZE)
    q8_pages = equal_hbm_kv_pages(spec, tp, pages, DEFAULT_PAGE_SIZE)
    if q8_pages < 2 * pages:
        findings.append(ShardFinding(
            "KV-QUANT", config,
            f"equal-HBM q8 pool holds {q8_pages} pages for {pages} f32 "
            f"pages — below the 2x capacity floor the q8 column claims"))
    return findings


def check_paged_equivalence(spec, tp: int, config: str,
                            contiguous_bytes: int) -> list[ShardFinding]:
    """KV-PAGED: the paged pool at the engine's default sizing (one slot's
    worth of pages, scrap excluded) must charge EXACTLY the bytes of the
    contiguous max-seq stripe — the invariant that lets the support
    matrix's HBM verdicts carry over to paged engines unchanged, and that
    the --kv-pages oversubscription math rests on. Checked across the
    whole matrix so a drifting page-size default or a pool formula edit
    fails loudly (tests/test_shardcheck_repo.py mutation-tests it)."""
    from .memory_model import (DEFAULT_PAGE_SIZE, default_kv_pages,
                               kv_page_pool_bytes)

    findings = []
    ps = DEFAULT_PAGE_SIZE
    if spec.seq_len % ps:
        findings.append(ShardFinding(
            "KV-PAGED", config,
            f"seq_len={spec.seq_len} is not a multiple of the default "
            f"page size {ps} — paged engines cannot run this config"))
        return findings
    paged = kv_page_pool_bytes(spec, tp, default_kv_pages(spec, 1, ps), ps,
                               include_scrap=False)
    if paged != contiguous_bytes:
        findings.append(ShardFinding(
            "KV-PAGED", config,
            f"paged pool at default sizing charges {paged} B but the "
            f"contiguous stripe charges {contiguous_bytes} B — the "
            f"memory_model formulas drifted apart"))
    return findings


def check_tier_staging(spec, tp: int, config: str, report,
                       kv_quant: str, expect_fits: bool) -> list:
    """KV-TIER: price the tiering promotion staging buffer (the 2-page
    double-buffered upload target a tiered engine keeps device-side,
    ISSUE 12) in device_footprint and require that it (a) follows the
    page-byte formula exactly and (b) fits inside a fitting config's
    declared headroom — turning on --kv-host-pages/--kv-disk-dir must
    never flip a support-matrix verdict."""
    from .memory_model import (DEFAULT_PAGE_SIZE, device_footprint,
                               kv_page_bytes)

    findings = []
    staged = device_footprint(spec, tp, report.scheme, model=report.model,
                              kv_page_size=DEFAULT_PAGE_SIZE,
                              kv_quant=kv_quant, tier_staging_pages=2)
    want = 2 * kv_page_bytes(spec, tp, DEFAULT_PAGE_SIZE,
                             kv_quant=kv_quant)
    if staged.tier_staging_bytes != want:
        findings.append(ShardFinding(
            "KV-TIER", config,
            f"tier staging priced {staged.tier_staging_bytes} B != "
            f"{want} B (2 pages at the pool byte rate) — the "
            f"memory_model staging formula drifted"))
    if expect_fits and report.fits and not staged.fits:
        findings.append(ShardFinding(
            "KV-TIER", config,
            f"the 2-page tiering staging buffer "
            f"({staged.tier_staging_bytes / GIB:.3f} GiB) pushes this "
            f"fitting config over budget — tiering cannot be enabled "
            f"on it; shrink the page size or update the matrix"))
    return findings


def check_mixed_budget(spec, tp: int, config: str, report,
                       kv_quant: str, expect_fits: bool,
                       budget: int = 16) -> list:
    """MIXED-HBM: price the token-budget mixed dispatch (ISSUE 18) in
    device_footprint and require that (a) the activation/staging width
    follows the same t_len shape math as the K-query verify dispatch
    (pricing spec_k=budget and mixed_budget=budget must agree exactly —
    one formula, two knobs) and (b) a fitting config still fits with the
    default budget window enabled — turning on --dispatch-tokens must
    never flip a support-matrix verdict. Weights and KV are unchanged by
    construction; only the per-dispatch activation rows widen."""
    from .memory_model import DEFAULT_PAGE_SIZE, device_footprint

    findings = []
    mixed = device_footprint(spec, tp, report.scheme, model=report.model,
                             kv_page_size=DEFAULT_PAGE_SIZE,
                             kv_quant=kv_quant, mixed_budget=budget)
    twin = device_footprint(spec, tp, report.scheme, model=report.model,
                            kv_page_size=DEFAULT_PAGE_SIZE,
                            kv_quant=kv_quant, spec_k=budget)
    if mixed.total_bytes != twin.total_bytes:
        findings.append(ShardFinding(
            "MIXED-HBM", config,
            f"mixed_budget={budget} prices {mixed.total_bytes} B but "
            f"spec_k={budget} prices {twin.total_bytes} B — the two "
            f"t_len knobs drifted apart in memory_model"))
    if expect_fits and report.fits and not mixed.fits:
        findings.append(ShardFinding(
            "MIXED-HBM", config,
            f"the {budget}-token mixed dispatch window "
            f"({mixed.total_bytes / GIB:.3f} GiB) pushes this fitting "
            f"config over budget — --dispatch-tokens cannot be enabled "
            f"on it; shrink the budget or update the matrix"))
    return findings


# -- per-config driver ------------------------------------------------------


def check_config(entry: MatrixEntry, device: str = "v5e",
                 forward_builder=None, spec=None) -> ConfigResult:
    """Run every check for one matrix entry. Trace failures become TRACE
    findings (the CLI reports them and fails), not crashes. ``spec``
    overrides the model lookup (synth-model mutation self-tests)."""
    spec = spec if spec is not None else model_spec(entry.model, entry.wtype)
    config = entry.label
    kv_quant = getattr(entry, "kv_quant", "f32")
    if kv_quant not in ("f32", "q8"):
        return ConfigResult(config, entry.expect_fits, None, (ShardFinding(
            "KV-QUANT", config,
            f"unknown kv_quant {kv_quant!r} (expected f32|q8) — the "
            f"matrix declares a column the memory model cannot price"),
        ), kv_quant=kv_quant)
    findings = check_uniform_shards(spec, entry.tp, entry.scheme, config)
    act_bytes = None
    if not findings and kv_quant == "q8":
        # the q8 column prices KV only: its (spec, tp, scheme, wtype)
        # twin in the f32 matrix already traced this exact forward
        # (J004/J005 and the activation peak are kv-quant-invariant —
        # the trace carries no KV-quant dimension), so re-tracing 12
        # identical programs would just slow every --all run. The
        # footprint uses the analytic activation bound, which lands
        # within a few MB of the traced peak at decode shapes
        # (memory_model.activation_bytes_analytic).
        pass
    elif not findings:
        try:
            closed, params = trace_tp_forward(spec, entry.tp, entry.scheme,
                                              forward_builder)
            sm = _find_shard_map(closed.jaxpr)
            if sm is not None:
                act_bytes = live_interval_peak(
                    sm.params["jaxpr"], exclude_eqn=_dequant_site_filter())
            findings += check_traced_sharding(closed, params, entry.scheme,
                                              entry.tp, config)
            findings += check_dequant_sites(closed, config)
        except ValueError as e:
            # validate_sharding raises on the same ragged shapes J006
            # models — surface under the contract id, not as a crash
            findings.append(ShardFinding("J006", config,
                                         f"trace rejected the config: {e}"))
        except Exception as e:  # noqa: BLE001 - report, don't crash the run
            findings.append(ShardFinding(
                "TRACE", config, f"raised {type(e).__name__}: {e}"))
    if kv_quant == "q8":
        # the q8 column prices the paged pool at the ENGINE default page
        # count in the Q80 layout; the pricing check pins the formula and
        # the 2x equal-HBM capacity floor
        from .memory_model import DEFAULT_PAGE_SIZE

        report = device_footprint(spec, entry.tp, entry.scheme,
                                  model=entry.model,
                                  activation_bytes=act_bytes,
                                  device=device,
                                  kv_page_size=DEFAULT_PAGE_SIZE,
                                  kv_quant="q8")
        findings += check_kv_quant_pricing(spec, entry.tp, config)
    else:
        report = device_footprint(spec, entry.tp, entry.scheme,
                                  model=entry.model,
                                  activation_bytes=act_bytes,
                                  device=device)
        findings += check_paged_equivalence(spec, entry.tp, config,
                                            report.kv_cache_bytes)
    from .memory_model import DEFAULT_PAGE_SIZE

    if spec.seq_len % DEFAULT_PAGE_SIZE == 0:
        findings += check_tier_staging(spec, entry.tp, config, report,
                                       kv_quant, entry.expect_fits)
        findings += check_mixed_budget(spec, entry.tp, config, report,
                                       kv_quant, entry.expect_fits)
    if report.fits != entry.expect_fits:
        if entry.expect_fits:
            findings.append(ShardFinding(
                "HBM-BUDGET", config,
                f"declared to fit but total "
                f"{report.total_bytes / GIB:.2f} GiB exceeds the "
                f"{report.budget_bytes / GIB:.2f} GiB usable budget by "
                f"{-report.headroom_bytes / GIB:.2f} GiB"))
        else:
            findings.append(ShardFinding(
                "HBM-BUDGET", config,
                f"declared NOT to fit but total "
                f"{report.total_bytes / GIB:.2f} GiB now leaves "
                f"{report.headroom_bytes / GIB:.2f} GiB headroom — "
                f"update the support matrix"))
    return ConfigResult(config, entry.expect_fits, report, tuple(findings),
                        kv_quant=kv_quant)


def run_shardcheck(matrix=None, device: str = "v5e") -> list[ConfigResult]:
    return [check_config(e, device=device)
            for e in (matrix if matrix is not None else SUPPORT_MATRIX)]


def load_matrix(path) -> tuple[MatrixEntry, ...]:
    """A JSON support matrix override: a list of {model, tp, scheme,
    wtype, expect_fits} objects (tools/shardcheck --matrix; also the
    seeded-violation path of the CLI tests)."""
    raw = json.loads(Path(path).read_text(encoding="utf-8"))
    return tuple(MatrixEntry(e["model"], int(e["tp"]), e["scheme"],
                             e["wtype"], bool(e["expect_fits"]),
                             kv_quant=e.get("kv_quant", "f32"))
                 for e in raw)


def report_json(results: list[ConfigResult], device: str = "v5e") -> dict:
    """The machine-readable memory report (tools/shardcheck emits this;
    PARITY.md's footprint table is generated from it)."""
    return {
        "device": device,
        "n_configs": len(results),
        "n_violations": sum(not r.ok for r in results),
        "configs": [{
            "config": r.config,
            "kv_quant": r.kv_quant,
            "expect_fits": r.expect_fits,
            "ok": r.ok,
            "findings": [{"rule": f.rule, "detail": f.detail}
                         for f in r.findings],
            "report": r.report.as_json() if r.report else None,
        } for r in results],
    }
