"""dlint hazard rules D001–D005.

Each rule is a callable ``rule(ctx: ModuleContext) -> Iterator[Finding]``
with ``rule_id``/``title``/``hint`` attributes and an optional ``scope``
(repo-path substrings the rule is restricted to). They encode the hazard
classes that cost this repo real benchmark regressions in earlier rounds —
the reference C++ program shows its sync points and transfer sizes in the
source, while tracing hides ours; these rules make the same classes visible
at lint time:

  D001  implicit device->host sync in a hot-path module
  D002  jax.jit retrace traps (static_argnames drift / non-static literals)
  D003  jitted function closing over mutable module/instance state
  D004  per-step list-comp feeding jnp.asarray in the decode step
  D005  time.time() deltas around device work without block_until_ready
  D006  tp collective issued outside parallel/tp.py's _ici_* helpers
  D007  implicit dtype promotion: a bf16/f16 value mixed with an explicit
        f32 operand silently upcasts the whole expression
  D008  monotonic/perf_counter delta around device work with neither a
        sync nor a span — invisible to the timeline, measures dispatch

False-positive policy: rules stay *narrow* (better to miss a hazard than to
train people to pragma reflexively); intentional sites carry
``# dlint: allow[Dnnn] reason`` pragmas and pre-existing debt lives in
``tools/dlint_baseline.txt``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .lint import Finding, ModuleContext

HOT_PATH_SCOPE = ("runtime/", "ops/", "parallel/")

# call targets (post alias-resolution) that force a device->host transfer
_SYNC_CALLS = {
    "numpy.asarray": "np.asarray on a device value blocks on the transfer",
    "jax.device_get": "device_get is an explicit device->host sync",
    "jax.block_until_ready": "block_until_ready drains the device queue",
}
# numpy.asarray over these argument forms is host-side staging, not a sync
_HOST_LITERALS = (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.Constant,
                  ast.ListComp, ast.GeneratorExp, ast.DictComp, ast.SetComp)


def _finding(ctx: ModuleContext, node: ast.AST, rule_id: str, message: str,
             hint: str) -> Finding:
    line = getattr(node, "lineno", 0)
    snippet = (ctx.lines[line - 1].strip()
               if 0 < line <= len(ctx.lines) else "")
    return Finding(rule=rule_id, path=ctx.relpath, line=line,
                   message=message, hint=hint, context=ctx.qualname(node),
                   snippet=snippet)


def rule(rule_id: str, title: str, hint: str, scope=None):
    def deco(fn):
        fn.rule_id, fn.title, fn.hint, fn.scope = rule_id, title, hint, scope
        return fn
    return deco


@rule("D001", "implicit device->host sync in hot-path module",
      "keep the hot path async; if the sync is intentional, annotate it "
      "with `# dlint: allow[D001] <reason>`",
      scope=HOT_PATH_SCOPE)
def d001_implicit_sync(ctx: ModuleContext) -> Iterator[Finding]:
    """np.asarray / .item() / device_get / block_until_ready — and
    float()/int()/bool() wrapped directly around a jnp/jax call result —
    inside runtime/, ops/, or parallel/. Every one of these blocks the
    Python thread on the device stream; in the decode loop that turns an
    async dispatch pipeline into lock-step round-trips."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        target = ctx.call_target(node)
        if target in _SYNC_CALLS:
            if (target == "numpy.asarray" and node.args
                    and isinstance(node.args[0], _HOST_LITERALS)):
                continue  # host literal in, host array out — no device sync
            yield _finding(ctx, node, "D001",
                           f"implicit device->host sync: {_SYNC_CALLS[target]}",
                           d001_implicit_sync.hint)
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "item" and not node.args
              and not node.keywords):
            yield _finding(ctx, node, "D001",
                           ".item() forces a device->host sync",
                           d001_implicit_sync.hint)
        elif (isinstance(node.func, ast.Name)
              and node.func.id in ("float", "int", "bool")
              and len(node.args) == 1 and isinstance(node.args[0], ast.Call)):
            inner = ctx.call_target(node.args[0])
            if inner and inner.split(".", 2)[0] in ("jax", "jnp") or (
                    inner and inner.startswith("jax.numpy.")):
                yield _finding(
                    ctx, node, "D001",
                    f"{node.func.id}() on a jax value syncs the device",
                    d001_implicit_sync.hint)


def _def_param_names(fn: ast.AST) -> tuple[set[str], bool, list[str]]:
    """(named params, has **kwargs, positional order) of a def/lambda."""
    a = fn.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    names = set(positional) | {p.arg for p in a.kwonlyargs}
    return names, a.kwarg is not None, positional


@rule("D002", "jax.jit retrace trap",
      "declare compile-time parameters in static_argnames (and only "
      "parameters that exist)")
def d002_retrace_trap(ctx: ModuleContext) -> Iterator[Finding]:
    """Two traps around jit static arguments:

    (a) ``static_argnames`` naming a parameter the function doesn't have —
        dead weight at best, and it silently stops being static when the
        real parameter is renamed;
    (b) a call into a module-local jitted function passing a str/bool
        literal to a parameter NOT in static_argnames — strings fail at
        trace time, and branch-y bools retrace per value.
    """
    for def_node, (site, static) in ctx.jitted_defs.items():
        if isinstance(def_node, ast.Lambda):
            continue
        names, has_kwargs, _ = _def_param_names(def_node)
        if has_kwargs:
            continue
        for s in sorted(static - names):
            yield _finding(
                ctx, site, "D002",
                f"static_argnames names '{s}' but "
                f"{def_node.name}() has no such parameter",
                "static_argnames must match the signature")

    # (b): literal str/bool flowing into a jitted callable, non-static
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func,
                                                            ast.Name):
            continue
        callee = ctx.jitted_names.get(node.func.id)
        if node.func.id not in ctx.jitted_names or callee is None:
            continue
        static = ctx.jit_static.get(callee, set())
        names, has_kwargs, positional = _def_param_names(callee)
        if has_kwargs:
            continue

        def literal(expr):
            return (isinstance(expr, ast.JoinedStr)
                    or (isinstance(expr, ast.Constant)
                        and isinstance(expr.value, (str, bool))))

        for i, arg in enumerate(node.args):
            if literal(arg) and i < len(positional) \
                    and positional[i] not in static:
                yield _finding(
                    ctx, node, "D002",
                    f"literal {ast.dump(arg)[:40]} passed to traced "
                    f"parameter '{positional[i]}' of jitted "
                    f"{node.func.id}()", d002_retrace_trap.hint)
        for kw in node.keywords:
            if kw.arg and literal(kw.value) and kw.arg in names \
                    and kw.arg not in static:
                yield _finding(
                    ctx, node, "D002",
                    f"literal passed to traced parameter '{kw.arg}' of "
                    f"jitted {node.func.id}()", d002_retrace_trap.hint)


def _mutable_globals(ctx: ModuleContext) -> set[str]:
    """Module-level names bound to a mutable display ({} / [] / set())."""
    out: set[str] = set()
    for stmt in ctx.tree.body:
        if isinstance(stmt, ast.Assign):
            if isinstance(stmt.value, (ast.Dict, ast.List, ast.Set,
                                       ast.DictComp, ast.ListComp,
                                       ast.SetComp)):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


@rule("D003", "jitted function closes over mutable state",
      "pass the value as an argument (traced or static) — closures are "
      "baked in at trace time and silently go stale")
def d003_jit_closure(ctx: ModuleContext) -> Iterator[Finding]:
    """A jitted function reading ``self.attr`` or a mutable module global
    captures whatever the value was at FIRST trace; later mutations are
    invisible (or worse, trigger surprise retraces via weak refs)."""
    mutable = _mutable_globals(ctx)
    for def_node in ctx.jitted_defs:
        params, _, _ = _def_param_names(def_node)
        # one dedup namespace per kind: `self.cache` and a module global
        # `cache` are distinct hazards and must both be reported
        seen: set[tuple[str, str]] = set()
        for node in ast.walk(def_node):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self" and "self" not in params
                    and ("attr", node.attr) not in seen):
                seen.add(("attr", node.attr))
                yield _finding(
                    ctx, node, "D003",
                    f"jitted function reads self.{node.attr} from its "
                    f"closure", d003_jit_closure.hint)
            elif (isinstance(node, ast.Name) and node.id in mutable
                  and isinstance(node.ctx, ast.Load)
                  and node.id not in params
                  and ("global", node.id) not in seen):
                seen.add(("global", node.id))
                yield _finding(
                    ctx, node, "D003",
                    f"jitted function reads mutable module global "
                    f"'{node.id}'", d003_jit_closure.hint)


@rule("D004", "per-step host list materialization in the decode step",
      "stage rows into one persistent numpy buffer and upload it in a "
      "single jnp.asarray call",
      scope=("runtime/",))
def d004_hot_loop_alloc(ctx: ModuleContext) -> Iterator[Finding]:
    """``jnp.asarray([f(s) for s in pool])`` in a per-step function builds
    B boxed Python objects + one fresh host array + one tiny transfer PER
    LIST — per decode step. Fires inside functions named step*/\\_step* and
    inside explicit loops in runtime/ modules; the fix is one pre-allocated
    staging buffer and one upload."""
    asarray_targets = ("jax.numpy.asarray", "jax.numpy.array")

    def in_step_fn(node):
        fn = ctx.enclosing_function(node)
        return (fn is not None and isinstance(fn, (ast.FunctionDef,
                                                   ast.AsyncFunctionDef))
                and fn.name.lstrip("_").startswith("step"))

    # names bound to list comprehensions inside step functions, so
    # `x = [..]; jnp.asarray(x)` is caught too
    comp_names: set[tuple[ast.AST, str]] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Assign) and in_step_fn(node)
                and isinstance(node.value, (ast.ListComp, ast.List))):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    comp_names.add((ctx.enclosing_function(node), t.id))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if ctx.call_target(node) not in asarray_targets or not node.args:
            continue
        if not (in_step_fn(node) or ctx.in_loop(node)):
            continue
        arg = node.args[0]
        is_comp = isinstance(arg, (ast.ListComp, ast.List, ast.GeneratorExp))
        is_comp_name = (isinstance(arg, ast.Name)
                        and (ctx.enclosing_function(node),
                             arg.id) in comp_names)
        if is_comp or is_comp_name:
            yield _finding(
                ctx, node, "D004",
                "per-step list materialized into jnp.asarray",
                d004_hot_loop_alloc.hint)


@rule("D005", "time.time() delta around device work",
      "use time.perf_counter() and block_until_ready() so the interval "
      "measures device work, not dispatch")
def d005_bare_time(ctx: ModuleContext) -> Iterator[Finding]:
    """A ``time.time()`` delta in a function that dispatches jax work but
    never calls block_until_ready measures only the async dispatch — the
    round-1 'TPU is infinitely fast' trap. (time.monotonic/perf_counter
    deltas with an explicit sync, or a blocking np.asarray, are the
    sanctioned patterns — see obs/trace.sync_device_timing.)"""
    funcs: dict[ast.AST, dict] = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not ctx.function_calls_device(node):
            continue
        if ctx.function_calls(node, "block_until_ready"):
            continue
        t_names: set[str] = set()
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign)
                    and ctx.enclosing_function(sub) is node
                    and isinstance(sub.value, ast.Call)
                    and ctx.call_target(sub.value) == "time.time"):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        t_names.add(t.id)
        funcs[node] = {"t_names": t_names}

    for fn, info in funcs.items():
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.BinOp) or not isinstance(sub.op,
                                                                ast.Sub):
                continue
            # a delta inside a NESTED def is that def's business (it gets
            # its own entry iff it dispatches device work) — without this,
            # host-only timeout math in a helper is falsely flagged and a
            # qualifying nested fn is reported twice
            if ctx.enclosing_function(sub) is not fn:
                continue

            def is_time_side(expr):
                if (isinstance(expr, ast.Call)
                        and ctx.call_target(expr) == "time.time"):
                    return True
                return (isinstance(expr, ast.Name)
                        and expr.id in info["t_names"])

            if is_time_side(sub.left) or is_time_side(sub.right):
                yield _finding(
                    ctx, sub, "D005",
                    "time.time() interval around un-synced device work",
                    d005_bare_time.hint)


# jax.lax collectives that would add un-modeled ICI traffic to the tp
# forward; pmean/pmax/pmin included — any reduction over the mesh crosses
# the wire
_COLLECTIVE_CALLS = frozenset(
    f"jax.lax.{name}" for name in
    ("all_gather", "psum", "psum_scatter", "all_to_all", "ppermute",
     "pmax", "pmin", "pmean", "reduce_scatter"))
# the blessed sites: the ONLY functions in parallel/tp.py allowed to bind a
# collective — comm_stats.tp_collective_budget models exactly what flows
# through these, and the J001 contract pins the traced program to it.
# _ici_ppermute is the overlap scheme's ring hop; _ici_ring_reduce builds
# the ring but binds its collective THROUGH _ici_ppermute (blessed here so
# a future inline ppermute refactor stays inside the family).
_TP_COMM_HELPERS = frozenset(("_ici_gather", "_ici_psum", "_ici_scatter",
                              "_ici_ppermute", "_ici_ring_reduce"))


@rule("D006", "tp collective outside the comm-model helpers",
      "route tp collectives through the _ici_* helpers in parallel/tp.py "
      "and land the matching parallel/comm_stats.py budget term in the "
      "same change, or the J001 contract (and every ICI projection) drifts "
      "from the program",
      scope=("parallel/tp.py",))
def d006_unmodeled_collective(ctx: ModuleContext) -> Iterator[Finding]:
    """Every collective the tp forward issues must have a comm_stats term.
    J001 catches traced drift after the fact; this rule catches it at the
    source: any ``jax.lax`` collective call in parallel/tp.py outside the
    _ici_gather/_ici_psum/_ici_scatter helpers is flagged — a new
    collective belongs in a helper (so shard_sim can stand it in locally)
    with its budget entry, not inline in a layer body."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if ctx.call_target(node) not in _COLLECTIVE_CALLS:
            continue
        fn = ctx.enclosing_function(node)
        if fn is not None and getattr(fn, "name", "") in _TP_COMM_HELPERS:
            continue
        yield _finding(
            ctx, node, "D006",
            f"collective {ctx.call_target(node)} issued outside the "
            f"_ici_* comm-model helpers",
            d006_unmodeled_collective.hint)


# dtype names on each side of the D007 promotion hazard, post alias
# resolution (jnp -> jax.numpy). String forms cover .astype("bfloat16").
_LOW_DTYPES = frozenset(("jax.numpy.bfloat16", "jax.numpy.float16",
                         "numpy.float16", "bfloat16", "float16"))
_F32_DTYPES = frozenset(("jax.numpy.float32", "numpy.float32", "float32"))
# calls whose RESULT is a strong-typed f32/f64 scalar or array — unlike a
# bare Python literal (weak-typed, keeps the array's dtype), these win the
# promotion against a bf16/f16 operand
_F32_CONSTRUCTORS = frozenset(("jax.numpy.float32", "numpy.float32",
                               "numpy.float64"))


@rule("D007", "implicit dtype promotion to f32 in a low-precision path",
      "pick ONE dtype for the expression: cast the constant/operand to the "
      "bf16/f16 side (or the value to f32 explicitly) — a silent upcast "
      "doubles the bytes of every downstream read",
      scope=("ops/", "parallel/"))
def d007_dtype_promotion(ctx: ModuleContext) -> Iterator[Finding]:
    """Arithmetic mixing a KNOWN-low-precision local (assigned from
    ``.astype(jnp.bfloat16/float16)`` or a dtype=bf16/f16 builder) with an
    EXPLICIT f32 operand (``jnp.float32(...)``/``np.float32(...)``
    constructors — strong-typed, unlike weak Python literals — or a local
    assigned from ``.astype(jnp.float32)``). JAX promotes the whole
    expression to f32 silently: the Q40/bf16 memory saving evaporates one
    op downstream, with no error and no visible cast. Stays narrow by
    design: both sides must be provably typed within the same function —
    a bare ``x * 0.5`` never fires (weak scalars keep the array dtype)."""

    def dtype_class(expr) -> str | None:
        """'low' / 'f32' for a dtype-expression (jnp.bfloat16, "float16",
        np.float32, ...), else None."""
        name = None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            name = expr.value
        elif isinstance(expr, (ast.Attribute, ast.Name)):
            name = ctx.dotted(expr)
        if name in _LOW_DTYPES:
            return "low"
        if name in _F32_DTYPES:
            return "f32"
        return None

    def value_class(expr, local) -> str | None:
        """'low' / 'f32' for a value expression: a tracked local name, an
        .astype(...) call, or a dtype=... builder / f32 constructor."""
        if isinstance(expr, ast.Name):
            return local.get(expr.id)
        if not isinstance(expr, ast.Call):
            return None
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "astype" and expr.args):
            return dtype_class(expr.args[0])
        if ctx.call_target(expr) in _F32_CONSTRUCTORS:
            return "f32"
        for kw in expr.keywords:
            if kw.arg == "dtype":
                return dtype_class(kw.value)
        return None

    # per-function map of local name -> 'low' | 'f32'
    locals_of: dict = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            cls = value_class(node.value, {})
            if cls is not None:
                fn = ctx.enclosing_function(node)
                locals_of.setdefault(fn, {})[node.targets[0].id] = cls

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.BinOp):
            continue
        local = locals_of.get(ctx.enclosing_function(node), {})
        sides = {value_class(node.left, local),
                 value_class(node.right, local)}
        if sides == {"low", "f32"}:
            yield _finding(
                ctx, node, "D007",
                "bf16/f16 value mixed with an explicit f32 operand — the "
                "expression silently upcasts to f32",
                d007_dtype_promotion.hint)


# the clocks the obs stack standardized on (D005 owns the time.time()
# spelling); a delta of either around un-synced device work is the same
# dispatch-vs-execution trap, PLUS a hole in the span timeline
_D008_CLOCKS = frozenset(("time.monotonic", "time.perf_counter"))


def _calls_span(ctx: ModuleContext, func: ast.AST) -> bool:
    """Does this def open a span? Matches ``tracer.span(...)``,
    ``self._spans.span(...)``, and guard helpers like ``self._span(...)``
    — the final attribute segment, underscores stripped, is 'span'."""
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            t = ctx.call_target(node)
            if t is not None and t.rsplit(".", 1)[-1].lstrip("_") == "span":
                return True
    return False


def _calls_blocking_asarray(ctx: ModuleContext, func: ast.AST) -> bool:
    """np.asarray over a non-literal is a blocking transfer — the
    sanctioned sync D005's docstring blesses (host literals don't sync)."""
    for node in ast.walk(func):
        if (isinstance(node, ast.Call)
                and ctx.call_target(node) == "numpy.asarray" and node.args
                and not isinstance(node.args[0], _HOST_LITERALS)):
            return True
    return False


@rule("D008", "timed region wraps device work with neither a sync nor a span",
      "open a span (obs/spans.SpanTracer; the timeline then owns the "
      "region) or drain with block_until_ready / the "
      "obs/trace.sync_device_timing gate — otherwise the interval "
      "measures dispatch and /debug/timeline has a hole",
      scope=("runtime/", "parallel/"))
def d008_span_hygiene(ctx: ModuleContext) -> Iterator[Finding]:
    """A ``time.monotonic()``/``time.perf_counter()`` delta in a function
    that dispatches jax work but never syncs (block_until_ready, the
    sync_device_timing gate, a blocking np.asarray) and never opens a
    span. D005 catches the time.time() spelling of the dispatch trap;
    this rule covers the monotonic clocks AND enforces that timed device
    regions appear in the span timeline (ISSUE 5)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not ctx.function_calls_device(node):
            continue
        if (ctx.function_calls(node, "block_until_ready")
                or ctx.function_calls(node, "sync_device_timing")
                or _calls_span(ctx, node)
                or _calls_blocking_asarray(ctx, node)):
            continue
        t_names: set[str] = set()
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign)
                    and ctx.enclosing_function(sub) is node
                    and isinstance(sub.value, ast.Call)
                    and ctx.call_target(sub.value) in _D008_CLOCKS):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        t_names.add(t.id)

        def is_clock_side(expr):
            if (isinstance(expr, ast.Call)
                    and ctx.call_target(expr) in _D008_CLOCKS):
                return True
            return isinstance(expr, ast.Name) and expr.id in t_names

        for sub in ast.walk(node):
            if not isinstance(sub, ast.BinOp) or not isinstance(sub.op,
                                                                ast.Sub):
                continue
            # deltas inside a nested def are that def's business (same
            # ownership rule as D005)
            if ctx.enclosing_function(sub) is not node:
                continue
            if is_clock_side(sub.left) or is_clock_side(sub.right):
                yield _finding(
                    ctx, sub, "D008",
                    "monotonic/perf_counter interval around device work "
                    "with no sync and no span",
                    d008_span_hygiene.hint)


RULES = (d001_implicit_sync, d002_retrace_trap, d003_jit_closure,
         d004_hot_loop_alloc, d005_bare_time, d006_unmodeled_collective,
         d007_dtype_promotion, d008_span_hygiene)
