"""Closed-form per-device HBM accounting for the sharded serving path.

Megatron-LM budgets per-device memory analytically before a job ever
touches an accelerator; vLLM refuses to serve a config that cannot fit.
This module is that arithmetic for our (model, tp, scheme, dtype) grid —
every term hand-checkable against the spec dims:

  weights      Q40 shards resident in the Pallas kernel layout (16 B codes
               + 4 B f32 scale per 32-block — see io/loader.to_kernel_layout;
               the on-disk codec layout is 18 B/block, ``q40_codec_bytes``),
               f16/f32 shards at 2/4 B per value. Every matmul weight is
               sharded 1/tp in BOTH schemes (output bands everywhere in
               ref; wo/w2 flip to input bands in fused — same byte count).
  replicated   the f32 embedding table + rms norms every chip holds whole.
  kv_cache     2 (K and V) x L x B x S/sp x n_kv/tp x head_size planes.
  activations  live-interval peak of the traced rank program
               (``live_interval_peak``; analysis/shardcheck.py feeds it the
               shard_map body), or the closed-form vector bound
               (``activation_bytes_analytic``) on no-trace paths like the
               bench projection column.
  collectives  double-buffer staging for the largest in-flight collective
               (parallel/comm_stats.collective_staging_bytes — same cut
               points as the ICI byte budget).

The budget table is v5e-centric (16 GiB HBM/chip) with a 10% headroom
reserve for the XLA runtime, compiled executables, and fragmentation; a
config "fits" when the component total stays inside the usable fraction.
``analysis/shardcheck.py`` gates the declared support matrix on these
verdicts; ``parallel/shard_sim.project_full_system`` and bench.py surface
the same fits/headroom numbers next to every multi-chip projection.
"""

from __future__ import annotations

import dataclasses

from ..models.spec import TransformerSpec
from ..ops.quants import QK, FloatType

GIB = 1024 ** 3

# Per-device HBM by accelerator. v5e is the measurement platform of record
# (BASELINE.json); add entries as new device kinds appear in bench rows.
DEVICE_HBM_BYTES = {"v5e": 16 * GIB}
# Fraction of HBM reserved for the XLA runtime/executables/fragmentation —
# the footprint must fit in (1 - headroom) * HBM.
HBM_HEADROOM_FRACTION = 0.10

Q40_KERNEL_BLOCK_BYTES = 16 + 4   # u8 nibble planes + f32 scale (resident)
Q40_CODEC_BLOCK_BYTES = 16 + 2    # u8 nibble planes + f16 delta (file/wire)


def usable_hbm_bytes(device: str = "v5e") -> int:
    return int(DEVICE_HBM_BYTES[device] * (1 - HBM_HEADROOM_FRACTION))


def q40_kernel_bytes(values: int) -> int:
    """Resident bytes of ``values`` Q40-quantized scalars in the Pallas
    kernel layout (f32 scales — io/loader.to_kernel_layout)."""
    return (values // QK) * Q40_KERNEL_BLOCK_BYTES


def q40_codec_bytes(values: int) -> int:
    """File/wire bytes of ``values`` Q40 scalars (f16 deltas)."""
    return (values // QK) * Q40_CODEC_BLOCK_BYTES


def weight_values_per_device(spec: TransformerSpec, n_slices: int) -> int:
    """Matmul-weight scalars per device: all 7 per-layer matmuls plus wcls
    shard exactly 1/tp of their values in both schemes (tp.py)."""
    per_layer = sum(d * n for _, (d, n) in spec.layer_matmul_shapes())
    total = spec.n_layers * per_layer + spec.vocab_size * spec.dim
    return total // n_slices


def weights_device_bytes(spec: TransformerSpec, n_slices: int) -> int:
    """Resident bytes of this device's matmul-weight shards."""
    values = weight_values_per_device(spec, n_slices)
    ft = spec.weights_float_type
    if ft == FloatType.Q40:
        return q40_kernel_bytes(values)
    if ft == FloatType.F16:
        return 2 * values
    if ft == FloatType.F32:
        return 4 * values
    raise ValueError(f"no weight byte model for {ft!r}")


def replicated_device_bytes(spec: TransformerSpec) -> int:
    """Bytes every chip holds whole regardless of tp: the f32 embedding
    table and the rms norm vectors (2 per layer + final)."""
    embedding = spec.vocab_size * spec.dim * 4
    norms = (2 * spec.n_layers + 1) * spec.dim * 4
    return embedding + norms


def kv_cache_device_bytes(spec: TransformerSpec, n_slices: int,
                          batch: int = 1, n_sp: int = 1,
                          cache_itemsize: int = 4) -> int:
    """K+V planes at max sequence: kv heads shard over tp, sequence chunks
    over sp (tp.CACHE_SPEC / CACHE_SPEC_BATCH)."""
    return (2 * spec.n_layers * batch * (spec.seq_len // n_sp)
            * (spec.n_kv_heads // n_slices) * spec.head_size
            * cache_itemsize)


# The page size the documented tables/benches use (positions per page).
# Small enough that a chat-sized request strands < page_size positions,
# large enough that page-table gathers stay coarse; the engine knob
# (--kv-page-size) accepts any divisor of seq_len.
DEFAULT_PAGE_SIZE = 16


def default_kv_pages(spec: TransformerSpec, batch: int,
                     page_size: int = DEFAULT_PAGE_SIZE) -> int:
    """The engine's default pool sizing: byte-parity with the contiguous
    ``batch``-slot cache (runtime/continuous.ContinuousEngine)."""
    return batch * (spec.seq_len // page_size)


def kv_position_bytes(spec: TransformerSpec, n_slices: int,
                      cache_itemsize: int = 4,
                      kv_quant: str = "f32") -> int:
    """K+V bytes of ONE sequence position on one device (all layers).

    f32/bf16: ``cache_itemsize`` per value. q8 (ISSUE 11): the Q80 wire
    layout from ops/quants.py — 1 int8 code per value plus one f16 delta
    per 32-value block of the flattened (n_kv/tp, hs) row
    (models/llama.PagedKVQ8), i.e. 34 bytes per 32 values: a 32/34 ≈
    3.76x cut vs f32 (1.88x vs bf16). Exact, not approximate — the
    equal-HBM page multiplier the engine/bench use is derived from this
    number, and the shardcheck KV-quant column pins it."""
    kv_dim = (spec.n_kv_heads // n_slices) * spec.head_size
    if kv_quant == "q8":
        per = kv_dim + 2 * (kv_dim // QK)   # int8 codes + f16 deltas
    elif kv_quant == "f32":
        per = kv_dim * cache_itemsize
    else:
        raise ValueError(f"no KV byte model for kv_quant={kv_quant!r}")
    return 2 * spec.n_layers * per


def equal_hbm_kv_pages(spec: TransformerSpec, n_slices: int,
                       n_pages_f32: int,
                       page_size: int = DEFAULT_PAGE_SIZE,
                       cache_itemsize: int = 4) -> int:
    """How many q8 pages the HBM of ``n_pages_f32`` f32 pages holds — the
    capacity lever the continuous_bench equal-HBM section drives (~3.76x
    at f32 baseline, ~1.88x at bf16)."""
    f32_bytes = n_pages_f32 * page_size * kv_position_bytes(
        spec, n_slices, cache_itemsize, "f32")
    page_q8 = page_size * kv_position_bytes(spec, n_slices, kv_quant="q8")
    return f32_bytes // page_q8


def kv_page_pool_bytes(spec: TransformerSpec, n_slices: int, n_pages: int,
                       page_size: int = DEFAULT_PAGE_SIZE,
                       cache_itemsize: int = 4,
                       include_scrap: bool = True,
                       kv_quant: str = "f32") -> int:
    """Paged-pool K+V bytes: 2 x L x pages x page_size x n_kv/tp x hs
    (per-position pricing via ``kv_position_bytes`` — q8 pages charge the
    Q80 codes + f16 block deltas exactly).

    The paged lever: ``n_pages`` is a FREE knob — contiguous slots charge
    ``slots * seq_len`` positions whether requests use them or not, the
    pool charges exactly what it holds. At the engine's default sizing
    (default_kv_pages) the two layouts are byte-identical per position
    (shardcheck pins that equivalence across the whole support matrix);
    undersized pools trade eviction pressure for concurrency at equal
    HBM (the continuous_bench columns). ``include_scrap`` charges the
    reserved dead-write page 0 the engine actually allocates
    (models/llama.init_cache_paged gets n_pages + 1)."""
    pages = n_pages + (1 if include_scrap else 0)
    return pages * page_size * kv_position_bytes(spec, n_slices,
                                                 cache_itemsize, kv_quant)


# -- KV tier hierarchy (ISSUE 12) -------------------------------------------

# Modeled transfer rates for the tier hierarchy's promotion/demotion
# paths. Host<->device rides PCIe (a v5e host link — the TPU's non-ICI
# attach point); disk is a modest NVMe read stream. Like the ICI numbers
# in shard_sim these are MODELED planning constants, not measurements —
# PARITY.md carries the honest-N/A measured column.
HOST_DEVICE_GBPS = 16.0
DISK_READ_GBPS = 1.5
# per-page fixed cost of a promotion apply (dispatch + descriptor work)
TIER_PROMOTE_LATENCY_US = 30.0


def kv_page_bytes(spec: TransformerSpec, n_slices: int,
                  page_size: int = DEFAULT_PAGE_SIZE,
                  cache_itemsize: int = 4, kv_quant: str = "f32") -> int:
    """Bytes of ONE physical page's planes on one device (all layers,
    K+V, codes+deltas for q8) — the unit every tier transfer moves."""
    return page_size * kv_position_bytes(spec, n_slices, cache_itemsize,
                                         kv_quant)


def kv_tier_model(spec: TransformerSpec, n_slices: int,
                  hbm_pages: int, host_pages: int = 0,
                  disk_bytes: int = 0,
                  page_size: int = DEFAULT_PAGE_SIZE,
                  cache_itemsize: int = 4,
                  kv_quant: str = "f32") -> dict:
    """Per-tier capacity + bandwidth model of the KV hierarchy: bytes
    held per tier, pages the budgets buy, and the modeled per-page
    promotion/demotion cost — the numbers that justify spilling instead
    of recomputing. The comparison that matters: promoting one page
    costs ~page_bytes/PCIe-bw, while re-PREFILLING its page_size
    positions costs a full forward pass over them — at 7B shapes the
    upload is microseconds against milliseconds of recompute, priced
    per kv_quant (q8 pages move ~3.76x cheaper than f32). Budgets are
    per-device for HBM (kv heads shard over tp) and per-HOST for the
    host/disk tiers (one host feeds its local devices)."""
    pb = kv_page_bytes(spec, n_slices, page_size, cache_itemsize, kv_quant)
    host_ms = pb / (HOST_DEVICE_GBPS * GIB) * 1e3
    disk_ms = pb / (DISK_READ_GBPS * GIB) * 1e3
    lat_ms = TIER_PROMOTE_LATENCY_US / 1e3
    return {
        "page_size": page_size,
        "kv_quant": kv_quant,
        "page_bytes": pb,
        "hbm": {"pages": hbm_pages, "bytes": hbm_pages * pb},
        "host": {"pages": host_pages, "bytes": host_pages * pb},
        "disk": {"bytes": disk_bytes,
                 "pages": (disk_bytes // pb) if disk_bytes else 0},
        # promotion = upload (+ disk read below host); demotion mirrors
        # the upload cost (device->host readback at the same link rate)
        "promote_host_ms_per_page": round(host_ms + lat_ms, 6),
        "promote_disk_ms_per_page": round(host_ms + disk_ms + lat_ms, 6),
        "demote_ms_per_page": round(host_ms + lat_ms, 6),
    }


# -- prefill/decode disaggregation (ISSUE 14) -------------------------------

# Modeled DCN bandwidth between the prefill and decode pools: a 25 GbE
# data-center link's useful throughput. A planning constant like the
# PCIe/disk numbers above — PARITY.md's measured column stays honest N/A
# until a hardware session.
DCN_GBPS = 3.0
# per-handoff fixed cost (connection reuse + framing + the admit RPC)
DCN_HANDOFF_LATENCY_US = 200.0


def disagg_pool_model(spec: TransformerSpec, n_slices: int,
                      prefill_pages: int, decode_pages: int,
                      page_size: int = DEFAULT_PAGE_SIZE,
                      cache_itemsize: int = 4, kv_quant: str = "f32",
                      prompt_positions: int = 512) -> dict:
    """Per-pool capacity + handoff-bandwidth model of the two-pool
    topology: page-pool bytes per pool, and the modeled cost of shipping
    one request's full prompt pages over the DCN — the number that
    justifies disaggregation's trade. The comparison that matters: a
    handoff moves pages/request x page_bytes at DCN_GBPS (milliseconds),
    while the interference it removes is every decode step that would
    have queued behind the prefill dispatch on a colocated chip. Priced
    per kv_quant: q8 pages ship ~3.76x cheaper than f32 — the PR 11 wire
    cut compounds straight into the DCN budget."""
    from ..parallel.comm_stats import dcn_handoff_budget

    pb = kv_page_bytes(spec, n_slices, page_size, cache_itemsize, kv_quant)
    budget = dcn_handoff_budget(spec, n_slices, prompt_positions,
                                page_size, kv_quant, cache_itemsize)
    ship_ms = budget["bytes"] / (DCN_GBPS * GIB) * 1e3 \
        + DCN_HANDOFF_LATENCY_US / 1e3
    return {
        "page_size": page_size,
        "kv_quant": kv_quant,
        "page_bytes": pb,
        "prefill": {"pages": prefill_pages, "bytes": prefill_pages * pb},
        "decode": {"pages": decode_pages, "bytes": decode_pages * pb},
        "handoff": {**budget,
                    "dcn_gbps": DCN_GBPS,
                    "ship_ms_per_page": round(
                        pb / (DCN_GBPS * GIB) * 1e3, 6),
                    "ship_ms_per_request": round(ship_ms, 6)},
    }


def activation_bytes_analytic(spec: TransformerSpec, n_slices: int,
                              t_len: int = 1) -> int:
    """No-trace activation bound for projection columns: the residual
    stream + norm buffer + local qkv/swiglu bands + full and local logits,
    all f32. The traced live-interval peak (shardcheck) supersedes this
    where a jaxpr is available; both land within a few MB of each other at
    decode shapes — activations are a rounding error next to weights/KV."""
    s = n_slices
    vecs = (4 * spec.dim                      # x, xb, gathered block outs
            + 2 * (spec.hidden_dim // s)      # swiglu bands
            + (spec.dim + 2 * spec.kv_dim) // s   # local q/k/v
            + spec.vocab_size + spec.vocab_size // s)  # logits full + band
    return 4 * t_len * vecs


# -- live-interval walk -----------------------------------------------------


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * dtype.itemsize


def sub_jaxprs(eqn):
    """Inner jaxprs of an eqn (scan/while/cond/pjit bodies, tuple-valued
    branch params included), unwrapped to raw Jaxpr — the ONE recursion
    helper for both the live walk and shardcheck's eqn searches."""
    out = []
    for v in eqn.params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            # unwrap ClosedJaxpr (which also proxies .eqns) to its Jaxpr
            inner = getattr(item, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                out.append(inner)
            elif hasattr(item, "eqns") and hasattr(item, "outvars"):
                out.append(item)
    return out


def live_interval_peak(jaxpr, exclude_eqn=None) -> int:
    """Peak bytes of simultaneously-live *intermediate* values in ``jaxpr``.

    A linear walk over the eqns in program order: each eqn allocates its
    outputs, and a value is freed after its last use — the classic live-
    interval model of a straight-line allocator. What the model charges:

    * jaxpr invars/constvars are NOT counted — weights, cache, and tokens
      are accounted by the closed-form components (and per-layer weight
      slices of a scan over top-level invars are a CPU-fallback artifact:
      the serving path reads stacked Q40 weights in place via scalar
      prefetch, ops/linear.StackedQ40);
    * a ``dynamic_update_slice`` whose operand is dead after the eqn (or is
      an untracked input — the donated-cache carry) updates in place, and a
      scan/while carry output whose carry INIT is untracked or dies at the
      loop aliases that init: zero new bytes — mirroring XLA's donation and
      loop-carry aliasing on the real device (the decode cache rides the
      scan carry donated; charging it again would double-count the KV
      component);
    * control-flow eqns recurse: a scan's peak is its body's peak (plus the
      per-iteration slices of any *intermediate* scanned xs), branches take
      the max, and the inner peak lands on top of everything live outside;
    * ``exclude_eqn(eqn)`` -> True drops that eqn's outputs from the model —
      shardcheck passes the dequant-site filter so registered XLA-fallback
      dequant transients (absent on the Pallas path) don't read as serving
      HBM.
    """
    def is_var(v) -> bool:
        # core.Var (hashable, has aval); Literals carry .val and are not
        # hashable — they hold no buffer and are skipped
        return hasattr(v, "aval") and not hasattr(v, "val")

    eqns = list(jaxpr.eqns)
    last_use: dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if is_var(v):
                last_use[v] = i
    for v in jaxpr.outvars:
        if is_var(v):
            last_use[v] = len(eqns)

    live: dict = {}       # var -> counted bytes
    live_total = 0
    peak = 0
    for i, eqn in enumerate(eqns):
        prim = eqn.primitive.name
        excluded = exclude_eqn is not None and exclude_eqn(eqn)

        def freeable(v, i=i):
            # an operand that is untracked (jaxpr input: donated/accounted
            # elsewhere) or dead after this eqn can be updated in place
            return not is_var(v) or v not in live \
                or last_use.get(v, -1) == i

        alias_out: set = set()
        if prim == "dynamic_update_slice" and eqn.invars \
                and freeable(eqn.invars[0]):
            alias_out.add(id(eqn.outvars[0]))
        elif prim == "scan":
            nc = eqn.params.get("num_consts", 0)
            ncar = eqn.params.get("num_carry", 0)
            for k in range(min(ncar, len(eqn.outvars))):
                if freeable(eqn.invars[nc + k]):
                    alias_out.add(id(eqn.outvars[k]))
        elif prim == "while":
            n_carry = len(eqn.outvars)
            inits = eqn.invars[len(eqn.invars) - n_carry:]
            for k, init in enumerate(inits):
                if freeable(init):
                    alias_out.add(id(eqn.outvars[k]))

        inner = 0
        subs = sub_jaxprs(eqn)
        if subs:
            inner = max(live_interval_peak(s, exclude_eqn) for s in subs)
            if prim == "scan":
                n_xs = (len(eqn.invars) - eqn.params.get("num_consts", 0)
                        - eqn.params.get("num_carry", 0))
                length = max(int(eqn.params.get("length", 1)), 1)
                for v in eqn.invars[len(eqn.invars) - n_xs:]:
                    if is_var(v) and v in live:
                        # intermediate xs: per-iteration slice copy
                        inner += live[v] // length

        counted = []
        if not excluded:
            counted = [v for v in eqn.outvars
                       if is_var(v) and id(v) not in alias_out]
        out_bytes = sum(_aval_bytes(v.aval) for v in counted)
        peak = max(peak, live_total + out_bytes + inner)
        for v in counted:
            live[v] = _aval_bytes(v.aval)
            live_total += live[v]
        for v in eqn.invars + list(eqn.outvars):
            if is_var(v) and v in live and last_use.get(v, -1) <= i:
                live_total -= live.pop(v)
    return peak


# -- the assembled report ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MemoryReport:
    """Per-device HBM footprint of one (spec, tp, scheme) config."""

    model: str
    tp: int
    scheme: str
    weights_float_type: str
    weights_bytes: int
    replicated_bytes: int
    kv_cache_bytes: int
    activation_bytes: int
    collective_bytes: int
    budget_bytes: int
    # KV-tiering promotion staging (ISSUE 12): the double-buffered page
    # upload target (2 pages of planes) a tiered engine keeps device-side.
    # 0 (the default) for untiered configs — pinned totals unchanged.
    tier_staging_bytes: int = 0

    @property
    def total_bytes(self) -> int:
        return (self.weights_bytes + self.replicated_bytes
                + self.kv_cache_bytes + self.activation_bytes
                + self.collective_bytes + self.tier_staging_bytes)

    @property
    def headroom_bytes(self) -> int:
        return self.budget_bytes - self.total_bytes

    @property
    def fits(self) -> bool:
        return self.headroom_bytes >= 0

    def as_json(self) -> dict:
        gib = {k: round(getattr(self, k) / GIB, 3)
               for k in ("weights_bytes", "replicated_bytes",
                         "kv_cache_bytes", "activation_bytes",
                         "collective_bytes")}
        if self.tier_staging_bytes:
            gib["tier_staging_bytes"] = round(
                self.tier_staging_bytes / GIB, 3)
        return {
            "model": self.model, "tp": self.tp, "scheme": self.scheme,
            "weights_float_type": self.weights_float_type,
            "components_gib": {k.replace("_bytes", ""): v
                               for k, v in gib.items()},
            "total_gib": round(self.total_bytes / GIB, 3),
            "budget_gib": round(self.budget_bytes / GIB, 3),
            "headroom_gib": round(self.headroom_bytes / GIB, 3),
            "fits": self.fits,
        }


def device_footprint(spec: TransformerSpec, n_slices: int, scheme: str,
                     model: str = "?", batch: int = 1,
                     activation_bytes: int | None = None,
                     device: str = "v5e", kv_page_size: int = 0,
                     kv_pages: int | None = None,
                     spec_k: int = 0, kv_quant: str = "f32",
                     tier_staging_pages: int = 0,
                     mixed_budget: int = 0) -> MemoryReport:
    """Assemble the per-device report; ``activation_bytes`` overrides the
    analytic bound with a traced live-interval peak when available.
    ``kv_page_size > 0`` charges KV as the paged pool (default pool =
    engine default: byte-parity with ``batch`` contiguous slots, plus the
    scrap page) instead of ``batch`` contiguous max-seq stripes.
    ``spec_k > 0`` charges activations and collective staging at the
    K-query verify width (the speculative dispatch runs batch * spec_k
    activation rows through every layer — ISSUE 7); weights and KV are
    unchanged, which is exactly why the verify dispatch is nearly free in
    HBM terms. ``kv_quant='q8'`` (paged only) prices the pool at the Q80
    codes+deltas byte rate (kv_position_bytes). ``tier_staging_pages``
    (ISSUE 12) charges the KV-tiering promotion staging buffer — the
    device-side upload target a tiered engine double-buffers (2 pages is
    the engine's shape) — priced at the pool's page byte rate.
    ``mixed_budget > 0`` (ISSUE 18) charges activations and collective
    staging at the token-budget dispatch width — the mixed forward runs
    batch * budget activation rows through every layer, same shape math
    as the verify window; mutually exclusive with ``spec_k`` (the engine
    rejects the pairing, so a report pricing both would describe a
    config that cannot exist)."""
    from ..parallel.comm_stats import collective_staging_bytes

    if spec_k and mixed_budget:
        raise ValueError("spec_k and mixed_budget are mutually exclusive "
                         "(the engine rejects --spec-k with "
                         "--dispatch-tokens; price one dispatch shape)")
    t_len = max(1, spec_k, mixed_budget)
    if kv_quant != "f32" and kv_page_size <= 0:
        raise ValueError(f"kv_quant={kv_quant!r} prices PAGE planes; "
                         f"pass kv_page_size > 0")
    if tier_staging_pages and kv_page_size <= 0:
        raise ValueError("tier_staging_pages prices PAGE planes; pass "
                         "kv_page_size > 0")
    if activation_bytes is None:
        activation_bytes = activation_bytes_analytic(spec, n_slices,
                                                     t_len=t_len)
    if kv_page_size > 0:
        pages = (kv_pages if kv_pages is not None
                 else default_kv_pages(spec, batch, kv_page_size))
        kv_bytes = kv_page_pool_bytes(spec, n_slices, pages, kv_page_size,
                                      kv_quant=kv_quant)
    else:
        kv_bytes = kv_cache_device_bytes(spec, n_slices, batch=batch)
    return MemoryReport(
        model=model, tp=n_slices, scheme=scheme,
        weights_float_type=FloatType(spec.weights_float_type).name,
        weights_bytes=weights_device_bytes(spec, n_slices),
        replicated_bytes=replicated_device_bytes(spec),
        kv_cache_bytes=kv_bytes,
        activation_bytes=int(activation_bytes),
        collective_bytes=collective_staging_bytes(spec, n_slices, scheme,
                                                  t_len=t_len),
        budget_bytes=usable_hbm_bytes(device),
        tier_staging_bytes=(tier_staging_pages * kv_page_bytes(
            spec, n_slices, kv_page_size, kv_quant=kv_quant)
            if tier_staging_pages else 0))
